"""Seeded chaos schedules for ``bench.py --chaos``.

``build_schedule(seed, rounds)`` is a pure function: the same seed and
round count produce the identical event list on every machine and every
run — the bench's whole fault sequence (which seam, which kind, which
stall length, in which order) derives from one integer.  The first
``len(FAULT_CLASSES)`` rounds are a deterministic shuffle covering every
fault class once (so per-class time-to-ready is always measurable);
remaining rounds draw uniformly.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import List

__all__ = ["FAULT_CLASSES", "FaultEvent", "build_schedule"]

# fault class -> (fault point, action kind).  The catalog of seams wired
# through ``chaos.fault`` — see README "Robustness & chaos".
FAULT_CLASSES = {
    "log_enospc": ("delta_log.append", "enospc"),
    "log_torn": ("delta_log.append", "torn"),
    "repl_drop": ("repl.server.send", "drop"),
    "repl_garbage": ("repl.server.send", "garbage"),
    "repl_stall": ("repl.server.send", "stall"),
    # stall with the hold sampled per fire from the injector's seeded
    # lognormal (no fixed stall_s in the event data) — the heavy-tailed
    # degradation the photonwatch SLO burn episodes alarm on
    "repl_stall_dist": ("repl.server.send", "stall_dist"),
    "client_drop": ("repl.client.read", "drop"),
    "front_drop": ("front.conn", "drop"),
    "snapshot_disconnect": ("repl.server.snapshot", "disconnect"),
    "swap_crash": ("swap.activate", "crash"),
}


@dataclass(frozen=True)
class FaultEvent:
    """One chaos round: arm ``point`` with ``kind``, drive traffic,
    disarm, wait for the topology to heal."""

    round: int
    fault_class: str
    point: str
    kind: str
    data: dict = field(default_factory=dict)


def build_schedule(seed: int, rounds: int) -> List[FaultEvent]:
    """Deterministic event list: coverage pass over every fault class
    (shuffled by ``seed``), then seeded uniform draws."""
    rng = random.Random(seed)
    classes = sorted(FAULT_CLASSES)
    order = list(classes)
    rng.shuffle(order)
    picks = [order[i] if i < len(order) else rng.choice(classes)
             for i in range(rounds)]
    events = []
    for i, cls in enumerate(picks):
        point, kind = FAULT_CLASSES[cls]
        data = {}
        if kind == "stall":
            data["stall_s"] = round(rng.uniform(0.02, 0.10), 4)
        events.append(FaultEvent(round=i, fault_class=cls, point=point,
                                 kind=kind, data=data))
    return events
