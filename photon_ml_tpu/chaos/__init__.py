"""photonchaos: deterministic fault injection, health/readiness, and the
seeded chaos schedule behind ``bench.py --chaos``.

Seam-side usage (one boolean check when disabled)::

    from photon_ml_tpu.chaos import fault

    act = fault("delta_log.append")
    if act is not None:
        raise act.to_error()

Test/bench-side usage::

    from photon_ml_tpu.chaos import get_injector

    inj = get_injector()
    inj.arm("repl.server.send", kind="drop", nth=3)
    try:
        ...drive traffic, assert the topology heals...
    finally:
        inj.reset()
"""

from photon_ml_tpu.chaos.health import (HealthState, Watchdog, WorkerWatch,
                                        delta_log_check,
                                        follower_staleness_check)
from photon_ml_tpu.chaos.injector import (FaultAction, FaultInjector,
                                          InjectedCrash, InjectedFault,
                                          fault, get_injector, set_injector)
from photon_ml_tpu.chaos.schedule import (FAULT_CLASSES, FaultEvent,
                                          build_schedule)

__all__ = [
    "FAULT_CLASSES", "FaultAction", "FaultEvent", "FaultInjector",
    "HealthState", "InjectedCrash", "InjectedFault", "Watchdog",
    "WorkerWatch", "build_schedule", "delta_log_check", "fault",
    "follower_staleness_check", "get_injector", "set_injector",
]
