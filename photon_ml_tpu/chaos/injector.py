"""Deterministic fault injection (photonchaos).

The availability story of the reference GLMix system is delegated to
Spark's driver/executor supervision; this repo runs owner, replica, and
frontend as cooperating processes and has to prove the topology heals on
its own.  The delta log already learned that lesson at byte granularity
(the every-offset truncation property test) — this module generalizes it
to the process level: every failure seam carries a NAMED fault point, and
a test or ``bench.py --chaos`` arms a deterministic schedule against it.

Discipline (photonscope's ``obs.span`` rule applies unchanged):

  - **Disabled is free.**  A fault point costs ONE boolean check when no
    injector is armed — ``fault(point)`` reads ``_injector.enabled`` and
    returns ``None`` before touching any lock, dict, or RNG.
  - **Deterministic.**  Every schedule is a pure function of its
    configuration: fire-on-Nth-hit counts calls, seeded probability draws
    from a per-point ``random.Random(seed)``, timed windows measure from
    the moment the point was armed.  Same arms + same call sequence →
    same fires.  ``bench.py --chaos`` builds its whole run from one seed.
  - **Sites interpret, the injector schedules.**  ``check`` returns a
    ``FaultAction`` (kind + data) or None; the seam decides what "drop"
    or "torn" means locally (raise, sleep, write garbage, close).  Sites
    that just want an exception use ``FaultAction.to_error()``.

Fault-point names are dotted, seam-local constants — the catalog lives in
the README ("Robustness & chaos").  Armed points that a run never hits
are visible via ``FaultInjector.hits`` — a chaos schedule asserting on a
misspelled point fails loudly instead of testing nothing.
"""

from __future__ import annotations

import errno
import math
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

__all__ = [
    "FaultAction", "FaultInjector", "InjectedCrash", "InjectedFault",
    "fault", "get_injector", "set_injector",
]


class InjectedFault(Exception):
    """An exception raised on purpose by an armed fault point."""


class InjectedCrash(InjectedFault):
    """Process-death stand-in: seams NEVER catch this (tests do)."""


@dataclass(frozen=True)
class FaultAction:
    """What an armed fault point should do on this hit.

    ``kind`` is interpreted by the seam (``"enospc"``, ``"torn"``,
    ``"drop"``, ``"stall"``, ``"stall_dist"``, ``"garbage"``,
    ``"disconnect"``, ``"crash"``, ``"corrupt"``, ``"slow"``,
    ``"error"``); ``data`` carries kind-specific knobs (e.g.
    ``stall_s``).  ``stall_dist`` is ``stall`` with the hold sampled per
    fire from the rule's seeded lognormal (see ``FaultInjector.check``) —
    stall-interpreting seams treat the two identically."""

    point: str
    kind: str
    data: dict = field(default_factory=dict)

    def to_error(self) -> BaseException:
        """The canonical exception for this action — seams that only
        need "make this operation fail" raise it verbatim."""
        if self.kind == "enospc":
            return OSError(errno.ENOSPC,
                           f"injected ENOSPC at {self.point}")
        if self.kind == "torn":
            # a torn write IS an I/O error after a partial write
            return OSError(errno.EIO,
                           f"injected torn write at {self.point}")
        if self.kind == "crash":
            return InjectedCrash(f"injected crash at {self.point}")
        if self.kind in ("drop", "disconnect"):
            return ConnectionResetError(
                f"injected {self.kind} at {self.point}")
        return InjectedFault(f"injected {self.kind} at {self.point}")


# stall_dist defaults: median 30ms holds, heavy-tailed (sigma 0.6 puts the
# p99 near 4x the median), capped so a pathological draw cannot wedge a
# bench; all three overridable via the rule's data
_STALL_DIST_MU = math.log(0.03)
_STALL_DIST_SIGMA = 0.6
_STALL_DIST_CAP_S = 0.25


class _Rule:
    """One armed schedule on one point.  ``decide(hit_no, now)`` is
    called under the injector lock with the 1-based hit number."""

    def __init__(self, kind: str, data: dict, nth: Optional[int],
                 repeat: bool, probability: Optional[float],
                 seed: int, window: Optional[Tuple[float, float]],
                 max_fires: Optional[int]):
        self.kind = kind
        self.data = dict(data or {})
        self.nth = nth
        self.repeat = repeat
        self.probability = probability
        self.window = window
        self.max_fires = max_fires
        self.fires = 0
        self.armed_at = time.monotonic()
        # per-rule RNG: probability schedules replay identically for the
        # same seed regardless of what other points draw
        self._rng = random.Random(seed)

    def decide(self, hit_no: int, now: float) -> bool:
        if self.max_fires is not None and self.fires >= self.max_fires:
            return False
        if self.window is not None:
            after, duration = self.window
            dt = now - self.armed_at
            if dt < after or dt >= after + duration:
                return False
        if self.nth is not None:
            if self.repeat:
                if hit_no % self.nth != 0:
                    return False
            elif hit_no != self.nth:
                return False
        if self.probability is not None:
            if self._rng.random() >= self.probability:
                return False
        self.fires += 1
        return True


class FaultInjector:
    """Named fault points with deterministic, seeded schedules.

    Thread-safe: seams call ``check`` from asyncio loops, daemon
    threads, and the request path concurrently.  ``enabled`` is a plain
    attribute read outside the lock — the disabled fast path never
    synchronizes (stale reads only extend the no-op window by one call,
    exactly like ``obs.trace``'s tracer swap)."""

    def __init__(self, registry=None):
        self.enabled = False
        self.registry = registry
        self._lock = threading.Lock()
        self._rules: Dict[str, _Rule] = {}
        self._hits: Dict[str, int] = {}

    def arm(self, point: str, kind: str = "error", *,
            nth: Optional[int] = None, repeat: bool = False,
            probability: Optional[float] = None, seed: int = 0,
            window: Optional[Tuple[float, float]] = None,
            max_fires: Optional[int] = None,
            data: Optional[dict] = None) -> None:
        """Arm ``point`` with one schedule (re-arming replaces it).

        ``nth``: fire on the Nth hit (every Nth with ``repeat=True``).
        ``probability``: fire when ``Random(seed).random() < p`` —
        deterministic per arm.  ``window``: ``(after_s, duration_s)``
        measured from this call.  Omitting all three fires on EVERY hit.
        ``max_fires`` caps total fires for any schedule."""
        if nth is not None and nth < 1:
            raise ValueError(f"nth must be >= 1, got {nth}")
        with self._lock:
            self._rules[point] = _Rule(kind, data or {}, nth, repeat,
                                       probability, seed, window, max_fires)
            self.enabled = True

    def disarm(self, point: Optional[str] = None) -> None:
        """Disarm one point, or everything when ``point`` is None (hit
        counters survive — a schedule can assert coverage after)."""
        with self._lock:
            if point is None:
                self._rules.clear()
            else:
                self._rules.pop(point, None)
            self.enabled = bool(self._rules)

    def check(self, point: str) -> Optional[FaultAction]:
        """One hit on ``point``: returns the action to take, or None."""
        with self._lock:
            hit_no = self._hits.get(point, 0) + 1
            self._hits[point] = hit_no
            rule = self._rules.get(point)
            if rule is None or not rule.decide(hit_no, time.monotonic()):
                return None
            data = rule.data
            if rule.kind == "stall_dist":
                # latency-distribution stall: every fire samples its OWN
                # hold from the rule's seeded lognormal — one armed rule
                # yields a realistic heavy-tailed degradation instead of a
                # square pulse.  Sampled under the lock from the per-rule
                # RNG, so same seed + same call sequence -> same holds.
                mu = float(data.get("mu", _STALL_DIST_MU))
                sigma = float(data.get("sigma", _STALL_DIST_SIGMA))
                cap = float(data.get("cap_s", _STALL_DIST_CAP_S))
                data = dict(data, stall_s=min(
                    rule._rng.lognormvariate(mu, sigma), cap))
            action = FaultAction(point=point, kind=rule.kind, data=data)
        if self.registry is not None:
            self.registry.inc("chaos_faults_fired_total", point=point,
                              kind=action.kind)
        return action

    def hits(self, point: str) -> int:
        """Times ``point`` was reached (armed or not, fired or not)."""
        with self._lock:
            return self._hits.get(point, 0)

    def fired(self, point: str) -> int:
        """Times the CURRENTLY armed schedule on ``point`` fired."""
        with self._lock:
            rule = self._rules.get(point)
            return rule.fires if rule is not None else 0

    def reset(self) -> None:
        """Disarm everything and zero the hit counters."""
        with self._lock:
            self._rules.clear()
            self._hits.clear()
            self.enabled = False


# ---------------------------------------------------------------------------
# process-wide injector (obs.trace's tracer-swap idiom)
# ---------------------------------------------------------------------------
_injector = FaultInjector()


def get_injector() -> FaultInjector:
    """The process-wide injector (disabled until something arms it)."""
    return _injector


def set_injector(injector: FaultInjector) -> FaultInjector:
    """Swap the process-wide injector; returns the previous one (tests
    restore it in a finally)."""
    global _injector
    prev = _injector
    _injector = injector
    return prev


def fault(point: str) -> Optional[FaultAction]:
    """The seam-side entry point.  Disabled cost: one boolean check."""
    inj = _injector
    if not inj.enabled:
        return None
    return inj.check(point)
