"""Health and readiness state (photonchaos).

One ``HealthState`` per serving process aggregates named checks into the
single bit an orchestrator acts on: **ready** (serve traffic) or **not
ready** (drain me).  The metrics sidecar exposes it as ``/readyz``
(503 while any check fails, 200 when all pass) next to ``/healthz``
(process liveness: 200 whenever the HTTP thread can answer at all).

Checks come in two shapes:

  - ``add_check(name, fn)`` — *pull*: ``fn() -> (ok, detail)`` evaluated
    at request time against live state (follower staleness, delta-log
    writability, watchdog sweep).  A check that raises counts as failed
    with the exception text as detail — a broken probe must never report
    healthy.
  - ``set_condition(name, ok, detail)`` — *push*: a latched bit flipped
    by the component itself (engine warmed after build).

``Watchdog`` covers the failure the injector makes easy to produce and a
pull check cannot see from outside: a daemon worker (batcher flusher,
log follower, replication subscriber) that died or wedged mid-item.
Workers wrap their per-item work in ``watch.busy()``; the watchdog calls
a worker stalled when its registered thread is no longer alive or when
one item has been in flight longer than ``stall_after_s``.  The watchdog
is itself a pull check — readiness flips while a worker is stalled and
recovers the moment it drains.

photonpulse hooks (PR 15): an ok -> failed transition of any check or
condition, and a worker's transition into stalled, each (a) land on the
trace timeline as a ``chaos.degraded`` / ``chaos.stall`` instant — so the
stall sits inline next to the spans it starved — and (b) trigger a flight
recorder dump, spooling the ring *around* the degradation before it gets
lapped.  Both fire on the TRANSITION only (a degraded process polled by
``/readyz`` every second must not flood the ring), and both are a
no-op-cost boolean/None check when tracing / the recorder are off.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Callable, Dict, Optional, Tuple

from photon_ml_tpu.obs.pulse.flight import flight_dump
from photon_ml_tpu.obs.trace import instant as obs_instant

__all__ = ["HealthState", "Watchdog", "WorkerWatch",
           "delta_log_check", "follower_staleness_check"]

Check = Callable[[], Tuple[bool, str]]


class HealthState:
    """Named readiness checks aggregated into one ready bit."""

    def __init__(self, registry=None):
        self.registry = registry
        self._lock = threading.Lock()
        self._checks: Dict[str, Check] = {}
        self._conditions: Dict[str, Tuple[bool, str]] = {}
        self._last_ok: Dict[str, bool] = {}  # transition edge detection

    def add_check(self, name: str, fn: Check) -> None:
        """Register a pull check, evaluated on every ``readyz`` call."""
        with self._lock:
            self._checks[name] = fn

    def _note_transition(self, name: str, ok: bool, detail: str) -> None:
        """Fire the degradation hooks when ``name`` flips ok -> failed."""
        with self._lock:
            was_ok = self._last_ok.get(name, True)
            self._last_ok[name] = ok
        if was_ok and not ok:
            obs_instant("chaos.degraded", check=name, detail=detail)
            flight_dump("health_degraded", check=name, detail=detail)

    def set_condition(self, name: str, ok: bool, detail: str = "") -> None:
        """Latch a push condition (overwrites the previous value)."""
        with self._lock:
            self._conditions[name] = (bool(ok), detail)
        if self.registry is not None:
            self.registry.set_gauge("health_check_ok", 1.0 if ok else 0.0,
                                    check=name)
        self._note_transition(name, bool(ok), detail)

    def readyz(self) -> Tuple[bool, Dict[str, dict]]:
        """Evaluate everything: ``(ready, {name: {"ok", "detail"}})``."""
        with self._lock:
            checks = list(self._checks.items())
            results = {name: {"ok": ok, "detail": detail}
                       for name, (ok, detail) in self._conditions.items()}
        for name, fn in checks:
            try:
                ok, detail = fn()
            except Exception as e:  # a broken probe is a failed probe
                ok, detail = False, f"check raised: {e!r}"
            results[name] = {"ok": bool(ok), "detail": detail}
            if self.registry is not None:
                self.registry.set_gauge("health_check_ok",
                                        1.0 if ok else 0.0, check=name)
            self._note_transition(name, bool(ok), detail)
        ready = all(r["ok"] for r in results.values())
        if self.registry is not None:
            self.registry.set_gauge("health_ready", 1.0 if ready else 0.0)
        return ready, results


class WorkerWatch:
    """Per-worker stall tracker handed out by ``Watchdog.register``."""

    def __init__(self, name: str, stall_after_s: float,
                 thread: Optional[threading.Thread] = None):
        self.name = name
        self.stall_after_s = stall_after_s
        self.thread = thread
        self._busy_since: Optional[float] = None
        self._lock = threading.Lock()

    def set_thread(self, thread: Optional[threading.Thread]) -> None:
        self.thread = thread

    @contextmanager
    def busy(self):
        """Wrap one unit of worker work; open too long = stalled."""
        with self._lock:
            self._busy_since = time.monotonic()
        try:
            yield
        finally:
            with self._lock:
                self._busy_since = None

    def beat(self) -> None:
        """Re-stamp a long busy section that is legitimately making
        progress (a snapshot ship, a big replay)."""
        with self._lock:
            if self._busy_since is not None:
                self._busy_since = time.monotonic()

    def stalled(self) -> Tuple[bool, str]:
        """``(stalled, detail)`` — dead thread or over-age busy item."""
        t = self.thread
        if t is not None and not t.is_alive():
            return True, f"{self.name}: worker thread not alive"
        with self._lock:
            since = self._busy_since
        if since is not None:
            age = time.monotonic() - since
            if age > self.stall_after_s:
                return True, (f"{self.name}: item in flight "
                              f"{age:.1f}s > {self.stall_after_s:.1f}s")
        return False, f"{self.name}: ok"


class Watchdog:
    """Stall detection over a set of daemon workers, consumed as one
    HealthState pull check (``health.add_check("workers",
    watchdog.check)``)."""

    def __init__(self, stall_after_s: float = 10.0, registry=None):
        self.stall_after_s = stall_after_s
        self.registry = registry
        self._lock = threading.Lock()
        self._watches: Dict[str, WorkerWatch] = {}
        self._was_stalled: Dict[str, bool] = {}  # transition edges

    def register(self, name: str,
                 thread: Optional[threading.Thread] = None,
                 stall_after_s: Optional[float] = None) -> WorkerWatch:
        w = WorkerWatch(name, stall_after_s if stall_after_s is not None
                        else self.stall_after_s, thread)
        with self._lock:
            self._watches[name] = w
        return w

    def check(self) -> Tuple[bool, str]:
        """``(ok, detail)``: ok iff no registered worker is stalled."""
        with self._lock:
            watches = list(self._watches.values())
        bad = []
        for w in watches:
            stalled, detail = w.stalled()
            if self.registry is not None:
                self.registry.set_gauge("worker_stalled",
                                        1.0 if stalled else 0.0,
                                        worker=w.name)
            with self._lock:
                was = self._was_stalled.get(w.name, False)
                self._was_stalled[w.name] = stalled
            if stalled and not was:
                # the stall appears ON the timeline, inline with the
                # spans it starved, then the ring around it is spooled
                obs_instant("chaos.stall", worker=w.name, detail=detail)
                flight_dump("watchdog_stall", worker=w.name, detail=detail)
            if stalled:
                bad.append(detail)
        if bad:
            return False, "; ".join(bad)
        return True, f"{len(watches)} worker(s) healthy"


def delta_log_check(log) -> Check:
    """Ready iff the delta log's last append landed (``DeltaLog.healthy``
    flips False on a write error and True again when an append
    succeeds — the disk healed)."""

    def _check():
        if log.healthy:
            return True, "delta log writable"
        return False, (f"delta log degraded "
                       f"({log.write_errors} write error(s))")

    return _check


def follower_staleness_check(follower, bound_s: float) -> Check:
    """Ready iff the log follower applied the tail within ``bound_s``.
    Never-succeeded counts as stale: a replica is not ready before its
    first complete catch-up."""

    def _check():
        last = follower.last_success_at
        if last is None:
            return False, "catch-up has not completed yet"
        age = time.monotonic() - last
        if age > bound_s:
            return False, (f"catch-up stale: {age:.1f}s > {bound_s:.1f}s "
                           f"({follower.errors_total} error(s))")
        return True, f"catch-up fresh ({age:.2f}s ago)"

    return _check
