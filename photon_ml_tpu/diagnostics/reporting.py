"""Logical→physical diagnostic report tree with HTML and text renderers.

Reference: photon-diagnostics diagnostics/reporting/** (~45 files) — a
logical document tree (Document/Chapter/Section containing Text/Plot items)
rendered by pluggable strategies (xhtml renderer with JFreeChart plots, and a
ToString renderer).  Here: the same tree with two renderers — plain text and
self-contained HTML whose plots are inline SVG polylines (no image deps).
"""

from __future__ import annotations

import dataclasses
import html
from typing import Dict, List, Sequence, Union


@dataclasses.dataclass
class Text:
    body: str


@dataclasses.dataclass
class Table:
    headers: List[str]
    rows: List[List[str]]


@dataclasses.dataclass
class Plot:
    """A line plot: shared x values, named y series."""

    title: str
    x: Sequence[float]
    series: Dict[str, Sequence[float]]
    x_label: str = ""
    y_label: str = ""


@dataclasses.dataclass
class Bars:
    """A horizontal bar chart: (label, value) pairs, e.g. feature importance
    rankings (the reference renders these as JFreeChart bar plots —
    reporting/PlotToHTMLRenderer; here inline SVG rects)."""

    title: str
    labels: List[str]
    values: Sequence[float]
    x_label: str = ""


@dataclasses.dataclass
class Scatter:
    """A scatter plot, e.g. prediction-vs-residual clouds."""

    title: str
    x: Sequence[float]
    y: Sequence[float]
    x_label: str = ""
    y_label: str = ""


@dataclasses.dataclass
class Bullets:
    """A bulleted list (reference reporting/BulletedListPhysicalReport)."""

    items: List[str]


@dataclasses.dataclass
class NumberedList:
    """A numbered list (reference reporting/NumberedListPhysicalReport)."""

    items: List[str]


@dataclasses.dataclass
class Reference:
    """A cross-reference to a labeled chapter/section (reference
    reporting/ReferencePhysicalReport): renders as an anchor link in HTML
    and as "see §x.y (title)" in text.  ``label`` names the target
    (Chapter/Section label=); unresolved labels render as plain text so a
    dangling reference degrades loudly-but-safely."""

    label: str
    text: str = ""


Item = Union[Text, Table, Plot, Bars, Scatter, Bullets, NumberedList,
             Reference]


@dataclasses.dataclass
class Section:
    """A section that may NEST (reference SectionPhysicalReport holds
    arbitrary child physical reports, including sections): numbering walks
    the tree depth-first — chapter.section.subsection → x.y.z — exactly the
    reference's NumberingContext."""

    title: str
    items: List[Item] = dataclasses.field(default_factory=list)
    subsections: List["Section"] = dataclasses.field(default_factory=list)
    label: str = ""

    def add(self, item: Item) -> "Section":
        self.items.append(item)
        return self

    def subsection(self, title: str, label: str = "") -> "Section":
        s = Section(title, label=label)
        self.subsections.append(s)
        return s


@dataclasses.dataclass
class Chapter:
    title: str
    sections: List[Section] = dataclasses.field(default_factory=list)
    label: str = ""

    def section(self, title: str, label: str = "") -> Section:
        s = Section(title, label=label)
        self.sections.append(s)
        return s


@dataclasses.dataclass
class Document:
    title: str
    chapters: List[Chapter] = dataclasses.field(default_factory=list)

    def chapter(self, title: str, label: str = "") -> Chapter:
        c = Chapter(title, label=label)
        self.chapters.append(c)
        return c


def _walk_sections(sections, prefix):
    """Depth-first (numbers, section) pairs; numbers like (1, 2, 3)."""
    for i, s in enumerate(sections, 1):
        nums = prefix + (i,)
        yield nums, s
        yield from _walk_sections(s.subsections, nums)


def _number_map(doc: Document) -> Dict[str, tuple]:
    """label -> ((numbers...), title) for every labeled chapter/section —
    the resolution pass References need (reference NumberingContext)."""
    out: Dict[str, tuple] = {}
    for ci, chapter in enumerate(doc.chapters, 1):
        if chapter.label:
            out[chapter.label] = ((ci,), chapter.title)
        for nums, s in _walk_sections(chapter.sections, (ci,)):
            if s.label:
                out[s.label] = (nums, s.title)
    return out


def _anchor(nums: tuple) -> str:
    return "s" + "-".join(str(n) for n in nums)


def _dotted(nums: tuple) -> str:
    return ".".join(str(n) for n in nums)


# -- renderers -----------------------------------------------------------------

_SVG_W, _SVG_H, _PAD = 480, 240, 36
_COLORS = ("#1f77b4", "#d62728", "#2ca02c", "#9467bd", "#ff7f0e")


def _svg_plot(plot: Plot) -> str:
    xs = [float(v) for v in plot.x]
    all_ys = [float(v) for ys in plot.series.values() for v in ys]
    if not xs or not all_ys:
        return "<svg/>"
    x0, x1 = min(xs), max(xs)
    y0, y1 = min(all_ys), max(all_ys)
    xr = (x1 - x0) or 1.0
    yr = (y1 - y0) or 1.0

    def sx(v): return _PAD + (v - x0) / xr * (_SVG_W - 2 * _PAD)
    def sy(v): return _SVG_H - _PAD - (v - y0) / yr * (_SVG_H - 2 * _PAD)

    parts = [f'<svg xmlns="http://www.w3.org/2000/svg" width="{_SVG_W}" height="{_SVG_H}">',
             f'<text x="{_SVG_W//2}" y="16" text-anchor="middle" font-size="13">'
             f"{html.escape(plot.title)}</text>",
             f'<rect x="{_PAD}" y="{_PAD}" width="{_SVG_W-2*_PAD}" height="{_SVG_H-2*_PAD}" '
             'fill="none" stroke="#999"/>']
    for i, (name, ys) in enumerate(plot.series.items()):
        pts = " ".join(f"{sx(x):.1f},{sy(float(y)):.1f}" for x, y in zip(xs, ys))
        color = _COLORS[i % len(_COLORS)]
        parts.append(f'<polyline points="{pts}" fill="none" stroke="{color}" stroke-width="1.5"/>')
        parts.append(f'<text x="{_SVG_W-_PAD+4}" y="{_PAD+14*i+10}" font-size="11" '
                     f'fill="{color}">{html.escape(name)}</text>')
    parts.append(f'<text x="{_PAD}" y="{_SVG_H-8}" font-size="10">'
                 f"[{x0:.3g}, {x1:.3g}] {html.escape(plot.x_label)}</text>")
    parts.append("</svg>")
    return "".join(parts)


def _svg_bars(item: Bars) -> str:
    vals = [float(v) for v in item.values]
    if not vals:
        return "<svg/>"
    n = len(vals)
    row_h, label_w = 18, 180
    w = 520
    h = 40 + n * row_h
    vmax = max(abs(v) for v in vals) or 1.0
    bar_w = w - label_w - 60
    parts = [f'<svg xmlns="http://www.w3.org/2000/svg" width="{w}" height="{h}">',
             f'<text x="{w//2}" y="16" text-anchor="middle" font-size="13">'
             f"{html.escape(item.title)}</text>"]
    for i, (label, v) in enumerate(zip(item.labels, vals)):
        y = 28 + i * row_h
        bw = abs(v) / vmax * bar_w
        color = _COLORS[0] if v >= 0 else _COLORS[1]
        parts.append(f'<text x="{label_w-6}" y="{y+12}" text-anchor="end" '
                     f'font-size="10">{html.escape(str(label)[:28])}</text>')
        parts.append(f'<rect x="{label_w}" y="{y+2}" width="{bw:.1f}" '
                     f'height="{row_h-6}" fill="{color}"/>')
        parts.append(f'<text x="{label_w+bw+4:.1f}" y="{y+12}" font-size="10">'
                     f"{v:.4g}</text>")
    parts.append("</svg>")
    return "".join(parts)


def _svg_scatter(item: Scatter) -> str:
    xs = [float(v) for v in item.x]
    ys = [float(v) for v in item.y]
    if not xs or not ys:
        return "<svg/>"
    x0, x1 = min(xs), max(xs)
    y0, y1 = min(ys), max(ys)
    xr = (x1 - x0) or 1.0
    yr = (y1 - y0) or 1.0

    def sx(v): return _PAD + (v - x0) / xr * (_SVG_W - 2 * _PAD)
    def sy(v): return _SVG_H - _PAD - (v - y0) / yr * (_SVG_H - 2 * _PAD)

    parts = [f'<svg xmlns="http://www.w3.org/2000/svg" width="{_SVG_W}" height="{_SVG_H}">',
             f'<text x="{_SVG_W//2}" y="16" text-anchor="middle" font-size="13">'
             f"{html.escape(item.title)}</text>",
             f'<rect x="{_PAD}" y="{_PAD}" width="{_SVG_W-2*_PAD}" '
             f'height="{_SVG_H-2*_PAD}" fill="none" stroke="#999"/>']
    parts.extend(f'<circle cx="{sx(x):.1f}" cy="{sy(y):.1f}" r="1.6" '
                 f'fill="{_COLORS[0]}" fill-opacity="0.5"/>'
                 for x, y in zip(xs, ys))
    parts.append(f'<text x="{_PAD}" y="{_SVG_H-8}" font-size="10">'
                 f"[{x0:.3g}, {x1:.3g}] {html.escape(item.x_label)}"
                 f" vs [{y0:.3g}, {y1:.3g}] {html.escape(item.y_label)}</text>")
    parts.append("</svg>")
    return "".join(parts)


def _html_item(item: Item, labels: Dict[str, tuple] = {}) -> str:
    if isinstance(item, Reference):
        tgt = labels.get(item.label)
        if tgt is None:
            return (f"<p>[unresolved reference {html.escape(item.label)!s}"
                    f"{': ' + html.escape(item.text) if item.text else ''}]</p>")
        nums, title = tgt
        disp = item.text or f"§{_dotted(nums)} {title}"
        return (f'<p><a href="#{_anchor(nums)}">'
                f"{html.escape(disp)}</a></p>")
    if isinstance(item, NumberedList):
        lis = "".join(f"<li>{html.escape(b)}</li>" for b in item.items)
        return f"<ol>{lis}</ol>"
    if isinstance(item, Text):
        return f"<p>{html.escape(item.body)}</p>"
    if isinstance(item, Table):
        head = "".join(f"<th>{html.escape(h)}</th>" for h in item.headers)
        rows = "".join("<tr>" + "".join(f"<td>{html.escape(str(c))}</td>" for c in r) + "</tr>"
                       for r in item.rows)
        return f"<table border='1' cellspacing='0' cellpadding='3'><tr>{head}</tr>{rows}</table>"
    if isinstance(item, Plot):
        return _svg_plot(item)
    if isinstance(item, Bars):
        return _svg_bars(item)
    if isinstance(item, Scatter):
        return _svg_scatter(item)
    if isinstance(item, Bullets):
        lis = "".join(f"<li>{html.escape(b)}</li>" for b in item.items)
        return f"<ul>{lis}</ul>"
    raise TypeError(f"unknown report item {type(item)!r}")


def render_html(doc: Document) -> str:
    """Self-contained HTML: an index (table of contents with anchor links —
    the reference's DocumentToHTMLRenderer navigation) followed by
    recursively numbered chapters/sections/subsections."""
    labels = _number_map(doc)
    out = [f"<!DOCTYPE html><html><head><meta charset='utf-8'>"
           f"<title>{html.escape(doc.title)}</title></head><body>"
           f"<h1>{html.escape(doc.title)}</h1>"]

    def toc_sections(sections, prefix):
        if not sections:
            return
        out.append("<ul>")
        for i, s in enumerate(sections, 1):
            nums = prefix + (i,)
            out.append(f'<li><a href="#{_anchor(nums)}">{_dotted(nums)}. '
                       f"{html.escape(s.title)}</a>")
            toc_sections(s.subsections, nums)
            out.append("</li>")
        out.append("</ul>")

    out.append("<h2>Index</h2><ul>")
    for ci, chapter in enumerate(doc.chapters, 1):
        out.append(f'<li><a href="#{_anchor((ci,))}">{ci}. '
                   f"{html.escape(chapter.title)}</a>")
        toc_sections(chapter.sections, (ci,))
        out.append("</li>")
    out.append("</ul>")

    def body_sections(sections, prefix):
        for i, s in enumerate(sections, 1):
            nums = prefix + (i,)
            level = min(1 + len(nums), 6)  # h3 for x.y, h4 for x.y.z, ...
            out.append(f'<h{level} id="{_anchor(nums)}">{_dotted(nums)}. '
                       f"{html.escape(s.title)}</h{level}>")
            out.extend(_html_item(item, labels) for item in s.items)
            body_sections(s.subsections, nums)

    for ci, chapter in enumerate(doc.chapters, 1):
        out.append(f'<h2 id="{_anchor((ci,))}">{ci}. '
                   f"{html.escape(chapter.title)}</h2>")
        body_sections(chapter.sections, (ci,))
    out.append("</body></html>")
    return "".join(out)


def _text_item(item: Item, labels: Dict[str, tuple] = {}) -> str:
    if isinstance(item, Reference):
        tgt = labels.get(item.label)
        if tgt is None:
            return f"[unresolved reference {item.label}]"
        nums, title = tgt
        disp = f" ({item.text})" if item.text else ""
        return f"see §{_dotted(nums)} {title}{disp}"
    if isinstance(item, NumberedList):
        return "\n".join(f"  {i}. {b}" for i, b in enumerate(item.items, 1))
    if isinstance(item, Text):
        return item.body
    if isinstance(item, Table):
        lines = ["\t".join(item.headers)]
        lines += ["\t".join(str(c) for c in r) for r in item.rows]
        return "\n".join(lines)
    if isinstance(item, Plot):
        lines = [f"[plot] {item.title}"]
        for name, ys in item.series.items():
            lines.append(f"  {name}: " + ", ".join(f"{float(y):.4g}" for y in ys))
        return "\n".join(lines)
    if isinstance(item, Bars):
        lines = [f"[bars] {item.title}"]
        lines += [f"  {l}: {float(v):.4g}"
                  for l, v in zip(item.labels, item.values)]
        return "\n".join(lines)
    if isinstance(item, Scatter):
        return (f"[scatter] {item.title}: {len(list(item.x))} points "
                f"({item.x_label} vs {item.y_label})")
    if isinstance(item, Bullets):
        return "\n".join(f"  * {b}" for b in item.items)
    raise TypeError(f"unknown report item {type(item)!r}")


def render_text(doc: Document) -> str:
    """The reference's ToString render strategy: same recursively numbered
    tree, plain text."""
    labels = _number_map(doc)
    out = [doc.title, "=" * len(doc.title)]
    for ci, chapter in enumerate(doc.chapters, 1):
        out.append(f"\n{ci}. {chapter.title}")
        for nums, section in _walk_sections(chapter.sections, (ci,)):
            out.append(f"\n{_dotted(nums)}. {section.title}")
            out.extend(_text_item(item, labels) for item in section.items)
    return "\n".join(out)
