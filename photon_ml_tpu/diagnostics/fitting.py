"""Learning-curve (fitting) diagnostic.

Reference: photon-diagnostics diagnostics/fitting/FittingDiagnostic.scala:33-131
— train on growing fractions of the training set (default 10%..100%), compute
each metric on the training portion and on a holdout, and report the two
curves; diverging train/holdout curves indicate over/under-fitting.

TPU-first: a "fraction" is a weight mask over the full static-shape batch (the
first ⌈f·n⌉ examples keep their weight, the rest get 0) so every fraction
reuses one compiled solve — no reshaping, no recompilation.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Sequence

import numpy as np

from photon_ml_tpu.core.batch import Batch
from photon_ml_tpu.models.glm import GLMModel

TrainFn = Callable[[Batch], GLMModel]
# metric_fn(model, batch) -> float, evaluated on train portion and holdout
MetricFn = Callable[[GLMModel, Batch], float]


@dataclasses.dataclass(frozen=True)
class FittingReport:
    """metric -> (fractions, train curve, holdout curve)."""

    fractions: np.ndarray  # [f]
    train_metrics: Dict[str, np.ndarray]  # name -> [f]
    holdout_metrics: Dict[str, np.ndarray]  # name -> [f]


def fitting_diagnostic(
    train_fn: TrainFn,
    metrics: Dict[str, MetricFn],
    train_batch: Batch,
    holdout_batch: Batch,
    fractions: Sequence[float] = (0.1, 0.25, 0.5, 0.75, 1.0),
    seed: int = 0,
) -> FittingReport:
    """Train at each fraction; report train-vs-holdout metric curves.

    Examples are shuffled once (seeded) then prefix-masked, so smaller
    fractions are nested subsets of larger ones, as in the reference's
    ``downSample`` chain.
    """
    weight = np.asarray(train_batch.weight)
    alive = np.flatnonzero(weight > 0)
    order = np.random.default_rng(seed).permutation(alive)

    train_curves: Dict[str, List[float]] = {k: [] for k in metrics}
    holdout_curves: Dict[str, List[float]] = {k: [] for k in metrics}
    for f in fractions:
        take = order[: max(1, int(round(f * len(order))))]
        w = np.zeros_like(weight)
        w[take] = weight[take]
        sub = train_batch.replace(weight=w)
        model = train_fn(sub)
        for name, fn in metrics.items():
            train_curves[name].append(float(fn(model, sub)))
            holdout_curves[name].append(float(fn(model, holdout_batch)))

    return FittingReport(
        fractions=np.asarray(list(fractions)),
        train_metrics={k: np.asarray(v) for k, v in train_curves.items()},
        holdout_metrics={k: np.asarray(v) for k, v in holdout_curves.items()},
    )
