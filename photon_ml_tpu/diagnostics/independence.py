"""Prediction-error independence analysis (Kendall tau).

Reference: photon-diagnostics diagnostics/independence/KendallTauAnalysis.scala:131
— rank correlation between predictions and prediction errors; |tau| far from 0
signals structure left in the residuals (model misspecification).

Implementation: scipy's O(n log n) Knight algorithm (the reference computes
concordant/discordant pairs over an RDD cartesian sample).
"""

from __future__ import annotations

import dataclasses

import numpy as np
from scipy.stats import kendalltau


@dataclasses.dataclass(frozen=True)
class KendallTauReport:
    tau: float
    p_value: float
    num_samples: int

    def summary(self) -> str:
        return f"kendall tau={self.tau:.4f} p={self.p_value:.4g} n={self.num_samples}"


def kendall_tau_analysis(
    predictions: np.ndarray,
    labels: np.ndarray,
    max_samples: int = 100_000,
    seed: int = 0,
) -> KendallTauReport:
    """Tau between predictions and errors (label - prediction).

    Subsamples above ``max_samples`` (the reference samples pairs for the
    same reason: the pair count is quadratic).
    """
    pred = np.asarray(predictions, np.float64)
    err = np.asarray(labels, np.float64) - pred
    if len(pred) > max_samples:
        idx = np.random.default_rng(seed).choice(len(pred), max_samples, replace=False)
        pred, err = pred[idx], err[idx]
    tau, p = kendalltau(pred, err)
    return KendallTauReport(tau=float(tau), p_value=float(p), num_samples=len(pred))
