"""Model diagnostics.

Reference: photon-diagnostics (SURVEY.md §2.4) — bootstrap confidence
intervals (BootstrapTraining.scala:29-181), learning-curve fitting diagnostic
(diagnostics/fitting/FittingDiagnostic.scala:33-131), Hosmer-Lemeshow
calibration (diagnostics/hl/), feature importance
(diagnostics/featureimportance/), Kendall-tau independence analysis
(diagnostics/independence/KendallTauAnalysis.scala:131), and the
logical→physical report tree with HTML rendering (diagnostics/reporting/**).
"""

from photon_ml_tpu.diagnostics.bootstrap import BootstrapReport, bootstrap_training  # noqa: F401
from photon_ml_tpu.diagnostics.fitting import FittingReport, fitting_diagnostic  # noqa: F401
from photon_ml_tpu.diagnostics.hosmer_lemeshow import HosmerLemeshowReport, hosmer_lemeshow  # noqa: F401
from photon_ml_tpu.diagnostics.feature_importance import (  # noqa: F401
    FeatureImportanceReport, expected_magnitude_importance, variance_importance)
from photon_ml_tpu.diagnostics.independence import KendallTauReport, kendall_tau_analysis  # noqa: F401
from photon_ml_tpu.diagnostics.reporting import (  # noqa: F401
    Chapter, Document, Section, render_html, render_text)
