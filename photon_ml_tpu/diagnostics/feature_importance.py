"""Per-feature importance reports.

Reference: photon-diagnostics diagnostics/featureimportance/*.scala —
ExpectedMagnitudeFeatureImportanceDiagnostic (|w_j|·E[|x_j|]: how much a
feature moves the margin in expectation) and
VarianceFeatureImportanceDiagnostic (w_j²·Var[x_j]: margin-variance
contribution), each reporting the top-k ranked features.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import numpy as np


@dataclasses.dataclass(frozen=True)
class FeatureImportanceReport:
    kind: str
    importance: np.ndarray  # [d]
    ranked: List[Tuple[str, float]]  # top-k (name, importance) desc

    def summary(self) -> str:
        return "\n".join(f"{n}\t{v:.6g}" for n, v in self.ranked)


def _rank(importance: np.ndarray, feature_names: Optional[Sequence[str]],
          top_k: int) -> List[Tuple[str, float]]:
    order = np.argsort(-importance)[:top_k]
    names = feature_names if feature_names is not None else [str(i) for i in range(len(importance))]
    return [(str(names[i]), float(importance[i])) for i in order]


def expected_magnitude_importance(
    coefficients: np.ndarray,
    mean_abs_features: np.ndarray,
    feature_names: Optional[Sequence[str]] = None,
    top_k: int = 20,
) -> FeatureImportanceReport:
    """|w_j| · E[|x_j|] (reference ExpectedMagnitudeFeatureImportance)."""
    imp = np.abs(np.asarray(coefficients, np.float64)) * np.asarray(mean_abs_features, np.float64)
    return FeatureImportanceReport("expected_magnitude", imp,
                                   _rank(imp, feature_names, top_k))


def variance_importance(
    coefficients: np.ndarray,
    feature_variances: np.ndarray,
    feature_names: Optional[Sequence[str]] = None,
    top_k: int = 20,
) -> FeatureImportanceReport:
    """w_j² · Var[x_j] (reference VarianceFeatureImportance)."""
    w = np.asarray(coefficients, np.float64)
    imp = w * w * np.asarray(feature_variances, np.float64)
    return FeatureImportanceReport("variance", imp, _rank(imp, feature_names, top_k))
