"""Hosmer-Lemeshow calibration diagnostic for logistic models.

Reference: photon-diagnostics diagnostics/hl/HosmerLemeshowDiagnostic.scala:98
+ binners — bin predicted probabilities (default deciles), compare observed
positive counts against expected within each bin, form the χ² statistic
Σ_bins [(O₁-E₁)²/E₁ + (O₀-E₀)²/E₀], and report the p-value against
χ²(bins-2) plus the per-bin table.

TPU-first: binning is one histogram pass (``np.digitize`` host-side or
segment sums on device); no sort needed for equal-width bins; equal-mass
(decile) bins use a quantile split of the scores.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np
from scipy.stats import chi2


@dataclasses.dataclass(frozen=True)
class HosmerLemeshowReport:
    bin_edges: np.ndarray  # [b+1]
    observed_pos: np.ndarray  # [b] weighted positive counts
    expected_pos: np.ndarray  # [b] sum of predicted probabilities
    totals: np.ndarray  # [b] weighted example counts
    chi_square: float
    degrees_of_freedom: int
    p_value: float

    def summary(self) -> str:
        lines = ["bin    total    obs+    exp+"]
        for i in range(len(self.totals)):
            lines.append(f"[{self.bin_edges[i]:.3f},{self.bin_edges[i+1]:.3f})"
                         f"  {self.totals[i]:.1f}  {self.observed_pos[i]:.1f}"
                         f"  {self.expected_pos[i]:.1f}")
        lines.append(f"chi2={self.chi_square:.4f} df={self.degrees_of_freedom} "
                     f"p={self.p_value:.4g}")
        return "\n".join(lines)


def hosmer_lemeshow(
    probabilities: np.ndarray,
    labels: np.ndarray,
    weights: Optional[np.ndarray] = None,
    num_bins: int = 10,
    equal_mass: bool = True,
) -> HosmerLemeshowReport:
    """HL χ² over probability bins (reference HosmerLemeshowDiagnostic).

    ``equal_mass=True`` splits at score quantiles (the reference's default
    decile binning); ``False`` uses equal-width bins on [0, 1].
    """
    p = np.asarray(probabilities, np.float64)
    y = np.asarray(labels, np.float64)
    w = np.ones_like(p) if weights is None else np.asarray(weights, np.float64)
    keep = w > 0
    p, y, w = p[keep], y[keep], w[keep]

    if equal_mass:
        qs = np.quantile(p, np.linspace(0.0, 1.0, num_bins + 1))
        # collapse duplicate edges (heavy ties) to keep bins well-defined
        edges = np.unique(qs)
    else:
        edges = np.linspace(0.0, 1.0, num_bins + 1)
    edges = edges.copy()
    edges[0], edges[-1] = -np.inf, np.inf
    idx = np.digitize(p, edges[1:-1])

    b = len(edges) - 1
    totals = np.bincount(idx, weights=w, minlength=b)
    obs_pos = np.bincount(idx, weights=w * y, minlength=b)
    exp_pos = np.bincount(idx, weights=w * p, minlength=b)

    if b < 3:
        raise ValueError(
            f"Hosmer-Lemeshow needs >= 3 distinct probability bins, got {b} "
            "(scores are (near-)constant; the test is undefined, df = bins-2 <= 0)")

    def _chi_terms(obs, exp):
        # exp == 0 with obs > 0 is infinite evidence of miscalibration;
        # exp == obs == 0 (empty bin) contributes nothing.
        return np.where(exp > 0, (obs - exp) ** 2 / np.where(exp > 0, exp, 1.0),
                        np.where(obs > 0, np.inf, 0.0))

    exp_neg = totals - exp_pos
    obs_neg = totals - obs_pos
    chi = float(np.sum(_chi_terms(obs_pos, exp_pos) + _chi_terms(obs_neg, exp_neg)))
    df = b - 2
    finite_edges = edges.copy()
    finite_edges[0], finite_edges[-1] = 0.0, 1.0
    return HosmerLemeshowReport(
        bin_edges=finite_edges,
        observed_pos=obs_pos,
        expected_pos=exp_pos,
        totals=totals,
        chi_square=chi,
        degrees_of_freedom=df,
        p_value=float(chi2.sf(chi, df)),
    )
