"""Bootstrap training: coefficient confidence intervals + metric distributions.

Reference: photon-diagnostics BootstrapTraining.scala:29-181 — train k models
on bootstrap resamples (RDD.sample with replacement), then run aggregation
functions over the fitted models: per-coefficient confidence intervals and
metric distributions.

TPU-first redesign: resampling-with-replacement is equivalent to multiplying
example weights by multinomial counts Multinomial(n, 1/n).  That keeps every
replicate the SAME static shape, so one jitted solve is compiled once and
reused k times (or vmapped) — no data movement at all, only a fresh weight
vector per replicate.  The reference pays a full RDD resample + shuffle per
replicate instead.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from photon_ml_tpu.core.batch import Batch
from photon_ml_tpu.models.glm import Coefficients, GLMModel

TrainFn = Callable[[Batch], GLMModel]
MetricFn = Callable[[GLMModel], float]


@dataclasses.dataclass(frozen=True)
class BootstrapReport:
    """Aggregated bootstrap results (reference aggregation-function outputs)."""

    num_replicates: int
    # [d, 2] lower/upper per-coefficient percentile interval
    coefficient_intervals: np.ndarray
    coefficient_means: np.ndarray  # [d] bagged means
    metric_distributions: Dict[str, np.ndarray]  # name -> [k] per-replicate values
    models: Optional[List[GLMModel]] = None

    def metric_summary(self) -> Dict[str, Tuple[float, float]]:
        return {k: (float(np.mean(v)), float(np.std(v)))
                for k, v in self.metric_distributions.items()}


def bootstrap_weights(rng: np.random.Generator, weight: np.ndarray) -> np.ndarray:
    """Multinomial resample-with-replacement counts as weight multipliers.

    Rows with weight 0 (padding) are excluded from the draw and stay 0.
    """
    alive = weight > 0
    n = int(alive.sum())
    counts = np.zeros(weight.shape, np.float64)
    if n:
        draw = rng.multinomial(n, np.full(n, 1.0 / n))
        counts[alive] = draw
    return (weight * counts).astype(weight.dtype)


def bootstrap_training(
    train_fn: TrainFn,
    batch: Batch,
    num_replicates: int = 16,
    metrics: Optional[Dict[str, MetricFn]] = None,
    percentile: float = 95.0,
    seed: int = 0,
    keep_models: bool = False,
) -> BootstrapReport:
    """Train ``num_replicates`` models on bootstrap-reweighted batches.

    ``train_fn(batch) -> GLMModel`` should be a closure over a jitted solver;
    since every replicate has identical shapes it compiles exactly once.
    (Reference BootstrapTraining.bootstrap:132 with aggregations =
    {confidence intervals, metric distributions}.)
    """
    rng = np.random.default_rng(seed)
    base_weight = np.asarray(batch.weight)
    coefs: List[np.ndarray] = []
    models: List[GLMModel] = []
    metric_values: Dict[str, List[float]] = {k: [] for k in (metrics or {})}

    for _ in range(num_replicates):
        w = bootstrap_weights(rng, base_weight)
        model = train_fn(batch.replace(weight=w))
        coefs.append(np.asarray(model.coefficients.means))
        for name, fn in (metrics or {}).items():
            metric_values[name].append(float(fn(model)))
        if keep_models:
            models.append(model)

    stacked = np.stack(coefs)  # [k, d]
    half = (100.0 - percentile) / 2.0
    intervals = np.stack([np.percentile(stacked, half, axis=0),
                          np.percentile(stacked, 100.0 - half, axis=0)], axis=-1)
    return BootstrapReport(
        num_replicates=num_replicates,
        coefficient_intervals=intervals,
        coefficient_means=stacked.mean(axis=0),
        metric_distributions={k: np.asarray(v) for k, v in metric_values.items()},
        models=models if keep_models else None,
    )


def bagged_model(report: BootstrapReport, task) -> GLMModel:
    """Bagging aggregate: mean coefficients across replicates."""
    return GLMModel(coefficients=Coefficients(means=report.coefficient_means), task=task)
