"""Evaluator objects, suites, and grouped (per-id-tag) evaluation.

Reference: photon-lib .../evaluation/Evaluator.scala:69 (betterThan + evaluate),
EvaluatorType.scala (AUC, AUPR, RMSE, LogisticLoss, PoissonLoss, SquaredLoss,
SmoothedHingeLoss, PrecisionAtK), MultiEvaluator.scala:36-70 (group by id tag,
evaluate each group with a LocalEvaluator, average the per-group metrics),
EvaluationSuite.scala:33-115 (evaluator set + distinguished primary).

Grouped evaluation on TPU: groups are padded to a common size and the metric
is ``vmap``-ed over the group lane (weight-0 padding rows are inert in every
metric) — the reference's shuffle-and-iterate becomes one batched kernel.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.evaluation import metrics as M

Array = jax.Array
MetricFn = Callable[[Array, Array, Array], Array]


class EvaluatorType(enum.Enum):
    AUC = "auc"
    AUPR = "aupr"
    RMSE = "rmse"
    LOGISTIC_LOSS = "logistic_loss"
    POISSON_LOSS = "poisson_loss"
    SQUARED_LOSS = "squared_loss"
    SMOOTHED_HINGE_LOSS = "smoothed_hinge_loss"
    PRECISION_AT_K = "precision_at_k"


_LARGER_IS_BETTER = {
    EvaluatorType.AUC, EvaluatorType.AUPR, EvaluatorType.PRECISION_AT_K,
}

_METRIC_FNS: Dict[EvaluatorType, MetricFn] = {
    EvaluatorType.AUC: M.auc_roc,
    EvaluatorType.AUPR: M.auc_pr,
    EvaluatorType.RMSE: M.rmse,
    EvaluatorType.LOGISTIC_LOSS: M.logistic_loss_metric,
    EvaluatorType.POISSON_LOSS: M.poisson_loss_metric,
    EvaluatorType.SQUARED_LOSS: M.squared_loss_metric,
    EvaluatorType.SMOOTHED_HINGE_LOSS: M.smoothed_hinge_loss_metric,
}


@dataclasses.dataclass(frozen=True)
class Evaluator:
    """A named metric with an ordering (reference Evaluator.betterThan).

    ``group_ids`` (set at construction for Multi- evaluators): per-sample group
    labels; the metric is computed per group and averaged, reference
    MultiEvaluator semantics.
    """

    kind: EvaluatorType
    k: int = 0  # PRECISION_AT_K only
    group_name: Optional[str] = None  # None = single evaluator

    @property
    def name(self) -> str:
        base = f"{self.kind.value}@{self.k}" if self.kind == EvaluatorType.PRECISION_AT_K else self.kind.value
        return f"{base}:{self.group_name}" if self.group_name else base

    @property
    def larger_is_better(self) -> bool:
        return self.kind in _LARGER_IS_BETTER

    def better_than(self, a: float, b: float) -> bool:
        return a > b if self.larger_is_better else a < b

    def metric_fn(self) -> MetricFn:
        if self.kind == EvaluatorType.PRECISION_AT_K:
            k = self.k
            return lambda s, l, w: M.precision_at_k(k, s, l, w)
        return _METRIC_FNS[self.kind]

    def evaluate(self, scores: Array, labels: Array, weights: Array,
                 group_ids: Optional[np.ndarray] = None) -> float:
        fn = self.metric_fn()
        if self.group_name is None:
            return float(fn(scores, labels, weights))
        if group_ids is None:
            raise ValueError(f"evaluator {self.name} needs group ids '{self.group_name}'")
        return float(grouped_evaluate(fn, group_ids, scores, labels, weights))


def make_evaluator(spec: str) -> Evaluator:
    """Parse an evaluator spec: 'auc', 'rmse', 'precision@5', 'auc:userId'
    (grouped), 'precision@3:songId' (reference MultiEvaluatorType grammar)."""
    group = None
    if ":" in spec:
        spec, group = spec.split(":", 1)
    if spec.startswith("precision@"):
        return Evaluator(EvaluatorType.PRECISION_AT_K, k=int(spec.split("@")[1]), group_name=group)
    return Evaluator(EvaluatorType(spec), group_name=group)


def grouped_evaluate(metric_fn: MetricFn, group_ids: np.ndarray, scores: Array,
                     labels: Array, weights: Array) -> float:
    """Per-group metric, unweighted-averaged over groups with >0 total weight
    (reference MultiEvaluator.evaluate:36-70).

    Pads groups to the max group size and vmaps the metric; padding rows have
    weight 0 and score -inf is NOT needed because every metric is weight-aware.
    """
    group_ids = np.asarray(group_ids)
    uniq, inverse, counts = np.unique(group_ids, return_inverse=True, return_counts=True)
    g, smax = len(uniq), int(counts.max()) if len(counts) else 0
    if g == 0:
        return float("nan")
    order = np.argsort(inverse, kind="stable")
    # slot position of each sample within its group
    pos = np.arange(len(group_ids)) - np.concatenate([[0], np.cumsum(counts)])[inverse[order]]

    def pad(a, fill=0.0):
        out = np.full((g, smax), fill, np.asarray(a).dtype)
        out[inverse[order], pos] = np.asarray(a)[order]
        return jnp.asarray(out)

    ps, pl, pw = pad(np.asarray(scores)), pad(np.asarray(labels)), pad(np.asarray(weights))
    vals = jax.vmap(metric_fn)(ps, pl, pw)
    has_w = jnp.sum(pw, axis=1) > 0
    denom = jnp.maximum(jnp.sum(has_w), 1)
    return float(jnp.sum(jnp.where(has_w, vals, 0.0)) / denom)


@dataclasses.dataclass
class EvaluationResults:
    """Metric name -> value, with the primary distinguished
    (reference EvaluationResults.scala)."""

    values: Dict[str, float]
    primary_name: str

    @property
    def primary(self) -> float:
        return self.values[self.primary_name]


@dataclasses.dataclass
class EvaluationSuite:
    """Evaluator set + primary (reference EvaluationSuite.scala:33-115)."""

    evaluators: List[Evaluator]
    primary: Evaluator

    def __post_init__(self):
        if self.primary not in self.evaluators:
            self.evaluators = [self.primary] + list(self.evaluators)

    @classmethod
    def from_specs(cls, specs: Sequence[str], primary: Optional[str] = None) -> "EvaluationSuite":
        evs = [make_evaluator(s) for s in specs]
        prim = make_evaluator(primary) if primary else evs[0]
        return cls(evaluators=evs, primary=prim)

    def evaluate(self, scores: Array, labels: Array, weights: Array,
                 group_ids: Optional[Dict[str, np.ndarray]] = None) -> EvaluationResults:
        out = {}
        for ev in self.evaluators:
            gids = (group_ids or {}).get(ev.group_name) if ev.group_name else None
            out[ev.name] = ev.evaluate(scores, labels, weights, gids)
        return EvaluationResults(values=out, primary_name=self.primary.name)

    def better_than(self, a: EvaluationResults, b: Optional[EvaluationResults]) -> bool:
        if b is None:
            return True
        return self.primary.better_than(a.primary, b.primary)
