"""Evaluation metrics as pure JAX reductions.

Reference: photon-api .../evaluation/** — AreaUnderROCCurveLocalEvaluator.scala:33-72
(exact sort-based AUC with tie handling), AUPR, RMSE, pointwise-loss metrics,
PrecisionAtKLocalEvaluator.

TPU shape: metrics are weighted, statically-shaped reductions over
(score, label, weight) arrays; invalid/padded rows carry weight 0.  AUC uses a
full sort (jnp.argsort) — exact, like the reference's local evaluator, not a
histogram approximation; ties are handled by trapezoidal integration over
tied-score groups.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

Array = jax.Array


def _wsum(x: Array, w: Array) -> Array:
    return jnp.sum(x * w)


def rmse(scores: Array, labels: Array, weights: Array) -> Array:
    """Weighted RMSE (reference RMSEEvaluator.scala)."""
    tot = jnp.sum(weights)
    se = _wsum((scores - labels) ** 2, weights)
    return jnp.sqrt(se / jnp.where(tot == 0, 1.0, tot))


def squared_loss_metric(scores: Array, labels: Array, weights: Array) -> Array:
    from photon_ml_tpu.core.losses import squared_loss

    return _wsum(squared_loss.loss(scores, labels), weights)


def logistic_loss_metric(scores: Array, labels: Array, weights: Array) -> Array:
    from photon_ml_tpu.core.losses import logistic_loss

    return _wsum(logistic_loss.loss(scores, labels), weights)


def poisson_loss_metric(scores: Array, labels: Array, weights: Array) -> Array:
    from photon_ml_tpu.core.losses import poisson_loss

    return _wsum(poisson_loss.loss(scores, labels), weights)


def smoothed_hinge_loss_metric(scores: Array, labels: Array, weights: Array) -> Array:
    from photon_ml_tpu.core.losses import smoothed_hinge_loss

    return _wsum(smoothed_hinge_loss.loss(scores, labels), weights)


def _rank_stats(scores: Array, labels: Array, weights: Array):
    """Sort by score desc; return cumulative weighted TP/FP plus totals.

    Tie handling: within a tied-score group every point gets the group-end
    cumulative counts (equivalent to the trapezoid over the tie, matching the
    reference's grouped iteration, AreaUnderROCCurveLocalEvaluator.scala:45-70).
    """
    order = jnp.argsort(-scores, stable=True)
    s = scores[order]
    pos_w = (weights * (labels > 0.5))[order]
    neg_w = (weights * (labels <= 0.5))[order]
    ctp = jnp.cumsum(pos_w)
    cfp = jnp.cumsum(neg_w)

    # Tied-score groups: position i ends a group if s[i] != s[i+1].
    n = s.shape[0]
    is_end = jnp.concatenate([s[:-1] != s[1:], jnp.ones((1,), bool)])
    is_start = jnp.concatenate([jnp.ones((1,), bool), s[1:] != s[:-1]])
    seg = jnp.cumsum(is_start) - 1  # segment id per element
    # Per-segment group-end cumulative counts (segment-indexed slots 0..G-1),
    # gathered back per element.
    seg_end_tp = jnp.zeros((n,), ctp.dtype).at[seg].max(jnp.where(is_end, ctp, 0.0))
    seg_end_fp = jnp.zeros((n,), cfp.dtype).at[seg].max(jnp.where(is_end, cfp, 0.0))
    end_ctp = seg_end_tp[seg]
    end_cfp = seg_end_fp[seg]
    prev_ctp = jnp.where(seg > 0, seg_end_tp[jnp.maximum(seg - 1, 0)], 0.0)
    prev_cfp = jnp.where(seg > 0, seg_end_fp[jnp.maximum(seg - 1, 0)], 0.0)
    return seg, is_end, end_ctp, end_cfp, prev_ctp, prev_cfp, ctp[-1], cfp[-1]


@jax.jit
def auc_roc(scores: Array, labels: Array, weights: Array) -> Array:
    """Exact weighted ROC AUC with tie handling (trapezoidal).

    Degenerate inputs (no positives or no negatives) return 0.5, the
    convention downstream model selection relies on.

    jitted at definition: the ~15-op rank pipeline otherwise dispatches
    eagerly per call (~20ms of op-launch overhead on 13k rows — it
    dominated the gp_tune profile); under jit the same call is ~1ms and
    repeated same-shape evaluations (every tuning fit) hit the cache.
    Inside an outer jit the decorator is a no-op (inlined)."""
    seg, is_end, end_tp, end_fp, prev_tp, prev_fp, tot_p, tot_n = _rank_stats(
        scores, labels, weights
    )
    # Per tied group (counted once at its end): trapezoid on the ROC curve
    # between (prev_fp, prev_tp) and (end_fp, end_tp).
    area = jnp.where(is_end, (end_fp - prev_fp) * 0.5 * (end_tp + prev_tp), 0.0)
    auc = jnp.sum(area) / jnp.where((tot_p == 0) | (tot_n == 0), 1.0, tot_p * tot_n)
    return jnp.where((tot_p == 0) | (tot_n == 0), 0.5, auc)


@jax.jit
def auc_pr(scores: Array, labels: Array, weights: Array) -> Array:
    """Weighted area under the precision-recall curve (linear interpolation
    in recall, like the reference's Spark BinaryClassificationMetrics).
    jitted at definition for the same reason as auc_roc."""
    seg, is_end, end_tp, end_fp, prev_tp, prev_fp, tot_p, tot_n = _rank_stats(
        scores, labels, weights
    )
    prec_end = end_tp / jnp.maximum(end_tp + end_fp, 1e-30)
    prec_prev = jnp.where(prev_tp + prev_fp > 0, prev_tp / jnp.maximum(prev_tp + prev_fp, 1e-30), 1.0)
    rec_end = end_tp / jnp.where(tot_p == 0, 1.0, tot_p)
    rec_prev = prev_tp / jnp.where(tot_p == 0, 1.0, tot_p)
    area = jnp.where(is_end, (rec_end - rec_prev) * 0.5 * (prec_end + prec_prev), 0.0)
    return jnp.where(tot_p == 0, 0.0, jnp.sum(area))


def precision_at_k(k: int, scores: Array, labels: Array, weights: Array) -> Array:
    """Unweighted precision among the top-k scores (reference
    PrecisionAtKLocalEvaluator; the reference ignores weights here too).
    Rows with weight 0 (padding) are pushed out of the ranking."""
    masked = jnp.where(weights > 0, scores, -jnp.inf)
    order = jnp.argsort(-masked, stable=True)
    topk = order[:k]
    valid = weights[topk] > 0
    hits = jnp.sum((labels[topk] > 0.5) & valid)
    denom = jnp.maximum(jnp.sum(valid), 1)
    return hits / denom
