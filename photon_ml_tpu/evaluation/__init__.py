from photon_ml_tpu.evaluation.metrics import (  # noqa: F401
    auc_roc,
    auc_pr,
    rmse,
    logistic_loss_metric,
    poisson_loss_metric,
    squared_loss_metric,
    smoothed_hinge_loss_metric,
    precision_at_k,
)
from photon_ml_tpu.evaluation.evaluator import (  # noqa: F401
    Evaluator,
    EvaluatorType,
    EvaluationSuite,
    EvaluationResults,
    make_evaluator,
    grouped_evaluate,
)
