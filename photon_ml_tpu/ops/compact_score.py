"""Pallas TPU kernel for sparse-features x sparse-model compact scoring.

The XLA path (models/game._score_sparse_compact) binary-searches every
sample feature id into its entity's sorted coefficient columns:
``vmap(searchsorted)`` + two ``take_along_axis`` gathers + masks — five
[n, k]-shaped HBM intermediates per call.  This kernel replaces the search
with a match-dot while one sample block is resident in VMEM:

    score[i] = sum_{f, m} (w_idx[i, m] == f_idx[i, f]) * w_val[i, m] * f_val[i, f]

which is exact because coefficient columns are unique per entity (sorted
``np.nonzero`` output), model padding carries value 0 (inert whatever it
matches), and duplicate FEATURE ids accumulate — the same convention the
searchsorted chain and ``SparseBatch.margins`` implement.

Layout: samples-on-lanes.  [n, k] arrays put k on the 128-lane axis (a
k=8 coefficient row wastes 15/16 of every vector register); the kernel
takes [k, n] transposed operands so every compare/multiply uses all 128
lanes and the k_model reduction is a sublane sum.

Gating follows ops/fused_glm.py: TPU-only (``eligible``), interpret=True
for CPU correctness tests, PHOTON_COMPACT_DISABLE_PALLAS=1 escape hatch
(also the bench's pallas on/off A/B knob).  The O(k_model * k_feat)
compare-accumulate only beats the O(k_feat log k_model) search while the
product is small — ``_MAX_MATCH_WORK`` bounds it.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from photon_ml_tpu.ops.fused_glm import has_tpu

Array = jax.Array

_LANE = 128
_MAX_MATCH_WORK = 4096  # k_model * k_feat above this: keep the searchsorted
# chain (the match-dot's elementwise work grows with the product while the
# search grows with k_feat * log2(k_model))


def eligible(k_model: int, k_feat: int, interpret: bool = False) -> bool:
    """True when the pallas match-dot can replace the searchsorted chain.
    Callers (models/game._score_sparse_compact) keep the XLA path otherwise.

    PHOTON_COMPACT_DISABLE_PALLAS=1 forces the XLA path everywhere — the
    bench's pallas-vs-XLA A/B knob (and an escape hatch)."""
    if os.environ.get("PHOTON_COMPACT_DISABLE_PALLAS") == "1":
        return False
    if k_model < 1 or k_feat < 1 or k_model * k_feat > _MAX_MATCH_WORK:
        return False
    if interpret:
        return True
    return has_tpu()


def _match_dot_kernel(k_feat: int, w_idx_ref, w_val_ref, f_idx_ref, f_val_ref,
                      out_ref):
    """One sample block: (k_model, BN) coefficient rows vs (k_feat, BN)
    feature rows.  The k_feat loop unrolls statically; every op is
    elementwise over the 128-lane sample axis, the k_model reduction is a
    sublane sum."""
    w_idx = w_idx_ref[:]                       # (k_model, BN) int32
    w_val = w_val_ref[:]                       # (k_model, BN)
    acc = jnp.zeros_like(out_ref)              # (1, BN)
    zero = jnp.zeros((), w_val.dtype)
    for f in range(k_feat):
        fi = f_idx_ref[f:f + 1, :]             # (1, BN), broadcasts below
        wv = jnp.sum(jnp.where(w_idx == fi, w_val, zero),
                     axis=0, keepdims=True)    # (1, BN)
        acc = acc + f_val_ref[f:f + 1, :] * wv
    out_ref[:] = acc


def _pad_lanes(a: Array, n_pad: int) -> Array:
    pad = n_pad - a.shape[-1]
    return a if pad == 0 else jnp.pad(a, ((0, 0), (0, pad)))


def match_dot(rows_idx_t: Array, rows_val_t: Array, f_idx_t: Array,
              f_val_t: Array, block_lanes: Optional[int] = None,
              interpret: bool = False) -> Array:
    """Per-sample compact margins from TRANSPOSED [k, n] operands.

    ``rows_idx_t``/``rows_val_t``: each sample's entity coefficient row
    (already gathered, [k_model, n]); ``f_idx_t``/``f_val_t``: the sample's
    sparse features ([k_feat, n]).  Returns margins [n].  Samples are padded
    to a lane-block multiple internally (zero feature values -> margin 0).
    Callers must gate on ``eligible()``.
    """
    k_model, n = rows_idx_t.shape
    k_feat = f_idx_t.shape[0]
    if not eligible(k_model, k_feat, interpret):
        raise ValueError("compact_score.match_dot called on an ineligible "
                         "shape; gate on ops.compact_score.eligible()")
    bl = block_lanes or min(512, max(_LANE, 1 << (max(n - 1, 0)).bit_length()))
    bl = max(_LANE, (bl // _LANE) * _LANE)
    n_pad = -(-max(n, 1) // bl) * bl
    args = (_pad_lanes(rows_idx_t, n_pad), _pad_lanes(rows_val_t, n_pad),
            _pad_lanes(f_idx_t, n_pad), _pad_lanes(f_val_t, n_pad))
    kernel = functools.partial(_match_dot_kernel, k_feat)
    out = pl.pallas_call(
        kernel,
        grid=(n_pad // bl,),
        in_specs=[
            pl.BlockSpec((k_model, bl), lambda i: (0, i)),
            pl.BlockSpec((k_model, bl), lambda i: (0, i)),
            pl.BlockSpec((k_feat, bl), lambda i: (0, i)),
            pl.BlockSpec((k_feat, bl), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((1, bl), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((1, n_pad), rows_val_t.dtype),
        interpret=interpret,
    )(*args)
    return out[0, :n]


def score_sparse_compact(w_idx: Array, w_val: Array, slots: Array,
                         f_idx: Array, f_val: Array,
                         interpret: bool = False) -> Array:
    """Drop-in twin of models/game._score_sparse_compact's math on the
    pallas path: gather each sample's entity row (XLA gather — the only
    HBM-efficient way to index [E, k] by slot), transpose to lanes-last,
    match-dot in VMEM, mask missing entities to 0."""
    e = jnp.where(slots >= 0, slots, 0)
    s = match_dot(w_idx[e].T, w_val[e].T, f_idx.T.astype(jnp.int32),
                  f_val.T, interpret=interpret)
    return jnp.where(slots >= 0, s, jnp.zeros((), s.dtype))
