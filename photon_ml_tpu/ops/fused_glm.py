"""Fused GLM objective kernels (pallas TPU).

One optimizer iteration reads X twice under plain XLA (z = X@w, then
g = X^T r) and three times for TRON's Hv (z, mv = X@v, X^T q).  These kernels
tile X into row blocks and do all per-block work while the block is resident
in VMEM, so X streams from HBM exactly once per call:

  fused_value_and_grad:  (value, X^T r, sum r)   in one pass
  fused_hvp:             (X^T q,  sum q)         in one pass (z and X@v fused)

Raw-space outputs: callers (GLMObjective) apply the normalization chain rule
and regularization on the O(d) results — the same split the reference uses
(ValueAndGradientAggregator keeps normalization algebra outside the per-datum
hot loop via effectiveCoefficients + marginShift, scala:36-49).

Grid iterations on TPU run sequentially on a core, so accumulating into the
same output block across steps (init at program_id 0) is race-free.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from photon_ml_tpu.core.batch import DenseBatch
from photon_ml_tpu.core.losses import PointwiseLoss

Array = jax.Array

_LANE = 128  # TPU lane width: last dim of X blocks must be a multiple


@functools.cache
def has_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:  # pragma: no cover - no backend at all
        return False


_MAX_DIM = 8192  # VMEM cap: the whole-array (_NACC, d) accumulator block plus
# the double-buffered (block_rows, d) X tile must fit ~16MB/core.


def _pick_block_rows(n: int, d: int, itemsize: int = 4,
                     vmem_budget_bytes: int = 1 << 20) -> int:
    """Multiple of 128: block_rows is the LANE dim of the (3, bn) yow block
    (and the sublane dim of the X block), so 128 is the only always-legal
    granule.  Budget counts only the X tile; double-buffering + accumulators
    bring actual VMEM use to ~3-4x this, against the ~16MB/core limit.
    ``itemsize`` is X's storage width — bf16 tiles carry twice the rows in
    the same VMEM, halving grid steps.

    IDEMPOTENT under its own padding: pick(pad(n, pick(n))) == pick(n), so a
    caller that pre-pads once (FixedEffectCoordinate) never re-pads per call.
    """
    budget_rows = max(_LANE, (vmem_budget_bytes // max(itemsize * d, 1)
                              // _LANE) * _LANE)
    if n <= budget_rows:
        return int(-(-max(n, 1) // _LANE) * _LANE)  # one block: ceil to 128
    return int(budget_rows)


def _pad_rows(batch: DenseBatch, block_rows: int) -> DenseBatch:
    """Pad the example axis to a block multiple with weight-0 rows."""
    n = batch.num_examples
    pad = (-n) % block_rows
    if pad == 0:
        return batch
    return DenseBatch(
        x=jnp.pad(batch.x, ((0, pad), (0, 0))),
        y=jnp.pad(batch.y, (0, pad)),
        offset=jnp.pad(batch.offset, (0, pad)),
        weight=jnp.pad(batch.weight, (0, pad)),
    )


def _acc_dtype(dtype) -> jnp.dtype:
    """Accumulate in >= f32 (f64 stays f64 for interpret-mode parity tests)."""
    return jnp.promote_types(dtype, jnp.float32)


# -- kernels -------------------------------------------------------------------


_HIGHEST = jax.lax.Precision.HIGHEST  # default MXU f32 precision is a single
# bf16 pass (~1e-3 rel err); HIGHEST uses the multi-pass f32 decomposition.


def _mxu_precision(dtype):
    """HIGHEST only makes sense for >=f32 operands (the multi-pass f32
    decomposition).  Sub-f32 storage (bf16) is already the MXU's native input
    width — a single DEFAULT pass is exact for those operands, and Mosaic
    rejects an fp32-precision contract on bf16 vregs outright ("Bad lhs
    type", seen on a real v5e, TPU_CHECKLIST round 5)."""
    return _HIGHEST if jnp.dtype(dtype).itemsize >= 4 else jax.lax.Precision.DEFAULT


def _row_margins(w, x, acc):
    """(D,C)^T @ (BN,D)^T -> (C, BN): margins as ROWS.

    Row layout puts examples on the lane axis, so the loss/residual
    elementwise work uses all 128 VPU lanes (a (BN,1) column layout wastes
    127/128 of them) and the MXU emits a full-width row."""
    return jax.lax.dot_general(w, x, (((0,), (1,)), ((), ())),
                               preferred_element_type=acc,
                               precision=_mxu_precision(x.dtype))


def _rowsum(row, ones, acc):
    """(1,BN)·(1,BN) -> (1,1) lane-contraction on the MXU."""
    return jax.lax.dot_general(row, ones, (((1,), (1,)), ((), ())),
                               preferred_element_type=acc,
                               precision=_mxu_precision(row.dtype))


def _row_xt(row, x, acc):
    """(1,BN) @ (BN,D) -> (1,D) contraction on the MXU."""
    return jax.lax.dot_general(row, x, (((1,), (0,)), ((), ())),
                               preferred_element_type=acc,
                               precision=_mxu_precision(x.dtype))


_NACC = 32  # accumulator rows: grid step i adds into row i % _NACC, cutting
# the sequential f32 accumulation chain by 32x (precision), while the output
# block stays whole-array (the only tiling-legal shape for accumulation).


def _slot_mask(i):
    rows = jax.lax.broadcasted_iota(jnp.int32, (_NACC, 1), 0)
    return rows == (i % _NACC).astype(jnp.int32)


def _value_grad_kernel(loss: PointwiseLoss, shift_ref, w_ref, x_ref, yow_ref,
                       val_ref, rsum_ref, grad_ref):
    i = pl.program_id(0)
    x = x_ref[:]  # (BN, D) — the only HBM->VMEM traffic that matters
    acc = _acc_dtype(x.dtype)
    z = _row_margins(w_ref[:], x, acc)  # (1, BN)
    z = z + yow_ref[1:2, :].astype(acc) + shift_ref[0, 0].astype(acc)
    wt = yow_ref[2:3, :].astype(acc)
    z = jnp.where(wt > 0, z, 0.0)  # safe margins: padded rows stay finite
    y = yow_ref[0:1, :].astype(acc)
    l, d1 = loss.loss_and_d1(z, y)
    r = wt * d1
    ones = jnp.ones_like(wt)

    @pl.when(i == 0)
    def _():
        val_ref[:] = jnp.zeros_like(val_ref)
        rsum_ref[:] = jnp.zeros_like(rsum_ref)
        grad_ref[:] = jnp.zeros_like(grad_ref)

    mask = _slot_mask(i)
    zero = jnp.zeros((), acc)
    val_ref[:] += jnp.where(mask, _rowsum(wt * l, ones, acc), zero)
    rsum_ref[:] += jnp.where(mask, _rowsum(r, ones, acc), zero)
    grad_ref[:] += jnp.where(mask, _row_xt(r.astype(x.dtype), x, acc), zero)


def _hvp_kernel(loss: PointwiseLoss, shift_ref, vshift_ref, wv_ref, x_ref,
                yow_ref, hv_ref, qsum_ref):
    i = pl.program_id(0)
    x = x_ref[:]
    acc = _acc_dtype(x.dtype)
    zz = _row_margins(wv_ref[:], x, acc)  # (2, BN): X@w row and X@v row
    z = zz[0:1, :] + yow_ref[1:2, :].astype(acc) + shift_ref[0, 0].astype(acc)
    mv = zz[1:2, :] + vshift_ref[0, 0].astype(acc)
    wt = yow_ref[2:3, :].astype(acc)
    z = jnp.where(wt > 0, z, 0.0)
    q = wt * loss.d2(z, yow_ref[0:1, :].astype(acc)) * mv

    @pl.when(i == 0)
    def _():
        qsum_ref[:] = jnp.zeros_like(qsum_ref)
        hv_ref[:] = jnp.zeros_like(hv_ref)

    mask = _slot_mask(i)
    zero = jnp.zeros((), acc)
    qsum_ref[:] += jnp.where(mask, _rowsum(q, jnp.ones_like(wt), acc), zero)
    hv_ref[:] += jnp.where(mask, _row_xt(q.astype(x.dtype), x, acc), zero)


# -- public entry points -------------------------------------------------------


def storage_narrowing_ok(x_dtype, w_dtype) -> bool:
    """ONE definition of the mixed-precision storage contract, shared by
    GLMObjective._fused_eligible and FixedEffectCoordinate's pre-padding
    decision (two separate copies drifted once — a gate mismatch wastes a
    permanent padded X copy on a path that then never runs fused).

    x may equal the solver dtype, or be a STRICTLY narrower float that
    promotes back to it (bf16/f16 against f32): kernels then take
    storage-width MXU operands with solver-width accumulation, mirroring
    DenseBatch.margins.  Widening storage (f64 x / f32 w) is out — promotion
    would change solver numerics."""
    xd, wd = jnp.dtype(x_dtype), jnp.dtype(w_dtype)
    if xd == wd:
        return True
    return bool(jnp.issubdtype(xd, jnp.floating) and xd.itemsize < wd.itemsize
                and jnp.promote_types(xd, wd) == wd)


def eligible(batch, interpret: bool = False) -> bool:
    """True when the pallas kernel path can run: TPU present, lane-aligned
    dim, and dim small enough that the (_NACC, d) accumulators + X tile fit
    VMEM.  Callers (GLMObjective) use their plain-XLA path otherwise — the
    kernels raise rather than silently duplicating that math here.

    PHOTON_GLM_DISABLE_PALLAS=1 forces the plain-XLA path everywhere —
    the bench's pallas-vs-XLA A/B knob (and an escape hatch)."""
    import os

    if os.environ.get("PHOTON_GLM_DISABLE_PALLAS") == "1":
        return False
    if not isinstance(batch, DenseBatch):
        return False
    if interpret:
        return True
    return has_tpu() and batch.dim % _LANE == 0 and batch.dim <= _MAX_DIM


def fused_value_and_grad(
    loss: PointwiseLoss,
    w_eff: Array,
    batch: DenseBatch,
    margin_shift: Array | float = 0.0,
    block_rows: Optional[int] = None,
    interpret: bool = False,
) -> Tuple[Array, Array, Array]:
    """(Σ wt·l, X^T r, Σ r) in one pass over X.

    ``w_eff``/``margin_shift``: normalization-effective coefficients and shift
    (GLMObjective.margins semantics).  Callers must gate on ``eligible()`` —
    the equivalent XLA math lives in GLMObjective, not duplicated here.
    """
    if not eligible(batch, interpret):
        raise ValueError("fused_value_and_grad called on an ineligible batch; "
                         "gate on ops.fused_glm.eligible()")
    if batch.x.dtype != w_eff.dtype:
        raise ValueError(
            f"fused_value_and_grad needs one uniform dtype (x {batch.x.dtype} "
            f"vs w {w_eff.dtype}); mixed-precision storage uses the XLA path")

    n, d = batch.x.shape
    bn = block_rows or _pick_block_rows(
        n, d, np.dtype(batch.x.dtype).itemsize)
    batch = _pad_rows(batch, bn)
    n_pad = batch.num_examples
    acc = _acc_dtype(batch.x.dtype)
    shift = jnp.asarray(margin_shift, acc).reshape(1, 1)

    grid = (n_pad // bn,)
    yow = jnp.stack([batch.y, batch.offset, batch.weight])  # (3, n): rows on lanes
    kernel = functools.partial(_value_grad_kernel, loss)
    val, rsum, grad = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1), lambda i: (0, 0)),            # margin shift
            pl.BlockSpec((d, 1), lambda i: (0, 0)),            # w_eff
            pl.BlockSpec((bn, d), lambda i: (i, 0)),           # X row block
            pl.BlockSpec((3, bn), lambda i: (0, i)),           # y/offset/weight rows
        ],
        out_specs=[
            pl.BlockSpec((_NACC, 1), lambda i: (0, 0)),
            pl.BlockSpec((_NACC, 1), lambda i: (0, 0)),
            pl.BlockSpec((_NACC, d), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((_NACC, 1), acc),
            jax.ShapeDtypeStruct((_NACC, 1), acc),
            jax.ShapeDtypeStruct((_NACC, d), acc),
        ],
        interpret=interpret,
    )(shift, w_eff.reshape(-1, 1), batch.x, yow)
    return jnp.sum(val), jnp.sum(grad, axis=0), jnp.sum(rsum)


def fused_hvp(
    loss: PointwiseLoss,
    w_eff: Array,
    v_eff: Array,
    batch: DenseBatch,
    margin_shift: Array | float = 0.0,
    v_shift: Array | float = 0.0,
    block_rows: Optional[int] = None,
    interpret: bool = False,
) -> Tuple[Array, Array]:
    """(X^T q, Σ q) with q = wt·l''(z)·(X@v_eff + v_shift), one pass over X.

    Callers must gate on ``eligible()`` (see fused_value_and_grad).
    """
    if not eligible(batch, interpret):
        raise ValueError("fused_hvp called on an ineligible batch; "
                         "gate on ops.fused_glm.eligible()")
    if batch.x.dtype != w_eff.dtype:
        raise ValueError(
            f"fused_hvp needs one uniform dtype (x {batch.x.dtype} "
            f"vs w {w_eff.dtype}); mixed-precision storage uses the XLA path")

    n, d = batch.x.shape
    bn = block_rows or _pick_block_rows(
        n, d, np.dtype(batch.x.dtype).itemsize)
    batch = _pad_rows(batch, bn)
    n_pad = batch.num_examples
    acc = _acc_dtype(batch.x.dtype)
    shift = jnp.asarray(margin_shift, acc).reshape(1, 1)
    vshift = jnp.asarray(v_shift, acc).reshape(1, 1)

    yow = jnp.stack([batch.y, batch.offset, batch.weight])
    wv = jnp.stack([w_eff, v_eff], axis=1)  # (d, 2)
    kernel = functools.partial(_hvp_kernel, loss)
    hv, qsum = pl.pallas_call(
        kernel,
        grid=(n_pad // bn,),
        in_specs=[
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
            pl.BlockSpec((d, 2), lambda i: (0, 0)),            # [w_eff | v_eff]
            pl.BlockSpec((bn, d), lambda i: (i, 0)),
            pl.BlockSpec((3, bn), lambda i: (0, i)),
        ],
        out_specs=[
            pl.BlockSpec((_NACC, d), lambda i: (0, 0)),
            pl.BlockSpec((_NACC, 1), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((_NACC, d), acc),
            jax.ShapeDtypeStruct((_NACC, 1), acc),
        ],
        interpret=interpret,
    )(shift, vshift, wv, batch.x, yow)
    return jnp.sum(hv, axis=0), jnp.sum(qsum)
