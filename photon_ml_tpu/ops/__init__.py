"""Pallas TPU kernels for the hot ops.

The framework's hot loop (SURVEY.md §3.2) is one fused pass over the design
matrix per optimizer iteration: margins -> pointwise loss -> residuals ->
gradient reduction (reference ValueAndGradientAggregator.scala:137-161 runs it
one datum at a time on executors; XLA runs it as matmul + elementwise +
transposed matmul).  The pallas kernels here stream each row-block of X
through VMEM ONCE, computing the margin matmul, the loss/residual VPU work,
and the gradient back-matmul per block — halving HBM traffic for X, the
usual bottleneck.
"""

from photon_ml_tpu.ops.fused_glm import (  # noqa: F401
    eligible, fused_hvp, fused_value_and_grad, has_tpu)

# sibling kernel modules (imported lazily by their callers; listed here for
# discoverability): ops.soa_newton — the SoA Newton step (Hessian assembly
# + batched small-Cholesky solve in one VMEM pass, opt/newton_soa.py's hot
# op); ops.compact_score — the sparse-compact match-dot scorer
# (models/game.score_compact_sparse's hot op).
