"""Pallas TPU kernel for the SoA Newton step: fused per-lane Hessian
assembly + batched small-Cholesky factor/solve.

The XLA path (opt/newton_soa.py) computes the per-iteration Newton step in
two stages: ``_hess`` materializes ``xq = x * q`` as a full ``[cap, d, L]``
HBM array (as large as the design itself) and reads the design again for
every of the d(d+1)/2 weighted column products, then ``_cholesky_solve_soa``
runs the unrolled factorization over ~d^2 separate [L] arrays.  This kernel
does the whole step — margins, curvature weights, Hessian lower triangle,
Cholesky, two triangular solves — while one lane-block of the design is
resident in VMEM, so X streams from HBM exactly once per Newton iteration
and ``xq`` never exists as an array (one column product lives at a time).

Layout: everything lanes-last, exactly the SoA solver's layout — [d, L]
state rows ride the 8-sublane tile, per-lane scalars are (1, L) rows using
all 128 VPU lanes, and there is no dot_general anywhere (d <= 16 is far
below the MXU's useful width; the VPU column products ARE the fast path).

Gating follows ops/fused_glm.py: TPU-only (``eligible``), CPU correctness
via ``interpret=True`` (tests) or the PHOTON_SOA_PALLAS_INTERPRET=1 env
knob (drives the WHOLE solver through the kernel in interpret mode), and a
PHOTON_SOA_DISABLE_PALLAS=1 escape hatch — also the bench's A/B knob.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from photon_ml_tpu.core.losses import PointwiseLoss
from photon_ml_tpu.ops.fused_glm import has_tpu

Array = jax.Array

_LANE = 128  # TPU lane width: lane blocks must be a multiple

# VMEM budget for the design block (cap, d, BL): the SoA gate already bounds
# cap*d^2/2 <= 1280 so cap*d <= 2560/d <= 640 at d>=4 — a 512-lane block is
# ~1.3MB, comfortably inside ~16MB/core with double buffering.
_X_BLOCK_BUDGET_BYTES = 4 << 20


def interpret_forced() -> bool:
    """CPU end-to-end testing knob: run the kernel in interpret mode inside
    the real solver (slow — tests only)."""
    return os.environ.get("PHOTON_SOA_PALLAS_INTERPRET") == "1"


def eligible(d: int, num_lanes: int, interpret: bool = False) -> bool:
    """True when the pallas Newton-step kernel can run.  Callers
    (opt/newton_soa.solve_newton_soa) keep the XLA path otherwise — the
    kernel raises rather than duplicating that math here.

    PHOTON_SOA_DISABLE_PALLAS=1 forces the XLA path everywhere — the bench's
    pallas-vs-XLA A/B knob (and an escape hatch)."""
    if os.environ.get("PHOTON_SOA_DISABLE_PALLAS") == "1":
        return False
    if d < 1 or num_lanes < 1 or num_lanes % _LANE != 0:
        return False
    if interpret or interpret_forced():
        return True
    return has_tpu()


def _pick_block_lanes(cap: int, d: int, num_lanes: int, itemsize: int) -> int:
    """Largest 128-multiple block whose (cap, d, BL) design tile fits the
    VMEM budget, capped at the lane count (which is already a multiple)."""
    per_lane = max(1, cap * d * itemsize)
    bl = max(_LANE, (_X_BLOCK_BUDGET_BYTES // per_lane // _LANE) * _LANE)
    return int(min(bl, num_lanes))


def _newton_step_kernel(loss: PointwiseLoss, d: int, eps: float,
                        w_ref, g_ref, x_ref, y_ref, off_ref, wt_ref, l2_ref,
                        out_ref):
    """One lane-block: margins -> q -> Hessian lower triangle -> Cholesky ->
    two triangular solves.  Every array below is (cap, BL) or (1, BL); the
    d loops unroll statically (d <= 16 by the SoA gate)."""
    x = x_ref[:]                                    # (cap, d, BL)
    acc = jnp.promote_types(x.dtype, w_ref.dtype)
    w = w_ref[:].astype(acc)                        # (d, BL)
    # margins: sublane sum over the static d axis, no dot_general — the
    # EXACT op sequence of newton_soa._margins ((x*w).sum(axis=1) + off),
    # so interpret-mode runs are bitwise the XLA path's
    z = jnp.sum(x.astype(acc) * w[None], axis=1) + off_ref[:]
    q = wt_ref[:].astype(acc) * loss.d2(z, y_ref[:])  # (cap, BL)

    # Hessian lower triangle: one xq column product at a time — the [cap,
    # d, L] xq array of the XLA path never exists (newton_soa._hess parity:
    # hh[i][j] = sum_cap x_i x_j q, + l2 on the diagonal)
    l2 = l2_ref[:].astype(acc)                      # (1, BL)
    hh = [[None] * d for _ in range(d)]
    for i in range(d):
        xq_i = x[:, i, :].astype(acc) * q
        for j in range(i + 1):
            hij = jnp.sum(xq_i * x[:, j, :].astype(acc), axis=0,
                          keepdims=True)            # (1, BL)
            if i == j:
                hij = hij + l2
            hh[i][j] = hij

    # scale-relative jitter — newton_soa's exact rule: eps * (max |diag| + 1)
    diag_max = functools.reduce(
        jnp.maximum, (jnp.abs(hh[i][i]) for i in range(d)))
    jitter = eps * (diag_max + 1.0)

    # unrolled Cholesky + forward/back substitution, elementwise over lanes
    # (newton_soa._cholesky_solve_soa parity, including the sqrt floor)
    g = g_ref[:].astype(acc)
    lo = [[None] * d for _ in range(d)]
    for i in range(d):
        s = hh[i][i] + jitter
        for k in range(i):
            s = s - lo[i][k] * lo[i][k]
        lii = jnp.sqrt(jnp.maximum(s, jitter))
        lo[i][i] = lii
        for j in range(i + 1, d):
            s2 = hh[j][i]
            for k in range(i):
                s2 = s2 - lo[j][k] * lo[i][k]
            lo[j][i] = s2 / lii
    zz = [None] * d
    for i in range(d):
        s = g[i:i + 1, :]
        for k in range(i):
            s = s - lo[i][k] * zz[k]
        zz[i] = s / lo[i][i]
    xs = [None] * d
    for i in reversed(range(d)):
        s = zz[i]
        for k in range(i + 1, d):
            s = s - lo[k][i] * xs[k]
        xs[i] = s / lo[i][i]
    out_ref[:] = jnp.concatenate(xs, axis=0).astype(out_ref.dtype)


def newton_step(loss: PointwiseLoss, w: Array, g: Array, x_t: Array,
                y_t: Array, off_t: Array, wt_t: Array, l2: Array,
                block_lanes: Optional[int] = None,
                interpret: bool = False) -> Array:
    """step = (H(w) + jitter I)^-1 g in one pass over the design.

    ``w``/``g``: [d, L]; ``x_t``: [cap, d, L]; ``y/off/wt_t``: [cap, L];
    ``l2``: [L] per-lane regularization.  Returns the [d, L] Newton step —
    bitwise the same algorithm as newton_soa's ``_hess`` +
    ``_cholesky_solve_soa`` chain (parity-tested in interpret mode).
    Callers must gate on ``eligible()``.
    """
    d, num_l = w.shape
    cap = x_t.shape[0]
    if not eligible(d, num_l, interpret):
        raise ValueError("soa_newton.newton_step called on an ineligible "
                         "shape; gate on ops.soa_newton.eligible()")
    bl = block_lanes or _pick_block_lanes(
        cap, d, num_l, np.dtype(x_t.dtype).itemsize)
    if num_l % bl != 0:
        raise ValueError(f"block_lanes {bl} must divide num_lanes {num_l}")
    eps = float(np.finfo(np.dtype(w.dtype)).eps)
    kernel = functools.partial(_newton_step_kernel, loss, d, eps)
    return pl.pallas_call(
        kernel,
        grid=(num_l // bl,),
        in_specs=[
            pl.BlockSpec((d, bl), lambda i: (0, i)),        # w
            pl.BlockSpec((d, bl), lambda i: (0, i)),        # g
            pl.BlockSpec((cap, d, bl), lambda i: (0, 0, i)),  # x_t
            pl.BlockSpec((cap, bl), lambda i: (0, i)),      # y_t
            pl.BlockSpec((cap, bl), lambda i: (0, i)),      # off_t
            pl.BlockSpec((cap, bl), lambda i: (0, i)),      # wt_t
            pl.BlockSpec((1, bl), lambda i: (0, i)),        # l2
        ],
        out_specs=pl.BlockSpec((d, bl), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((d, num_l), w.dtype),
        interpret=interpret or interpret_forced(),
    )(w, g, x_t, y_t, off_t, wt_t,
      jnp.broadcast_to(jnp.asarray(l2), (num_l,)).reshape(1, num_l))
