"""Structured tracer: nestable spans into a fixed-size ring buffer.

Photon ML reference counterpart: util/Timed.scala wraps pipeline phases and
logs wall-clock durations — a flat, text-only timeline.  Production serving
needs the question Timed cannot answer: *where inside this request's 2ms did
the time go*, across threads (the async batcher worker, the hot-swap thread,
the scoring caller) and across layers (submit -> flush -> resolve -> AOT
execute).  This tracer records **complete spans** (name, start, duration,
thread, parent span) plus **instant events** (the ``utils/events`` lifecycle
bridge) into a preallocated ring buffer and exports the Chrome
``trace_event`` JSON format, so one Perfetto load shows training sweeps and
serving requests on the same nested timeline.

Concurrency model ("lock-free-ish"): every record claims a slot by bumping
a cursor under a single lock — the lock protects ONLY the increment — and
then fills the preallocated slot outside the lock.  Two writers can never
share a slot; a reader (the exporter) skips slots whose sequence stamp says
they are mid-write.  Slots are preallocated fixed-arity lists, so steady-
state tracing allocates nothing but the per-span attrs dict.

Disabled cost: call sites go through the module-level ``span()`` /
``instant()`` helpers, which check one boolean and return a shared no-op
context manager — a few ns guard (``bench.py --obs`` holds this under
1µs/call).  Tracing is OFF by default; ``enable()`` / ``cli`` flags turn it
on.

Device-accurate timings: wall-clocking a host block around async device
work measures dispatch, not execution (the gap ``utils/logging.py``
documents).  ``span(..., device_sync=True)`` runs a device fence at entry
and exit when tracing is enabled — enqueue a trivial op and block on it, so
on an in-order accelerator stream the span brackets the actual device work.
The fence costs a device round-trip, which is why it is per-span opt-in and
completely absent when tracing is disabled.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

# slot layout (preallocated lists; indices, not attributes, for write speed)
_SEQ = 0      # claim sequence; -1 while the writer is mid-fill
_NAME = 1
_PHASE = 2    # "X" complete span | "i" instant
_TS = 3       # perf_counter_ns at start
_DUR = 4      # ns
_TID = 5
_SPAN = 6     # span id
_PARENT = 7   # parent span id (0 = root)
_ATTRS = 8
_WIDTH = 9


# -- cross-process trace context (photonpulse) ------------------------------
# One thread-local cell shared by every Tracer instance: the binding is a
# property of the THREAD doing the work (this request, this publish), not of
# whichever ring it records into, so tracer swaps in tests never strand a
# binding.  The cell holds an opaque ``(trace_id, origin_span)`` pair minted
# by ``obs.pulse`` — trace.py only copies it into record attrs, keeping this
# module free of any pulse import.  Cost: one getattr on the ENABLED record
# path; the disabled ``span()`` guard is untouched.
_ctx_local = threading.local()


def current_context():
    """The thread's bound ``(trace_id, origin_span)`` pair, or None."""
    return getattr(_ctx_local, "ctx", None)


def set_context(ctx) -> object:
    """Bind ``ctx`` (or None to unbind) on this thread; returns the previous
    binding so callers can restore it (``obs.pulse.bind`` does)."""
    prev = getattr(_ctx_local, "ctx", None)
    _ctx_local.ctx = ctx
    return prev


# Export metadata: a stable human label for this process ("frontend",
# "owner", "replica") plus a provider hook pulse uses to attach its clock
# offsets without trace.py importing pulse.
_process_label: Optional[str] = None
_export_meta_provider: Optional[Callable[[], dict]] = None


def set_process_label(label: Optional[str]) -> None:
    """Name this process in Chrome exports (``process_name`` metadata)."""
    global _process_label
    _process_label = label


def get_process_label() -> Optional[str]:
    return _process_label


def set_export_meta_provider(provider: Optional[Callable[[], dict]]) -> None:
    """Extra ``otherData`` fields for ``chrome_trace()`` (pulse installs its
    clock-offset table here)."""
    global _export_meta_provider
    _export_meta_provider = provider


def _default_device_fence() -> None:
    """Enqueue a trivial device op and block on it: on an in-order
    accelerator stream this drains previously enqueued work, giving span
    boundaries that bracket device execution instead of dispatch.  Never
    raises — a host without jax initialized just gets wall clock."""
    try:
        import jax
        import jax.numpy as jnp

        (jnp.zeros(()) + 0).block_until_ready()
    except Exception:
        pass


class _NoopSpan:
    """Shared do-nothing context manager for the disabled path."""

    __slots__ = ()

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False


_NOOP = _NoopSpan()


class _Span:
    """Active span handle; records the slot on exit."""

    __slots__ = ("_tracer", "_name", "_attrs", "_sync", "_t0", "_id",
                 "_parent")

    def __init__(self, tracer: "Tracer", name: str,
                 attrs: Optional[Dict[str, Any]], sync: bool):
        self._tracer = tracer
        self._name = name
        self._attrs = attrs
        self._sync = sync

    def __enter__(self) -> "_Span":
        t = self._tracer
        stack = t._stack()
        self._parent = stack[-1] if stack else 0
        self._id = next(t._ids)
        stack.append(self._id)
        if self._sync:
            t.device_fence()
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc) -> bool:
        t = self._tracer
        if self._sync:
            t.device_fence()
        dur = time.perf_counter_ns() - self._t0
        stack = t._stack()
        if stack and stack[-1] == self._id:
            stack.pop()
        t._record("X", self._name, self._t0, dur, self._id, self._parent,
                  self._attrs)
        return False


class Tracer:
    """Fixed-capacity span recorder (see module docstring).

    ``capacity``: ring slots — the newest ``capacity`` records win; older
    ones are silently overwritten (bounded memory is the contract, not
    completeness).  ``enabled`` gates every record; flipping it never
    invalidates outstanding ``_Span`` handles (they record into the ring,
    which is harmless either way).
    """

    def __init__(self, capacity: int = 8192, enabled: bool = False):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.enabled = bool(enabled)
        self._slots: List[list] = [[0] * _WIDTH for _ in range(self.capacity)]
        self._cursor = 0
        self._cursor_lock = threading.Lock()
        self._ids = itertools.count(1)
        self._local = threading.local()
        self._fence: Callable[[], None] = _default_device_fence
        # tid -> thread name, filled the first time a thread records; export
        # emits these as Chrome "thread_name" metadata so merged timelines
        # show "batcher-worker" instead of a bare ident
        self._thread_names: Dict[int, str] = {}

    # -- per-thread span stack ---------------------------------------------
    def _stack(self) -> List[int]:
        s = getattr(self._local, "stack", None)
        if s is None:
            s = self._local.stack = []
            self._thread_names[threading.get_ident()] = \
                threading.current_thread().name
        return s

    # -- recording ---------------------------------------------------------
    def span(self, name: str, device_sync: bool = False, **attrs):
        """Nestable timed span; a context manager.  ``device_sync=True``
        fences the device at both edges (see module docstring)."""
        if not self.enabled:
            return _NOOP
        return _Span(self, name, attrs or None, device_sync)

    def instant(self, name: str, **attrs) -> None:
        """One point-in-time event (Chrome phase "i") at the current
        nesting level — the ``utils/events`` lifecycle bridge."""
        if not self.enabled:
            return
        stack = self._stack()
        parent = stack[-1] if stack else 0
        self._record("i", name, time.perf_counter_ns(), 0, next(self._ids),
                     parent, attrs or None)

    def complete(self, name: str, start_ns: int, dur_ns: int,
                 **attrs) -> None:
        """Record a complete span with explicit timing — for work whose
        start and end live in different callbacks (a frontend request
        admitted on one event-loop tick and settled on another), where a
        ``with`` block cannot bracket it."""
        if not self.enabled:
            return
        stack = self._stack()
        parent = stack[-1] if stack else 0
        self._record("X", name, start_ns, dur_ns, next(self._ids), parent,
                     attrs or None)

    def _record(self, phase: str, name: str, ts: int, dur: int,
                span_id: int, parent: int,
                attrs: Optional[Dict[str, Any]]) -> None:
        if not self.enabled:
            return
        ctx = getattr(_ctx_local, "ctx", None)
        if ctx is not None:
            # propagation: stamp the bound trace id (and the origin span on
            # the far side of a wire hop) into this record's attrs
            attrs = dict(attrs) if attrs else {}
            attrs["trace"] = ctx[0]
            if ctx[1]:
                attrs["origin"] = ctx[1]
        with self._cursor_lock:  # held ONLY to claim the slot
            seq = self._cursor
            self._cursor = seq + 1
        slot = self._slots[seq % self.capacity]
        slot[_SEQ] = -1  # mid-write marker: exporter skips torn slots
        slot[_NAME] = name
        slot[_PHASE] = phase
        slot[_TS] = ts
        slot[_DUR] = dur
        slot[_TID] = threading.get_ident()
        slot[_SPAN] = span_id
        slot[_PARENT] = parent
        slot[_ATTRS] = attrs
        slot[_SEQ] = seq + 1  # valid: seq stamps are 1-based, 0 = empty

    def device_fence(self) -> None:
        self._fence()

    def set_device_fence(self, fence: Callable[[], None]) -> None:
        """Override the ``device_sync=True`` fence (tests, exotic
        backends)."""
        self._fence = fence

    # -- control -----------------------------------------------------------
    def enable(self) -> "Tracer":
        self.enabled = True
        return self

    def disable(self) -> "Tracer":
        self.enabled = False
        return self

    def clear(self) -> None:
        with self._cursor_lock:
            self._cursor = 0
        for slot in self._slots:
            slot[_SEQ] = 0

    # -- export ------------------------------------------------------------
    def records(self) -> List[dict]:
        """Valid ring records, oldest first.  Skips empty and mid-write
        slots; the window is the last ``capacity`` claims."""
        with self._cursor_lock:
            cursor = self._cursor
        lo = max(0, cursor - self.capacity)
        out = []
        for seq in range(lo, cursor):
            slot = self._slots[seq % self.capacity]
            snap = list(slot)  # one read; a racing overwrite changes _SEQ
            if snap[_SEQ] != seq + 1:
                continue  # empty, torn, or already lapped
            out.append({
                "name": snap[_NAME], "ph": snap[_PHASE],
                "ts_ns": snap[_TS], "dur_ns": snap[_DUR],
                "tid": snap[_TID], "id": snap[_SPAN],
                "parent": snap[_PARENT], "attrs": snap[_ATTRS] or {},
            })
        return out

    def chrome_trace(self) -> dict:
        """Chrome ``trace_event`` JSON (load in Perfetto / chrome://tracing).

        Complete spans use phase "X" with microsecond ``ts``/``dur``;
        instants use phase "i" with thread scope.  Span/parent ids ride in
        ``args`` so nesting survives tools that re-sort events.

        The export carries the identity ``tools/tracemerge.py`` needs:
        "M"-phase ``process_name``/``thread_name`` metadata events (the
        label set via ``set_process_label``; thread names captured at first
        record) and an ``otherData`` block with the label, pid, and
        whatever the export-meta provider adds (pulse's clock-offset
        table)."""
        pid = os.getpid()
        label = _process_label or f"py-{pid}"
        events = [{"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
                   "ts": 0, "args": {"name": label}}]
        for tid, tname in sorted(self._thread_names.items()):
            events.append({"name": "thread_name", "ph": "M", "pid": pid,
                           "tid": tid, "ts": 0, "args": {"name": tname}})
        for r in sorted(self.records(), key=lambda r: (r["ts_ns"], r["id"])):
            ev = {
                "name": r["name"], "ph": r["ph"], "pid": pid,
                "tid": r["tid"], "ts": r["ts_ns"] / 1e3,
                "args": dict(r["attrs"], span_id=r["id"],
                             parent_id=r["parent"]),
            }
            if r["ph"] == "X":
                ev["dur"] = r["dur_ns"] / 1e3
            else:
                ev["s"] = "t"  # thread-scoped instant
            events.append(ev)
        other = {"process_label": label, "pid": pid}
        if _export_meta_provider is not None:
            try:
                other.update(_export_meta_provider())
            except Exception:
                pass  # export must never fail because a meta hook did
        return {"traceEvents": events, "displayTimeUnit": "ns",
                "otherData": other}

    def export_chrome_trace(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.chrome_trace(), f)


# ---------------------------------------------------------------------------
# module-level default tracer: the hot-path entry points
# ---------------------------------------------------------------------------
_default = Tracer()


def get_tracer() -> Tracer:
    return _default


def set_tracer(tracer: Tracer) -> Tracer:
    """Swap the process-default tracer; returns the previous one (tests
    restore it)."""
    global _default
    prev, _default = _default, tracer
    return prev


def span(name: str, device_sync: bool = False, **attrs):
    """``with span("solve", coordinate=cid):`` against the default tracer.
    Disabled: one boolean check + a shared no-op context manager."""
    t = _default
    if not t.enabled:
        return _NOOP
    return _Span(t, name, attrs or None, device_sync)


def instant(name: str, **attrs) -> None:
    t = _default
    if t.enabled:
        t.instant(name, **attrs)


def enabled() -> bool:
    return _default.enabled
