"""photonscope: unified tracing, metrics, and XLA runtime accounting.

Photon ML reference counterpart: the util/{PhotonLogger,Timed}.scala +
event/Event.scala trio — text logs, wall-clock phase blocks, and lifecycle
events, each its own silo.  Here the three become one observability layer
shared by training AND serving:

  - ``trace``: nestable spans in a fixed-size ring buffer with a Chrome
    ``trace_event`` exporter (Perfetto-loadable), instant events bridged
    from ``utils/events``, and opt-in per-span device fences
    (``device_sync=True``) for device-accurate timings;
  - ``registry``: one thread-safe ``MetricsRegistry`` — counters, gauges,
    fixed-bin latency histograms, label support — with Prometheus text
    exposition and JSON snapshots (``serving.ServingMetrics`` is a facade
    over it);
  - ``probe``: ``JaxRuntimeProbe`` counting XLA compiles per call site and
    host<->device transfer bytes at the chunked-upload path;
  - ``watch``: the fleet-global plane (photonwatch) — metrics federation
    (``DeltaExporter``/``FleetView``), multi-window SLO burn-rate alerting,
    and span-aligned device-time attribution.

Tracing is disabled by default; the module-level ``span()``/``instant()``
fast paths cost one boolean check when off (``bench.py --obs`` holds the
guard under 1µs/call).  Enable with ``photon_ml_tpu.obs.enable_tracing()``,
``cli/serve.py --trace``, or ``cli/train.py --trace-out``.
"""

from photon_ml_tpu.obs.probe import JaxRuntimeProbe, get_probe  # noqa: F401
from photon_ml_tpu.obs.registry import (LatencyHistogram,  # noqa: F401
                                        MetricsRegistry, export_build_info,
                                        family_bounds, get_registry,
                                        process_start_time, series_name,
                                        set_family_bounds, set_registry)
from photon_ml_tpu.obs.trace import (Tracer, enabled, get_tracer,  # noqa: F401
                                     instant, set_tracer, span)


def enable_tracing(capacity: int = None) -> Tracer:
    """Turn the default tracer on (optionally resized); returns it."""
    t = get_tracer()
    if capacity is not None and capacity != t.capacity:
        t = Tracer(capacity=capacity, enabled=True)
        set_tracer(t)
    return t.enable()


def disable_tracing() -> Tracer:
    return get_tracer().disable()
