"""Span-aligned XLA device-time attribution (the PR-5 follow-on).

Wall-clocking a host block around ``jit``-compiled work measures dispatch,
not execution: JAX returns as soon as the computation is enqueued.  This
module splits an execute site's elapsed time into **host** (python +
dispatch, up to the moment the call returns) and **device** (what is still
draining on the accelerator stream afterwards):

    fence                       # drain prior work off the stream
    t0 = perf_counter
    <body: dispatch the computation>
    t_ret = perf_counter        # host side done, device maybe still running
    fence                       # block until the stream drains
    t1 = perf_counter

    host_s   = t_ret - t0
    device_s = t1 - t_ret

On an in-order stream this brackets the actual device execution; on CPU
(where jax executes synchronously inside the call) ``device_s`` collapses
toward the fence cost, which is itself the honest answer — there IS no
async device tail.  A real profiler-derived timer (``jax.profiler`` hooks,
a TPU runtime counter) can replace the fence arithmetic via
:func:`set_device_timer` without touching call sites.

Output lands in two places per sample:

* ``xla_device_seconds{site=}`` / ``xla_host_seconds{site=}`` accumulating
  gauges in the process registry — federation sums these into the fleet
  view, answering "what fraction of fleet time is device execution",
* ``device_us`` / ``host_us`` attrs stamped onto the ENCLOSING tracer span
  (``serve.execute``, ``solve.bucket``) — ``_Span.__exit__`` records its
  attrs dict by reference, so mutating it before the ``with`` block closes
  lands the split in the Chrome export next to the span it explains.

Disabled cost: call sites hold one module boolean and get a shared no-op
context manager back — same discipline as ``trace.span`` (held under the
1µs ``bench.py --obs``/``--watch`` budget).
"""

from __future__ import annotations

import time
from typing import Callable, Optional

from photon_ml_tpu.obs import trace as _trace
from photon_ml_tpu.obs.registry import MetricsRegistry, get_registry

_enabled = False
_registry: Optional[MetricsRegistry] = None
# Optional replacement for the fence arithmetic: called as timer() -> float
# device-seconds consumed since the previous call on this thread.  None
# means "fence and subtract" (the portable default).
_device_timer: Optional[Callable[[], float]] = None


def enable_attribution(registry: Optional[MetricsRegistry] = None) -> None:
    """Turn the split on; samples accumulate into ``registry`` (the process
    default when None, resolved per sample so registry swaps in tests
    behave)."""
    global _enabled, _registry
    _registry = registry
    _enabled = True


def disable_attribution() -> None:
    global _enabled, _registry
    _enabled = False
    _registry = None


def attribution_enabled() -> bool:
    return _enabled


def set_device_timer(timer: Optional[Callable[[], float]]) -> None:
    """Install a profiler-derived device-seconds source (None restores the
    fence-based split)."""
    global _device_timer
    _device_timer = timer


class _NoopAttribution:
    __slots__ = ()

    def __enter__(self) -> "_NoopAttribution":
        return self

    def __exit__(self, *exc) -> bool:
        return False


_NOOP = _NoopAttribution()


class _Attribution:
    __slots__ = ("_site", "_span", "_t0", "_timer")

    def __init__(self, site: str, span) -> None:
        self._site = site
        self._span = span

    def __enter__(self) -> "_Attribution":
        self._timer = _device_timer
        if self._timer is not None:
            self._timer()  # reset the interval
        else:
            _trace.get_tracer().device_fence()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, *exc) -> bool:
        t_ret = time.perf_counter()
        if self._timer is not None:
            device_s = float(self._timer())
            host_s = max(t_ret - self._t0 - device_s, 0.0)
        else:
            _trace.get_tracer().device_fence()
            t1 = time.perf_counter()
            host_s = t_ret - self._t0
            device_s = t1 - t_ret
        if exc_type is None:
            reg = _registry if _registry is not None else get_registry()
            reg.add_gauge("xla_device_seconds", device_s, site=self._site)
            reg.add_gauge("xla_host_seconds", host_s, site=self._site)
            attrs = getattr(self._span, "_attrs", None)
            if attrs is not None:
                attrs["device_us"] = round(device_s * 1e6, 3)
                attrs["host_us"] = round(host_s * 1e6, 3)
        return False


def attribute(site: str, span=None):
    """``with attribute("serve.execute", span_handle):`` around the device
    dispatch.  ``span`` is the enclosing tracer span handle (may be the
    no-op span or None; the split is then registry-only)."""
    if not _enabled:
        return _NOOP
    return _Attribution(site, span)
