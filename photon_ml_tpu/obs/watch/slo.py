"""Declarative SLOs evaluated as multi-window burn rates.

An SLO declares an error budget (``objective=0.999`` leaves 0.1% of events
allowed to be bad).  The **burn rate** over a window is how fast that
budget is being spent relative to plan::

    burn(W) = (bad events in W / total events in W) / (1 - objective)

``burn == 1`` spends exactly the budget over the SLO period; ``burn == 14``
exhausts a 30-day budget in ~2 days.  Following the multi-window pattern,
an alert condition pairs a short and a long window at the same burn
threshold — the long window proves the problem is sustained, the short
window makes the alert RESOLVE quickly once the bleeding stops.  Two pairs
run in parallel: a *fast* pair (page-grade, high threshold) and a *slow*
pair (ticket-grade, low threshold).  Production windows are 5m/1h and
30m/6h; the dataclass takes them as plain seconds so tests and the bench
scale the same logic down to sub-second episodes.

Event sources are cumulative registry series, read from whatever registry
the caller hands ``evaluate()`` — a process's own registry for local mode,
a :class:`~photon_ml_tpu.obs.watch.federation.FleetView`'s merged registry
for fleet mode:

* ``kind="availability"``: total from one counter family, bad from one or
  more counter families (shed/error counters),
* ``kind="latency"``: both from one histogram family's fixed-bin ladder —
  total is the observation count, bad is observations above
  ``threshold_s`` (counted from the first bin bound >= the threshold, so
  pick a threshold on a bin edge for exactness).

Alert latches publish ``fleet_slo_burn_rate{slo=}`` / ``fleet_slo_alert``
gauges every evaluation and fire ``flight_dump("slo_burn", ...)`` on each
rising edge — the fleet-wide ring dump that answers "what was everyone
doing when the budget started burning".
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Sequence, Tuple

from photon_ml_tpu.obs.pulse.flight import flight_dump
from photon_ml_tpu.obs.registry import MetricsRegistry


@dataclasses.dataclass(frozen=True)
class SLO:
    """One declarative objective.  ``fast``/``slow`` are (short, long)
    window pairs in seconds; ``*_burn`` their shared burn thresholds."""

    name: str
    objective: float = 0.999
    kind: str = "availability"              # "availability" | "latency"
    # availability sources
    total: str = "front_requests_total"
    bad: Tuple[str, ...] = ("requests_shed_total",)
    # latency sources
    histogram: str = "serving_latency_s"
    threshold_s: float = 0.050
    # multi-window burn-rate alert policy
    fast: Tuple[float, float] = (300.0, 3600.0)
    slow: Tuple[float, float] = (1800.0, 21600.0)
    fast_burn: float = 14.4
    slow_burn: float = 6.0

    def __post_init__(self) -> None:
        if not 0.0 < self.objective < 1.0:
            raise ValueError(f"objective must be in (0, 1): {self.objective}")
        if self.kind not in ("availability", "latency"):
            raise ValueError(f"unknown SLO kind: {self.kind!r}")
        if self.fast[0] >= self.fast[1] or self.slow[0] >= self.slow[1]:
            raise ValueError("window pairs must be (short, long)")

    @classmethod
    def from_dict(cls, d: dict) -> "SLO":
        kw = dict(d)
        for field in ("bad", "fast", "slow"):
            if field in kw:
                kw[field] = tuple(kw[field])
        return cls(**kw)


def load_slos(path: str) -> List[SLO]:
    """Load a JSON spec file: either a list of SLO dicts or
    ``{"slos": [...]}`` (room for future top-level config)."""
    with open(path) as f:
        doc = json.load(f)
    items = doc["slos"] if isinstance(doc, dict) else doc
    return [SLO.from_dict(d) for d in items]


def _read_counter_family(registry: MetricsRegistry, name: str) -> float:
    return sum(registry.counter_series(name).values())


def _read_latency(registry: MetricsRegistry, name: str,
                  threshold_s: float) -> Tuple[float, float]:
    """(total observations, observations above threshold) summed across the
    family's label sets, from the cumulative fixed-bin ladders."""
    total = 0.0
    bad = 0.0
    for state in registry.histogram_state_series(name).values():
        total += state["count"]
        good = 0
        for bound, c in zip(state["bounds"], state["counts"]):
            if bound <= threshold_s:
                good += c
            else:
                break
        bad += state["count"] - good
    return total, bad


class _Track:
    """Per-SLO evaluation state: cumulative samples + alert latch."""

    def __init__(self, slo: SLO) -> None:
        self.slo = slo
        horizon = max(slo.fast[1], slo.slow[1])
        # samples are (t, total, bad); keep a little past the longest
        # window so the boundary lookup always has an anchor
        self.horizon = horizon * 1.25
        self.samples: Deque[Tuple[float, float, float]] = deque()
        self.firing = False

    def window_burn(self, now: float, window: float) -> float:
        """Burn rate over the trailing ``window`` seconds.  Uses the oldest
        sample inside the window as the anchor; with no in-window history
        (cold start) there is nothing to burn yet — 0.0, never a guess."""
        if not self.samples:
            return 0.0
        t_now, total_now, bad_now = self.samples[-1]
        anchor = None
        for t, total, bad in self.samples:
            if t >= now - window:
                anchor = (t, total, bad)
                break
        if anchor is None or anchor[0] >= t_now:
            return 0.0
        d_total = total_now - anchor[1]
        d_bad = bad_now - anchor[2]
        if d_total <= 0:
            return 0.0
        return (d_bad / d_total) / (1.0 - self.slo.objective)


class SLOEngine:
    """Evaluates a set of SLOs against a registry on each ``evaluate()``.

    Stateless about WHERE the registry comes from — the caller passes it
    every tick (the FleetView merge target, or a local process registry).
    Burn gauges are published into ``publish`` (defaults to the evaluated
    registry, which for fleet mode puts ``fleet_slo_burn_rate`` right next
    to the merged series the admission controller already reads).
    """

    def __init__(self, slos: Sequence[SLO],
                 publish: Optional[MetricsRegistry] = None,
                 on_alert: Optional[Callable[[dict], None]] = None) -> None:
        names = [s.name for s in slos]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO names: {names}")
        self._tracks = [_Track(s) for s in slos]
        self._publish = publish
        self._on_alert = on_alert
        self._events: List[dict] = []

    @property
    def slos(self) -> List[SLO]:
        return [t.slo for t in self._tracks]

    def events(self) -> List[dict]:
        """Every alert edge (firing/resolved) seen so far, oldest first."""
        return list(self._events)

    def firing(self) -> List[str]:
        return [t.slo.name for t in self._tracks if t.firing]

    def evaluate(self, registry: MetricsRegistry,
                 now: Optional[float] = None) -> List[dict]:
        """One tick: sample sources, compute window burns, publish gauges,
        latch alerts.  Returns the edges produced by THIS tick."""
        now = time.time() if now is None else now
        publish = self._publish if self._publish is not None else registry
        edges: List[dict] = []
        for track in self._tracks:
            slo = track.slo
            if slo.kind == "availability":
                total = _read_counter_family(registry, slo.total)
                bad = sum(_read_counter_family(registry, b)
                          for b in slo.bad)
            else:
                total, bad = _read_latency(registry, slo.histogram,
                                           slo.threshold_s)
            track.samples.append((now, total, bad))
            while track.samples and \
                    track.samples[0][0] < now - track.horizon:
                track.samples.popleft()

            fast_short = track.window_burn(now, slo.fast[0])
            fast_long = track.window_burn(now, slo.fast[1])
            slow_short = track.window_burn(now, slo.slow[0])
            slow_long = track.window_burn(now, slo.slow[1])
            fast_hit = (fast_short > slo.fast_burn
                        and fast_long > slo.fast_burn)
            slow_hit = (slow_short > slo.slow_burn
                        and slow_long > slo.slow_burn)
            alerting = fast_hit or slow_hit

            # the short fast window is the most reactive view of current
            # pressure — that is what admission consults
            publish.set_gauge("fleet_slo_burn_rate", fast_short,
                              slo=slo.name)
            publish.set_gauge("fleet_slo_alert", 1 if alerting else 0,
                              slo=slo.name)
            if alerting != track.firing:
                track.firing = alerting
                edge = {
                    "slo": slo.name,
                    "state": "firing" if alerting else "resolved",
                    "at_unix": now,
                    "burn_fast": (fast_short, fast_long),
                    "burn_slow": (slow_short, slow_long),
                    "pair": ("fast" if fast_hit else
                             "slow" if slow_hit else None),
                }
                edges.append(edge)
                self._events.append(edge)
                if alerting:
                    # fleet-wide ring dump: freeze what every process was
                    # doing the moment the budget started burning
                    flight_dump("slo_burn", slo=slo.name,
                                burn_rate=fast_short)
                if self._on_alert is not None:
                    self._on_alert(edge)
        return edges


class SLOEvalThread:
    """Sidecar thread ticking an :class:`SLOEngine` against a registry
    provider — how ``--slo`` runs inside serve/learn/fleetwatch without
    touching their event loops."""

    def __init__(self, engine: SLOEngine,
                 source: Callable[[], MetricsRegistry],
                 interval_s: float = 1.0) -> None:
        self._engine = engine
        self._source = source
        self._interval_s = float(interval_s)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.eval_errors = 0
        self.last_error: Optional[BaseException] = None

    @property
    def engine(self) -> SLOEngine:
        return self._engine

    def start(self) -> "SLOEvalThread":
        t = threading.Thread(target=self._run, name="slo-eval", daemon=True)
        self._thread = t
        t.start()
        return self

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self._engine.evaluate(self._source())
            except Exception as e:
                # keep the sidecar alive (obs must not kill the process
                # it observes), but leave evidence for the operator
                self.eval_errors += 1
                self.last_error = e
            self._stop.wait(self._interval_s)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
