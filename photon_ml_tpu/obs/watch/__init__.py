"""photonwatch: the fleet-global metrics plane.

Per-process observability (PR 5's registry/tracer, PR 15's pulse) answers
"what is THIS process doing"; photonwatch answers the questions that only
exist across the constellation:

* :mod:`federation` — each process exports its ``MetricsRegistry`` as a
  delta-compressed stream (``{"cmd": "watch"}`` on the serving socket, the
  ``/watchz`` HTTP route for pull), and a :class:`FleetView` merges N
  labeled snapshots into one global registry with staleness tracking.
* :mod:`slo` — declarative objectives evaluated as multi-window burn
  rates, publishing ``fleet_slo_burn_rate{slo=}`` gauges, latching alerts,
  and dumping the flight recorder on each burn edge.
* :mod:`attribution` — span-aligned device-vs-host time split for the XLA
  execute sites (``serve.execute``, ``solve.bucket``), exported as
  ``xla_device_seconds{site=}`` and stamped into the Chrome trace.
"""

from photon_ml_tpu.obs.watch.federation import (  # noqa: F401
    DeltaExporter,
    FleetView,
    apply_frame,
)
from photon_ml_tpu.obs.watch.slo import (  # noqa: F401
    SLO,
    SLOEngine,
    SLOEvalThread,
    load_slos,
)
from photon_ml_tpu.obs.watch.attribution import (  # noqa: F401
    attribute,
    attribution_enabled,
    disable_attribution,
    enable_attribution,
    set_device_timer,
)
