"""Metrics federation: delta-compressed registry export + fleet merge.

Wire unit
---------
A **frame** is what one process ships to one subscriber:

.. code-block:: python

    {"seq": 3, "full": False, "label": "replica-1", "at_unix": 1723...,
     "counters":   [[name, [[k, v], ...], value], ...],
     "gauges":     [[name, [[k, v], ...], value], ...],
     "histograms": [[name, [[k, v], ...], {bounds, counts, ...}], ...]}

Frame 1 is always the full registry; later frames carry only series whose
value changed since the last frame (registries never delete series, so
there are no tombstones).  Delta state is **per subscriber** — each
``{"cmd": "watch"}`` connection gets its own :class:`DeltaExporter`; the
``/watchz`` HTTP route is stateless and always serves a full state.

Merge semantics (:class:`FleetView`)
------------------------------------
* counters: summed across processes (they are rates of the same event),
* gauges: kept per process under an added ``process=<label>`` label (a
  gauge is a statement about one process; summing queue depths across
  owner and replica would be a lie),
* histograms: bucket-merged into one series when every process shares the
  family's bin ladder (the fixed-bin design exists for this); on a ladder
  mismatch the family degrades to per-process series, also
  ``process``-labeled, so nothing is silently dropped.

Each source carries a freshness timestamp; :meth:`FleetView.fleet_snapshot`
reports per-process age so ``/fleetz`` consumers can spot a wedged or
partitioned exporter before trusting the merged numbers.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

from photon_ml_tpu.obs.registry import (
    LabelKey,
    LatencyHistogram,
    MetricsRegistry,
    Series,
)

# series key inside exporter/ingest state: ("c"|"g"|"h", name, label_key)
_Key = Tuple[str, str, LabelKey]


def _decode_labels(pairs: List[List[str]]) -> LabelKey:
    return tuple((str(k), str(v)) for k, v in pairs)


class DeltaExporter:
    """Per-subscriber delta compression over ``registry.export_state()``.

    Holds the last-sent value of every series; ``frame()`` diffs the live
    registry against it.  Histogram change detection keys on ``(count,
    total)`` — a histogram that recorded anything moved both.
    """

    def __init__(self, registry: MetricsRegistry,
                 label: Optional[str] = None) -> None:
        self._registry = registry
        self._label = label
        self._seq = 0
        self._last: Dict[_Key, object] = {}

    def frame(self) -> dict:
        state = self._registry.export_state()
        self._seq += 1
        full = self._seq == 1
        out = {
            "seq": self._seq,
            "full": full,
            "at_unix": time.time(),
            "counters": [],
            "gauges": [],
            "histograms": [],
        }
        if self._label is not None:
            out["label"] = self._label
        for kind, field in (("c", "counters"), ("g", "gauges")):
            for name, pairs, value in state[field]:
                key = (kind, name, _decode_labels(pairs))
                if full or self._last.get(key) != value:
                    self._last[key] = value
                    out[field].append([name, pairs, value])
        for name, pairs, hist_state in state["histograms"]:
            key = ("h", name, _decode_labels(pairs))
            mark = (hist_state["count"], hist_state["total"])
            if full or self._last.get(key) != mark:
                self._last[key] = mark
                out["histograms"].append([name, pairs, hist_state])
        return out


def apply_frame(state: Dict[_Key, object], frame: dict) -> None:
    """Fold one frame (or a bare ``export_state()`` dump) into a flat
    per-process series dict — the FleetView ingest primitive."""
    for name, pairs, value in frame.get("counters", ()):
        state[("c", name, _decode_labels(pairs))] = value
    for name, pairs, value in frame.get("gauges", ()):
        state[("g", name, _decode_labels(pairs))] = value
    for name, pairs, hist_state in frame.get("histograms", ()):
        state[("h", name, _decode_labels(pairs))] = hist_state


class _Process:
    """One federated source: its series state and freshness bookkeeping."""

    def __init__(self, label: str) -> None:
        self.label = label
        self.series: Dict[_Key, object] = {}
        self.last_seq = 0
        self.frames = 0
        self.resyncs = 0
        self.last_at: Optional[float] = None     # exporter's at_unix
        self.last_seen: Optional[float] = None   # local ingest time


class FleetView:
    """Merges N labeled process snapshots into one global registry.

    ``ingest`` accepts delta frames (from a ``watch`` subscription) or full
    ``export_state`` dumps (from ``/watchz`` pulls); a sequence gap on a
    delta stream marks the source for resync and drops the frame rather
    than merging a hole.  ``registry`` is a long-lived
    :class:`MetricsRegistry` rebuilt in place on each ``refresh()``, so a
    metrics endpoint can hold it once and serve the merged view forever.
    """

    def __init__(self, stale_after_s: float = 5.0) -> None:
        self._lock = threading.Lock()
        self._procs: Dict[str, _Process] = {}
        self._stale_after_s = float(stale_after_s)
        self.registry = MetricsRegistry()

    # -- ingest ------------------------------------------------------------
    def ingest(self, label: str, frame: dict,
               at: Optional[float] = None) -> bool:
        """Apply one frame from process ``label``.  Returns False when a
        delta frame arrived with a sequence gap (caller should re-subscribe
        to get a fresh full frame)."""
        now = time.time() if at is None else at
        with self._lock:
            proc = self._procs.get(label)
            if proc is None:
                proc = self._procs[label] = _Process(label)
            seq = int(frame.get("seq", 0))
            full = bool(frame.get("full", seq == 0))
            if full:
                proc.series = {}
            elif seq and seq != proc.last_seq + 1:
                proc.resyncs += 1
                proc.last_seq = 0
                return False
            apply_frame(proc.series, frame)
            proc.last_seq = seq
            proc.frames += 1
            proc.last_at = float(frame.get("at_unix", now))
            proc.last_seen = now
        self.refresh()
        return True

    def forget(self, label: str) -> None:
        with self._lock:
            self._procs.pop(label, None)
        self.refresh()

    # -- merge -------------------------------------------------------------
    def refresh(self) -> None:
        """Rebuild the merged registry from current per-process state."""
        counters: Dict[Series, float] = {}
        gauges: Dict[Series, float] = {}
        # histogram families first group by series key so the bounds check
        # sees every contributing process before deciding merge vs degrade
        hist_groups: Dict[Tuple[str, LabelKey],
                          List[Tuple[str, dict]]] = {}
        with self._lock:
            procs = [(p.label, dict(p.series))
                     for p in self._procs.values()]
        for label, series in procs:
            for (kind, name, lk), value in series.items():
                if kind == "c":
                    key = (name, lk)
                    counters[key] = counters.get(key, 0) + value
                elif kind == "g":
                    relabeled = tuple(sorted(lk + (("process", label),)))
                    gauges[(name, relabeled)] = value
                else:
                    hist_groups.setdefault((name, lk), []).append(
                        (label, value))
        histograms: Dict[Series, LatencyHistogram] = {}
        for (name, lk), members in hist_groups.items():
            ladders = {tuple(st["bounds"]) for _, st in members}
            if len(ladders) == 1:
                merged = LatencyHistogram.from_state(members[0][1])
                for _, st in members[1:]:
                    merged.merge_state(st)
                histograms[(name, lk)] = merged
            else:
                for label, st in members:
                    relabeled = tuple(sorted(lk + (("process", label),)))
                    histograms[(name, relabeled)] = \
                        LatencyHistogram.from_state(st)
        self.registry.replace_content(counters, gauges, histograms)

    # -- reads -------------------------------------------------------------
    def processes(self) -> List[str]:
        with self._lock:
            return sorted(self._procs)

    def freshness(self, now: Optional[float] = None) -> Dict[str, float]:
        """Seconds since each source's last exporter-side timestamp."""
        now = time.time() if now is None else now
        with self._lock:
            return {p.label: (now - p.last_at) if p.last_at else float("inf")
                    for p in self._procs.values()}

    def fleet_snapshot(self, now: Optional[float] = None) -> dict:
        """The ``/fleetz`` payload: merged series plus per-source health."""
        now = time.time() if now is None else now
        with self._lock:
            sources = {
                p.label: {
                    "frames": p.frames,
                    "resyncs": p.resyncs,
                    "last_seq": p.last_seq,
                    "age_s": (now - p.last_at) if p.last_at else None,
                    "stale": (p.last_at is None
                              or now - p.last_at > self._stale_after_s),
                }
                for p in self._procs.values()
            }
        return {
            "processes": len(sources),
            "stale_after_s": self._stale_after_s,
            "sources": dict(sorted(sources.items())),
            "metrics": self.registry.snapshot(),
        }
