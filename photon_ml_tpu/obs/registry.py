"""Unified metrics registry: counters, gauges, fixed-bin histograms.

Photon ML reference counterpart: none — the reference logs Timed{} phase
durations as text.  Here every component (training descent, the serving
stack, the JAX runtime probe) reports into ONE thread-safe registry with
label support (``requests_total{bucket="64"}``), exported two ways: a JSON
snapshot (benches, the ``{"cmd": "metrics"}`` wire command) and Prometheus
text exposition (scrapers).  ``serving.metrics.ServingMetrics`` is a thin
facade over this registry that preserves its PR-4 ``snapshot()`` wire
format, so bench history stays comparable.

Series identity: ``(name, sorted(labels.items()))`` — label keyword order
at the call site never splits a series (``inc("x", a="1", b="2")`` and
``inc("x", b="2", a="1")`` are the same counter).

Locking: one registry lock around every read-modify-write; histogram
recording mutates the histogram under the same lock (fixed bins, O(1), no
allocation), so concurrent scorers, the swap thread, and exporters
interleave safely.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Dict, Iterable, List, Optional, Tuple

from photon_ml_tpu.obs import trace as _trace

LabelKey = Tuple[Tuple[str, str], ...]
Series = Tuple[str, LabelKey]

# Log-spaced histogram bin upper bounds: 1us .. ~67s, factor 2 per bin.
# Fixed bins (not reservoirs) so concurrent recording is O(1),
# allocation-free, and snapshots are mergeable across processes.
_BOUNDS = tuple(1e-6 * (2.0 ** i) for i in range(27))

# Per-FAMILY bound overrides (metric name -> bin upper bounds), applied when
# a histogram series of that family is first created.  Process-global, not
# per-registry: a family's bin layout is a property of WHAT is measured
# (solve latencies live in ms..minutes, span guards in ns..µs), and it must
# survive the registry swaps tests/benches do.  Existing series keep the
# bins they were created with — rebinning live counts would corrupt them.
_family_bounds: Dict[str, Tuple[float, ...]] = {}


def set_family_bounds(name: str, bounds: Iterable[float]) -> None:
    """Override the fixed-bin upper bounds for every FUTURE histogram series
    of family ``name`` (the carried-over photonscope follow-on): callers
    whose latency distribution doesn't fit the default 1µs..67s ladder
    register a sane one once at import time.  Bounds are sorted ascending;
    values above the last bound land in the +Inf bucket as usual."""
    _family_bounds[name] = tuple(sorted(float(b) for b in bounds))


def family_bounds(name: str) -> Tuple[float, ...]:
    """The bin bounds a new series of ``name`` would get."""
    return _family_bounds.get(name, _BOUNDS)


# Histogram exemplars (photonpulse): when enabled, each latency bucket
# remembers the trace id of the most recent sample that landed in it, so a
# scraper's "what made p99 spike?" resolves to a concrete merged-timeline
# trace.  OFF by default — the flag gates both the per-observe work and the
# exposition suffix, keeping the existing Prometheus output byte-stable.
_exemplars_enabled = False


def enable_exemplars(on: bool = True) -> None:
    """Process-wide switch for per-bucket trace-id exemplars."""
    global _exemplars_enabled
    _exemplars_enabled = bool(on)


def exemplars_enabled() -> bool:
    return _exemplars_enabled


class LatencyHistogram:
    """Fixed-bin latency histogram with percentile estimates.

    Percentiles interpolate inside the containing bin (log-linear would be
    marginally better; linear keeps the math obvious and the error is
    bounded by one 2x bin).  ``bounds`` default to the module ladder;
    families registered via ``set_family_bounds`` get their own.
    """

    def __init__(self, bounds: Optional[Tuple[float, ...]] = None) -> None:
        self.bounds = _BOUNDS if bounds is None else tuple(bounds)
        self.counts = [0] * (len(self.bounds) + 1)
        self.count = 0
        self.total = 0.0
        self.min = float("inf")
        self.max = 0.0
        # per-bucket (trace_id, seconds) of the latest exemplar-eligible
        # sample; allocated lazily on the first one so histograms stay
        # allocation-free with exemplars off (the common case)
        self.exemplars: Optional[List[Optional[Tuple[str, float]]]] = None

    def record(self, seconds: float) -> int:
        """Record one sample; returns the bin index it landed in (the
        exemplar hook keys on it)."""
        bounds = self.bounds
        lo, hi = 0, len(bounds)
        while lo < hi:  # first bin whose bound >= seconds
            mid = (lo + hi) // 2
            if bounds[mid] < seconds:
                lo = mid + 1
            else:
                hi = mid
        self.counts[lo] += 1
        self.count += 1
        self.total += seconds
        self.min = min(self.min, seconds)
        self.max = max(self.max, seconds)
        return lo

    def note_exemplar(self, bin_index: int, trace_id: str,
                      seconds: float) -> None:
        if self.exemplars is None:
            self.exemplars = [None] * len(self.counts)
        self.exemplars[bin_index] = (trace_id, seconds)

    def percentile(self, p: float) -> float:
        if self.count == 0:
            return 0.0
        bounds = self.bounds
        target = p * self.count
        seen = 0
        for i, c in enumerate(self.counts):
            if seen + c >= target and c > 0:
                hi = bounds[i] if i < len(bounds) else self.max
                lo = bounds[i - 1] if i > 0 else 0.0
                frac = (target - seen) / c
                return min(lo + frac * (hi - lo), self.max)
            seen += c
        return self.max

    def snapshot(self) -> dict:
        return {
            "count": self.count,
            "mean_s": self.total / self.count if self.count else 0.0,
            "p50_s": self.percentile(0.50),
            "p99_s": self.percentile(0.99),
            "min_s": self.min if self.count else 0.0,
            "max_s": self.max,
        }

    def to_state(self) -> dict:
        """Lossless JSON-safe form (full bucket counts, not percentiles) —
        the federation wire unit.  ``min`` is reported as 0.0 when empty so
        the payload never carries a non-JSON ``inf``."""
        return {
            "bounds": list(self.bounds),
            "counts": list(self.counts),
            "count": self.count,
            "total": self.total,
            "min": self.min if self.count else 0.0,
            "max": self.max,
        }

    @classmethod
    def from_state(cls, state: dict) -> "LatencyHistogram":
        h = cls(tuple(state["bounds"]))
        h.counts = [int(c) for c in state["counts"]]
        h.count = int(state["count"])
        h.total = float(state["total"])
        h.min = float(state["min"]) if h.count else float("inf")
        h.max = float(state["max"])
        return h

    def merge_state(self, state: dict) -> None:
        """Fold another process's bucket counts into this histogram.  Only
        legal when the bin ladders match — the caller (FleetView) checks and
        falls back to per-process series otherwise."""
        if tuple(state["bounds"]) != self.bounds:
            raise ValueError("histogram bounds mismatch")
        for i, c in enumerate(state["counts"]):
            self.counts[i] += int(c)
        self.count += int(state["count"])
        self.total += float(state["total"])
        if int(state["count"]):
            self.min = min(self.min, float(state["min"]))
            self.max = max(self.max, float(state["max"]))


def _label_key(labels: Dict[str, object]) -> LabelKey:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


def series_name(name: str, labels: LabelKey) -> str:
    """Canonical display form: ``name{a="1",b="2"}`` (sorted labels)."""
    if not labels:
        return name
    inner = ",".join(f'{k}="{v}"' for k, v in labels)
    return f"{name}{{{inner}}}"


def _prom_name(name: str) -> str:
    """Prometheus metric names allow ``[a-zA-Z_:][a-zA-Z0-9_:]*``."""
    out = [c if (c.isalnum() or c in "_:") else "_" for c in name]
    if out and out[0].isdigit():
        out.insert(0, "_")
    return "".join(out)


def _prom_escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _prom_labels(labels: LabelKey, extra: Tuple[Tuple[str, str], ...] = ()
                 ) -> str:
    pairs = tuple(labels) + tuple(extra)
    if not pairs:
        return ""
    inner = ",".join(f'{_prom_name(k)}="{_prom_escape(v)}"'
                     for k, v in pairs)
    return f"{{{inner}}}"


def _fmt(v: float) -> str:
    """Exposition value: integers without a trailing .0, floats as repr."""
    if isinstance(v, int):
        return str(v)
    return repr(float(v))


class MetricsRegistry:
    """Thread-safe counter/gauge/histogram registry (see module docstring)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[Series, float] = {}
        self._gauges: Dict[Series, float] = {}
        self._histograms: Dict[Series, LatencyHistogram] = {}

    # -- mutators ----------------------------------------------------------
    def inc(self, name: str, n: float = 1, **labels) -> None:
        """Monotonic counter add (ints stay ints for JSON fidelity)."""
        key = (name, _label_key(labels))
        with self._lock:
            self._counters[key] = self._counters.get(key, 0) + n

    def set_gauge(self, name: str, value: float, **labels) -> None:
        key = (name, _label_key(labels))
        with self._lock:
            self._gauges[key] = value

    def add_gauge(self, name: str, delta: float, **labels) -> None:
        """Accumulating gauge (cumulative phase seconds and kin)."""
        key = (name, _label_key(labels))
        with self._lock:
            self._gauges[key] = self._gauges.get(key, 0.0) + delta

    def observe(self, name: str, seconds: float, **labels) -> None:
        key = (name, _label_key(labels))
        with self._lock:
            h = self._histograms.get(key)
            if h is None:
                h = self._histograms[key] = LatencyHistogram(
                    _family_bounds.get(name))
            bin_index = h.record(seconds)
            if _exemplars_enabled:
                ctx = _trace.current_context()
                if ctx is not None:
                    h.note_exemplar(bin_index, ctx[0], seconds)

    # -- reads -------------------------------------------------------------
    def counter(self, name: str, **labels) -> float:
        with self._lock:
            return self._counters.get((name, _label_key(labels)), 0)

    def gauge(self, name: str, **labels) -> Optional[float]:
        with self._lock:
            return self._gauges.get((name, _label_key(labels)))

    def histogram_snapshot(self, name: str, **labels) -> Optional[dict]:
        with self._lock:
            h = self._histograms.get((name, _label_key(labels)))
            return None if h is None else h.snapshot()

    def snapshot_raw_counters(self) -> List[Tuple[Series, float]]:
        """Every counter series as ``((name, labels), value)`` — the
        structured form facades (serving.ServingMetrics) rebuild their wire
        views from."""
        with self._lock:
            return list(self._counters.items())

    def counter_series(self, name: str) -> Dict[LabelKey, float]:
        """Every label combination recorded under one counter family."""
        with self._lock:
            return {lk: v for (n, lk), v in self._counters.items()
                    if n == name}

    def gauge_series(self, name: str) -> Dict[LabelKey, float]:
        with self._lock:
            return {lk: v for (n, lk), v in self._gauges.items() if n == name}

    def histogram_series(self, name: str) -> Dict[LabelKey, dict]:
        with self._lock:  # snapshot inside the lock: no torn count/total
            return {lk: h.snapshot()
                    for (n, lk), h in self._histograms.items() if n == name}

    # -- exports -----------------------------------------------------------
    def snapshot(self) -> dict:
        """JSON-ready view: ``{"counters": {series: v}, "gauges": ...,
        "histograms": {series: {count, mean_s, p50_s, ...}}}`` with series
        rendered as ``name{label="v"}`` strings, sorted."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            hists = [(k, h.snapshot()) for k, h in self._histograms.items()]
        return {
            "counters": {series_name(n, lk): v
                         for (n, lk), v in sorted(counters.items())},
            "gauges": {series_name(n, lk): v
                       for (n, lk), v in sorted(gauges.items())},
            "histograms": {series_name(n, lk): snap
                           for (n, lk), snap in sorted(hists,
                                                       key=lambda e: e[0])},
        }

    def to_json(self, indent: Optional[int] = None) -> str:
        return json.dumps(self.snapshot(), indent=indent)

    def to_prometheus(self) -> str:
        """Prometheus text exposition (format 0.0.4).

        Counters/gauges one sample per series; histograms expose the
        cumulative ``_bucket{le=...}`` ladder over the fixed bins plus
        ``_sum``/``_count``, which is exactly what the fixed-bin layout was
        chosen for (mergeable, O(1) record)."""
        with self._lock:
            counters = sorted(self._counters.items())
            gauges = sorted(self._gauges.items())
            hists = sorted(((k, h.bounds, list(h.counts), h.total, h.count,
                             list(h.exemplars) if h.exemplars else None)
                            for k, h in self._histograms.items()),
                           key=lambda e: e[0])
        want_exemplars = _exemplars_enabled
        lines: List[str] = []

        def _family(items: Iterable, kind: str) -> None:
            seen = None
            for (name, labels), value in items:
                pname = _prom_name(name)
                if pname != seen:
                    lines.append(f"# TYPE {pname} {kind}")
                    seen = pname
                lines.append(f"{pname}{_prom_labels(labels)} {_fmt(value)}")

        _family(counters, "counter")
        _family(gauges, "gauge")
        seen = None
        for (name, labels), bounds, counts, total, count, exemplars in hists:
            pname = _prom_name(name)
            if pname != seen:
                lines.append(f"# TYPE {pname} histogram")
                seen = pname
            cum = 0
            for i, (bound, c) in enumerate(zip(bounds, counts)):
                cum += c
                line = (f"{pname}_bucket"
                        f"{_prom_labels(labels, (('le', repr(bound)),))}"
                        f" {cum}")
                if want_exemplars and exemplars and exemplars[i]:
                    # OpenMetrics exemplar suffix: the trace id of the
                    # latest sample that landed in this bucket
                    tid, secs = exemplars[i]
                    line += f' # {{trace_id="{tid}"}} {repr(float(secs))}'
                lines.append(line)
            lines.append(f"{pname}_bucket"
                         f"{_prom_labels(labels, (('le', '+Inf'),))} {count}")
            lines.append(f"{pname}_sum{_prom_labels(labels)} {_fmt(total)}")
            lines.append(f"{pname}_count{_prom_labels(labels)} {count}")
        return "\n".join(lines) + "\n"

    def to_openmetrics(self) -> str:
        """OpenMetrics 1.0.0 text exposition — the format exemplars are
        actually SPECIFIED in (Prometheus 0.0.4 parsers merely tolerate the
        suffix; an OpenMetrics scraper ingests it and links the trace id).

        Differences from :meth:`to_prometheus`: counter samples carry the
        mandatory ``_total`` suffix (family name loses it in the TYPE line),
        exemplars attach whenever the histogram recorded one (the
        ``enable_exemplars`` switch gates recording, not exposition), and
        the stream ends with the required ``# EOF`` terminator."""
        with self._lock:
            counters = sorted(self._counters.items())
            gauges = sorted(self._gauges.items())
            hists = sorted(((k, h.bounds, list(h.counts), h.total, h.count,
                             list(h.exemplars) if h.exemplars else None)
                            for k, h in self._histograms.items()),
                           key=lambda e: e[0])
        lines: List[str] = []

        def _exemplar_suffix(ex) -> str:
            if not ex:
                return ""
            tid, secs = ex
            return f' # {{trace_id="{_prom_escape(tid)}"}} ' \
                   f'{repr(float(secs))}'

        seen = None
        for (name, labels), value in counters:
            fam = _prom_name(name)
            if fam.endswith("_total"):
                fam = fam[:-len("_total")]
            if fam != seen:
                lines.append(f"# TYPE {fam} counter")
                seen = fam
            lines.append(f"{fam}_total{_prom_labels(labels)} {_fmt(value)}")
        seen = None
        for (name, labels), value in gauges:
            fam = _prom_name(name)
            if fam != seen:
                lines.append(f"# TYPE {fam} gauge")
                seen = fam
            lines.append(f"{fam}{_prom_labels(labels)} {_fmt(value)}")
        seen = None
        for (name, labels), bounds, counts, total, count, exemplars in hists:
            fam = _prom_name(name)
            if fam != seen:
                lines.append(f"# TYPE {fam} histogram")
                seen = fam
            cum = 0
            for i, (bound, c) in enumerate(zip(bounds, counts)):
                cum += c
                lines.append(
                    f"{fam}_bucket"
                    f"{_prom_labels(labels, (('le', repr(bound)),))} {cum}"
                    + _exemplar_suffix(exemplars[i] if exemplars else None))
            lines.append(
                f"{fam}_bucket{_prom_labels(labels, (('le', '+Inf'),))} "
                f"{count}"
                + _exemplar_suffix(exemplars[len(bounds)] if exemplars
                                   else None))
            lines.append(f"{fam}_sum{_prom_labels(labels)} {_fmt(total)}")
            lines.append(f"{fam}_count{_prom_labels(labels)} {count}")
        lines.append("# EOF")
        return "\n".join(lines) + "\n"

    def export(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.to_json(indent=2))

    # -- federation (obs/watch) --------------------------------------------
    def export_state(self) -> dict:
        """Every series in structured, MERGEABLE form: labels as pair lists
        (not rendered strings) and histograms as full fixed-bin bucket
        counts.  This is what a :class:`~photon_ml_tpu.obs.watch.FleetView`
        needs to sum counters, re-label gauges, and bucket-merge histograms
        across processes — ``snapshot()`` cannot serve that role because it
        collapses histograms to percentile summaries."""
        with self._lock:
            counters = sorted(self._counters.items())
            gauges = sorted(self._gauges.items())
            hists = sorted((k, h.to_state())
                           for k, h in self._histograms.items())
        return {
            "counters": [[n, [list(p) for p in lk], v]
                         for (n, lk), v in counters],
            "gauges": [[n, [list(p) for p in lk], v]
                       for (n, lk), v in gauges],
            "histograms": [[n, [list(p) for p in lk], st]
                           for (n, lk), st in hists],
        }

    def put_counter(self, name: str, labels: LabelKey, value: float) -> None:
        """Install/overwrite one counter series by structured key — the
        FleetView merge path, not an instrument-site mutator (use ``inc``)."""
        with self._lock:
            self._counters[(name, tuple(labels))] = value

    def put_gauge(self, name: str, labels: LabelKey, value: float) -> None:
        with self._lock:
            self._gauges[(name, tuple(labels))] = value

    def put_histogram(self, name: str, labels: LabelKey,
                      hist: LatencyHistogram) -> None:
        with self._lock:
            self._histograms[(name, tuple(labels))] = hist

    def histogram_state_series(self, name: str) -> Dict[LabelKey, dict]:
        """Raw bucket state per label set for one family — what the SLO
        engine's latency ladders read (``histogram_series`` returns
        percentile summaries, which can't answer "how many observations
        exceeded the threshold bound")."""
        with self._lock:
            return {lk: h.to_state()
                    for (n, lk), h in self._histograms.items() if n == name}

    def replace_content(self, counters: Dict[Series, float],
                        gauges: Dict[Series, float],
                        histograms: Dict[Series, LatencyHistogram]) -> None:
        """Atomically replace every series — the FleetView merge target
        rebuilds the same registry object in place so long-lived readers
        (the /fleetz endpoint's facade) never hold a stale reference."""
        with self._lock:
            self._counters = dict(counters)
            self._gauges = dict(gauges)
            self._histograms = dict(histograms)


# ---------------------------------------------------------------------------
# build info / process identity
# ---------------------------------------------------------------------------
# Stamped once at import so every registry in the process reports the same
# start time regardless of when a role wires its metrics surface up.
_PROCESS_START_UNIX = time.time()


def process_start_time() -> float:
    """Unix time this process imported the metrics module."""
    return _PROCESS_START_UNIX


def export_build_info(registry: Optional["MetricsRegistry"] = None,
                      role: str = "unknown",
                      version: Optional[str] = None) -> None:
    """Export ``photon_build_info{version=,role=}`` (constant 1) and
    ``process_start_time_seconds`` into ``registry`` (process default when
    None) so federation can label and age each source it merges."""
    if registry is None:
        registry = get_registry()
    if version is None:
        from photon_ml_tpu import __version__ as version  # avoid import cycle
    registry.set_gauge("photon_build_info", 1, version=version, role=role)
    registry.set_gauge("process_start_time_seconds", _PROCESS_START_UNIX)


# ---------------------------------------------------------------------------
# process-default registry
# ---------------------------------------------------------------------------
_default = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _default


def set_registry(registry: MetricsRegistry) -> MetricsRegistry:
    """Swap the process-default registry; returns the previous one."""
    global _default
    prev, _default = _default, registry
    return prev
