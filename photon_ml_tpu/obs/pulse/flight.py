"""photonpulse flight recorder: dump the trace ring when health degrades.

The photonscope ring is a bounded always-on window over the last N spans —
exactly the evidence an operator needs after a chaos-plane incident, except
that by the time anyone runs ``{"cmd": "trace"}`` the interesting spans
have been lapped.  The flight recorder closes that gap: degradation
triggers (a ``HealthState`` condition transitioning out of ok, a
``Watchdog`` stall, the admission shed-latch engaging) synchronously
snapshot the ring to a bounded on-disk spool, so the spans *surrounding*
the degradation survive for post-hoc merge and inspection.

Bounds, because an unattended flapping trigger must not fill a disk:

  - ``min_interval_s`` rate-limits dumps globally (a degrading process
    tends to fire many triggers at once — one dump covers them all);
  - ``max_bytes`` caps the spool — oldest dumps are deleted first;
  - each dump is one self-contained JSON file: reason, trigger detail,
    wall-clock time, and the full Chrome export (which carries the
    process label and clock offsets, so spooled dumps merge like live
    exports).

Retrieval: ``{"cmd": "flight"}`` on the frontend/stdio wire and
``GET /flightz`` on the metrics endpoint both return ``snapshot()`` — the
dump index plus the latest dump inline.

The module-level ``flight_dump()`` is the trigger entry point: one None
check when no recorder is installed, mirroring the chaos injector's
disabled-cost discipline.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from typing import List, Optional

from photon_ml_tpu.obs import trace as _trace

_REASON_RE = re.compile(r"[^A-Za-z0-9_.-]+")


class FlightRecorder:
    """Bounded on-disk spool of trace-ring snapshots (see module doc)."""

    def __init__(self, spool_dir: str, max_bytes: int = 16 << 20,
                 min_interval_s: float = 0.5):
        self.spool_dir = spool_dir
        self.max_bytes = int(max_bytes)
        self.min_interval_s = float(min_interval_s)
        self._lock = threading.Lock()
        self._seq = 0
        self._last_dump = 0.0
        os.makedirs(spool_dir, exist_ok=True)

    # -- dumping -----------------------------------------------------------
    def dump(self, reason: str, **detail) -> Optional[str]:
        """Snapshot the ring now; returns the dump path, or None when
        rate-limited.  Never raises — a sick disk must not take the
        triggering health path down with it."""
        now = time.monotonic()
        with self._lock:
            if now - self._last_dump < self.min_interval_s:
                return None
            self._last_dump = now
            self._seq += 1
            seq = self._seq
        slug = _REASON_RE.sub("-", reason)[:48] or "unknown"
        name = f"flight-{int(time.time() * 1000):013d}-{seq:04d}-{slug}.json"
        path = os.path.join(self.spool_dir, name)
        payload = {
            "reason": reason,
            "detail": detail,
            "at_unix": time.time(),
            "trace": _trace.get_tracer().chrome_trace(),
        }
        try:
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(payload, f)
            os.replace(tmp, path)
        except OSError:
            return None
        self._enforce_bound()
        return path

    def _enforce_bound(self) -> None:
        dumps = self._dump_files()
        total = sum(sz for _, sz in dumps)
        # oldest first (lexicographic name order embeds the ms timestamp)
        for name, sz in dumps:
            if total <= self.max_bytes:
                break
            try:
                os.remove(os.path.join(self.spool_dir, name))
                total -= sz
            except OSError:
                pass

    def _dump_files(self) -> List[tuple]:
        out = []
        try:
            names = sorted(os.listdir(self.spool_dir))
        except OSError:
            return out
        for name in names:
            if not (name.startswith("flight-") and name.endswith(".json")):
                continue
            try:
                out.append((name,
                            os.path.getsize(os.path.join(self.spool_dir,
                                                         name))))
            except OSError:
                continue
        return out

    # -- retrieval ---------------------------------------------------------
    def index(self) -> List[dict]:
        """One entry per spooled dump, oldest first: name/reason/bytes."""
        out = []
        for name, sz in self._dump_files():
            parts = name[len("flight-"):-len(".json")].split("-", 2)
            out.append({"name": name, "bytes": sz,
                        "reason": parts[2] if len(parts) == 3 else ""})
        return out

    def latest(self) -> Optional[dict]:
        """The newest dump, parsed; None when the spool is empty or the
        newest file is unreadable/torn."""
        dumps = self._dump_files()
        if not dumps:
            return None
        path = os.path.join(self.spool_dir, dumps[-1][0])
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def snapshot(self) -> dict:
        """Wire form for ``{"cmd": "flight"}`` / ``GET /flightz``."""
        return {"spool_dir": self.spool_dir, "dumps": self.index(),
                "latest": self.latest()}


# ---------------------------------------------------------------------------
# process-default recorder: the trigger entry point
# ---------------------------------------------------------------------------
_recorder: Optional[FlightRecorder] = None


def get_flight() -> Optional[FlightRecorder]:
    return _recorder


def set_flight(recorder: Optional[FlightRecorder]
               ) -> Optional[FlightRecorder]:
    """Install (or clear) the process-default recorder; returns previous."""
    global _recorder
    prev, _recorder = _recorder, recorder
    return prev


def flight_dump(reason: str, **detail) -> Optional[str]:
    """Trigger a dump if a recorder is installed: one None check when the
    flight recorder is not configured."""
    r = _recorder
    if r is None:
        return None
    return r.dump(reason, **detail)
