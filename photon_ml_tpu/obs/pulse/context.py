"""photonpulse trace context: mint, bind, and carry trace ids across wires.

Photon ML reference counterpart: none — the reference's Timed{} blocks are
process-local.  The distributed serving stack needs what Dapper-style
tracers call *context propagation*: a compact id minted once at the edge
(frontend admission, or the owner's publish) and carried on every hop the
request or delta takes, so the per-process photonscope rings can be joined
into one causal timeline by ``tools/tracemerge.py``.

A context is an opaque ``(trace_id, origin)`` pair of short hex tokens:

  - ``trace_id`` (16 hex chars): names the whole causal trace — one served
    request, or one publish -> store-visible path;
  - ``origin`` (8 hex chars): names the hop that forwarded the context, so
    a downstream process can record which remote span handed it work.

Wire form is the single string ``"<trace_id>/<origin>"`` carried in a
``"tp"`` field on existing JSON lines (frontend requests, replication
delta frames).  Decoding is *strictly tolerant*: anything that is not
exactly a well-formed pair — wrong type, wrong length, non-hex, torn by a
crashed peer — decodes to ``None`` and the work proceeds untraced.  A
malformed trace header must never fail a request.

Binding uses the thread-local cell in ``obs.trace``: while bound, every
``span()``/``instant()`` the thread records carries ``trace=`` (and
``origin=``) attrs automatically, so existing call sites join the trace
without signature changes.  All entry points are gated by the caller on
``obs.enabled()`` — when tracing is off nothing mints, binds, or looks up,
preserving the one-boolean disabled cost ``bench.py --obs`` asserts.

The module also keeps a small bounded map from delta-log identity
``(generation, delta_version)`` to the context that published it: the owner
fills it at ``publish_delta`` time so the replication sender can stamp
outgoing frames, and the replica fills it from incoming frames so the
catch-up follower can mark the store-visible point under the same trace.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Optional, Tuple

from photon_ml_tpu.obs import trace as _trace

TraceContext = Tuple[str, str]

_TRACE_LEN = 16
_ORIGIN_LEN = 8
_HEX = set("0123456789abcdef")


def mint() -> TraceContext:
    """A fresh context: random 64-bit trace id, random 32-bit origin."""
    return (os.urandom(8).hex(), os.urandom(4).hex())


_sample_lock = threading.Lock()
_sample_counter = 0


def maybe_mint(sample_n: int) -> Optional[TraceContext]:
    """Sampled always-on minting: every ``sample_n``-th call mints, the rest
    return None.  The 1-in-N gate is a deterministic shared counter — not
    RNG — so a steady request stream yields an evenly spaced trace sample
    and tests can predict exactly which requests carry context.  The edge
    (frontend admission) calls this when tracing is enabled but the client
    sent no ``"tp"``, so production flight dumps always hold *some* traced
    requests without the cost of tracing every one.  ``sample_n <= 0``
    disables sampling; ``sample_n == 1`` mints for every request."""
    if sample_n <= 0:
        return None
    global _sample_counter
    with _sample_lock:
        _sample_counter += 1
        hit = _sample_counter % sample_n == 0
    return mint() if hit else None


def reset_sampling() -> None:
    """Tests: restart the 1-in-N counter so sampling is phase-deterministic."""
    global _sample_counter
    with _sample_lock:
        _sample_counter = 0


def current() -> Optional[TraceContext]:
    """This thread's bound context, or None."""
    ctx = _trace.current_context()
    return ctx if ctx is not None else None


class _Bound:
    """Context manager restoring the previous binding on exit.  Re-entrant
    and cheap: one thread-local store each way."""

    __slots__ = ("_ctx", "_prev")

    def __init__(self, ctx: Optional[TraceContext]):
        self._ctx = ctx

    def __enter__(self) -> Optional[TraceContext]:
        self._prev = _trace.set_context(self._ctx)
        return self._ctx

    def __exit__(self, *exc) -> bool:
        _trace.set_context(self._prev)
        return False


def bind(ctx: Optional[TraceContext]) -> _Bound:
    """``with bind(ctx):`` — spans/instants recorded by this thread inside
    the block carry the context.  ``bind(None)`` explicitly unbinds (a
    worker thread picking up unrelated work)."""
    return _Bound(ctx)


def to_wire(ctx: TraceContext) -> str:
    """Compact wire form: ``"<16-hex>/<8-hex>"``."""
    return f"{ctx[0]}/{ctx[1]}"


def from_wire(value: object) -> Optional[TraceContext]:
    """Decode a wire field back to a context; anything malformed (wrong
    type, torn, garbage) degrades to None — never raises."""
    if not isinstance(value, str) or len(value) != _TRACE_LEN + _ORIGIN_LEN + 1:
        return None
    tid, sep, origin = value.partition("/")
    if (not sep or len(tid) != _TRACE_LEN or len(origin) != _ORIGIN_LEN
            or not _HEX.issuperset(tid) or not _HEX.issuperset(origin)):
        return None
    return (tid, origin)


def forwarded(ctx: TraceContext) -> TraceContext:
    """The context to put on the wire for the next hop: same trace id, a
    fresh origin naming THIS hop as the forwarder."""
    return (ctx[0], os.urandom(4).hex())


# ---------------------------------------------------------------------------
# delta identity -> context map (bounded; owner and replica both use it)
# ---------------------------------------------------------------------------
_DELTA_MAP_CAP = 1024

_delta_lock = threading.Lock()
_delta_ctx: Dict[Tuple[int, int], TraceContext] = {}


def note_delta(identity: Tuple[int, int], ctx: Optional[TraceContext]) -> None:
    """Remember which context published/shipped delta ``identity``.  Bounded:
    oldest insertions are evicted (dict preserves insertion order)."""
    if ctx is None:
        return
    with _delta_lock:
        _delta_ctx[identity] = ctx
        while len(_delta_ctx) > _DELTA_MAP_CAP:
            _delta_ctx.pop(next(iter(_delta_ctx)))


def delta_ctx(identity: Tuple[int, int]) -> Optional[TraceContext]:
    with _delta_lock:
        return _delta_ctx.get(identity)


def clear_delta_ctx() -> None:
    """Tests: drop all remembered delta contexts."""
    with _delta_lock:
        _delta_ctx.clear()
