"""photonpulse: the distributed half of photonscope.

Photon ML reference counterpart: none — the reference is a single driver
process; its Timed{} output needs no alignment.  The serving stack built
in PRs 4-14 is a pod slice of cooperating processes (frontend/owner,
replicas, the online trainer), and a request or a published delta crosses
several of them.  photonpulse makes that crossing observable:

  - ``context``  — mint/bind/carry compact trace ids across the existing
    wire protocols; malformed wire contexts degrade to untraced;
  - ``clock``    — NTP-style offset estimation piggybacked on handshakes
    that already happen, exported with every Chrome trace;
  - ``merge``    — align + join per-process traces into one
    Perfetto-loadable pod-slice timeline (backs ``tools/tracemerge.py``);
  - ``flight``   — degradation-triggered ring dumps to a bounded on-disk
    spool, retrievable via ``{"cmd": "flight"}`` / ``GET /flightz``.

Everything is host-side stdlib; nothing here imports jax.  All hot-path
hooks preserve photonscope's discipline: one boolean (tracing disabled) or
one None check (no flight recorder) when off.

``configure(label)`` is the per-process entry point the CLIs call: it
names the process for Chrome exports ("frontend", "owner", "replica") and
installs the clock-offset export hook.
"""

from photon_ml_tpu.obs.pulse import clock  # noqa: F401
from photon_ml_tpu.obs.pulse.context import (TraceContext,  # noqa: F401
                                             bind, current, delta_ctx,
                                             forwarded, from_wire,
                                             maybe_mint, mint, note_delta,
                                             reset_sampling, to_wire)
from photon_ml_tpu.obs.pulse.flight import (FlightRecorder,  # noqa: F401
                                            flight_dump, get_flight,
                                            set_flight)
from photon_ml_tpu.obs.pulse.merge import (load_trace,  # noqa: F401
                                           merge_traces, spans_by_trace)
from photon_ml_tpu.obs.trace import (get_process_label,  # noqa: F401
                                     set_process_label)


def configure(label: str) -> None:
    """Name this process and wire clock offsets into Chrome exports."""
    set_process_label(label)
    clock.install_export_meta()
