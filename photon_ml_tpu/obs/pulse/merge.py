"""photonpulse merge: join per-process Chrome traces into one timeline.

Each process exports its photonscope ring with (a) ``process_name``
metadata and a stable pid, (b) ``otherData.clock`` — the NTP-style offsets
this process estimated against its named peers (``pulse.clock``), and
(c) ``trace=`` attrs stamped on every span recorded under a bound context
(``pulse.context``).  Those three are exactly what a merge needs:

  1. **align** — pick a reference process (the one every other process
     measured an offset against, e.g. the owner), chain offsets across at
     most a few hops, and shift every event onto the reference clock;
  2. **join** — bucket events by the trace ids in their args (``trace``
     for single-request spans, ``traces`` for batched spans like the
     engine flush that serve many requests at once);
  3. **emit** — one Perfetto-loadable Chrome trace with per-process rows
     (re-numbered pids so two processes that shared an OS pid across
     restarts cannot collide) and a ``trace_ids`` summary in
     ``otherData``.

Pure host-side JSON transforms — no jax, no sockets — so the same code
backs ``tools/tracemerge.py``, the e2e tests, and the merge-throughput
leg of ``bench.py --obs``.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence


def load_trace(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def _labels(traces: Sequence[dict]) -> List[str]:
    """One stable, unique label per input trace."""
    out: List[str] = []
    for i, t in enumerate(traces):
        label = (t.get("otherData") or {}).get("process_label") or f"p{i}"
        if label in out:
            label = f"{label}#{i}"
        out.append(label)
    return out


def _clock_shifts(traces: Sequence[dict], labels: List[str],
                  reference: Optional[str]) -> Dict[str, int]:
    """ns shift per label mapping its clock onto the reference's.

    Offsets are directed (``clock[peer] = peer_clock - my_clock``); the
    graph walks them in both directions so a replica that measured the
    owner aligns even though the owner measured nobody.

    A DISCONNECTED offset graph (two islands of processes that never
    exchanged clock pings — e.g. traces from two separate deployments
    merged after the fact) cannot be aligned onto one clock; pretending
    otherwise by zero-shifting the unreachable island would silently
    interleave unrelated timelines.  Instead the merge degrades: each
    extra component gets its OWN local reference (BFS from its first
    label), and a warning per component is smuggled out under
    ``__warnings__`` for ``otherData.clock_warnings`` /
    ``tools/tracemerge.py`` stderr.  Within a component, relative timing
    is still exact."""
    # adjacency: edge (a -> b, w) means t_b = t_a + w
    edges: Dict[str, List[tuple]] = {lb: [] for lb in labels}
    for lb, t in zip(labels, traces):
        clock = (t.get("otherData") or {}).get("clock") or {}
        for peer, est in clock.items():
            if peer not in edges or not isinstance(est, dict):
                continue
            try:
                off = int(est["offset_ns"])
            except (KeyError, TypeError, ValueError):
                continue
            edges[lb].append((peer, off))
            edges[peer].append((lb, -off))
    if reference is None or reference not in edges:
        # prefer the label others measured against but which measured no
        # one itself — the natural root (owner/frontend) of the exchange
        measured = {peer for t in traces
                    for peer in ((t.get("otherData") or {}).get("clock")
                                 or {})}
        roots = [lb for lb, t in zip(labels, traces)
                 if lb in measured
                 and not ((t.get("otherData") or {}).get("clock") or {})]
        reference = roots[0] if roots else labels[0]
    def _bfs(root: str) -> None:
        shifts[root] = shifts.get(root, 0)
        frontier = [root]
        while frontier:
            nxt = []
            for a in frontier:
                for b, w in edges[a]:
                    if b in shifts:
                        continue
                    # t_ref = t_a + shifts[a] and t_b = t_a + w
                    shifts[b] = shifts[a] - w
                    nxt.append(b)
            frontier = nxt

    shifts: Dict[str, int] = {reference: 0}
    _bfs(reference)
    warnings: List[str] = []
    component_refs = {reference: reference}
    for lb in labels:
        if lb in shifts:
            component_refs.setdefault(lb, reference)
            continue
        # disconnected component: align it to its own local reference
        before = set(shifts)
        _bfs(lb)
        members = sorted((set(shifts) - before) & set(labels))
        for m in members:
            component_refs[m] = lb
        warnings.append(
            f"clock-offset graph disconnected: {members} share no "
            f"measured peer with reference {reference!r}; aligned to "
            f"local reference {lb!r} instead (cross-component timing "
            "is NOT comparable)")
    shifts["__reference__"] = reference  # smuggled out; popped by caller
    shifts["__warnings__"] = warnings
    shifts["__component_refs__"] = component_refs
    return shifts


def _event_trace_ids(ev: dict) -> List[str]:
    args = ev.get("args") or {}
    ids = []
    t = args.get("trace")
    if isinstance(t, str):
        ids.append(t)
    for t in (args.get("traces") or ()):
        if isinstance(t, str) and t not in ids:
            ids.append(t)
    return ids


def merge_traces(traces: Sequence[dict],
                 reference: Optional[str] = None) -> dict:
    """Merge per-process Chrome traces into one aligned timeline."""
    labels = _labels(traces)
    shifts = _clock_shifts(traces, labels, reference)
    reference = shifts.pop("__reference__")
    clock_warnings = shifts.pop("__warnings__")
    component_refs = shifts.pop("__component_refs__")
    events: List[dict] = []
    processes: Dict[str, str] = {}
    trace_counts: Dict[str, int] = {}
    for i, (label, t) in enumerate(zip(labels, traces)):
        pid = i + 1
        processes[str(pid)] = label
        shift_us = shifts[label] / 1e3
        saw_process_name = False
        for ev in t.get("traceEvents", ()):
            ev = dict(ev)
            ev["pid"] = pid
            if ev.get("ph") == "M":
                if ev.get("name") == "process_name":
                    saw_process_name = True
                    ev = dict(ev, args={"name": label})
            else:
                ev["ts"] = ev.get("ts", 0) + shift_us
                for tid in _event_trace_ids(ev):
                    trace_counts[tid] = trace_counts.get(tid, 0) + 1
            events.append(ev)
        if not saw_process_name:
            events.append({"name": "process_name", "ph": "M", "pid": pid,
                           "tid": 0, "ts": 0, "args": {"name": label}})
    events.sort(key=lambda e: (e.get("ph") != "M", e.get("ts", 0)))
    return {
        "traceEvents": events,
        "displayTimeUnit": "ns",
        "otherData": {
            "merged_from": labels,
            "reference": reference,
            "offsets_ns": {lb: shifts[lb] for lb in labels},
            "processes": processes,
            "trace_ids": dict(sorted(trace_counts.items())),
            # degradation record: per-label local reference (== the global
            # reference when the offset graph was connected) and one
            # warning per disconnected component
            "component_references": component_refs,
            "clock_warnings": clock_warnings,
        },
    }


def spans_by_trace(merged: dict) -> Dict[str, List[dict]]:
    """Events of a merged trace bucketed by trace id (batched spans that
    serve several requests appear under each), each sorted by aligned
    start time."""
    out: Dict[str, List[dict]] = {}
    for ev in merged.get("traceEvents", ()):
        if ev.get("ph") == "M":
            continue
        for tid in _event_trace_ids(ev):
            out.setdefault(tid, []).append(ev)
    for evs in out.values():
        evs.sort(key=lambda e: e.get("ts", 0))
    return out
