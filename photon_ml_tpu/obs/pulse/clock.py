"""photonpulse clock alignment: NTP-style offset estimation between peers.

Each process's tracer timestamps with ``time.perf_counter_ns()`` — a
monotonic clock with an *arbitrary per-process epoch*, so two processes'
rings cannot be overlaid until the epoch difference is estimated.  The
classic four-timestamp exchange does it with one round trip:

    client sends t0 (its clock) -> server notes t1 on receipt,
    replies carrying (t0, t1, t2=send time) -> client notes t3.

    offset = ((t1 - t0) + (t2 - t3)) / 2        (server_clock - client_clock)
    rtt    = (t3 - t0) - (t2 - t1)

The exchange piggybacks on handshakes that already happen — the frontend
accepts a ``{"cmd": "clock"}`` wire command, and the replication subscribe
hello/resume exchange carries the timestamps — so no new connection or
protocol is introduced.  Accuracy is bounded by rtt/2, which for the
loopback/pod-slice links this serves is microseconds: far below the
millisecond-scale spans being aligned.

Estimated offsets are stored per peer label ("owner", "frontend") in a
process-global table that ``install_export_meta()`` exposes through the
Chrome export's ``otherData.clock`` — which is exactly where
``tools/tracemerge.py`` reads them back to shift every event onto the
reference process's timeline.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Tuple

from photon_ml_tpu.obs import trace as _trace

_lock = threading.Lock()
_offsets: Dict[str, dict] = {}


def now_ns() -> int:
    """The clock every tracer timestamp uses; exchanged on the wire."""
    return time.perf_counter_ns()


def estimate(t0: int, t1: int, t2: int, t3: int) -> Tuple[int, int]:
    """``(offset_ns, rtt_ns)`` from one four-timestamp exchange.  ``offset``
    is *peer clock minus ours*: ``t_peer ~= t_ours + offset``."""
    offset = ((t1 - t0) + (t2 - t3)) // 2
    rtt = (t3 - t0) - (t2 - t1)
    return offset, rtt


def observe_exchange(peer: str, t0: int, t1: int, t2: int,
                     t3: Optional[int] = None) -> Tuple[int, int]:
    """Record the result of one exchange with ``peer``.  Keeps the
    lowest-rtt estimate seen (least queueing noise), like NTP's filter."""
    if t3 is None:
        t3 = now_ns()
    offset, rtt = estimate(t0, t1, t2, t3)
    with _lock:
        prev = _offsets.get(peer)
        if prev is None or rtt < prev["rtt_ns"]:
            _offsets[peer] = {"offset_ns": offset, "rtt_ns": rtt}
    return offset, rtt


def set_offset(peer: str, offset_ns: int, rtt_ns: int = 0) -> None:
    with _lock:
        _offsets[peer] = {"offset_ns": int(offset_ns), "rtt_ns": int(rtt_ns)}


def offsets() -> Dict[str, dict]:
    """Copy of the per-peer offset table (label -> offset_ns/rtt_ns)."""
    with _lock:
        return {k: dict(v) for k, v in _offsets.items()}


def reset() -> None:
    """Tests: forget every estimated offset."""
    with _lock:
        _offsets.clear()


def install_export_meta() -> None:
    """Expose the offset table in every Chrome export's ``otherData`` so
    tracemerge can align this process against its peers."""
    _trace.set_export_meta_provider(lambda: {"clock": offsets()})
