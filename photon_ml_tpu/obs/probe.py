"""JAX runtime accounting: compiles, host<->device transfer bytes, fences.

The two runtime costs a wall-clock phase log cannot attribute are XLA
compilation (tens of seconds on a TPU first-compile; the serving stack's
zero-recompile guarantee exists because of it) and host<->device transfer
(the chunked upload path in ``utils/transfer`` exists because one transport
degraded under a monolithic 512MB put).  ``JaxRuntimeProbe`` counts both
into the unified ``MetricsRegistry`` with per-site labels, so "which
coordinate's solver recompiled mid-sweep" and "how many bytes crossed the
wire during warm" become registry queries instead of log archaeology.

Instrumented sites:
  - ``serving/engine.ScoringEngine._executable`` — every AOT
    ``jit().lower().compile()`` goes through ``compile_span``;
  - ``utils/compile_cache.enable_compilation_cache`` — reports cache
    residency as a gauge (a disabled cache means every process pays full
    first-compiles; that should be visible, not inferred);
  - ``utils/transfer.chunked_device_put`` — per-chunk transfer bytes;
  - ``utils/transfer.stream_device_put`` — streaming-ingest batch uploads
    (``site="stream_feed"``), the bench's ingest-bytes axis.

Per-span device fences (``span(..., device_sync=True)``) live on the
tracer; this module only provides the default fence wiring.
"""

from __future__ import annotations

import contextlib
import time
from typing import Iterator, Optional

from photon_ml_tpu.obs import registry as _registry_mod
from photon_ml_tpu.obs import trace as _trace_mod
from photon_ml_tpu.obs.registry import MetricsRegistry


class JaxRuntimeProbe:
    """Counts XLA compiles and transfer bytes into a MetricsRegistry.

    ``registry=None`` binds LAZILY to the process-default registry at each
    record, so a test that swaps the default registry sees probe traffic
    without re-wiring the probe.
    """

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        self._registry = registry

    @property
    def registry(self) -> MetricsRegistry:
        return self._registry or _registry_mod.get_registry()

    # -- compiles ----------------------------------------------------------
    def record_compile(self, site: str, seconds: Optional[float] = None,
                       **labels) -> None:
        self.registry.inc("jax_compiles_total", site=site, **labels)
        if seconds is not None:
            self.registry.observe("jax_compile_seconds", seconds, site=site)

    @contextlib.contextmanager
    def compile_span(self, site: str, **attrs) -> Iterator[None]:
        """Wrap one jit/AOT compile call site: counts it, times it, and
        emits a tracer span — the whole accounting in one ``with``."""
        t0 = time.perf_counter()
        with _trace_mod.span("jax.compile", site=site, **attrs):
            yield
        self.record_compile(site, time.perf_counter() - t0, **attrs)

    def compile_count(self, site: Optional[str] = None) -> int:
        """Compiles recorded (at one site, or in total).  Sums across any
        extra labels a site attached (e.g. ``bucket=...``)."""
        total = 0
        for lk, v in self.registry.counter_series(
                "jax_compiles_total").items():
            if site is None or ("site", site) in lk:
                total += v
        return int(total)

    # -- transfers ---------------------------------------------------------
    def record_transfer(self, nbytes: int, direction: str = "h2d",
                        site: str = "") -> None:
        self.registry.inc("jax_transfer_bytes_total", int(nbytes),
                          direction=direction, site=site)
        self.registry.inc("jax_transfers_total", direction=direction,
                          site=site)

    def transfer_bytes(self, direction: Optional[str] = None,
                       site: Optional[str] = None) -> int:
        """Transfer bytes recorded, optionally filtered by direction and/or
        call site (e.g. ``site="stream_feed"`` isolates streaming-ingest
        uploads from design-matrix puts)."""
        total = 0
        for lk, v in self.registry.counter_series(
                "jax_transfer_bytes_total").items():
            if direction is not None and ("direction", direction) not in lk:
                continue
            if site is not None and ("site", site) not in lk:
                continue
            total += v
        return int(total)

    # -- cache residency ---------------------------------------------------
    def record_compile_cache(self, enabled: bool, cache_dir: str = "") -> None:
        self.registry.set_gauge("xla_compile_cache_enabled", int(enabled))
        _trace_mod.instant("compile_cache.enabled" if enabled else
                           "compile_cache.disabled", dir=cache_dir)


# ---------------------------------------------------------------------------
# process-default probe
# ---------------------------------------------------------------------------
_default = JaxRuntimeProbe()


def get_probe() -> JaxRuntimeProbe:
    return _default
