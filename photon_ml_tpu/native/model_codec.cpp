// Native Avro codec for BayesianLinearModelAvro record BODIES — the
// huge-d fixed-effect model files (reference BayesianLinearModelAvro,
// photon-avro-schemas; written by ModelProcessingUtils.scala:77-141).
//
// Why native: the portable model format stores one (name, term, value)
// triple per nonzero coefficient.  At 1e7 features the pure-python codec
// spends minutes building/parsing 1e7 python dicts; this codec moves the
// whole triple array across the boundary as three flat buffers (packed
// key blob + offsets + f64 values), so python-side work is O(1) in d.
// The container framing (magic, schema header, deflate blocks, sync
// markers) stays in data/avro.py — zlib is already C-speed there.
//
// Key blob convention matches native_index._pack_keys / index_store.cpp:
// concatenated utf-8 feature keys (name + '\x1f' + term), offsets[n+1].
//
// C ABI + ctypes (no pybind11 in this image); two-pass decode protocol
// (scan for sizes, then fill caller-allocated buffers).

#include <cstdint>
#include <cstring>

namespace {

constexpr char SEP = '\x1f';  // data/index_map.py SEP

// ---- zigzag varints (Avro spec) -------------------------------------------

inline int put_varint(int64_t v, uint8_t* out) {
    uint64_t z = (static_cast<uint64_t>(v) << 1) ^ static_cast<uint64_t>(v >> 63);
    int n = 0;
    while (z >= 0x80) {
        out[n++] = static_cast<uint8_t>(z | 0x80);
        z >>= 7;
    }
    out[n++] = static_cast<uint8_t>(z);
    return n;
}

inline bool get_varint(const uint8_t* buf, int64_t len, int64_t* pos, int64_t* out) {
    uint64_t z = 0;
    int shift = 0;
    while (*pos < len && shift <= 63) {
        uint8_t b = buf[(*pos)++];
        z |= static_cast<uint64_t>(b & 0x7F) << shift;
        if (!(b & 0x80)) {
            *out = static_cast<int64_t>(z >> 1) ^ -static_cast<int64_t>(z & 1);
            return true;
        }
        shift += 7;
    }
    return false;
}

inline bool skip_string(const uint8_t* buf, int64_t len, int64_t* pos) {
    int64_t n;
    if (!get_varint(buf, len, pos, &n) || n < 0 || n > len - *pos) return false;
    *pos += n;
    return true;
}

// one NTV item: name string, term string, value double
inline bool scan_ntv(const uint8_t* buf, int64_t len, int64_t* pos,
                     int64_t* key_bytes) {
    int64_t n;
    if (!get_varint(buf, len, pos, &n) || n < 0 || n > len - *pos) return false;
    *key_bytes += n + 1;  // + SEP
    *pos += n;
    if (!get_varint(buf, len, pos, &n) || n < 0 || n > len - *pos) return false;
    *key_bytes += n;
    *pos += n + 0;
    if (8 > len - *pos) return false;
    *pos += 8;
    return true;
}

// Avro array decode driver: f(item) for each item across all blocks.
// Handles negative block counts (count<0 => followed by byte size).
template <typename F>
inline bool walk_array(const uint8_t* buf, int64_t len, int64_t* pos, F&& f) {
    for (;;) {
        int64_t count;
        if (!get_varint(buf, len, pos, &count)) return false;
        if (count == 0) return true;
        if (count < 0) {
            int64_t nbytes;
            if (!get_varint(buf, len, pos, &nbytes)) return false;
            count = -count;
        }
        for (int64_t i = 0; i < count; ++i)
            if (!f()) return false;
    }
}

}  // namespace

extern "C" {

// ---------------------------------------------------------------------------
// ENCODE one record body.
//
// keys_blob/key_off[n+1]: packed feature keys (name SEP term) indexed by
// coefficient position j (the index map's key blob order).  values[d],
// variances[d] or null.  Zero means are skipped (sparse NTV storage, like
// the reference); a variance is emitted iff its mean is emitted.
// Returns bytes written, or -(bytes needed) when cap is too small (call
// again with a bigger buffer), or 0 on malformed input.
// ---------------------------------------------------------------------------
int64_t plmc_encode(const char* model_id, int64_t model_id_len,
                    const char* model_class, int64_t model_class_len,  // <0: null branch
                    const char* loss, int64_t loss_len,                // <0: null branch
                    const char* keys_blob, const int64_t* key_off,
                    const double* values, const double* variances,
                    int64_t d, char* out, int64_t cap) {
    if (d < 0) return 0;
    // conservative size bound: per item 2 varints(<=5B ea for typical keys)
    // + key bytes + 8B double; strings + unions + block headers
    int64_t nnz = 0, key_bytes = 0;
    for (int64_t j = 0; j < d; ++j) {
        if (values[j] == 0.0) continue;
        ++nnz;
        key_bytes += key_off[j + 1] - key_off[j];
    }
    int64_t bound = 64 + model_id_len + model_class_len + loss_len
        + 2 * (key_bytes + nnz * 28) + 64;
    if (cap < bound) return -bound;

    uint8_t* o = reinterpret_cast<uint8_t*>(out);
    int64_t p = 0;
    auto put_str = [&](const char* s, int64_t n) {
        p += put_varint(n, o + p);
        std::memcpy(o + p, s, n);
        p += n;
    };
    auto put_double = [&](double v) {
        std::memcpy(o + p, &v, 8);  // IEEE754 little-endian (x86/ARM LE)
        p += 8;
    };
    auto put_items = [&](const double* arr) {
        // one positive-count block then the 0 terminator (legal Avro;
        // both our python decoder and Java Avro read it).  An EMPTY array
        // is just the terminator — emitting count=0 twice would shift
        // every following field by one byte.
        if (nnz > 0) p += put_varint(nnz, o + p);
        if (nnz > 0) {
            for (int64_t j = 0; j < d; ++j) {
                if (values[j] == 0.0) continue;
                const char* key = keys_blob + key_off[j];
                int64_t klen = key_off[j + 1] - key_off[j];
                const char* sep = static_cast<const char*>(
                    std::memchr(key, SEP, static_cast<size_t>(klen)));
                int64_t name_len = sep ? (sep - key) : klen;
                const char* term = sep ? sep + 1 : key + klen;
                int64_t term_len = sep ? (key + klen - term) : 0;
                put_str(key, name_len);
                put_str(term, term_len);
                put_double(arr[j]);
            }
        }
        p += put_varint(0, o + p);
    };

    put_str(model_id, model_id_len);                     // modelId
    if (model_class_len < 0) p += put_varint(0, o + p);  // modelClass union
    else { p += put_varint(1, o + p); put_str(model_class, model_class_len); }
    put_items(values);                                   // means
    if (variances == nullptr) p += put_varint(0, o + p); // variances union
    else { p += put_varint(1, o + p); put_items(variances); }
    if (loss_len < 0) p += put_varint(0, o + p);         // lossFunction union
    else { p += put_varint(1, o + p); put_str(loss, loss_len); }
    return p;
}

// ---------------------------------------------------------------------------
// DECODE pass 1: scan one record body for sizes.
// Outputs: consumed bytes, n_means, means_key_bytes (packed keys incl. SEP),
// n_vars (-1 when the variances branch is null), vars_key_bytes,
// model_id/class/loss lengths (class/loss -1 when null).
// Returns 1 on success, 0 on malformed input.
// ---------------------------------------------------------------------------
int64_t plmc_scan(const char* buf_, int64_t len, int64_t* consumed,
                  int64_t* n_means, int64_t* means_key_bytes,
                  int64_t* n_vars, int64_t* vars_key_bytes,
                  int64_t* id_len, int64_t* class_len, int64_t* loss_len) {
    const uint8_t* buf = reinterpret_cast<const uint8_t*>(buf_);
    int64_t pos = 0, n;
    if (!get_varint(buf, len, &pos, &n) || n < 0 || n > len - pos) return 0;
    *id_len = n; pos += n;                               // modelId
    if (!get_varint(buf, len, &pos, &n)) return 0;       // modelClass union
    if (n == 1) {
        int64_t s = pos;
        if (!skip_string(buf, len, &pos)) return 0;
        int64_t hdr; get_varint(buf, len, &s, &hdr); *class_len = hdr;
    } else if (n == 0) *class_len = -1; else return 0;
    *n_means = 0; *means_key_bytes = 0;
    if (!walk_array(buf, len, &pos, [&] {                // means
            ++*n_means;
            return scan_ntv(buf, len, &pos, means_key_bytes);
        }))
        return 0;
    if (!get_varint(buf, len, &pos, &n)) return 0;       // variances union
    if (n == 1) {
        *n_vars = 0; *vars_key_bytes = 0;
        if (!walk_array(buf, len, &pos, [&] {
                ++*n_vars;
                return scan_ntv(buf, len, &pos, vars_key_bytes);
            }))
            return 0;
    } else if (n == 0) { *n_vars = -1; *vars_key_bytes = 0; } else return 0;
    if (!get_varint(buf, len, &pos, &n)) return 0;       // lossFunction union
    if (n == 1) {
        int64_t s = pos;
        if (!skip_string(buf, len, &pos)) return 0;
        int64_t hdr; get_varint(buf, len, &s, &hdr); *loss_len = hdr;
    } else if (n == 0) *loss_len = -1; else return 0;
    *consumed = pos;
    return 1;
}

// ---------------------------------------------------------------------------
// DECODE pass 2: fill caller-allocated buffers sized from plmc_scan.
// Key blobs are packed (name SEP term) with offsets[n+1] — feed them
// straight to phidx_get_batch (store maps) or split python-side.
// ---------------------------------------------------------------------------
int64_t plmc_fill(const char* buf_, int64_t len,
                  char* model_id, char* model_class, char* loss,
                  char* means_keys, int64_t* means_off, double* means_vals,
                  char* vars_keys, int64_t* vars_off, double* vars_vals) {
    const uint8_t* buf = reinterpret_cast<const uint8_t*>(buf_);
    int64_t pos = 0, n;

    auto copy_str = [&](char* dst) -> bool {
        int64_t sl;
        if (!get_varint(buf, len, &pos, &sl) || sl < 0 || sl > len - pos)
            return false;
        if (dst) std::memcpy(dst, buf + pos, sl);
        pos += sl;
        return true;
    };
    auto fill_items = [&](char* keys, int64_t* off, double* vals) -> bool {
        int64_t i = 0, kp = 0;
        off[0] = 0;
        return walk_array(buf, len, &pos, [&] {
            int64_t sl;
            if (!get_varint(buf, len, &pos, &sl) || sl < 0 || sl > len - pos)
                return false;
            std::memcpy(keys + kp, buf + pos, sl);
            kp += sl; pos += sl;
            keys[kp++] = SEP;
            if (!get_varint(buf, len, &pos, &sl) || sl < 0 || sl > len - pos)
                return false;
            std::memcpy(keys + kp, buf + pos, sl);
            kp += sl; pos += sl;
            if (8 > len - pos) return false;
            std::memcpy(&vals[i], buf + pos, 8);
            pos += 8;
            off[++i] = kp;
            return true;
        });
    };

    if (!copy_str(model_id)) return 0;
    if (!get_varint(buf, len, &pos, &n)) return 0;
    if (n == 1 && !copy_str(model_class)) return 0;
    if (!fill_items(means_keys, means_off, means_vals)) return 0;
    if (!get_varint(buf, len, &pos, &n)) return 0;
    if (n == 1 && !fill_items(vars_keys, vars_off, vars_vals)) return 0;
    if (!get_varint(buf, len, &pos, &n)) return 0;
    if (n == 1 && !copy_str(loss)) return 0;
    return pos;
}

// ---------------------------------------------------------------------------
// BLOCK decode: N records in TWO calls (the per-entity random-effect path —
// millions of small records where per-record boundary crossings dominate).
//
// scan: totals for buffer sizing.  fill: concatenated outputs —
//   ids blob + id_off[n+1]
//   means keys blob + mkey_off[total_means+1] + vals[total_means]
//     + mrec_off[n+1] (record boundaries into the means arrays)
//   vars: same shape; absent variance arrays contribute 0-length spans.
// model_class/lossFunction strings are skipped (per-entity loaders don't
// use them).
// ---------------------------------------------------------------------------
extern "C" int64_t plmc_scan_block(const char* buf_, int64_t len, int64_t n_records,
                                   int64_t* total_means, int64_t* means_key_bytes,
                                   int64_t* total_vars, int64_t* vars_key_bytes,
                                   int64_t* id_bytes) {
    const uint8_t* buf = reinterpret_cast<const uint8_t*>(buf_);
    int64_t pos = 0;
    *total_means = 0; *means_key_bytes = 0;
    *total_vars = 0; *vars_key_bytes = 0; *id_bytes = 0;
    for (int64_t r = 0; r < n_records; ++r) {
        int64_t n;
        if (!get_varint(buf, len, &pos, &n) || n < 0 || n > len - pos) return 0;
        *id_bytes += n; pos += n;                        // modelId
        if (!get_varint(buf, len, &pos, &n)) return 0;   // modelClass union
        if (n == 1) { if (!skip_string(buf, len, &pos)) return 0; }
        else if (n != 0) return 0;
        if (!walk_array(buf, len, &pos, [&] {            // means
                ++*total_means;
                return scan_ntv(buf, len, &pos, means_key_bytes);
            }))
            return 0;
        if (!get_varint(buf, len, &pos, &n)) return 0;   // variances union
        if (n == 1) {
            if (!walk_array(buf, len, &pos, [&] {
                    ++*total_vars;
                    return scan_ntv(buf, len, &pos, vars_key_bytes);
                }))
                return 0;
        } else if (n != 0) return 0;
        if (!get_varint(buf, len, &pos, &n)) return 0;   // lossFunction union
        if (n == 1) { if (!skip_string(buf, len, &pos)) return 0; }
        else if (n != 0) return 0;
    }
    return pos;
}

extern "C" int64_t plmc_fill_block(const char* buf_, int64_t len, int64_t n_records,
                                   char* ids, int64_t* id_off,
                                   char* mkeys, int64_t* mkey_off, double* mvals,
                                   int64_t* mrec_off,
                                   char* vkeys, int64_t* vkey_off, double* vvals,
                                   int64_t* vrec_off) {
    const uint8_t* buf = reinterpret_cast<const uint8_t*>(buf_);
    int64_t pos = 0;
    int64_t ip = 0, mi = 0, mkp = 0, vi = 0, vkp = 0;
    id_off[0] = 0; mkey_off[0] = 0; mrec_off[0] = 0;
    vkey_off[0] = 0; vrec_off[0] = 0;

    auto fill_one = [&](char* keys, int64_t* koff, double* vals,
                        int64_t* i, int64_t* kp) -> bool {
        return walk_array(buf, len, &pos, [&] {
            int64_t sl;
            if (!get_varint(buf, len, &pos, &sl) || sl < 0 || sl > len - pos)
                return false;
            std::memcpy(keys + *kp, buf + pos, sl);
            *kp += sl; pos += sl;
            keys[(*kp)++] = SEP;
            if (!get_varint(buf, len, &pos, &sl) || sl < 0 || sl > len - pos)
                return false;
            std::memcpy(keys + *kp, buf + pos, sl);
            *kp += sl; pos += sl;
            if (8 > len - pos) return false;
            std::memcpy(&vals[*i], buf + pos, 8);
            pos += 8;
            koff[++*i] = *kp;
            return true;
        });
    };
    auto skip_union_string = [&]() -> bool {
        int64_t n;
        if (!get_varint(buf, len, &pos, &n)) return false;
        if (n == 1) return skip_string(buf, len, &pos);
        return n == 0;
    };

    for (int64_t r = 0; r < n_records; ++r) {
        int64_t sl;
        if (!get_varint(buf, len, &pos, &sl) || sl < 0 || sl > len - pos)
            return 0;
        std::memcpy(ids + ip, buf + pos, sl);
        ip += sl; pos += sl;
        id_off[r + 1] = ip;
        if (!skip_union_string()) return 0;              // modelClass
        if (!fill_one(mkeys, mkey_off, mvals, &mi, &mkp)) return 0;
        mrec_off[r + 1] = mi;
        int64_t n;
        if (!get_varint(buf, len, &pos, &n)) return 0;   // variances union
        if (n == 1) {
            if (!fill_one(vkeys, vkey_off, vvals, &vi, &vkp)) return 0;
        } else if (n != 0) return 0;
        vrec_off[r + 1] = vi;
        if (!skip_union_string()) return 0;              // lossFunction
    }
    return pos;
}

}  // extern "C"
