"""Native (C++) runtime components.

The reference's native-performance substrate is JVM-adjacent (netlib BLAS via
JNI, PalDB off-heap stores — SURVEY.md §2.7); this package holds the C++
equivalents for the host-side runtime.  Compiled lazily with g++ into shared
libraries loaded via ctypes; every consumer has a pure-Python fallback so the
framework works without a toolchain.
"""

from photon_ml_tpu.native.build import compile_library, library_path  # noqa: F401
