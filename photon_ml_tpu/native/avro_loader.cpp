// Native Avro container decoder for the training-data hot path.
//
// Reference: photon-client .../data/avro/AvroDataReader.scala:54-475 decodes
// Avro records into per-row vectors on Spark executors (JVM codegen'd
// decoders).  The Python fallback (data/avro.py) builds one dict per record
// — the dominant cost of data loading.  This decoder walks the WRITER SCHEMA
// (serialized by Python as an int32 pre-order tree, see data/native_avro.py)
// once per value and captures role-tagged nodes into columnar buffers:
//
//   numeric roles     -> f64 column + validity byte per record
//   uid               -> long column or interned string
//   features          -> (record-count, interned "name\x1fterm" id, value)
//   metadata map      -> (record-count, interned key id, interned value id)
//
// Strings are INTERNED in C++ (open-addressing hash over a blob), so Python
// resolves only unique feature names / entity ids — the per-record Python
// work drops to zero and index-map lookups become one vectorized batch.
//
// Container framing per the Avro 1.x spec: "Obj\x01" magic, metadata map
// (avro.schema / avro.codec), 16-byte sync, blocks of (count, size, payload)
// with null or deflate (raw, wbits=-15) codecs.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>
#include <zlib.h>

namespace {

// ---- schema tree opcodes (keep in sync with data/native_avro.py) ----------
enum TypeCode : int32_t {
  T_NULL = 0, T_BOOL = 1, T_INT = 2, T_LONG = 3, T_FLOAT = 4, T_DOUBLE = 5,
  T_STRING = 6, T_BYTES = 7, T_UNION = 8, T_ARRAY = 9, T_MAP = 10,
  T_RECORD = 11, T_ENUM = 12, T_FIXED = 13,
};

enum Role : int32_t {
  R_NONE = 0,
  R_NUM0 = 1,  // numeric columns: role 1..8 -> column index role-1
  R_NUM_LAST = 8,
  R_UID_LONG = 10, R_UID_STR = 11,
  R_FEAT_ARRAY = 20, R_FEAT_NAME = 21, R_FEAT_TERM = 22, R_FEAT_VALUE = 23,
  R_META_MAP = 30, R_META_KEY = 31, R_META_VALUE = 32,
};

struct Intern {
  // open-addressing over (blob offsets); returns dense ids in insert order
  std::vector<uint8_t> blob;
  std::vector<int64_t> offsets{0};
  std::vector<int64_t> slots;  // -1 empty, else id
  size_t mask = 0;

  Intern() { rehash(1 << 10); }

  static uint64_t hash(const uint8_t* p, size_t n) {
    uint64_t h = 1469598103934665603ULL;
    for (size_t i = 0; i < n; ++i) { h ^= p[i]; h *= 1099511628211ULL; }
    return h;
  }

  void rehash(size_t n) {
    std::vector<int64_t> fresh(n, -1);
    for (size_t i = 0; i < slots.size(); ++i) {
      int64_t id = slots[i];
      if (id < 0) continue;
      const uint8_t* p = blob.data() + offsets[id];
      size_t len = offsets[id + 1] - offsets[id];
      uint64_t j = hash(p, len) & (n - 1);
      while (fresh[j] >= 0) j = (j + 1) & (n - 1);
      fresh[j] = id;
    }
    slots.swap(fresh);
    mask = n - 1;
  }

  int32_t intern(const uint8_t* p, size_t n) {
    if ((offsets.size() - 1) * 2 >= slots.size()) rehash(slots.size() * 2);
    uint64_t j = hash(p, n) & mask;
    while (true) {
      int64_t id = slots[j];
      if (id < 0) break;
      size_t len = offsets[id + 1] - offsets[id];
      if (len == n && std::memcmp(blob.data() + offsets[id], p, n) == 0)
        return static_cast<int32_t>(id);
      j = (j + 1) & mask;
    }
    int32_t id = static_cast<int32_t>(offsets.size() - 1);
    blob.insert(blob.end(), p, p + n);
    offsets.push_back(static_cast<int64_t>(blob.size()));
    slots[j] = id;
    return id;
  }

  size_t count() const { return offsets.size() - 1; }
};

constexpr int kNumCols = 8;
constexpr uint8_t kSep = 0x1f;  // feature key separator (index_map.SEP)

struct Loader {
  // decoded outputs
  int64_t n = 0;  // records
  std::vector<double> num_cols[kNumCols];
  std::vector<uint8_t> num_valid[kNumCols];
  std::vector<int64_t> uid_long;
  std::vector<uint8_t> uid_kind;  // 0 none, 1 long, 2 string(intern id in uid_long)
  std::vector<int32_t> feat_counts;   // per record
  std::vector<int32_t> feat_ids;      // interned name\x1fterm
  std::vector<double> feat_values;
  std::vector<int32_t> meta_counts;   // per record
  std::vector<int32_t> meta_keys;     // interned
  std::vector<int32_t> meta_vals;     // interned
  Intern feat_intern;
  Intern meta_intern;
  Intern uid_intern;
  std::string error;

  // decode state
  const uint8_t* cur = nullptr;
  const uint8_t* end = nullptr;
  bool fail = false;
  // per-record feature scratch (name/term captured before combining)
  std::vector<uint8_t> name_buf, term_buf;
  bool have_name = false, have_term = false;
  double fval = 0.0;

  bool need(size_t k) {
    if (static_cast<size_t>(end - cur) < k) { fail = true; return false; }
    return true;
  }

  int64_t vlong() {
    uint64_t acc = 0;
    int shift = 0;
    while (true) {
      if (!need(1)) return 0;
      uint8_t b = *cur++;
      acc |= static_cast<uint64_t>(b & 0x7F) << shift;
      if (!(b & 0x80)) break;
      shift += 7;
      if (shift > 63) { fail = true; return 0; }
    }
    return static_cast<int64_t>((acc >> 1) ^ (~(acc & 1) + 1));
  }

  double vfloat() {
    if (!need(4)) return 0;
    float f;
    std::memcpy(&f, cur, 4);
    cur += 4;
    return f;
  }

  double vdouble() {
    if (!need(8)) return 0;
    double d;
    std::memcpy(&d, cur, 8);
    cur += 8;
    return d;
  }

  // returns pointer+len of a length-prefixed byte string (no copy)
  const uint8_t* vbytes(size_t* len) {
    int64_t n = vlong();
    if (fail || n < 0 || !need(static_cast<size_t>(n))) { fail = true; *len = 0; return nullptr; }
    const uint8_t* p = cur;
    cur += n;
    *len = static_cast<size_t>(n);
    return p;
  }

  void capture_numeric(int32_t role, double v) {
    if (role >= R_NUM0 && role <= R_NUM_LAST) {
      int c = role - R_NUM0;
      num_cols[c].back() = v;
      num_valid[c].back() = 1;
    } else if (role == R_FEAT_VALUE) {
      fval = v;
    } else if (role == R_UID_LONG) {
      uid_long.back() = static_cast<int64_t>(v);
      uid_kind.back() = 1;
    }
  }

  // walk one value; tree points at its type node; returns node length (i.e.
  // number of int32s consumed) so callers can advance over siblings.
  size_t walk(const int32_t* t);
};

// length of a subtree in int32 units (for sibling traversal without decode)
size_t tree_len(const int32_t* t) {
  switch (t[0]) {
    case T_UNION: {
      size_t k = 3;
      for (int32_t i = 0; i < t[2]; ++i) k += tree_len(t + k);
      return k;
    }
    case T_ARRAY: case T_MAP:
      return 2 + tree_len(t + 2);
    case T_RECORD: {
      size_t k = 3;
      for (int32_t i = 0; i < t[2]; ++i) k += tree_len(t + k);
      return k;
    }
    case T_FIXED:
      return 3;
    default:
      return 2;  // primitives/enum: [code, role]
  }
}

size_t Loader::walk(const int32_t* t) {
  const int32_t code = t[0], role = t[1];
  switch (code) {
    case T_NULL:
      return 2;
    case T_BOOL: {
      if (need(1)) capture_numeric(role, *cur++ != 0);
      return 2;
    }
    case T_INT:
    case T_LONG: {
      int64_t v = vlong();
      if (role == R_UID_LONG) { uid_long.back() = v; uid_kind.back() = 1; }
      else capture_numeric(role, static_cast<double>(v));  // incl. R_FEAT_VALUE
      return 2;
    }
    case T_ENUM:
      vlong();
      return 2;
    case T_FLOAT:
      capture_numeric(role, vfloat());
      return 2;
    case T_DOUBLE:
      capture_numeric(role, vdouble());
      return 2;
    case T_STRING:
    case T_BYTES: {
      size_t len;
      const uint8_t* p = vbytes(&len);
      if (fail) return 2;
      if (role == R_FEAT_NAME) {
        name_buf.assign(p, p + len);
        have_name = true;
      } else if (role == R_FEAT_TERM) {
        term_buf.assign(p, p + len);
        have_term = true;
      } else if (role == R_META_KEY) {
        meta_keys.push_back(meta_intern.intern(p, len));
      } else if (role == R_META_VALUE) {
        meta_vals.push_back(meta_intern.intern(p, len));
      } else if (role == R_UID_STR) {
        uid_long.back() = uid_intern.intern(p, len);
        uid_kind.back() = 2;
      }
      return 2;
    }
    case T_FIXED: {
      size_t sz = static_cast<size_t>(t[2]);
      if (need(sz)) cur += sz;
      return 3;
    }
    case T_UNION: {
      int64_t idx = vlong();
      size_t k = 3;
      for (int32_t i = 0; i < t[2]; ++i) {
        size_t sub = tree_len(t + k);
        if (i == idx && !fail) walk(t + k);
        k += sub;
      }
      if (idx < 0 || idx >= t[2]) fail = true;
      return k;
    }
    case T_ARRAY: {
      const int32_t* item = t + 2;
      bool is_feat = (role == R_FEAT_ARRAY);
      while (!fail) {
        int64_t cnt = vlong();
        if (cnt == 0 || fail) break;
        if (cnt < 0) { vlong(); cnt = -cnt; }  // block size present
        for (int64_t i = 0; i < cnt && !fail; ++i) {
          if (is_feat) { have_name = have_term = false; fval = 0.0; name_buf.clear(); term_buf.clear(); }
          walk(item);
          if (is_feat) {
            // key = name \x1f term  (term may be absent/null -> empty)
            name_buf.push_back(kSep);
            name_buf.insert(name_buf.end(), term_buf.begin(), term_buf.end());
            feat_ids.push_back(feat_intern.intern(name_buf.data(), name_buf.size()));
            feat_values.push_back(fval);
            feat_counts.back() += 1;
          }
        }
      }
      return 2 + tree_len(t + 2);
    }
    case T_MAP: {
      const int32_t* val = t + 2;
      bool is_meta = (role == R_META_MAP);
      while (!fail) {
        int64_t cnt = vlong();
        if (cnt == 0 || fail) break;
        if (cnt < 0) { vlong(); cnt = -cnt; }
        for (int64_t i = 0; i < cnt && !fail; ++i) {
          size_t klen;
          const uint8_t* kp = vbytes(&klen);
          if (fail) break;
          if (is_meta) {
            meta_keys.push_back(meta_intern.intern(kp, klen));
            // value node: capture as meta value if it's a plain/nullable string
            size_t before = meta_vals.size();
            walk(val);
            if (meta_vals.size() == before)  // value wasn't a captured string
              meta_vals.push_back(-1);
            meta_counts.back() += 1;
          } else {
            walk(val);
          }
        }
      }
      return 2 + tree_len(t + 2);
    }
    case T_RECORD: {
      size_t k = 3;
      for (int32_t i = 0; i < t[2] && !fail; ++i) k += walk(t + k);
      // advance over remaining fields if we bailed early
      if (fail) return tree_len(t);
      return k;
    }
    default:
      fail = true;
      return 2;
  }
}

bool read_exact(FILE* f, void* p, size_t n) {
  return std::fread(p, 1, n, f) == n;
}

bool inflate_raw(const std::vector<uint8_t>& in, std::vector<uint8_t>& out) {
  z_stream zs{};
  if (inflateInit2(&zs, -15) != Z_OK) return false;
  out.clear();
  out.resize(in.size() * 4 + 1024);
  zs.next_in = const_cast<Bytef*>(in.data());
  zs.avail_in = static_cast<uInt>(in.size());
  size_t written = 0;
  int rc;
  do {
    if (written == out.size()) out.resize(out.size() * 2);
    zs.next_out = out.data() + written;
    zs.avail_out = static_cast<uInt>(out.size() - written);
    rc = inflate(&zs, Z_NO_FLUSH);
    written = out.size() - zs.avail_out;
  } while (rc == Z_OK);
  inflateEnd(&zs);
  if (rc != Z_STREAM_END) return false;
  out.resize(written);
  return true;
}

}  // namespace

extern "C" {

// Decode a container file with the given schema tree (int32 pre-order, see
// data/native_avro.py).  header_meta is matched by Python beforehand; we
// re-read the header here to find codec + sync + data start.
void* avl_open(const char* path, const int32_t* tree, int64_t tree_len_i32) {
  (void)tree_len_i32;
  FILE* f = std::fopen(path, "rb");
  if (!f) return nullptr;
  Loader* L = new Loader;

  uint8_t magic[4];
  bool deflate_codec = false;
  std::vector<uint8_t> header_tail;
  // parse header with a tiny inline reader
  {
    if (!read_exact(f, magic, 4) || std::memcmp(magic, "Obj\x01", 4) != 0) {
      std::fclose(f); delete L; return nullptr;
    }
    // metadata map: blocks of (count, [keylen key vallen val]*) ... 0
    auto file_vlong = [&](bool* ok) -> int64_t {
      uint64_t acc = 0; int shift = 0;
      while (true) {
        int c = std::fgetc(f);
        if (c == EOF) { *ok = false; return 0; }
        acc |= static_cast<uint64_t>(c & 0x7F) << shift;
        if (!(c & 0x80)) break;
        shift += 7;
      }
      *ok = true;
      return static_cast<int64_t>((acc >> 1) ^ (~(acc & 1) + 1));
    };
    bool ok = true;
    while (ok) {
      int64_t cnt = file_vlong(&ok);
      if (!ok || cnt == 0) break;
      if (cnt < 0) { file_vlong(&ok); cnt = -cnt; }
      for (int64_t i = 0; i < cnt && ok; ++i) {
        int64_t klen = file_vlong(&ok);
        std::string key(static_cast<size_t>(klen), 0);
        ok = ok && read_exact(f, key.data(), klen);
        int64_t vlen = file_vlong(&ok);
        std::string val(static_cast<size_t>(vlen), 0);
        ok = ok && read_exact(f, val.data(), vlen);
        if (key == "avro.codec") deflate_codec = (val == "deflate");
      }
    }
    if (!ok) { std::fclose(f); delete L; return nullptr; }
  }
  uint8_t sync[16];
  if (!read_exact(f, sync, 16)) { std::fclose(f); delete L; return nullptr; }

  // decode blocks
  std::vector<uint8_t> raw, plain;
  while (true) {
    // block: count, byte-size, payload, sync
    auto file_vlong2 = [&](bool* ok) -> int64_t {
      uint64_t acc = 0; int shift = 0;
      while (true) {
        int c = std::fgetc(f);
        if (c == EOF) { *ok = false; return 0; }
        acc |= static_cast<uint64_t>(c & 0x7F) << shift;
        if (!(c & 0x80)) break;
        shift += 7;
      }
      *ok = true;
      return static_cast<int64_t>((acc >> 1) ^ (~(acc & 1) + 1));
    };
    bool ok = true;
    int64_t cnt = file_vlong2(&ok);
    if (!ok) break;  // EOF
    int64_t size = file_vlong2(&ok);
    if (!ok || cnt < 0 || size < 0) { L->fail = true; break; }
    raw.resize(static_cast<size_t>(size));
    if (!read_exact(f, raw.data(), raw.size())) { L->fail = true; break; }
    uint8_t bsync[16];
    if (!read_exact(f, bsync, 16) || std::memcmp(bsync, sync, 16) != 0) {
      L->fail = true; break;
    }
    const std::vector<uint8_t>* payload = &raw;
    if (deflate_codec) {
      if (!inflate_raw(raw, plain)) { L->fail = true; break; }
      payload = &plain;
    }
    L->cur = payload->data();
    L->end = payload->data() + payload->size();
    for (int64_t i = 0; i < cnt && !L->fail; ++i) {
      // per-record defaults
      for (int c = 0; c < kNumCols; ++c) {
        L->num_cols[c].push_back(0.0);
        L->num_valid[c].push_back(0);
      }
      L->uid_long.push_back(0);
      L->uid_kind.push_back(0);
      L->feat_counts.push_back(0);
      L->meta_counts.push_back(0);
      L->walk(tree);
      L->n += 1;
    }
    if (L->cur != L->end) L->fail = true;
    if (L->fail) break;
  }
  std::fclose(f);
  if (L->fail) { delete L; return nullptr; }
  return L;
}

int64_t avl_num_records(const void* h) { return static_cast<const Loader*>(h)->n; }

int64_t avl_numeric_col(const void* h, int32_t col, const double** vals,
                        const uint8_t** valid) {
  const Loader* L = static_cast<const Loader*>(h);
  if (col < 0 || col >= kNumCols) return 0;
  *vals = L->num_cols[col].data();
  *valid = L->num_valid[col].data();
  return L->n;
}

int64_t avl_uid(const void* h, const int64_t** vals, const uint8_t** kinds) {
  const Loader* L = static_cast<const Loader*>(h);
  *vals = L->uid_long.data();
  *kinds = L->uid_kind.data();
  return L->n;
}

int64_t avl_features(const void* h, const int32_t** counts, const int32_t** ids,
                     const double** values) {
  const Loader* L = static_cast<const Loader*>(h);
  *counts = L->feat_counts.data();
  *ids = L->feat_ids.data();
  *values = L->feat_values.data();
  return static_cast<int64_t>(L->feat_ids.size());
}

int64_t avl_feature_table(const void* h, const uint8_t** blob, const int64_t** offsets) {
  const Loader* L = static_cast<const Loader*>(h);
  *blob = L->feat_intern.blob.data();
  *offsets = L->feat_intern.offsets.data();
  return static_cast<int64_t>(L->feat_intern.count());
}

int64_t avl_meta(const void* h, const int32_t** counts, const int32_t** keys,
                 const int32_t** vals) {
  const Loader* L = static_cast<const Loader*>(h);
  *counts = L->meta_counts.data();
  *keys = L->meta_keys.data();
  *vals = L->meta_vals.data();
  return static_cast<int64_t>(L->meta_keys.size());
}

int64_t avl_meta_table(const void* h, const uint8_t** blob, const int64_t** offsets) {
  const Loader* L = static_cast<const Loader*>(h);
  *blob = L->meta_intern.blob.data();
  *offsets = L->meta_intern.offsets.data();
  return static_cast<int64_t>(L->meta_intern.count());
}

int64_t avl_uid_table(const void* h, const uint8_t** blob, const int64_t** offsets) {
  const Loader* L = static_cast<const Loader*>(h);
  *blob = L->uid_intern.blob.data();
  *offsets = L->uid_intern.offsets.data();
  return static_cast<int64_t>(L->uid_intern.count());
}

void avl_close(void* h) { delete static_cast<Loader*>(h); }

}  // extern "C"
