// Off-heap feature index store: the PalDB replacement.
//
// Reference: photon-api .../index/PalDBIndexMap.scala:16-278 — the reference
// keeps ~1e8-entry feature name<->index maps OFF the JVM heap in PalDB stores
// shared by executors.  TPU-native equivalent: one mmap'd file holding an
// open-addressing hash table over an id-ordered key blob.  Lookups touch two
// cache lines (slot + key bytes); no load/deserialize step; the page cache
// shares the store across processes the way PalDB shared it across executors.
//
// File layout (PHIDX002, little-endian):
//   0   8B   magic "PHIDX002"
//   8   i64  n               (number of keys; ids are 0..n-1)
//   16  i64  table_size      (power of two, >= 2n)
//   24  i64  slots[table_size]   key id, or -1 for empty
//   ..  i64  offsets[n + 1]      byte offsets into blob, id-ordered
//   ..  u8   blob[]              concatenated utf-8 keys
//
// C ABI only (consumed via ctypes).  Thread-safe for concurrent reads.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr char kMagic[8] = {'P', 'H', 'I', 'D', 'X', '0', '0', '2'};

inline uint64_t fnv1a(const uint8_t* data, int64_t len) {
  uint64_t h = 1469598103934665603ULL;
  for (int64_t i = 0; i < len; ++i) {
    h ^= data[i];
    h *= 1099511628211ULL;
  }
  return h;
}

inline int64_t next_pow2(int64_t x) {
  int64_t p = 1;
  while (p < x) p <<= 1;
  return p;
}

struct Store {
  void* map = nullptr;
  size_t map_len = 0;
  int64_t n = 0;
  int64_t table_size = 0;
  const int64_t* slots = nullptr;
  const int64_t* offsets = nullptr;
  const uint8_t* blob = nullptr;
};

inline int64_t probe(const Store* s, const uint8_t* key, int64_t len) {
  const uint64_t mask = static_cast<uint64_t>(s->table_size - 1);
  uint64_t i = fnv1a(key, len) & mask;
  while (true) {
    const int64_t id = s->slots[i];
    if (id < 0 || id >= s->n) return -1;  // empty (or corrupt slot)
    const int64_t off = s->offsets[id];
    const int64_t klen = s->offsets[id + 1] - off;
    if (klen == len && std::memcmp(s->blob + off, key, len) == 0) return id;
    i = (i + 1) & mask;
  }
}

}  // namespace

extern "C" {

// Build the store file from an id-ordered key blob + offsets (offsets has
// n+1 entries).  Returns 0 on success, negative errno-style codes otherwise.
int64_t phidx_build(const char* path, const uint8_t* blob,
                    const int64_t* offsets, int64_t n) {
  if (n < 0) return -1;
  const int64_t table_size = next_pow2(n < 4 ? 8 : 2 * n);
  const uint64_t mask = static_cast<uint64_t>(table_size - 1);

  int64_t* slots = new int64_t[table_size];
  for (int64_t i = 0; i < table_size; ++i) slots[i] = -1;
  for (int64_t id = 0; id < n; ++id) {
    const int64_t off = offsets[id];
    const int64_t len = offsets[id + 1] - off;
    uint64_t i = fnv1a(blob + off, len) & mask;
    while (slots[i] >= 0) {
      const int64_t other = slots[i];
      const int64_t ooff = offsets[other];
      if (offsets[other + 1] - ooff == len &&
          std::memcmp(blob + ooff, blob + off, len) == 0) {
        delete[] slots;
        return -2;  // duplicate key
      }
      i = (i + 1) & mask;
    }
    slots[i] = id;
  }

  FILE* f = std::fopen(path, "wb");
  if (!f) {
    delete[] slots;
    return -3;
  }
  int64_t ok = 1;
  ok &= std::fwrite(kMagic, 1, 8, f) == 8;
  ok &= std::fwrite(&n, 8, 1, f) == 1;
  ok &= std::fwrite(&table_size, 8, 1, f) == 1;
  ok &= std::fwrite(slots, 8, static_cast<size_t>(table_size), f) ==
        static_cast<size_t>(table_size);
  ok &= std::fwrite(offsets, 8, static_cast<size_t>(n + 1), f) ==
        static_cast<size_t>(n + 1);
  const int64_t blob_len = offsets[n];
  if (blob_len > 0)
    ok &= std::fwrite(blob, 1, static_cast<size_t>(blob_len), f) ==
          static_cast<size_t>(blob_len);
  delete[] slots;
  if (std::fclose(f) != 0 || !ok) return -4;
  return 0;
}

void* phidx_open(const char* path) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size < 24) {
    ::close(fd);
    return nullptr;
  }
  void* map = mmap(nullptr, st.st_size, PROT_READ, MAP_SHARED, fd, 0);
  ::close(fd);  // mapping persists
  if (map == MAP_FAILED) return nullptr;
  const uint8_t* base = static_cast<const uint8_t*>(map);
  if (std::memcmp(base, kMagic, 8) != 0) {
    munmap(map, st.st_size);
    return nullptr;
  }
  int64_t n, table_size;
  std::memcpy(&n, base + 8, 8);
  std::memcpy(&table_size, base + 16, 8);
  // Reject truncated/corrupt stores BEFORE handing out pointers: a file cut
  // mid-write still has valid magic; probing it would fault off the mapping.
  bool ok = n >= 0 && table_size >= 8 &&
            (table_size & (table_size - 1)) == 0 &&
            table_size <= (1LL << 40) && n <= table_size;
  const int64_t fixed = 24 + 8 * table_size + 8 * (n + 1);
  ok = ok && fixed <= st.st_size;
  if (ok) {
    const int64_t* offs = reinterpret_cast<const int64_t*>(base + 24 + 8 * table_size);
    int64_t prev = 0;
    for (int64_t i = 0; i <= n && ok; ++i) {
      ok = offs[i] >= prev;
      prev = offs[i];
    }
    ok = ok && fixed + (n >= 0 ? offs[n] : 0) <= st.st_size;
  }
  if (!ok) {
    munmap(map, st.st_size);
    return nullptr;
  }
  Store* s = new Store;
  s->map = map;
  s->map_len = st.st_size;
  s->n = n;
  s->table_size = table_size;
  s->slots = reinterpret_cast<const int64_t*>(base + 24);
  s->offsets = s->slots + s->table_size;
  s->blob = reinterpret_cast<const uint8_t*>(s->offsets + s->n + 1);
  return s;
}

int64_t phidx_size(const void* h) { return static_cast<const Store*>(h)->n; }

int64_t phidx_get(const void* h, const uint8_t* key, int64_t len) {
  return probe(static_cast<const Store*>(h), key, len);
}

// Batch lookup: keys packed as blob + (nkeys+1) offsets; ids written to out.
void phidx_get_batch(const void* h, const uint8_t* keys, const int64_t* offs,
                     int64_t nkeys, int64_t* out) {
  const Store* s = static_cast<const Store*>(h);
  for (int64_t i = 0; i < nkeys; ++i)
    out[i] = probe(s, keys + offs[i], offs[i + 1] - offs[i]);
}

// Reverse lookup: pointer+length of key bytes for an id (0 on bad id).
int64_t phidx_name(const void* h, int64_t id, const uint8_t** ptr,
                   int64_t* len) {
  const Store* s = static_cast<const Store*>(h);
  if (id < 0 || id >= s->n) return 0;
  const int64_t off = s->offsets[id];
  *ptr = s->blob + off;
  *len = s->offsets[id + 1] - off;
  return 1;
}

void phidx_close(void* h) {
  Store* s = static_cast<Store*>(h);
  munmap(s->map, s->map_len);
  delete s;
}

}  // extern "C"
