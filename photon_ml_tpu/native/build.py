"""Lazy g++ compilation of the native components.

One .so per translation unit, cached next to the source with an mtime check.
No pybind11 in this image — C ABI + ctypes only (plain-C signatures keep the
boundary trivially stable).
"""

from __future__ import annotations

import logging
import os
import subprocess
import tempfile
from typing import Optional

logger = logging.getLogger(__name__)

_DIR = os.path.dirname(os.path.abspath(__file__))
_FLAGS = ["-O2", "-shared", "-fPIC", "-std=c++17", "-Wall"]
# per-translation-unit link flags
_EXTRA = {"avro_loader": ["-lz"]}


def library_path(name: str) -> str:
    return os.path.join(_DIR, f"_lib{name}.so")


def compile_library(name: str, force: bool = False) -> Optional[str]:
    """Compile native/<name>.cpp -> native/_lib<name>.so; None if unavailable.

    Rebuilds when the source is newer than the cached .so.  Compiles to a
    temp file then renames (atomic on POSIX) so concurrent processes never
    load a half-written library.
    """
    src = os.path.join(_DIR, f"{name}.cpp")
    out = library_path(name)
    if not os.path.exists(src):
        return None
    if not force and os.path.exists(out) and os.path.getmtime(out) >= os.path.getmtime(src):
        return out
    tmp = None
    try:
        fd, tmp = tempfile.mkstemp(suffix=".so", dir=_DIR)
        os.close(fd)
        subprocess.run(["g++", *_FLAGS, "-o", tmp, src, *_EXTRA.get(name, [])],
                       check=True, capture_output=True, text=True)
        os.replace(tmp, out)
        return out
    except (subprocess.CalledProcessError, OSError) as e:
        # OSError covers both a missing g++ and an unwritable package dir —
        # either way the pure-python fallback takes over.
        stderr = getattr(e, "stderr", "") or str(e)
        logger.warning("native build of %s failed (pure-python fallback): %s",
                       name, stderr.strip()[:500])
        if tmp is not None and os.path.exists(tmp):
            os.unlink(tmp)
        return None
