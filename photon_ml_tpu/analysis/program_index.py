"""Whole-program index: cross-module jit resolution for photonlint.

The per-module ``JitIndex`` (analysis/jit_index.py) deliberately stops at
module boundaries — a function defined in ``core/objective.py`` and jitted
in ``parallel/fixed.py`` is invisible to the trace-scoped rules (PL001
host-sync, PL003 tracer-safety, PL004 dtype-discipline).  This module adds
the whole-program layer:

  1. parse every module of the package ONCE;
  2. build a module/symbol table — ``import a.b as c``, ``from a import b``
     (absolute and relative), module-level function defs, module-level
     string/tuple constants;
  3. seed a call graph at every jit entry point: the per-module JitIndex
     roots plus ``jax.jit(target)`` call sites whose target resolves through
     the import table to a function in ANOTHER module;
  4. propagate "traced" reachability over the call graph: a call inside
     traced code to a resolvable function (local ``Name``, imported symbol,
     ``alias.fn`` through a module alias, or ``self.method`` by name within
     the module) marks the callee traced, to a fixpoint.

``extra_roots(relpath, base_index)`` then returns, per module, the traced
functions the per-module index did NOT already cover; ``ModuleContext``
splices them into its ``JitIndex`` so every existing trace-scoped rule sees
cross-module flows with no rule changes.

The index also collects the program's **mesh-axis universe** — the axis
names of every ``jax.sharding.Mesh(...)`` constructed anywhere in the
package, with name constants (``DATA_AXIS`` et al.) resolved through the
import table — which PL007 (mesh-axis) and PL008 (sharding-annotation)
validate collective axis names and ``PartitionSpec`` strings against.

Resolution is best-effort and conservative: anything unresolvable simply
contributes nothing (no finding), so whole-program mode can only ADD
findings relative to per-module mode, never invent phantom context.
"""

from __future__ import annotations

import ast
import hashlib
import os
import time
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from photon_ml_tpu.analysis.jit_index import (FunctionNode, JitIndex,
                                              _static_names_from_call,
                                              _static_nums_from_call,
                                              _unwrap_transform, _walk_scope,
                                              dotted_name, is_jit_call,
                                              param_names)

_MESH_TERMINALS = {"Mesh"}


def module_name_for(relpath: str) -> str:
    """``photon_ml_tpu/parallel/fixed.py`` -> ``photon_ml_tpu.parallel.fixed``."""
    name = relpath.replace(os.sep, "/")
    if name.endswith(".py"):
        name = name[:-3]
    name = name.strip("/").replace("/", ".")
    if name.endswith(".__init__"):
        name = name[: -len(".__init__")]
    return name


def _source_digest(source: str) -> str:
    return hashlib.sha1(source.encode("utf-8", "surrogatepass")).hexdigest()


# relpath -> (source digest, parsed tree).  Cross-run parse reuse: the
# summary cache (analysis/dataflow) is keyed by AST-node identity
# (``id(fn)``), so reusing a cached ModuleSummaries REQUIRES the index to
# adopt the very tree object those summaries were built over — this cache
# is what makes the two identities coincide across ProgramIndex builds in
# one process (e.g. photonlint --diff linting several changed files, or
# the lint bench's repeat loop).  Unbounded but tiny: one tree per module
# file actually linted.
_PARSE_CACHE: Dict[str, Tuple[str, ast.Module]] = {}


class ModuleInfo:
    """Symbol table of one parsed module."""

    def __init__(self, relpath: str, source: str):
        self.relpath = relpath.replace(os.sep, "/")
        self.name = module_name_for(self.relpath)
        self.source = source
        self.digest = _source_digest(source)
        self.tree: Optional[ast.Module] = None
        cached = _PARSE_CACHE.get(self.relpath)
        if cached is not None and cached[0] == self.digest:
            self.tree = cached[1]
        else:
            try:
                self.tree = ast.parse(source)
                _PARSE_CACHE[self.relpath] = (self.digest, self.tree)
            except SyntaxError:
                # the framework re-parses and surfaces this as a PL000
                # finding; an unparseable module just contributes nothing
                # to the index
                pass
        # local alias -> (module dotted path, symbol-in-module or None)
        self.imports: Dict[str, Tuple[str, Optional[str]]] = {}
        # module-level function defs (jit targets / call-graph callees)
        self.defs: Dict[str, FunctionNode] = {}
        # ALL function defs by name, any nesting (self.method resolution)
        self.defs_by_name: Dict[str, List[FunctionNode]] = {}
        # module-level simple constants: NAME = <expr>
        self.constants: Dict[str, ast.expr] = {}
        self.jit_index = JitIndex(self.tree)
        if self.tree is None:
            return
        self._collect()

    def _collect(self) -> None:
        pkg = self.name.rpartition(".")[0]  # enclosing package for relatives
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.defs_by_name.setdefault(node.name, []).append(node)
        for stmt in self.tree.body:
            if isinstance(stmt, ast.Import):
                for alias in stmt.names:
                    bound = alias.asname or alias.name.split(".")[0]
                    target = alias.name if alias.asname else alias.name.split(".")[0]
                    self.imports[bound] = (target, None)
                    if alias.asname is None and "." in alias.name:
                        # `import a.b.c` binds `a`, but the dotted chain
                        # a.b.c.fn resolves through the FULL path; remember
                        # it keyed by the head with the chain retained
                        self.imports.setdefault(
                            alias.name.split(".")[0],
                            (alias.name.split(".")[0], None))
            elif isinstance(stmt, ast.ImportFrom):
                base = stmt.module or ""
                if stmt.level:  # relative import
                    parts = pkg.split(".") if pkg else []
                    cut = stmt.level - 1
                    parts = parts[: len(parts) - cut] if cut else parts
                    base = ".".join(p for p in (".".join(parts), base) if p)
                for alias in stmt.names:
                    if alias.name == "*":
                        continue
                    bound = alias.asname or alias.name
                    self.imports[bound] = (base, alias.name)
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.defs[stmt.name] = stmt
            elif isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
                tgt = stmt.targets[0]
                if isinstance(tgt, ast.Name):
                    self.constants[tgt.id] = stmt.value
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                if isinstance(stmt.target, ast.Name):
                    self.constants[stmt.target.id] = stmt.value


class ProgramIndex:
    """Cross-module symbol table + traced-reachability index (see module
    docstring).  Build once per lint run; O(total AST nodes)."""

    def __init__(self, sources: Dict[str, str]):
        t0 = time.perf_counter()
        self.modules: Dict[str, ModuleInfo] = {}      # by relpath
        self.by_name: Dict[str, ModuleInfo] = {}      # by dotted module name
        for relpath in sorted(sources):
            info = ModuleInfo(relpath, sources[relpath])
            self.modules[info.relpath] = info
            self.by_name[info.name] = info
        # id(fn) -> (ModuleInfo, fn, tracer-param names)
        self._traced: Dict[int, Tuple[ModuleInfo, FunctionNode, Set[str]]] = {}
        self._propagate()
        self.axis_universe: Set[str] = self._collect_mesh_axes()
        # lazy caches for the dataflow-backed cross-module queries
        self._on_loop: Optional[Dict[int, Tuple[ModuleInfo,
                                                FunctionNode]]] = None
        self._mesh_scoped: Optional[Dict[int, Tuple[ModuleInfo,
                                                    FunctionNode]]] = None
        self._donor_exports: Optional[Dict[str, Dict[str, Tuple[Tuple[int, ...],
                                                                Tuple[str, ...]]]]] = None
        self._summaries: Optional["ProgramSummaries"] = None
        self.build_seconds = time.perf_counter() - t0

    @classmethod
    def from_paths(cls, paths: Sequence[str], root: str) -> "ProgramIndex":
        from photon_ml_tpu.analysis.framework import _iter_py_files

        root = os.path.abspath(root)
        sources: Dict[str, str] = {}
        for path in paths:
            for fpath in _iter_py_files(path):
                rel = os.path.relpath(os.path.abspath(fpath), root)
                with open(fpath, "r", encoding="utf-8") as f:
                    sources[rel.replace(os.sep, "/")] = f.read()
        return cls(sources)

    # -- lookups -------------------------------------------------------------
    def tree_for(self, relpath: str) -> Optional[ast.Module]:
        info = self.modules.get(relpath.replace(os.sep, "/"))
        return info.tree if info else None

    def _split_target(self, full: str) -> Optional[Tuple[ModuleInfo, str]]:
        """Longest-prefix match of a dotted path against known modules;
        the remainder must be a single symbol."""
        parts = full.split(".")
        for i in range(len(parts) - 1, 0, -1):
            mod = self.by_name.get(".".join(parts[:i]))
            if mod is not None:
                rest = parts[i:]
                if len(rest) == 1:
                    return mod, rest[0]
                return None
        return None

    def resolve_symbol(self, info: ModuleInfo,
                       dotted: str) -> Optional[Tuple[ModuleInfo, str]]:
        """A dotted name as WRITTEN in ``info`` -> (defining module, symbol),
        resolved through the import table.  None when it doesn't lead to a
        module in this program."""
        head, _, rest = dotted.partition(".")
        imp = info.imports.get(head)
        if imp is None:
            return None
        target_mod, target_sym = imp
        if target_sym is None:
            full = target_mod + ("." + rest if rest else "")
        else:
            full = target_mod + "." + target_sym + ("." + rest if rest else "")
        # `from a import b` where b is a MODULE (subpackage import)
        mod = self.by_name.get(full)
        if mod is not None:
            return None  # a bare module reference, not a symbol
        return self._split_target(full)

    def resolve_function(self, info: ModuleInfo,
                         dotted: str) -> Optional[Tuple[ModuleInfo,
                                                        FunctionNode]]:
        got = self.resolve_symbol(info, dotted)
        if got is None:
            return None
        mod, sym = got
        fn = mod.defs.get(sym)
        return (mod, fn) if fn is not None else None

    def const_value(self, info: ModuleInfo, expr: ast.AST, depth: int = 0):
        """Best-effort literal value of a module-level expression: constants,
        name references (local or imported), tuples/lists.  None = unknown."""
        if depth > 8 or expr is None:
            return None
        if isinstance(expr, ast.Constant):
            return expr.value
        if isinstance(expr, (ast.Tuple, ast.List)):
            vals = []
            for e in expr.elts:
                v = self.const_value(info, e, depth + 1)
                if v is None:
                    return None
                vals.append(v)
            return tuple(vals)
        name = dotted_name(expr)
        if name is None:
            return None
        if "." not in name and name in info.constants:
            return self.const_value(info, info.constants[name], depth + 1)
        got = self.resolve_symbol(info, name)
        if got is not None:
            mod, sym = got
            if sym in mod.constants:
                return self.const_value(mod, mod.constants[sym], depth + 1)
        return None

    # -- mesh axes -----------------------------------------------------------
    def _collect_mesh_axes(self) -> Set[str]:
        axes: Set[str] = set()
        for info in self.modules.values():
            if info.tree is None:
                continue
            for node in ast.walk(info.tree):
                if not isinstance(node, ast.Call):
                    continue
                fname = dotted_name(node.func)
                if fname is None or fname.rpartition(".")[2] not in _MESH_TERMINALS:
                    continue
                axes_expr = None
                for kw in node.keywords:
                    if kw.arg == "axis_names":
                        axes_expr = kw.value
                if axes_expr is None and len(node.args) >= 2:
                    axes_expr = node.args[1]
                val = self.const_value(info, axes_expr)
                if isinstance(val, str):
                    axes.add(val)
                elif isinstance(val, tuple):
                    axes.update(v for v in val if isinstance(v, str))
        return axes

    # -- traced propagation --------------------------------------------------
    def _seed(self, info: ModuleInfo) -> Iterable[Tuple[ModuleInfo,
                                                        FunctionNode,
                                                        Set[str]]]:
        # per-module roots (decorators, local jit call sites)
        for fn, params in info.jit_index.roots:
            yield info, fn, params
        if info.tree is None:
            return
        # cross-module jit call sites: jax.jit(target) where target is an
        # imported symbol or a module-alias attribute
        for node in ast.walk(info.tree):
            if not (isinstance(node, ast.Call) and is_jit_call(node)
                    and node.args):
                continue
            target = _unwrap_transform(node.args[0])
            dn = dotted_name(target) if target is not None else None
            if dn is None:
                continue
            if "." not in dn and dn in info.defs_by_name:
                continue  # local — per-module index already covers it
            got = self.resolve_function(info, dn)
            if got is None:
                continue
            mod, fn = got
            statics = _static_names_from_call(node)
            nums = _static_nums_from_call(node)
            yield mod, fn, param_names(fn, statics, nums)

    def _propagate(self) -> None:
        stack: List[Tuple[ModuleInfo, FunctionNode, Set[str]]] = []
        for info in self.modules.values():
            for mod, fn, params in self._seed(info):
                if id(fn) not in self._traced:
                    self._traced[id(fn)] = (mod, fn, params)
                    stack.append((mod, fn, params))
        while stack:
            info, fn, params = stack.pop()
            for node, _ in _walk_scope(fn, params):
                if not isinstance(node, ast.Call):
                    continue
                callee = self._resolve_callee(info, node.func)
                if callee is None:
                    continue
                mod, target = callee
                if id(target) in self._traced:
                    continue
                # conservatively every parameter of a call-graph-reached
                # function is a tracer (mirrors nested-def handling in
                # jit_index._walk_scope)
                tparams = param_names(target, set(), set())
                self._traced[id(target)] = (mod, target, tparams)
                stack.append((mod, target, tparams))

    def _resolve_callee(self, info: ModuleInfo, func: ast.AST
                        ) -> Optional[Tuple[ModuleInfo, FunctionNode]]:
        if isinstance(func, ast.Name):
            local = info.defs.get(func.id)
            if local is not None:
                return info, local
            return self.resolve_function(info, func.id)
        if isinstance(func, ast.Attribute):
            # self.method: by-name within the module (the same terminal-attr
            # convention the per-module JitIndex uses for jit targets)
            if isinstance(func.value, ast.Name) and func.value.id == "self":
                cands = info.defs_by_name.get(func.attr)
                if cands and len(cands) == 1:
                    return info, cands[0]
                return None
            dn = dotted_name(func)
            if dn is not None:
                return self.resolve_function(info, dn)
        return None

    # -- rule-facing queries ---------------------------------------------------
    def traced_in(self, relpath: str) -> List[Tuple[FunctionNode, Set[str]]]:
        relpath = relpath.replace(os.sep, "/")
        out = [(fn, params) for (mod, fn, params) in self._traced.values()
               if mod.relpath == relpath]
        out.sort(key=lambda t: t[0].lineno)
        return out

    # -- event-loop reachability (PL013) --------------------------------------
    def _compute_on_loop(self) -> Dict[int, Tuple[ModuleInfo, FunctionNode]]:
        """Cross-module fixpoint of "runs on the asyncio event loop": seeds
        are every ``async def`` plus loop-scheduled callbacks anywhere in
        the program; propagation follows resolvable CALLS only (function
        references handed to executors are exempt by construction)."""
        from photon_ml_tpu.analysis.dataflow import (_timed, lexical_calls,
                                                     loop_callback_exprs)

        on: Dict[int, Tuple[ModuleInfo, FunctionNode]] = {}
        stack: List[Tuple[ModuleInfo, FunctionNode]] = []

        def seed(info: ModuleInfo, fn: FunctionNode) -> None:
            if id(fn) not in on:
                on[id(fn)] = (info, fn)
                stack.append((info, fn))

        with _timed():
            for info in self.modules.values():
                if info.tree is None:
                    continue
                for fns in info.defs_by_name.values():
                    for fn in fns:
                        if isinstance(fn, ast.AsyncFunctionDef):
                            seed(info, fn)
                for cb in loop_callback_exprs(info.tree):
                    if isinstance(cb, ast.Lambda):
                        seed(info, cb)
                        continue
                    got = self._resolve_callee(info, cb)
                    if got is not None:
                        seed(got[0], got[1])
            while stack:
                info, fn = stack.pop()
                for call in lexical_calls(fn):
                    got = self._resolve_callee(info, call.func)
                    if got is not None:
                        seed(got[0], got[1])
        return on

    def async_reachable_in(self, relpath: str) -> List[FunctionNode]:
        """Functions of ``relpath`` that run on (or are call-graph-reachable
        from) the asyncio event loop anywhere in the program."""
        if self._on_loop is None:
            self._on_loop = self._compute_on_loop()
        relpath = relpath.replace(os.sep, "/")
        return [fn for (mod, fn) in self._on_loop.values()
                if mod.relpath == relpath]

    # -- mesh-scoped functions (PL012) ----------------------------------------
    _MESH_BINDERS = {"shard_map", "pmap", "xmap"}

    def _compute_mesh_scoped(self) -> Dict[int, Tuple[ModuleInfo,
                                                      FunctionNode]]:
        """Functions executing under a collective-binding transform anywhere
        in the program: shard_map/pmap/xmap targets (plus vmap targets that
        bind an ``axis_name``) and everything they transitively call."""
        from photon_ml_tpu.analysis.dataflow import _timed, lexical_calls

        scoped: Dict[int, Tuple[ModuleInfo, FunctionNode]] = {}
        stack: List[Tuple[ModuleInfo, FunctionNode]] = []

        def seed(info: ModuleInfo, fn: FunctionNode) -> None:
            if id(fn) not in scoped:
                scoped[id(fn)] = (info, fn)
                stack.append((info, fn))

        with _timed():
            for info in self.modules.values():
                if info.tree is None:
                    continue
                for node in ast.walk(info.tree):
                    if not (isinstance(node, ast.Call) and node.args):
                        continue
                    fname = dotted_name(node.func)
                    term = (fname or "").rpartition(".")[2]
                    binds = term in self._MESH_BINDERS or (
                        term == "vmap"
                        and any(kw.arg == "axis_name"
                                for kw in node.keywords))
                    if not binds:
                        continue
                    target = _unwrap_transform(node.args[0])
                    if isinstance(target, (ast.FunctionDef,
                                           ast.AsyncFunctionDef, ast.Lambda)):
                        seed(info, target)
                        continue
                    got = (self._resolve_callee(info, target)
                           if target is not None else None)
                    if got is not None:
                        seed(got[0], got[1])
            while stack:
                info, fn = stack.pop()
                for call in lexical_calls(fn):
                    got = self._resolve_callee(info, call.func)
                    if got is not None:
                        seed(got[0], got[1])
        return scoped

    def mesh_scoped_in(self, relpath: str) -> List[FunctionNode]:
        if self._mesh_scoped is None:
            self._mesh_scoped = self._compute_mesh_scoped()
        relpath = relpath.replace(os.sep, "/")
        return [fn for (mod, fn) in self._mesh_scoped.values()
                if mod.relpath == relpath]

    # -- cross-module donor table (PL014) -------------------------------------
    def donor_exports(self) -> Dict[str, Dict[str, Tuple[Tuple[int, ...],
                                                         Tuple[str, ...]]]]:
        """Per module relpath: symbol -> (donate_argnums, donate_argnames)
        for every module-level name whose value donates buffers — direct
        ``jax.jit(..., donate_argnums=...)`` bindings, AOT ``.lower().
        compile()`` chains over one, and (to a cross-module fixpoint)
        module-level functions that forward their own parameters into a
        donated position of another donor."""
        if self._donor_exports is not None:
            return self._donor_exports
        from photon_ml_tpu.analysis.dataflow import _timed

        exports: Dict[str, Dict[str, Tuple[Tuple[int, ...],
                                           Tuple[str, ...]]]] = {
            relpath: {} for relpath in self.modules}

        def as_ints(val) -> Tuple[int, ...]:
            if isinstance(val, bool):
                return ()
            if isinstance(val, int):
                return (val,)
            if isinstance(val, tuple):
                return tuple(v for v in val if isinstance(v, int)
                             and not isinstance(v, bool))
            return ()

        def as_strs(val) -> Tuple[str, ...]:
            if isinstance(val, str):
                return (val,)
            if isinstance(val, tuple):
                return tuple(v for v in val if isinstance(v, str))
            return ()

        def spec_of(info: ModuleInfo, expr: ast.AST, depth: int = 0
                    ) -> Optional[Tuple[Tuple[int, ...], Tuple[str, ...]]]:
            if depth > 6 or expr is None:
                return None
            if isinstance(expr, ast.Name):
                return exports[info.relpath].get(expr.id)
            if isinstance(expr, ast.Call):
                if is_jit_call(expr):
                    nums: Tuple[int, ...] = ()
                    names: Tuple[str, ...] = ()
                    for kw in expr.keywords:
                        if kw.arg == "donate_argnums":
                            nums = as_ints(self.const_value(info, kw.value))
                        elif kw.arg == "donate_argnames":
                            names = as_strs(self.const_value(info, kw.value))
                    return (nums, names) if (nums or names) else None
                f = expr.func
                if isinstance(f, ast.Attribute) and f.attr in ("lower",
                                                               "compile"):
                    return spec_of(info, f.value, depth + 1)
                return None
            if isinstance(expr, ast.Attribute):
                if expr.attr in ("lower", "compile"):
                    return spec_of(info, expr.value, depth + 1)
                dn = dotted_name(expr)
                if dn is not None and "." in dn:
                    got = self.resolve_symbol(info, dn)
                    if got is not None:
                        mod, sym = got
                        return exports[mod.relpath].get(sym)
            return None

        with _timed():
            # pass 1: direct module-level donor bindings
            for info in self.modules.values():
                if info.tree is None:
                    continue
                for name, expr in info.constants.items():
                    spec = spec_of(info, expr)
                    if spec is not None:
                        exports[info.relpath][name] = spec
            # pass 2 (fixpoint): imported donors + derived donor functions —
            # a module-level fn forwarding its own params into a donated
            # position exports those positions, across module boundaries
            changed = True
            guard = 0
            while changed and guard < 10:
                changed = False
                guard += 1
                for info in self.modules.values():
                    if info.tree is None:
                        continue
                    for name, expr in info.constants.items():
                        if name in exports[info.relpath]:
                            continue
                        spec = spec_of(info, expr)
                        if spec is not None:
                            exports[info.relpath][name] = spec
                            changed = True
                    for fname, fn in info.defs.items():
                        a = fn.args
                        ordered = [p.arg for p in
                                   list(a.posonlyargs) + list(a.args)]
                        nums: Set[int] = set()
                        old = exports[info.relpath].get(fname)
                        if old:
                            nums.update(old[0])
                        for node in ast.walk(fn):
                            if not isinstance(node, ast.Call):
                                continue
                            spec = spec_of(info, node.func)
                            if spec is None:
                                continue
                            for i, arg in enumerate(node.args):
                                if i in spec[0] and isinstance(arg, ast.Name) \
                                        and arg.id in ordered:
                                    nums.add(ordered.index(arg.id))
                            for kw in node.keywords:
                                if kw.arg in spec[1] \
                                        and isinstance(kw.value, ast.Name) \
                                        and kw.value.id in ordered:
                                    nums.add(ordered.index(kw.value.id))
                        if nums:
                            new = (tuple(sorted(nums)),
                                   old[1] if old else ())
                            if new != old:
                                exports[info.relpath][fname] = new
                                changed = True
        self._donor_exports = exports
        return exports

    # -- interprocedural summaries (v4, PL015–PL018) --------------------------
    def summaries(self) -> "ProgramSummaries":
        """Program-wide join of the per-module function summaries (built
        lazily on first use, cached for the run)."""
        if self._summaries is None:
            self._summaries = ProgramSummaries(self)
        return self._summaries

    def extra_roots(self, relpath: str, base: JitIndex
                    ) -> List[Tuple[FunctionNode, Set[str]]]:
        """Traced functions of ``relpath`` the per-module ``base`` index does
        not already walk (not jitted there, not nested under a base root or
        an earlier extra root)."""
        covered: Set[int] = set()
        for root, _ in base.roots:
            covered.update(id(n) for n in ast.walk(root))
        extras: List[Tuple[FunctionNode, Set[str]]] = []
        for fn, params in self.traced_in(relpath):
            if base.is_jitted(fn) or id(fn) in covered:
                continue
            extras.append((fn, params))
            covered.update(id(n) for n in ast.walk(fn))
        return extras


# -- program-wide summary fixpoints (v4) --------------------------------------

# an escape fact: (class key "relpath::Class", protected attr, lock attr)
EscapeFact = Tuple[str, str, str]
# a lock-order edge witness: (relpath, function name, AST site)
LockWitness = Tuple[str, str, ast.AST]


# method names of the builtin containers/strings: a call spelled with one
# of these is near-certainly a dict/list/set/str operation, not a program
# def, whatever unique name the program happens to hold
_BUILTIN_METHOD_NAMES = frozenset(
    m for t in (dict, list, set, tuple, str, bytes)
    for m in dir(t) if not m.startswith("__"))


class ProgramSummaries:
    """Join of the per-module ``dataflow.ModuleSummaries`` across the
    program call graph.  Three fixpoints:

      * **escapes** — which lock-protected ``self.<attr>`` objects a
        function's return value may alias, closed over ``return f(...)``
        chains so an accessor-of-an-accessor still leaks (PL016);
      * **return ranks** — definite array rank of return values, closed
        over single-call return chains (PL017);
      * **lock-order graph** — directed edges ``outer -> inner`` from
        direct lexical nesting AND from calls made while holding a lock
        into the callee's transitive acquisitions; strongly-connected
        components of size >= 2 are deadlock cycles (PL018).  Reentrant
        RLock self-nesting never forms an edge (self-edges are dropped),
        and lock identity is class-level, so a cycle here means two code
        paths take the same two locks in opposite orders somewhere.
    """

    def __init__(self, index: ProgramIndex):
        from photon_ml_tpu.analysis.dataflow import (_timed_summary,
                                                     cached_module_summaries)

        self.index = index
        self.mod: Dict[str, "ModuleSummaries"] = {}
        # id(fn) -> (owning ModuleInfo, its FunctionSummary)
        self._owner: Dict[int, Tuple[ModuleInfo, object]] = {}
        for relpath, info in index.modules.items():
            # digest-keyed summary reuse: a module whose source (and
            # therefore, via the index's parse cache, whose TREE object)
            # is unchanged since the last run in this process skips the
            # whole per-function summary pass — the id(fn) keys stay
            # valid because the tree is the same object
            ms = cached_module_summaries(info.tree, relpath, info.digest)
            self.mod[relpath] = ms
            for fid, summ in ms.by_id.items():
                self._owner[fid] = (info, summ)
        with _timed_summary():
            # program-wide def-name census (for the cautious unique-by-name
            # fallback PL016 uses on non-self attribute calls)
            self._name_count: Dict[str, int] = {}
            for info in index.modules.values():
                for name, fns in info.defs_by_name.items():
                    self._name_count[name] = (self._name_count.get(name, 0)
                                              + len(fns))
            self.escapes: Dict[int, frozenset] = self._fix_escapes()
            self._ranks: Dict[int, Optional[int]] = self._fix_ranks()
            self.lock_edges: Dict[Tuple[str, str], LockWitness] = {}
            self.lock_cycles: List[Tuple[Tuple[str, ...],
                                         Dict[Tuple[str, str],
                                              LockWitness]]] = []
            self._build_lock_graph()

    # -- shared resolution ----------------------------------------------------
    def _resolve_call(self, info: ModuleInfo,
                      func: ast.AST) -> Optional[int]:
        got = self.index._resolve_callee(info, func)
        if got is None:
            return None
        fid = id(got[1])
        return fid if fid in self._owner else None

    # -- escape fixpoint ------------------------------------------------------
    def _fix_escapes(self) -> Dict[int, frozenset]:
        esc: Dict[int, frozenset] = {}
        for fid, (info, s) in self._owner.items():
            if s.cls is None or not s.return_attrs:
                continue
            ms = self.mod[info.relpath]
            hits = s.return_attrs & ms.locked_attrs_of(s.cls)
            if hits:
                # immutable-valued attrs cannot be mutated through the
                # alias — classified lazily, only when a hit exists
                hits -= ms.immutable_attrs_of(s.cls)
            if hits:
                lock = ms.lock_display.get(s.cls, "_lock")
                key = f"{info.relpath}::{s.cls}"
                esc[fid] = frozenset((key, a, lock) for a in hits)
        changed, guard = True, 0
        while changed and guard < 12:
            changed, guard = False, guard + 1
            for fid, (info, s) in self._owner.items():
                if not s.return_calls:
                    continue
                cur = esc.get(fid, frozenset())
                new = cur
                for call in s.return_calls:
                    callee = self._resolve_call(info, call.func)
                    if callee is not None:
                        new = new | esc.get(callee, frozenset())
                if new != cur:
                    esc[fid] = new
                    changed = True
        return esc

    def escape_facts(self, fn: ast.AST) -> frozenset:
        """Escape facts of a function node (empty when it leaks nothing)."""
        return self.escapes.get(id(fn), frozenset())

    def resolve_escape_source(self, relpath: str, expr: ast.AST
                              ) -> Optional[Tuple[frozenset, str]]:
        """Escape facts of the function a VALUE expression was produced by:
        ``store.table()`` / ``self.hot()`` calls, or a bare attribute access
        hitting a @property.  Unresolvable receivers fall back to a
        program-wide unique-name match — only when exactly ONE def in the
        whole program carries that name, so the match cannot be wrong.
        Returns (facts, display name of the source) or None."""
        info = self.index.modules.get(relpath)
        if info is None:
            return None
        if isinstance(expr, ast.Call):
            fid = self._resolve_call(info, expr.func)
            if fid is None and isinstance(expr.func, ast.Attribute):
                fid = self._unique_by_name(expr.func.attr)
            if fid is not None and self.escapes.get(fid):
                _, s = self._owner[fid]
                return self.escapes[fid], self._display(fid)
            return None
        if isinstance(expr, ast.Attribute) \
                and not (isinstance(expr.value, ast.Name)
                         and expr.value.id == "self"):
            fid = self._unique_by_name(expr.attr)
            if fid is not None:
                _, s = self._owner[fid]
                if s.is_property and self.escapes.get(fid):
                    return self.escapes[fid], self._display(fid)
        return None

    def _unique_by_name(self, name: str) -> Optional[int]:
        if self._name_count.get(name) != 1:
            return None
        for fid, (info, s) in self._owner.items():
            if s.name == name:
                return fid
        return None

    def _display(self, fid: int) -> str:
        info, s = self._owner[fid]
        qual = f"{s.cls}.{s.name}" if s.cls else s.name
        return f"{qual} ({info.relpath})"

    # -- return-rank fixpoint -------------------------------------------------
    def _fix_ranks(self) -> Dict[int, Optional[int]]:
        ranks: Dict[int, Optional[int]] = {
            fid: s.return_rank for fid, (_, s) in self._owner.items()}
        changed, guard = True, 0
        while changed and guard < 12:
            changed, guard = False, guard + 1
            for fid, (info, s) in self._owner.items():
                if ranks.get(fid) is not None or s.return_rank_call is None:
                    continue
                callee = self._resolve_call(info, s.return_rank_call.func)
                if callee is not None and ranks.get(callee) is not None:
                    ranks[fid] = ranks[callee]
                    changed = True
        return ranks

    def call_rank(self, relpath: str, call: ast.Call) -> Optional[int]:
        """Definite return rank of a call expression, through the summary
        fixpoint (None when the callee or its rank is unknown)."""
        info = self.index.modules.get(relpath)
        if info is None:
            return None
        fid = self._resolve_call(info, call.func)
        return self._ranks.get(fid) if fid is not None else None

    # -- lock-order graph -----------------------------------------------------
    def _resolve_lock_call(self, info: ModuleInfo,
                           func: ast.AST) -> Optional[int]:
        """``_resolve_call`` plus a cautious unique-by-name fallback for
        method calls through an object attribute (``self.beta.grab()``) —
        the shape cross-object lock nesting actually takes in the serving
        plane.  Two guards keep the fallback honest: builtin-container/str
        method names never match (``dropped.append`` must not resolve to a
        class's own ``append``), and a chain rooted at an imported module
        alias never matches (``os.remove`` is not a method call).  A unique
        program-wide def name past both guards cannot mis-resolve; anything
        ambiguous stays unresolved and forms no edge."""
        fid = self._resolve_call(info, func)
        if fid is not None or not isinstance(func, ast.Attribute):
            return fid
        if func.attr in _BUILTIN_METHOD_NAMES:
            return None
        node: ast.AST = func.value
        while isinstance(node, (ast.Attribute, ast.Subscript)):
            node = node.value
        if isinstance(node, ast.Name) and node.id in info.imports:
            return None
        return self._unique_by_name(func.attr)

    def _transitive_acquires(self, fid: int, memo: Dict[int, Set[str]],
                             seen: Set[int]) -> Set[str]:
        got = memo.get(fid)
        if got is not None:
            return got
        if fid in seen:  # call cycle — contribute what is known so far
            return set()
        seen.add(fid)
        info, s = self._owner[fid]
        acc: Set[str] = set(s.lock_acquires)
        for call in s.calls:
            callee = self._resolve_lock_call(info, call.func)
            if callee is not None:
                acc |= self._transitive_acquires(callee, memo, seen)
        memo[fid] = acc
        return acc

    def _build_lock_graph(self) -> None:
        edges = self.lock_edges
        memo: Dict[int, Set[str]] = {}
        for fid, (info, s) in self._owner.items():
            for outer, inner, site in s.lock_pairs:
                if outer != inner:
                    edges.setdefault((outer, inner),
                                     (info.relpath, s.name, site))
            for outer, call in s.held_calls:
                callee = self._resolve_lock_call(info, call.func)
                if callee is None:
                    continue
                for inner in self._transitive_acquires(callee, memo, set()):
                    if inner != outer:
                        edges.setdefault((outer, inner),
                                         (info.relpath, s.name, call))
        # Tarjan SCC over the key graph; every SCC with >= 2 nodes is a
        # deadlock cycle (self-edges were never added)
        adj: Dict[str, List[str]] = {}
        for (a, b) in edges:
            adj.setdefault(a, []).append(b)
            adj.setdefault(b, [])
        index_of: Dict[str, int] = {}
        low: Dict[str, int] = {}
        on_stack: Set[str] = set()
        stack: List[str] = []
        counter = [0]
        sccs: List[List[str]] = []

        def strongconnect(v: str) -> None:
            work = [(v, iter(adj[v]))]
            index_of[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            on_stack.add(v)
            while work:
                node, it = work[-1]
                advanced = False
                for w in it:
                    if w not in index_of:
                        index_of[w] = low[w] = counter[0]
                        counter[0] += 1
                        stack.append(w)
                        on_stack.add(w)
                        work.append((w, iter(adj[w])))
                        advanced = True
                        break
                    if w in on_stack:
                        low[node] = min(low[node], index_of[w])
                if advanced:
                    continue
                work.pop()
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])
                if low[node] == index_of[node]:
                    comp: List[str] = []
                    while True:
                        w = stack.pop()
                        on_stack.discard(w)
                        comp.append(w)
                        if w == node:
                            break
                    if len(comp) >= 2:
                        sccs.append(comp)

        for v in sorted(adj):
            if v not in index_of:
                strongconnect(v)
        for comp in sccs:
            keys = tuple(sorted(comp))
            members = set(comp)
            cyc_edges = {e: w for e, w in edges.items()
                         if e[0] in members and e[1] in members}
            self.lock_cycles.append((keys, cyc_edges))
        self.lock_cycles.sort(key=lambda c: c[0])
