"""Baseline file: accepted pre-existing debt, committed to the repo.

The gate (tools/photonlint.py, tests/test_photonlint.py) fails only on
violations whose fingerprint is NOT in the baseline — so landing the linter
does not require fixing every historical finding at once, while any NEW
violation fails tier-1 immediately.  Entries carry the human-readable
finding alongside the fingerprint so reviewers can audit the debt; stale
entries (fingerprints no longer produced) are reported so the baseline
shrinks monotonically instead of accreting.

Fingerprints (framework.Violation.fingerprint) hash rule, path, message and
the stripped source line — not the line number — so pure renumbering edits
don't invalidate the baseline.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Iterable, List, Sequence, Tuple

from photon_ml_tpu.analysis.framework import Violation

FORMAT_VERSION = 1


class BaselineError(ValueError):
    """Malformed baseline file (bad JSON, wrong version/shape)."""


def empty_baseline() -> dict:
    return {"version": FORMAT_VERSION, "entries": {}}


def make_baseline(violations: Iterable[Violation]) -> dict:
    entries = {}
    for v in sorted(violations, key=lambda v: (v.path, v.line, v.col)):
        entries[v.fingerprint()] = {
            "rule": v.rule, "code": v.code, "path": v.path,
            "message": v.message, "snippet": v.snippet.strip(),
            "occurrence": v.occurrence,
        }
    return {"version": FORMAT_VERSION, "entries": entries}


def save_baseline(baseline: dict, path: str) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(baseline, f, indent=2, sort_keys=True)
        f.write("\n")


def load_baseline(path: str) -> dict:
    if not os.path.exists(path):
        return empty_baseline()
    with open(path, "r", encoding="utf-8") as f:
        try:
            data = json.load(f)
        except json.JSONDecodeError as e:
            raise BaselineError(f"baseline {path}: invalid JSON: {e}") from e
    if not isinstance(data, dict) or "entries" not in data:
        raise BaselineError(f"baseline {path}: expected "
                            "{{'version': ..., 'entries': {{...}}}}")
    if data.get("version") != FORMAT_VERSION:
        raise BaselineError(f"baseline {path}: unsupported version "
                            f"{data.get('version')!r} (want {FORMAT_VERSION})")
    if not isinstance(data["entries"], dict):
        raise BaselineError(f"baseline {path}: 'entries' must be an object")
    return data


def partition(violations: Sequence[Violation], baseline: dict
              ) -> Tuple[List[Violation], List[Violation], List[str]]:
    """Split findings against a baseline.

    Returns ``(new, baselined, stale_fingerprints)`` — ``new`` fails the
    gate, ``baselined`` is accepted debt, ``stale_fingerprints`` are
    baseline entries nothing matched (fixed debt; prune them)."""
    entries: Dict[str, dict] = baseline.get("entries", {})
    new: List[Violation] = []
    matched: List[Violation] = []
    seen = set()
    for v in violations:
        fp = v.fingerprint()
        if fp in entries:
            matched.append(v)
            seen.add(fp)
        else:
            new.append(v)
    stale = sorted(fp for fp in entries if fp not in seen)
    return new, matched, stale
