"""photonlint: JAX/TPU-aware static analysis for this codebase.

Entry points:
  - ``python -m tools.photonlint photon_ml_tpu/`` — the CLI gate;
  - ``tests/test_photonlint.py`` — the tier-1 wiring (fails on any
    non-baselined violation);
  - :func:`run_analysis` / :func:`analyze_source` — the library API.

See analysis/rules/__init__.py for the rule catalog and README "Static
analysis" for the suppression/baseline workflow.
"""

from photon_ml_tpu.analysis.framework import (AnalysisResult, ModuleContext,
                                              Rule, Violation, analyze_source,
                                              build_rules, register,
                                              registered_rules, run_analysis)
from photon_ml_tpu.analysis.baseline import (BaselineError, empty_baseline,
                                             load_baseline, make_baseline,
                                             partition, save_baseline)
from photon_ml_tpu.analysis.reporters import (render_json, render_sarif,
                                              render_text)

__all__ = [
    "AnalysisResult", "ModuleContext", "Rule", "Violation",
    "analyze_source", "build_rules", "register", "registered_rules",
    "run_analysis",
    "BaselineError", "empty_baseline", "load_baseline", "make_baseline",
    "partition", "save_baseline",
    "render_json", "render_sarif", "render_text",
]
