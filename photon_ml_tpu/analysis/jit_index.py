"""Which functions in a module execute under ``jax.jit``?

The rules that police trace-time behaviour (host-sync, tracer-safety,
dtype-discipline) only apply inside code that actually runs under a jit
trace.  This index resolves, per module, the idioms this codebase uses:

  - ``@jax.jit`` / ``@jit`` / ``@pjit`` decorators;
  - ``@functools.partial(jax.jit, static_argnames=...)`` decorators;
  - ``jax.jit(fn)`` / ``jax.jit(fn).lower(...)`` where ``fn`` is a function
    defined in the same module (any scope) — the AOT idiom of
    serving/engine.py and the solver-wrapping idiom of game/coordinate.py;
  - ``jax.jit(jax.vmap(fn))`` and other transform sandwiches — the wrapper
    chain (vmap/grad/value_and_grad/remat/partial) is unwrapped to the
    innermost function reference;
  - ``jax.jit(lambda ...: ...)`` — the lambda body is jit code.

Cross-module flows (a function passed to a jit defined elsewhere) are out of
scope HERE — per-module analysis keeps the pass dependency-free and O(file);
the whole-program layer (analysis/program_index.py) resolves them and
splices the result back in through :meth:`JitIndex.add_root`.
``static_argnames``/``static_argnums`` are honoured when given as literals:
static parameters are concrete Python values at trace time, not tracers, so
param-sensitive checks must skip them.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple, Union

FunctionNode = Union[ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda]

# dotted names that mean "this call/decorator jits its argument/target"
JIT_NAMES = {
    "jax.jit", "jit", "pjit", "jax.pjit",
    "jax.experimental.pjit.pjit",
}
# transforms whose first argument is (eventually) the traced function
WRAPPER_NAMES = {
    "jax.vmap", "vmap", "jax.grad", "grad", "jax.value_and_grad",
    "value_and_grad", "jax.remat", "jax.checkpoint", "remat",
    "functools.partial", "partial",
}
PARTIAL_NAMES = {"functools.partial", "partial"}


def dotted_name(node: ast.AST) -> Optional[str]:
    """``ast.Attribute``/``ast.Name`` chain -> "a.b.c" (else None)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def is_jit_call(node: ast.Call) -> bool:
    """True for ``jax.jit(...)`` / ``jit(...)`` style calls."""
    name = dotted_name(node.func)
    return name in JIT_NAMES


def is_partial_jit(node: ast.Call) -> bool:
    """True for ``functools.partial(jax.jit, ...)``."""
    name = dotted_name(node.func)
    if name not in PARTIAL_NAMES or not node.args:
        return False
    return dotted_name(node.args[0]) in JIT_NAMES


def _static_names_from_call(call: ast.Call) -> Set[str]:
    """Literal ``static_argnames`` from a jit call/decorator (best effort)."""
    names: Set[str] = set()
    for kw in call.keywords:
        if kw.arg != "static_argnames":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            names.add(v.value)
        elif isinstance(v, (ast.Tuple, ast.List)):
            for elt in v.elts:
                if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                    names.add(elt.value)
    return names


def _static_nums_from_call(call: ast.Call) -> Set[int]:
    nums: Set[int] = set()
    for kw in call.keywords:
        if kw.arg != "static_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            nums.add(v.value)
        elif isinstance(v, (ast.Tuple, ast.List)):
            for elt in v.elts:
                if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                    nums.add(elt.value)
    return nums


def _unwrap_transform(node: ast.AST) -> Optional[ast.AST]:
    """Peel vmap/grad/partial sandwiches down to the function reference."""
    while isinstance(node, ast.Call) and dotted_name(node.func) in WRAPPER_NAMES:
        if not node.args:
            return None
        node = node.args[0]
    return node


def param_names(fn: FunctionNode, static_names: Set[str],
                static_nums: Set[int]) -> Set[str]:
    """Parameter names that are TRACERS under jit (statics excluded)."""
    a = fn.args
    ordered = list(a.posonlyargs) + list(a.args)
    names: Set[str] = set()
    for i, arg in enumerate(ordered):
        if i in static_nums or arg.arg in static_names:
            continue
        names.add(arg.arg)
    for arg in a.kwonlyargs:
        if arg.arg not in static_names:
            names.add(arg.arg)
    if a.vararg:
        names.add(a.vararg.arg)
    # **kwargs of a jitted fn is at best unusual; treat values as tracers
    if a.kwarg:
        names.add(a.kwarg.arg)
    return names


class JitIndex:
    """Per-module map of jit-executed functions.

    ``roots``: list of (function node, tracer-param name set).  A root is a
    jitted function NOT nested inside another jitted function (rules walk a
    root's whole body, so nested defs are covered by their outermost root —
    their params are re-resolved during the walk).
    """

    def __init__(self, tree: Optional[ast.Module]):
        self.roots: List[Tuple[FunctionNode, Set[str]]] = []
        self._jitted: Dict[int, Tuple[FunctionNode, Set[str], Set[int]]] = {}
        if tree is None:
            return
        self._defs_by_name: Dict[str, List[FunctionNode]] = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._defs_by_name.setdefault(node.name, []).append(node)
        self._collect_decorated(tree)
        self._collect_call_sites(tree)
        self._resolve_roots(tree)

    # -- collection --------------------------------------------------------
    def _mark(self, fn: Optional[ast.AST], statics: Set[str],
              nums: Set[int]) -> None:
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            self._jitted[id(fn)] = (fn, statics, nums)

    def _collect_decorated(self, tree: ast.Module) -> None:
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for dec in node.decorator_list:
                if dotted_name(dec) in JIT_NAMES:
                    self._mark(node, set(), set())
                elif isinstance(dec, ast.Call) and (is_jit_call(dec) or
                                                    is_partial_jit(dec)):
                    self._mark(node, _static_names_from_call(dec),
                               _static_nums_from_call(dec))

    def _collect_call_sites(self, tree: ast.Module) -> None:
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call) and is_jit_call(node)):
                continue
            if not node.args:
                continue
            statics = _static_names_from_call(node)
            nums = _static_nums_from_call(node)
            target = _unwrap_transform(node.args[0])
            if isinstance(target, ast.Lambda):
                self._mark(target, statics, nums)
            elif isinstance(target, ast.Name):
                for fn in self._defs_by_name.get(target.id, ()):
                    self._mark(fn, statics, nums)
            elif isinstance(target, ast.Attribute):
                # self._method / module.fn: resolve by terminal attribute
                for fn in self._defs_by_name.get(target.attr, ()):
                    self._mark(fn, statics, nums)

    def _resolve_roots(self, tree: ast.Module) -> None:
        # a jitted def nested inside another jitted def is covered by the
        # outer root's walk; report each region once
        inner: Set[int] = set()
        for fn, _, _ in self._jitted.values():
            for sub in ast.walk(fn):
                if sub is fn:
                    continue
                if id(sub) in self._jitted:
                    inner.add(id(sub))
        for key, (fn, statics, nums) in self._jitted.items():
            if key in inner:
                continue
            self.roots.append((fn, param_names(fn, statics, nums)))
        self.roots.sort(key=lambda r: r[0].lineno)

    # -- queries -----------------------------------------------------------
    def is_jitted(self, fn: ast.AST) -> bool:
        return id(fn) in self._jitted

    def add_root(self, fn: FunctionNode, params: Set[str]) -> None:
        """Splice in an externally-resolved traced root (the whole-program
        layer's cross-module jit targets and call-graph-reached helpers)."""
        if id(fn) in self._jitted:
            return
        self._jitted[id(fn)] = (fn, set(), set())
        self.roots.append((fn, params))
        self.roots.sort(key=lambda r: r[0].lineno)


def walk_jit_code(index: JitIndex):
    """Yield (node, tracer_param_names) for every node that executes under a
    jit trace.  Entering a nested function swaps in that function's params
    (its arguments are traced values when called from traced code)."""
    for root, params in index.roots:
        yield from _walk_scope(root, params)


def _walk_scope(fn: FunctionNode, params: Set[str]):
    body = fn.body if isinstance(fn.body, list) else [fn.body]
    stack: List[Tuple[ast.AST, Set[str]]] = [(n, params) for n in body]
    while stack:
        node, cur = stack.pop()
        yield node, cur
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            sub_params = cur | param_names(node, set(), set())
            sub_body = node.body if isinstance(node.body, list) else [node.body]
            stack.extend((n, sub_params) for n in sub_body)
        else:
            stack.extend((child, cur) for child in ast.iter_child_nodes(node))


def expr_references(node: ast.AST, names: Set[str],
                    prune_static: bool = True) -> bool:
    """Does ``node`` reference any name in ``names`` as a (possibly derived)
    traced VALUE?  With ``prune_static``, sub-expressions that are concrete
    at trace time are skipped: ``x is None`` / ``x is not None`` tests,
    ``x.shape`` / ``x.ndim`` / ``x.dtype`` / ``x.size`` attribute reads, and
    ``len(x)`` / ``isinstance(x, ...)`` calls."""
    STATIC_ATTRS = {"shape", "ndim", "dtype", "size"}
    STATIC_CALLS = {"len", "isinstance", "type", "hasattr", "getattr"}

    def visit(n: ast.AST) -> bool:
        if prune_static:
            if isinstance(n, ast.Compare) and all(
                    isinstance(op, (ast.Is, ast.IsNot)) for op in n.ops):
                return False
            if isinstance(n, ast.Attribute) and n.attr in STATIC_ATTRS:
                return False
            if (isinstance(n, ast.Call) and isinstance(n.func, ast.Name)
                    and n.func.id in STATIC_CALLS):
                return False
        if isinstance(n, ast.Name) and n.id in names:
            return True
        return any(visit(c) for c in ast.iter_child_nodes(n))

    return visit(node)
