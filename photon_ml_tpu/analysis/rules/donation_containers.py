"""PL015 container-donation-taint: PL006/PL014's donated-buffer taint,
driven through container literals, subscripts, unpacks, and pytree helpers.

Why it matters here: the serving and transfer planes pack buffers before
handing them to donating executables — ``(features, slots)`` tuples into an
AOT scorer, ``dict(grads=g)`` into an update step, ``jax.tree_util``
flatten/map chains over parameter trees.  PL006 deliberately taints only
plain-``Name`` arguments; a buffer smuggled into a donated position inside
a tuple is invisible to it, and so is a read of the TUPLE after one of its
leaves was donated.  Both directions are use-after-frees on TPU/GPU that
CPU runs silently tolerate.

On top of the v4 summary layer's container-provenance tracking, this rule
extends the PL006 scope scan with an *element table*: which local names a
container name holds, per position where the literal is ordered.  It is
populated by tuple/list/dict literals, ``dict(x=buf)`` calls, positional
unpacking of a known literal, constant-index subscripts, and the pytree
helpers (``tree_leaves``/``tree_flatten``/``tree_map``/...; per the repo's
donation contracts a mapped tree is treated as aliasing its input's
leaves).  At a donating call:

  - a **container argument** in a donated position taints every
    contributing name (the packed leaves), so a later read of a leaf is
    flagged;
  - a **Name argument** in a donated position taints its known elements
    (the name itself stays PL006's jurisdiction — no double report) and
    every container that holds the name, so reading ``pair`` after
    ``donating(a)`` with ``pair = (a, b)`` is flagged too.

Donors are PL006's module-local discovery plus, in whole-program mode,
PL014's cross-module donor table — the same donor universe, one more level
of provenance.  Re-assignment of a name clears both taint and elements
(the rebind idiom stays sanctioned).
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, Iterator, List, Optional, Tuple

from photon_ml_tpu.analysis.framework import (ModuleContext, Rule, Violation,
                                              register)
from photon_ml_tpu.analysis.jit_index import dotted_name
from photon_ml_tpu.analysis.rules.donation import (DonateSpec,
                                                   discover_module_donors)
from photon_ml_tpu.analysis.rules.donation_flow import (_CrossModuleScanner,
                                                        cross_module_donors)

_EMPTY: FrozenSet[str] = frozenset()
_TREE_TERMINALS = {"tree_map", "tree_multimap", "tree_leaves", "tree_flatten",
                   "tree_unflatten", "tree_transpose"}
_TREE_SHORT = {"map", "leaves", "flatten", "unflatten", "transpose"}
_PACKERS = {"tuple", "list", "dict"}


def _is_tree_helper(call: ast.Call) -> bool:
    dn = dotted_name(call.func) or ""
    head, _, term = dn.rpartition(".")
    return term in _TREE_TERMINALS or (
        term in _TREE_SHORT and (head == "tree" or head.endswith(".tree")))


def _tree_value_args(call: ast.Call) -> List[ast.AST]:
    term = (dotted_name(call.func) or "").rpartition(".")[2]
    args = list(call.args)
    if term in ("tree_map", "tree_multimap", "map", "tree_unflatten",
                "unflatten") and args:
        args = args[1:]  # first arg is the mapped fn / the treedef
    return args


class _ContainerScanner(_CrossModuleScanner):
    """PL006's scope scanner plus the container element table.  Taint text
    is stored pre-rendered (the base scanner's message assumes the tainted
    name was donated directly, which is exactly what PL015 is NOT about)."""

    def __init__(self, rule, ctx, donors, fn_params, xresolve):
        super().__init__(rule, ctx, donors, fn_params, xresolve)
        # container name -> ordered per-slot contributing-name sets; helpers
        # and unordered literals collapse to a single slot
        self.slots: Dict[str, Tuple[FrozenSet[str], ...]] = {}

    # -- provenance ----------------------------------------------------------
    def _flat(self, name: str) -> FrozenSet[str]:
        got = self.slots.get(name)
        return frozenset().union(*got) if got else _EMPTY

    def _contrib(self, expr: ast.AST, depth: int = 0) -> FrozenSet[str]:
        """Names whose buffers the VALUE expression may hold."""
        if expr is None or depth > 5:
            return _EMPTY
        if isinstance(expr, ast.Name):
            return frozenset((expr.id,)) | self._flat(expr.id)
        if isinstance(expr, (ast.Tuple, ast.List, ast.Set)):
            out = _EMPTY
            for e in expr.elts:
                out |= self._contrib(e, depth + 1)
            return out
        if isinstance(expr, ast.Starred):
            return self._contrib(expr.value, depth + 1)
        if isinstance(expr, ast.Dict):
            out = _EMPTY
            for v in expr.values:
                out |= self._contrib(v, depth + 1)
            return out
        if isinstance(expr, ast.Subscript):
            slot = self._subscript_slot(expr)
            if slot is not None:
                return slot
            if isinstance(expr.value, ast.Name):
                return self._flat(expr.value.id)
            return self._contrib(expr.value, depth + 1)
        if isinstance(expr, ast.IfExp):
            return (self._contrib(expr.body, depth + 1)
                    | self._contrib(expr.orelse, depth + 1))
        if isinstance(expr, ast.Call):
            if _is_tree_helper(expr):
                out = _EMPTY
                for a in _tree_value_args(expr):
                    out |= self._contrib(a, depth + 1)
                return out
            if isinstance(expr.func, ast.Name) and expr.func.id in _PACKERS:
                out = _EMPTY
                for a in expr.args:
                    out |= self._contrib(a, depth + 1)
                for kw in expr.keywords:
                    out |= self._contrib(kw.value, depth + 1)
                return out
        return _EMPTY

    def _subscript_slot(self, expr: ast.Subscript
                        ) -> Optional[FrozenSet[str]]:
        """``pair[0]`` with an ordered provenance for ``pair`` -> the exact
        slot; None when the index or the ordering is unknown."""
        if not (isinstance(expr.value, ast.Name)
                and isinstance(expr.slice, ast.Constant)
                and isinstance(expr.slice.value, int)):
            return None
        got = self.slots.get(expr.value.id)
        if got is None or len(got) < 2:
            return None
        idx = expr.slice.value
        return got[idx] if -len(got) <= idx < len(got) else None

    def _ordered_slots(self, expr: ast.AST
                       ) -> Optional[Tuple[FrozenSet[str], ...]]:
        if isinstance(expr, (ast.Tuple, ast.List)) \
                and not any(isinstance(e, ast.Starred) for e in expr.elts):
            return tuple(self._contrib(e, 1) for e in expr.elts)
        if isinstance(expr, ast.Name):
            return self.slots.get(expr.id)
        return None

    # -- scanner overrides ---------------------------------------------------
    def _bind_donors(self, stmt: ast.stmt) -> None:
        super()._bind_donors(stmt)
        if not isinstance(stmt, ast.Assign) or len(stmt.targets) != 1:
            return
        tgt = stmt.targets[0]
        if isinstance(tgt, ast.Name):
            slots = self._ordered_slots(stmt.value)
            if slots is None:
                flat = self._contrib(stmt.value) - {tgt.id}
                slots = (flat,) if flat else None
            if slots:
                self.slots[tgt.id] = slots
            else:
                self.slots.pop(tgt.id, None)
        elif isinstance(tgt, ast.Tuple) \
                and not any(isinstance(e, ast.Starred) for e in tgt.elts):
            # positional unpack of a KNOWN ordered literal only — a generic
            # `a, b = pair` stays unbound rather than over-aliasing slots
            src = self._ordered_slots(stmt.value)
            names = [e.id if isinstance(e, ast.Name) else None
                     for e in tgt.elts]
            if src is not None and len(src) == len(names):
                for name, slot in zip(names, src):
                    if name is None:
                        continue
                    if slot:
                        self.slots[name] = (slot,)
                    else:
                        self.slots.pop(name, None)
            elif isinstance(stmt.value, ast.Call) \
                    and _is_tree_helper(stmt.value) and names and names[0]:
                # flat, treedef = tree_flatten(bufs) — leaves land first
                flat = self._contrib(stmt.value)
                if flat:
                    self.slots[names[0]] = (flat,)

    def _clear_stores(self, stmt: ast.stmt) -> None:
        super()._clear_stores(stmt)
        # every store kills provenance; _bind_donors re-derives it right
        # after for the single-Assign / known-unpack shapes
        for node in ast.walk(stmt):
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
                self.slots.pop(node.id, None)

    def _taint_calls(self, stmt: ast.stmt) -> None:
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Call):
                continue
            spec = self._spec_of_expr(node.func)
            if not spec:
                continue
            donor = dotted_name(node.func) or "<donating executable>"
            for i, arg in enumerate(node.args):
                if i in spec.argnums:
                    self._donate_value(arg, donor)
            for kw in node.keywords:
                if kw.arg in spec.argnames:
                    self._donate_value(kw.value, donor)

    def _donate_value(self, arg: ast.AST, donor: str) -> None:
        line = getattr(arg, "lineno", 0)
        if isinstance(arg, ast.Name):
            # the name itself is PL006's finding; here: its packed leaves
            # and every container that holds it
            for leaf in self._flat(arg.id):
                self._taint(leaf, line,
                            f"was packed into `{arg.id}`, which was donated "
                            f"to `{donor}`")
            for holder, slots in self.slots.items():
                if any(arg.id in s for s in slots):
                    self._taint(holder, line,
                                f"holds `{arg.id}`, which was donated to "
                                f"`{donor}`")
            return
        # container literal / dict() / pytree-helper argument: every
        # contributing name is donated with it
        for leaf in sorted(self._contrib(arg)):
            self._taint(leaf, line,
                        f"was packed into a container donated to `{donor}`")

    def _taint(self, name: str, line: int, why: str) -> None:
        self.tainted[name] = (line, why)

    def _expr(self, node: ast.AST) -> None:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load) \
                    and sub.id in self.tainted \
                    and id(sub) not in self._flagged:
                self._flagged.add(id(sub))
                line, why = self.tainted[sub.id]
                self.violations.append(self.ctx.violation(
                    self.rule, sub,
                    f"`{sub.id}` {why} (line {line}) and is read again — "
                    "donation invalidates every pytree leaf; on TPU/GPU "
                    "this is a use-after-free that CPU runs hide. Rebind "
                    "the result or drop the donation"))


@register
class ContainerDonationRule(Rule):
    name = "container-donation-taint"
    code = "PL015"
    severity = "error"
    description = ("no reads of a buffer donated inside a container "
                   "(tuple/list/dict literal, unpack, or pytree helper), "
                   "nor of a container whose leaf was donated")

    def check(self, ctx: ModuleContext) -> Iterator[Violation]:
        if ctx.tree is None:
            return
        xresolve = None
        donors: Dict[str, DonateSpec] = {}
        if ctx.program is not None:
            got = cross_module_donors(ctx)
            if got is not None:
                donors, xresolve = got
        if "donate_arg" not in ctx.source and not donors \
                and xresolve is None:
            return
        if "donate_arg" in ctx.source:
            local, self_donors = discover_module_donors(self, ctx)
            donors = {**local, **donors}
        else:
            self_donors = {}
        if xresolve is None:
            xresolve = lambda dn: None  # noqa: E731 — per-module mode
        yield from self._scan(ctx, ctx.tree.body, donors, self_donors, (),
                              xresolve)
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                a = node.args
                params = [p.arg for p in list(a.posonlyargs) + list(a.args)
                          + list(a.kwonlyargs)]
                yield from self._scan(ctx, node.body, donors, self_donors,
                                      params, xresolve)

    def _scan(self, ctx, body, donors, self_donors, params, xresolve
              ) -> Iterator[Violation]:
        scanner = _ContainerScanner(self, ctx, donors, params, xresolve)
        scanner.self_donors = self_donors
        scanner.run(body)
        yield from scanner.violations
