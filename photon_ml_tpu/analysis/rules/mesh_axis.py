"""PL007 mesh-axis: collective axis names must exist on the mesh in scope.

Why it matters here: the distributed objectives (parallel/fixed.py alone has
~15 ``jax.lax.psum`` sites over two axes) are explicit SPMD — every
collective names a mesh axis as a STRING, and nothing checks those strings
until the program actually runs on a mesh that is missing the axis.  On a
single-device CPU run the mesh often has every axis (or the collective is a
no-op), so a typo'd or stale axis name is exactly the failure class that
only reproduces on a pod slice (DrJAX, arxiv 2403.07128, calls mesh-axis
mistakes the dominant silent-failure mode for shard_map-heavy code).

Checked, for every collective call (``jax.lax.psum/pmean/pmax/pmin/
all_gather/ppermute/psum_scatter/all_to_all/axis_index``):

  - when the call sits lexically inside a function bound by a
    ``shard_map``/``pjit`` site whose ``mesh=...`` expression resolves to a
    ``Mesh(...)`` construction, the axis must be one of THAT mesh's axes;
  - otherwise the axis must appear in the program's mesh-axis universe —
    the union of every ``Mesh(axis_names=...)`` in the package, collected
    by the ProgramIndex (or, in ``--no-program-index`` mode, this module).

Axis names are resolved through analysis/resolve.py (parameter defaults,
``self.X`` attributes, tuple unpacks, imported constants like
``parallel/mesh.DATA_AXIS``); an unresolvable axis or an empty universe
stays quiet — resolution failures must never invent findings.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from photon_ml_tpu.analysis.framework import (ModuleContext, Rule, Violation,
                                              register)
from photon_ml_tpu.analysis.jit_index import _unwrap_transform, dotted_name
from photon_ml_tpu.analysis.resolve import (mesh_axes_in_module,
                                            mesh_axes_of_expr)

# collective terminal name -> positional index of its axis-name argument
_COLLECTIVES: Dict[str, int] = {
    "psum": 1, "pmean": 1, "pmax": 1, "pmin": 1, "all_gather": 1,
    "ppermute": 1, "psum_scatter": 1, "all_to_all": 1, "pshuffle": 1,
    "axis_index": 0,
}
_AXIS_KW = "axis_name"
_SHARD_MAP_TERMINALS = {"shard_map"}


def axis_universe(ctx: ModuleContext) -> Set[str]:
    """Every mesh axis name visible to this lint: program-wide when the
    ProgramIndex is attached, else the current module's own meshes."""
    if ctx.program is not None:
        return set(ctx.program.axis_universe)
    return mesh_axes_in_module(ctx.resolver)


def _bare_lax_collectives(tree: ast.Module) -> Dict[str, str]:
    """Names bound by ``from jax.lax import psum [as p]`` -> collective."""
    out: Dict[str, str] = {}
    for stmt in tree.body:
        if isinstance(stmt, ast.ImportFrom) and stmt.module == "jax.lax":
            for alias in stmt.names:
                if alias.name in _COLLECTIVES:
                    out[alias.asname or alias.name] = alias.name
    return out


def collective_axis_expr(node: ast.Call,
                         bare: Dict[str, str]) -> Optional[ast.expr]:
    """The axis-name argument expression when ``node`` is a collective call
    (else None).  Accepts ``jax.lax.psum`` / ``lax.psum`` dotted forms and
    names imported from ``jax.lax`` directly."""
    name = dotted_name(node.func)
    if name is None:
        return None
    prefix, _, term = name.rpartition(".")
    if prefix:
        if not (prefix == "lax" or prefix.endswith(".lax")):
            return None
        coll = term if term in _COLLECTIVES else None
    else:
        coll = bare.get(name)
    if coll is None:
        return None
    for kw in node.keywords:
        if kw.arg == _AXIS_KW:
            return kw.value
    pos = _COLLECTIVES[coll]
    if len(node.args) > pos:
        return node.args[pos]
    return None


def _def_in_scope_chain(ctx: ModuleContext, at: ast.AST,
                        name: str) -> Optional[ast.AST]:
    """Resolve a Name to the function def of that name in the nearest
    enclosing scope of ``at`` (module level last) — scope-aware, so six
    methods each defining a ``local`` closure don't cross-wire."""
    scopes = ctx.resolver.enclosing_scopes(at)
    if ctx.tree is not None:
        scopes = scopes + [ctx.tree]
    for scope in scopes:
        body = scope.body if isinstance(scope.body, list) else []
        stack = list(body)
        while stack:
            stmt = stack.pop()
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if stmt.name == name:
                    return stmt
                continue  # don't descend into other functions' bodies
            stack.extend(s for s in ast.iter_child_nodes(stmt)
                         if isinstance(s, ast.stmt))
    return None


def _shard_map_bindings(ctx: ModuleContext) -> Dict[int, Set[str]]:
    """id(node) -> axes of the mesh bound at the shard_map site wrapping the
    node, for every node lexically inside a shard_map target whose mesh
    expression resolves."""
    out: Dict[int, Set[str]] = {}
    for call in ast.walk(ctx.tree):
        if not isinstance(call, ast.Call):
            continue
        fname = dotted_name(call.func)
        if fname is None \
                or fname.rpartition(".")[2] not in _SHARD_MAP_TERMINALS:
            continue
        mesh_expr = None
        for kw in call.keywords:
            if kw.arg == "mesh":
                mesh_expr = kw.value
        if mesh_expr is None and len(call.args) >= 2:
            mesh_expr = call.args[1]
        if mesh_expr is None:
            continue
        axes = mesh_axes_of_expr(ctx.resolver, mesh_expr)
        if not axes:
            continue
        target = _unwrap_transform(call.args[0]) if call.args else None
        if isinstance(target, ast.Name):
            target = _def_in_scope_chain(ctx, call, target.id)
        if not isinstance(target, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.Lambda)):
            continue
        for sub in ast.walk(target):
            out[id(sub)] = axes
    return out


@register
class MeshAxisRule(Rule):
    name = "mesh-axis"
    code = "PL007"
    severity = "error"
    description = ("collective axis names must name an axis of the mesh in "
                   "scope (typos only fail on a pod slice)")

    def check(self, ctx: ModuleContext) -> Iterator[Violation]:
        if ctx.tree is None:
            return
        # findings anchor on collective calls — skip modules whose text
        # never names one (the overwhelmingly common case)
        if not any(c in ctx.source for c in _COLLECTIVES):
            return
        universe = axis_universe(ctx)
        bound = _shard_map_bindings(ctx)
        bare = _bare_lax_collectives(ctx.tree)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            axis_expr = collective_axis_expr(node, bare)
            if axis_expr is None:
                continue
            coll = dotted_name(node.func)
            site_axes = bound.get(id(node))
            for axis in ctx.resolver.strings(axis_expr):
                if site_axes is not None:
                    if axis not in site_axes:
                        yield ctx.violation(
                            self, node,
                            f"{coll} over axis '{axis}' inside a shard_map "
                            f"whose mesh has axes {sorted(site_axes)} — the "
                            "collective will fail (or silently no-op) when "
                            "this program runs on the mesh it was written "
                            "for")
                elif universe and axis not in universe:
                    yield ctx.violation(
                        self, node,
                        f"{coll} over axis '{axis}', which no Mesh in the "
                        f"program defines (known axes: {sorted(universe)}) — "
                        "a stale or typo'd axis name that only fails on a "
                        "pod slice")
