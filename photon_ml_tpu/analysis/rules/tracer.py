"""PL003 tracer-safety: Python control flow on traced values.

Why it matters here: inside ``jax.jit``, a Python ``if``/``while`` on a
traced value raises ``TracerBoolConversionError`` at trace time — or, when
the branch condition is accidentally concrete (a captured host scalar),
silently bakes ONE branch into the compiled program, which is the bug class
hardest to see in review.  Iterating a traced array unrolls the loop into
the XLA graph (compile-time blowup) or raises.  The solvers already use
``lax.while_loop``/``lax.cond`` (opt/newton_soa.py, opt/linesearch.py);
this rule keeps new trace-path code on that discipline.

Flags, inside jit-traced regions, against the function's NON-STATIC
parameters (``static_argnames``/``static_argnums`` are concrete — exempt):
  - ``if p ...:`` / ``while p ...:`` where the test references a traced
    parameter as a value (``is None`` tests and ``.shape``/``.ndim``/
    ``.dtype``/``.size``/``len()`` reads are trace-time-concrete — exempt);
  - ``for x in p:`` — loop unrolling over a traced array;
  - ternary ``a if p else b`` on a traced parameter (same bake-one-branch
    hazard as ``if``);
  - ``assert p`` on a traced parameter — trace-time no-op that reads like a
    runtime check (use ``checkify``); warning severity.
"""

from __future__ import annotations

import ast
from typing import Iterator

from photon_ml_tpu.analysis.framework import (ModuleContext, Rule, Violation,
                                              register)
from photon_ml_tpu.analysis.jit_index import expr_references, walk_jit_code


@register
class TracerSafetyRule(Rule):
    name = "tracer-safety"
    code = "PL003"
    severity = "error"
    description = ("no Python if/while/for/ternary/assert on traced values "
                   "inside jit (use lax.cond/while_loop/select)")

    def check(self, ctx: ModuleContext) -> Iterator[Violation]:
        for node, params in walk_jit_code(ctx.jit_index):
            if isinstance(node, (ast.If, ast.While)):
                if expr_references(node.test, params):
                    kind = "if" if isinstance(node, ast.If) else "while"
                    fix = ("lax.cond/jnp.where" if kind == "if"
                           else "lax.while_loop")
                    yield ctx.violation(
                        self, node,
                        f"Python `{kind}` on a traced value — "
                        "TracerBoolConversionError at trace time, or a "
                        f"silently baked-in branch; use {fix}")
            elif isinstance(node, ast.IfExp):
                if expr_references(node.test, params):
                    yield ctx.violation(
                        self, node,
                        "ternary on a traced value — use jnp.where or "
                        "lax.select (Python chooses one branch at trace "
                        "time)")
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                if expr_references(node.iter, params):
                    yield ctx.violation(
                        self, node,
                        "iterating a traced array unrolls the loop into the "
                        "compiled program (or raises); use lax.scan / "
                        "lax.fori_loop")
            elif isinstance(node, ast.Assert):
                if expr_references(node.test, params):
                    yield ctx.violation(
                        self, node,
                        "assert on a traced value is a trace-time no-op "
                        "that looks like a runtime check; use "
                        "checkify.check for a real guard",
                        severity="warning")
