"""PL014 cross-module-donation: PL006's donated-buffer taint, propagated
through the ProgramIndex call graph.

Why it matters here: the donation contracts in this codebase deliberately
cross module boundaries — ``utils/transfer.py`` exports helpers that donate
their buffer argument into a ``lax.dynamic_update_slice`` executable, and
``serving/``/``stream/`` call them from other files.  PL006 is per-module
by design: it sees ``f = jax.jit(fn, donate_argnums=0)`` and flags reads
after ``f(x)`` in the SAME file, but a caller in another module that reads
a buffer after passing it to an imported donating helper is invisible to
it.  That is precisely the "passes every CPU test, corrupts data on the
pod" hazard donation creates (CPU jax ignores donation; TPU reuses the
buffer).

The ProgramIndex computes a program-wide donor table
(:meth:`~photon_ml_tpu.analysis.program_index.ProgramIndex.donor_exports`):
module-level jit bindings with ``donate_argnums``/``donate_argnames``, AOT
``.lower().compile()`` chains over them, and — to a cross-module fixpoint —
functions that forward their own parameters into a donated position (so a
chain ``a.update → b._update_at → jitted donor`` donates through two
imports).  This rule then reruns PL006's scope scanner per module with ONLY
the cross-module donors seeded (imported names and ``module.fn`` dotted
references); local donors stay PL006's, so the two rules never double-
report.  Requires whole-program mode; per-module runs stay silent.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, Optional

from photon_ml_tpu.analysis.framework import (ModuleContext, Rule, Violation,
                                              register)
from photon_ml_tpu.analysis.jit_index import dotted_name
from photon_ml_tpu.analysis.rules.donation import DonateSpec, _ScopeScanner


def cross_module_donors(ctx: ModuleContext):
    """(imported donor names, dotted-reference resolver) for ``ctx``, or
    None when this module cannot reach a donor-exporting module at all —
    the precheck that lets PL014/PL015 skip the scan.  Shared with the
    container-taint rule so both see the same donor universe; memoized on
    the context since both rules ask."""
    cached = getattr(ctx, "_xmod_donors", False)
    if cached is not False:
        return cached
    got = _cross_module_donors(ctx)
    ctx._xmod_donors = got
    return got


def _cross_module_donors(ctx: ModuleContext):
    info = ctx.program.modules.get(ctx.relpath)
    if info is None:
        return None
    exports = ctx.program.donor_exports()

    def spec_for(mod_relpath: str, sym: str) -> Optional[DonateSpec]:
        got = exports.get(mod_relpath, {}).get(sym)
        if got is None:
            return None
        spec = DonateSpec(argnums=tuple(got[0]), argnames=tuple(got[1]))
        return spec if spec else None

    # imported names bound to donors defined in ANOTHER module
    donors: Dict[str, DonateSpec] = {}
    for bound in info.imports:
        got = ctx.program.resolve_symbol(info, bound)
        if got is None:
            continue
        mod, sym = got
        if mod.relpath == ctx.relpath:
            continue  # local donor — PL006's jurisdiction
        spec = spec_for(mod.relpath, sym)
        if spec is not None:
            donors[bound] = spec

    def xresolve(dn: str) -> Optional[DonateSpec]:
        """``alias.fn`` dotted reference -> cross-module donor spec."""
        got = ctx.program.resolve_symbol(info, dn)
        if got is None:
            return None
        mod, sym = got
        if mod.relpath == ctx.relpath:
            return None
        return spec_for(mod.relpath, sym)

    # precheck: the scanner is the expensive part, and a module can only
    # trip these rules by reaching a donor-exporting module through its
    # import table (bound names above, or `alias.fn` dotted references) —
    # skip the scan entirely otherwise
    if not donors:
        exporting = {name for name, m in ctx.program.by_name.items()
                     if exports.get(m.relpath)}
        reach = any(en == tm or en.startswith(tm + ".")
                    for tm, _sym in info.imports.values()
                    for en in exporting)
        if not reach:
            return None
    return donors, xresolve


class _CrossModuleScanner(_ScopeScanner):
    """PL006's scanner, extended to resolve ``module.fn`` dotted callees
    through the program's donor table."""

    def __init__(self, rule, ctx, donors, fn_params, xresolve):
        super().__init__(rule, ctx, donors, {}, fn_params)
        self._xresolve = xresolve

    def _spec_of_expr(self, expr: ast.AST, depth: int = 0
                      ) -> Optional[DonateSpec]:
        spec = super()._spec_of_expr(expr, depth)
        if spec is not None:
            return spec
        if isinstance(expr, ast.Attribute):
            dn = dotted_name(expr)
            if dn is not None and "." in dn and not dn.startswith("self."):
                return self._xresolve(dn)
        return None

    def _donate_name(self, arg: ast.Name, donor: str) -> None:
        # taint only — no function-boundary warning here: forwarding an own
        # parameter into an imported donor is the sanctioned wrapper pattern
        # the program-wide fixpoint models (the wrapper becomes a derived
        # donor and ITS callers are checked); the actionable cross-module
        # finding is the read-after-donate error
        self.tainted[arg.id] = (arg.lineno, donor)


@register
class CrossModuleDonationRule(Rule):
    name = "cross-module-donation"
    code = "PL014"
    severity = "error"
    description = ("no reads of a buffer after donating it through an "
                   "imported (cross-module) donating callable")

    def check(self, ctx: ModuleContext) -> Iterator[Violation]:
        if ctx.tree is None or ctx.program is None:
            return
        got = cross_module_donors(ctx)
        if got is None:
            return
        donors, xresolve = got
        yield from self._scan(ctx, ctx.tree.body, donors, (), xresolve)
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                a = node.args
                params = [p.arg for p in list(a.posonlyargs) + list(a.args)
                          + list(a.kwonlyargs)]
                yield from self._scan(ctx, node.body, donors, params,
                                      xresolve)

    def _scan(self, ctx, body, donors, params, xresolve
              ) -> Iterator[Violation]:
        scanner = _CrossModuleScanner(self, ctx, donors, params, xresolve)
        scanner.run(body)
        yield from scanner.violations
