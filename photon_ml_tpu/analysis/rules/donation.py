"""PL006 donation-after-use: reading a buffer after donating it to jit.

Why it matters here: ``serving/engine.py`` donates the per-request buffers
(features, slots, overflow) to its AOT executables so XLA can reuse their
device memory for outputs, and ``utils/transfer.py`` assembles chunked
uploads through a donated ``lax.dynamic_update_slice`` so the design matrix
is never double-resident in HBM.  Donation invalidates the argument buffer:
a later read of the SAME array is a use-after-free that CPU silently
tolerates (no donation support — jax only warns) and that corrupts data or
crashes only on TPU/GPU — the classic "passes every CPU test, fails on the
pod" bug.

Tracked, per scope (module body / each function body, statements in source
order; loop bodies are scanned twice so a donation in iteration N is seen
by the reads of iteration N+1):

  - donating callables: ``f = jax.jit(fn, donate_argnums=...)`` (also
    ``donate_argnames``), including AOT chains ``f.lower(...).compile()``
    and methods of the same class that RETURN such an executable
    (serving/engine.py's ``_executable``) — donate specs resolved through
    analysis/resolve.py, so conditional specs like engine's
    backend-gated ``(0, 3, 4) if ... else ()`` contribute both branches;
  - derived donors: a plain function that forwards one of its OWN
    parameters into a donated position (``transfer._update_at``) donates
    that parameter position too;
  - at each donating call, plain-Name arguments in donated positions become
    tainted; a later Name READ of a tainted variable in the same scope is
    the violation; any re-assignment of the name clears the taint (the
    ``out = update(out, ...)`` rebind idiom is the sanctioned pattern).

Additionally, passing one of the ENCLOSING function's parameters straight
into a donated position is flagged at warning severity: the caller may
still hold the buffer, and the donation contract has crossed a function
boundary where this per-scope analysis cannot follow it — either document
the consuming contract (suppress with a reason) or donate a locally-owned
buffer.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from photon_ml_tpu.analysis.framework import (ModuleContext, Rule, Violation,
                                              register)
from photon_ml_tpu.analysis.jit_index import dotted_name, is_jit_call

_AOT_ATTRS = {"lower", "compile"}


@dataclasses.dataclass(frozen=True)
class DonateSpec:
    argnums: Tuple[int, ...] = ()
    argnames: Tuple[str, ...] = ()

    def __bool__(self) -> bool:
        return bool(self.argnums or self.argnames)


def _as_ints(val) -> Tuple[int, ...]:
    if isinstance(val, bool):
        return ()
    if isinstance(val, int):
        return (val,)
    if isinstance(val, tuple):
        return tuple(v for v in val if isinstance(v, int)
                     and not isinstance(v, bool))
    return ()


def _as_strs(val) -> Tuple[str, ...]:
    if isinstance(val, str):
        return (val,)
    if isinstance(val, tuple):
        return tuple(v for v in val if isinstance(v, str))
    return ()


def _jit_donate_spec(ctx: ModuleContext, call: ast.Call) -> DonateSpec:
    """Donate spec of a ``jax.jit(...)`` call (union over every resolvable
    alternative of the spec expressions)."""
    nums: Set[int] = set()
    names: Set[str] = set()
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            for v in ctx.resolver.values(kw.value):
                nums.update(_as_ints(v))
        elif kw.arg == "donate_argnames":
            for v in ctx.resolver.values(kw.value):
                names.update(_as_strs(v))
    return DonateSpec(tuple(sorted(nums)), tuple(sorted(names)))


class _ScopeScanner:
    """Linear scan of one scope's statements tracking donating callables,
    taints, and reads.  Loop bodies run twice (see module docstring)."""

    def __init__(self, rule: "DonationRule", ctx: ModuleContext,
                 donors: Dict[str, DonateSpec],
                 self_donors: Dict[str, DonateSpec],
                 fn_params: Sequence[str]):
        self.rule = rule
        self.ctx = ctx
        self.donors = dict(donors)          # name -> spec (inherited + local)
        self.self_donors = self_donors      # self.method() -> spec
        self.fn_params = set(fn_params)
        self.tainted: Dict[str, Tuple[int, str]] = {}  # var -> (line, donor)
        self.violations: List[Violation] = []
        self._param_warned: Set[str] = set()
        self._flagged: Set[int] = set()  # node ids (loop bodies scan twice)

    # -- spec discovery ------------------------------------------------------
    def _spec_of_expr(self, expr: ast.AST, depth: int = 0
                      ) -> Optional[DonateSpec]:
        """Donate spec carried by an expression: a jit call with donate
        kwargs, an AOT ``.lower(...).compile()`` chain over one, a known
        donating Name, or ``self.method(...)`` returning one."""
        if depth > 6:
            return None
        if isinstance(expr, ast.Name):
            return self.donors.get(expr.id)
        if isinstance(expr, ast.Call):
            if is_jit_call(expr):
                spec = _jit_donate_spec(self.ctx, expr)
                return spec if spec else None
            func = expr.func
            if isinstance(func, ast.Attribute):
                if func.attr in _AOT_ATTRS:
                    return self._spec_of_expr(func.value, depth + 1)
                if isinstance(func.value, ast.Name) \
                        and func.value.id == "self":
                    return self.self_donors.get(func.attr)
        if isinstance(expr, ast.Attribute) and expr.attr in _AOT_ATTRS:
            return self._spec_of_expr(expr.value, depth + 1)
        return None

    # -- statement processing ------------------------------------------------
    def run(self, body: Sequence[ast.stmt]) -> None:
        for stmt in body:
            self._stmt(stmt)

    def _stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # nested scopes are scanned separately
        if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
            # two passes: taints created in pass 1 are visible to pass 2,
            # catching `for ...: donating(x)` buffer reuse across iterations
            for _ in range(2):
                for sub in stmt.body:
                    self._stmt(sub)
            for sub in stmt.orelse:
                self._stmt(sub)
            return
        if isinstance(stmt, (ast.If,)):
            for sub in stmt.body + stmt.orelse:
                self._stmt(sub)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._expr(item.context_expr)
            for sub in stmt.body:
                self._stmt(sub)
            return
        if isinstance(stmt, ast.Try):
            for sub in (stmt.body + stmt.orelse + stmt.finalbody
                        + [s for h in stmt.handlers for s in h.body]):
                self._stmt(sub)
            return
        # leaf statement: reads -> new taints -> stores (in that order, so
        # `x = donating(x)` reads the old buffer legally then clears)
        self._expr(stmt)
        self._taint_calls(stmt)
        self._clear_stores(stmt)
        self._bind_donors(stmt)

    def _expr(self, node: ast.AST) -> None:
        """Flag loads of tainted names anywhere under node."""
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load) \
                    and sub.id in self.tainted \
                    and id(sub) not in self._flagged:
                self._flagged.add(id(sub))
                line, donor = self.tainted[sub.id]
                self.violations.append(self.ctx.violation(
                    self.rule, sub,
                    f"`{sub.id}` was donated to `{donor}` (line {line}) and "
                    "read again — donation invalidates the buffer; on "
                    "TPU/GPU this is a use-after-free that CPU runs hide. "
                    "Rebind the result or drop the donation"))

    def _taint_calls(self, stmt: ast.stmt) -> None:
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Call):
                continue
            # covers donating Names, self.method() donors, AOT chains, and
            # immediately-invoked `jax.jit(f, donate_argnums=...)(x)`
            spec = self._spec_of_expr(node.func)
            if not spec:
                continue
            donor = dotted_name(node.func) or "<donating executable>"
            positions = list(spec.argnums)
            for i, arg in enumerate(node.args):
                donated = i in positions
                if not donated:
                    continue
                if isinstance(arg, ast.Name):
                    self._donate_name(arg, donor)
            for kw in node.keywords:
                if kw.arg in spec.argnames and isinstance(kw.value, ast.Name):
                    self._donate_name(kw.value, donor)

    def _donate_name(self, arg: ast.Name, donor: str) -> None:
        self.tainted[arg.id] = (arg.lineno, donor)
        if arg.id in self.fn_params and arg.id not in self._param_warned:
            self._param_warned.add(arg.id)
            self.violations.append(self.ctx.violation(
                self.rule, arg,
                f"parameter `{arg.id}` is donated to `{donor}` — the caller "
                "may still hold this buffer and the donation contract "
                "crosses the function boundary; donate a locally-owned "
                "array, or suppress with the documented consuming contract",
                severity="warning"))

    def _clear_stores(self, stmt: ast.stmt) -> None:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
                self.tainted.pop(node.id, None)

    def _bind_donors(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name):
            spec = self._spec_of_expr(stmt.value)
            name = stmt.targets[0].id
            if spec:
                self.donors[name] = spec
            else:
                self.donors.pop(name, None)


def _derived_donor_spec(ctx: ModuleContext, fn, donors: Dict[str, DonateSpec],
                        self_donors: Dict[str, DonateSpec]) -> DonateSpec:
    """Does ``fn`` forward its own parameters into donated positions?  The
    positions of those parameters become the function's own donate spec
    (transfer.py's ``_update_at`` pattern)."""
    a = fn.args
    ordered = [p.arg for p in list(a.posonlyargs) + list(a.args)]
    scanner = _ScopeScanner(None, ctx, donors, self_donors, ())  # type: ignore
    nums: Set[int] = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        spec = scanner._spec_of_expr(node.func)
        if not spec:
            continue
        for i, arg in enumerate(node.args):
            if i in spec.argnums and isinstance(arg, ast.Name) \
                    and arg.id in ordered:
                nums.add(ordered.index(arg.id))
        for kw in node.keywords:
            if kw.arg in spec.argnames and isinstance(kw.value, ast.Name) \
                    and kw.value.id in ordered:
                nums.add(ordered.index(kw.value.id))
    return DonateSpec(tuple(sorted(nums)))


def discover_module_donors(rule, ctx: ModuleContext
                           ) -> Tuple[Dict[str, DonateSpec],
                                      Dict[str, DonateSpec]]:
    """(module-level donor names, self.method donors) of one module — the
    PL006 discovery passes, shared with PL015's container-taint scan."""
    # pass 1: module-level donating names + methods returning donors
    module_donors: Dict[str, DonateSpec] = {}
    probe = _ScopeScanner(rule, ctx, {}, {}, ())
    for stmt in ctx.tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name):
            spec = probe._spec_of_expr(stmt.value)
            if spec:
                module_donors[stmt.targets[0].id] = spec
    self_donors = _method_donors(rule, ctx, module_donors)
    # pass 2: derived donors — module functions forwarding their params
    for name, fn in _module_functions(ctx.tree):
        spec = _derived_donor_spec(ctx, fn, module_donors, self_donors)
        if spec and name not in module_donors:
            module_donors[name] = spec
    return module_donors, self_donors


def _method_donors(rule, ctx: ModuleContext,
                   module_donors: Dict[str, DonateSpec]
                   ) -> Dict[str, DonateSpec]:
    """Methods whose RETURN value is a donating executable — resolved
    through the method's own local bindings (engine._executable's
    ``jitted -> lowered -> exe`` chain)."""
    out: Dict[str, DonateSpec] = {}
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        for item in node.body:
            if not isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            scanner = _ScopeScanner(rule, ctx, module_donors, {}, ())
            spec: Optional[DonateSpec] = None
            for stmt in ast.walk(item):
                if isinstance(stmt, ast.Assign):
                    scanner._bind_donors(stmt)
                elif isinstance(stmt, ast.Return) \
                        and stmt.value is not None:
                    got = scanner._spec_of_expr(stmt.value)
                    if got:
                        spec = got
            if spec:
                out[item.name] = spec
    return out


@register
class DonationRule(Rule):
    name = "donation-after-use"
    code = "PL006"
    severity = "error"
    description = ("no reads of a buffer after passing it through a "
                   "donate_argnums/donate_argnames position")

    def check(self, ctx: ModuleContext) -> Iterator[Violation]:
        if ctx.tree is None:
            return
        # every DonateSpec roots in a jit call carrying donate_argnums /
        # donate_argnames (module-level, method-local, or scope-local — the
        # .lower()/.compile() and derived-donor chains only FORWARD specs),
        # so a module whose text never names them cannot produce one; skip
        # the O(scopes × stmts) scan outright
        if "donate_arg" not in ctx.source:
            return
        module_donors, self_donors = discover_module_donors(self, ctx)
        # pass 3: scan every scope linearly
        yield from self._scan_scope(ctx, ctx.tree.body, module_donors,
                                    self_donors, ())
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                a = node.args
                params = [p.arg for p in list(a.posonlyargs) + list(a.args)
                          + list(a.kwonlyargs)]
                yield from self._scan_scope(ctx, node.body, module_donors,
                                            self_donors, params)

    def _scan_scope(self, ctx, body, donors, self_donors, params
                    ) -> Iterator[Violation]:
        scanner = _ScopeScanner(self, ctx, donors, self_donors, params)
        scanner.run(body)
        yield from scanner.violations


def _module_functions(tree: ast.Module):
    for stmt in tree.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield stmt.name, stmt
