"""PL018 lock-order: a cycle in the global lock acquisition-order graph is
a deadlock.

Why it matters here: the serving plane holds locks across object
boundaries — the batcher's condition variable, the coefficient store's
swap lock, the fleet registry's tenant lock — and the hot-swap path runs
methods of ALL of them from one background thread while request threads
come the other way.  Two locks taken in opposite orders on two such paths
deadlock under load, cross-module, with no single function to point at —
exactly what per-module analysis (PL005's discipline check, the
``lock_held_fns`` reachability) cannot see.

The v4 summary layer records, per function, which locks it acquires
(``with self.<lock>:``, bare ``self.<lock>.acquire()``/``.release()``
pairs, module-level locks, flow-resolved local aliases;
``Condition(self._lock)`` canonicalises to the lock it wraps) and which
calls it makes while holding one.  A bare acquire holds from the call
site to the matching release (or function end), in document order.  ``ProgramSummaries`` joins these into a
directed order graph: ``A -> B`` when some function nests B inside A
lexically, or calls — while holding A — a function that (transitively)
acquires B.  Every strongly-connected component of size >= 2 is a
deadlock finding, reported at each edge witness in the current module
with the full cycle and the opposing path's location in the message.
Lock identity is class-level (instances conflated — the conservative
direction for ordering), self-edges never form (so RLock re-entry and
same-class sibling instances cannot false-positive), and only resolvable
callees propagate.  Whole-program mode only; per-module runs stay silent.
"""

from __future__ import annotations

import ast
from typing import Iterator, Tuple

from photon_ml_tpu.analysis.framework import (ModuleContext, Rule, Violation,
                                              register)


def _short(key: str) -> str:
    """``serving/batcher.py::AsyncBatcher._lock`` -> ``AsyncBatcher._lock``
    (module kept only when needed for disambiguation in the message)."""
    return key.rpartition("::")[2]


@register
class LockOrderRule(Rule):
    name = "lock-order"
    code = "PL018"
    severity = "error"
    description = ("the program-wide lock acquisition-order graph must be "
                   "acyclic — any cycle is a deadlock under load")

    def check(self, ctx: ModuleContext) -> Iterator[Violation]:
        if ctx.tree is None or ctx.program is None:
            return
        summ = ctx.program.summaries()
        if not summ.lock_cycles:
            return
        for keys, edges in summ.lock_cycles:
            cycle_desc = " ; ".join(
                f"{_short(a)} -> {_short(b)} in {fn} ({relpath}:"
                f"{getattr(site, 'lineno', 0)})"
                for (a, b), (relpath, fn, site) in sorted(edges.items()))
            for (a, b), (relpath, fn, site) in sorted(edges.items()):
                if relpath != ctx.relpath:
                    continue
                others = self._opposing(edges, (a, b))
                yield ctx.violation(
                    self, site,
                    f"lock-order cycle over {{{', '.join(_short(k) for k in keys)}}}: "
                    f"`{fn}` takes {_short(a)} then {_short(b)}, but "
                    f"{others} — two threads on these paths deadlock; "
                    f"impose one global order (full cycle: {cycle_desc})")

    @staticmethod
    def _opposing(edges, edge: Tuple[str, str]) -> str:
        a, b = edge
        # the path that closes the cycle back from b to a — prefer the
        # direct reverse edge, else name any edge leaving b
        rev = edges.get((b, a))
        if rev is not None:
            relpath, fn, site = rev
            return (f"`{fn}` ({relpath}:{getattr(site, 'lineno', 0)}) takes "
                    f"{_short(b)} then {_short(a)}")
        for (x, y), (relpath, fn, site) in sorted(edges.items()):
            if x == b:
                return (f"`{fn}` ({relpath}:{getattr(site, 'lineno', 0)}) "
                        f"continues {_short(b)} -> {_short(y)}")
        return "another path closes the cycle"
