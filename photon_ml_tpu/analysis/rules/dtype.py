"""PL004 dtype-discipline: no float64 / host-numpy promotion on TPU paths.

Why it matters here: TPUs have no native float64 — a f64 op either errors
or falls back to a slow software path, and with ``jax_enable_x64`` set (the
test harness does, for scipy parity) an accidental ``jnp.float64`` silently
doubles memory traffic and halves MXU throughput on CPU/GPU runs too.
Library code is dtype-agnostic by convention (conftest.py): kernels follow
their INPUT dtypes, and f64 belongs only to host-side numpy (storage codecs,
diagnostics, normalization statistics).  Host ``np.float64`` OUTSIDE traced
code is therefore fine and not flagged.

Flags, only in files under the configured hot-path dirs (core/, ops/,
opt/, game/, parallel/, serving/, models/, evaluation/):
  - ``jnp.float64`` anywhere — a device f64 request;
  - ``dtype=np.float64`` / ``dtype=jnp.float64`` / ``dtype="float64"``
    (keyword or 2nd positional) in any ``jnp.*`` call — ditto;
  - ``np.float64`` referenced inside a jit-traced region — under x64 it
    promotes the whole expression to f64 on device;
  - promotion-prone host-numpy math (``np.exp``/``np.dot``/``np.sum``/...)
    applied to a traced parameter inside a jit region — numpy computes on
    host in f64 and breaks the trace (use ``jnp.*``).
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional, Tuple

from photon_ml_tpu.analysis.framework import (ModuleContext, Rule, Violation,
                                              register)
from photon_ml_tpu.analysis.jit_index import (dotted_name, expr_references,
                                              walk_jit_code)

HOT_PATH_DIRS: Tuple[str, ...] = (
    "core", "ops", "opt", "game", "parallel", "serving", "models",
    "evaluation",
)

_JNP_ALIASES = {"jnp", "jax.numpy"}
_NP_ALIASES = {"np", "numpy", "onp"}
_NP_MATH = {
    "exp", "log", "log1p", "expm1", "sqrt", "square", "abs", "sum", "mean",
    "dot", "matmul", "einsum", "tanh", "sigmoid", "clip", "where",
    "maximum", "minimum", "power", "outer", "cumsum", "prod",
}


def _in_hot_path(relpath: str) -> bool:
    parts = relpath.replace("\\", "/").split("/")
    if "photon_ml_tpu" in parts:
        parts = parts[parts.index("photon_ml_tpu") + 1:]
    return bool(parts) and parts[0] in HOT_PATH_DIRS


def _split_alias(name: Optional[str]) -> Tuple[Optional[str], Optional[str]]:
    if name is None or "." not in name:
        return None, None
    alias, _, attr = name.rpartition(".")
    return alias, attr


def _is_f64_expr(node: ast.AST) -> Optional[str]:
    """Returns a description when ``node`` denotes float64."""
    name = dotted_name(node)
    if name is not None:
        alias, attr = _split_alias(name)
        if attr == "float64" and (alias in _JNP_ALIASES
                                  or alias in _NP_ALIASES):
            return name
        if name == "float64":
            return name
    if isinstance(node, ast.Constant) and node.value == "float64":
        return '"float64"'
    return None


@register
class DtypeDisciplineRule(Rule):
    name = "dtype-discipline"
    code = "PL004"
    severity = "error"
    description = ("no float64 dtypes or host-numpy math on TPU hot paths "
                   "(core/, ops/, opt/, game/, parallel/, serving/)")

    def check(self, ctx: ModuleContext) -> Iterator[Violation]:
        if ctx.tree is None or not _in_hot_path(ctx.relpath):
            return
        # module-wide: jnp.float64 and float64 dtype args in jnp calls
        # (dotted_name only resolves Attribute chains; calls carry dtype=)
        for node in ctx.nodes_of(ast.Attribute, ast.Call):
            name = dotted_name(node)
            if name is not None:
                alias, attr = _split_alias(name)
                if alias in _JNP_ALIASES and attr == "float64":
                    yield ctx.violation(
                        self, node,
                        "jnp.float64 requests a device f64 — TPUs have no "
                        "native f64; follow the input dtype instead")
                    continue
            if isinstance(node, ast.Call):
                fname = dotted_name(node.func)
                alias, _ = _split_alias(fname)
                if alias not in _JNP_ALIASES:
                    continue
                dtype_arg = None
                for kw in node.keywords:
                    if kw.arg == "dtype":
                        dtype_arg = kw.value
                if dtype_arg is None and len(node.args) >= 2:
                    dtype_arg = node.args[1]
                if dtype_arg is not None:
                    desc = _is_f64_expr(dtype_arg)
                    if desc:
                        yield ctx.violation(
                            self, node,
                            f"{fname} called with dtype {desc} — f64 on a "
                            "TPU path; library code is dtype-agnostic "
                            "(follow the input dtype, keep f64 host-side)")
        # trace-scoped: np.float64 and host-numpy math on traced values
        for node, params in walk_jit_code(ctx.jit_index):
            name = dotted_name(node)
            alias, attr = _split_alias(name)
            if alias in _NP_ALIASES and attr == "float64":
                yield ctx.violation(
                    self, node,
                    "np.float64 inside a jit-traced region promotes to f64 "
                    "under x64 (and is meaningless on TPU); use the traced "
                    "operand's dtype")
                continue
            if isinstance(node, ast.Call):
                fname = dotted_name(node.func)
                falias, fattr = _split_alias(fname)
                if (falias in _NP_ALIASES and fattr in _NP_MATH
                        and any(expr_references(a, params)
                                for a in node.args)):
                    yield ctx.violation(
                        self, node,
                        f"{fname} on a traced value computes on host (f64 "
                        "promotion + trace break); use jnp."
                        f"{fattr}")
