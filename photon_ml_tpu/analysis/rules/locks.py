"""PL005 lock-discipline: unlocked mutation of lock-protected shared state.

Why it matters here: the serving stack is the one place this codebase is
genuinely multi-threaded — scoring requests, the background hot-swap thread
(serving/swap.py), and metrics exports interleave on shared objects
(serving/engine.py executable cache, serving/metrics.py registries,
serving/coefficient_store.py LRU).  The convention is a per-object
``self._lock`` and ``with self._lock:`` around every mutation; a single
forgotten site is a data race no test reliably catches.

Per class that owns a lock (an attribute assigned from ``threading.Lock()``
/ ``RLock()`` / ``Condition()``, or any ``self.*lock*`` used as a context
manager), flags:
  - a mutation of ``self.X`` outside any ``with self.<lock>:`` when the
    SAME attribute is also mutated under the lock elsewhere in the class —
    the canonical forgotten-lock race;
  - a mutation of ``self.X`` outside the ``with`` block in a method that
    takes the lock elsewhere — partially-locked methods (mutating after
    releasing is almost always an ordering bug).

``__init__``/``__new__`` are exempt (no aliasing before construction
returns).  Mutations counted: assignment/augmented assignment to
``self.X`` (tuple-unpacking and starred targets included), item
assignment/deletion ``self.X[k]``, calls of mutating container methods
(``append``/``update``/``pop``/``popitem``/``move_to_end``/...) on
``self.X``, and in-place ``operator`` module calls — ``operator.iadd(
self.X, v)`` / ``op.setitem(self.X, k, v)`` through any import alias —
which mutate exactly like ``+=`` / ``self.X[k] = v`` but previously slipped
past the target extraction.

v2 (dataflow-backed): mutation targets and lock context managers are now
resolved through the per-method alias analysis in ``analysis/dataflow.py``.
Two escape hatches the purely-syntactic v1 missed are closed:

  - **alias mutation** — ``store = self._store; store.table = ...`` (or
    ``store[k] = v`` / ``store.update(...)``) mutates the same object as
    ``self._store.…``; the local name's alias set identifies the root
    attribute, so the site participates in lock discipline.  Chained
    targets like ``self._store.table[k] = v`` root at ``_store`` too.
  - **alias locking** — ``lock = self._lock; with lock:`` counts as
    holding the class lock, so correctly-locked code that names the lock
    locally no longer produces false positives.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterator, List, Optional, Set, Tuple

from photon_ml_tpu.analysis.dataflow import (LOCK_FACTORIES as
                                             _LOCK_FACTORIES,
                                             MUTATOR_METHODS as _MUTATORS,
                                             class_lock_info)
from photon_ml_tpu.analysis.framework import (ModuleContext, Rule, Violation,
                                              register)
# operator-module functions that mutate their FIRST argument in place
_OP_MUTATORS = {
    "iadd", "isub", "imul", "imatmul", "itruediv", "ifloordiv", "imod",
    "ipow", "ilshift", "irshift", "iand", "ixor", "ior", "iconcat",
    "setitem", "delitem",
}
_EXEMPT_METHODS = {"__init__", "__new__", "__post_init__"}


def _operator_aliases(tree: ast.Module) -> Tuple[Set[str], Dict[str, str]]:
    """(names bound to the operator module, local name -> operator function
    for ``from operator import iadd [as x]``)."""
    modules: Set[str] = set()
    funcs: Dict[str, str] = {}
    for stmt in tree.body:
        if isinstance(stmt, ast.Import):
            for alias in stmt.names:
                if alias.name == "operator":
                    modules.add(alias.asname or alias.name)
        elif isinstance(stmt, ast.ImportFrom) and stmt.module == "operator":
            for alias in stmt.names:
                if alias.name in _OP_MUTATORS:
                    funcs[alias.asname or alias.name] = alias.name
    return modules, funcs


def _self_attr(node: ast.AST) -> Optional[str]:
    """``self.X`` -> "X" (else None)."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name) and node.value.id == "self"):
        return node.attr
    return None


def _lock_names(cls: ast.ClassDef) -> Set[str]:
    """Lock-attr detection shared with the v4 summary layer (factory
    assignments plus any ``with self.*lock*:`` context manager)."""
    names, _canon, _factory = class_lock_info(cls)
    return names


@dataclasses.dataclass
class _Site:
    attr: str
    method: str
    locked: bool
    node: ast.AST
    kind: str  # "assign" | "item" | "call"


class _MethodScanner(ast.NodeVisitor):
    """Collect self-attribute mutation sites in one method, tracking
    ``with self.<lock>`` nesting.  Nested function defs are skipped (their
    execution context is unknowable here)."""

    def __init__(self, method_name: str, locks: Set[str],
                 op_modules: Set[str] = frozenset(),
                 op_funcs: Optional[Dict[str, str]] = None,
                 flow=None):
        self.method = method_name
        self.locks = locks
        self.op_modules = op_modules
        self.op_funcs = op_funcs or {}
        self.flow = flow  # FunctionFlow for alias queries (None = v1 mode)
        self.depth = 0
        self.took_lock = False
        self.sites: List[_Site] = []

    def _add(self, attr: Optional[str], node: ast.AST, kind: str) -> None:
        if attr is None or attr in self.locks:
            return
        self.sites.append(_Site(attr=attr, method=self.method,
                                locked=self.depth > 0, node=node, kind=kind))

    def _roots(self, obj: ast.AST) -> Set[str]:
        """The self-attribute(s) whose object ``obj`` reaches: walk the
        attribute/subscript chain to its base — ``self`` roots at the
        innermost attribute (``self._store.table[k]`` -> ``_store``), any
        other name roots at its dataflow alias set (``store = self._store``
        makes ``store.…`` root at ``_store``)."""
        node, chain_attr = obj, None
        while isinstance(node, (ast.Attribute, ast.Subscript)):
            if isinstance(node, ast.Attribute):
                chain_attr = node.attr
            node = node.value
        if not isinstance(node, ast.Name):
            return set()
        if node.id == "self":
            return {chain_attr} if chain_attr is not None else set()
        if self.flow is not None:
            return set(self.flow.attr_aliases(node.id, obj))
        return set()

    # -- lock scope --------------------------------------------------------
    def _is_lock_expr(self, expr: ast.AST) -> bool:
        if _self_attr(expr) in self.locks:
            return True
        # `lock = self._lock; with lock:` — holding through an alias
        return (isinstance(expr, ast.Name) and self.flow is not None
                and bool(self.flow.attr_aliases(expr.id, expr)
                         & self.locks))

    def visit_With(self, node: ast.With) -> None:
        is_lock = any(self._is_lock_expr(i.context_expr)
                      for i in node.items)
        if is_lock:
            self.took_lock = True
            self.depth += 1
        self.generic_visit(node)
        if is_lock:
            self.depth -= 1

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        pass  # closures: out of scope

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef

    # -- mutations ---------------------------------------------------------
    def _target(self, tgt: ast.AST) -> None:
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for elt in tgt.elts:
                self._target(elt)
            return
        if isinstance(tgt, ast.Starred):
            # `self.head, *self.rest = xs` — the starred slot rebinds too
            self._target(tgt.value)
            return
        if isinstance(tgt, ast.Attribute):
            kind = "assign"
        elif isinstance(tgt, ast.Subscript):
            kind = "item"
        else:
            return
        for attr in self._roots(tgt):
            self._add(attr, tgt, kind)

    def visit_Assign(self, node: ast.Assign) -> None:
        for tgt in node.targets:
            self._target(tgt)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._target(node.target)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._target(node.target)
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        for tgt in node.targets:
            if isinstance(tgt, ast.Subscript):
                for attr in self._roots(tgt):
                    self._add(attr, tgt, "item")
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in _MUTATORS:
            for attr in self._roots(f.value):
                self._add(attr, node, "call")
        elif self._is_op_mutator(f) and node.args:
            for attr in self._roots(node.args[0]):
                self._add(attr, node, "call")
        self.generic_visit(node)

    def _is_op_mutator(self, f: ast.AST) -> bool:
        """``operator.iadd`` / ``op.setitem`` / bare ``iadd`` imported from
        operator — the ``+=``-through-an-alias forms."""
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
            return f.value.id in self.op_modules and f.attr in _OP_MUTATORS
        if isinstance(f, ast.Name):
            return f.id in self.op_funcs
        return False


@register
class LockDisciplineRule(Rule):
    name = "lock-discipline"
    code = "PL005"
    severity = "error"
    description = ("attributes mutated under a class's lock must never be "
                   "mutated outside it")

    def check(self, ctx: ModuleContext) -> Iterator[Violation]:
        if ctx.tree is None:
            return
        op_modules, op_funcs = _operator_aliases(ctx.tree)
        for node in ctx.nodes_of(ast.ClassDef):
            yield from self._check_class(ctx, node, op_modules, op_funcs)

    def _check_class(self, ctx: ModuleContext, cls: ast.ClassDef,
                     op_modules: Set[str],
                     op_funcs: Dict[str, str]) -> Iterator[Violation]:
        locks = _lock_names(cls)
        if not locks:
            return
        sites: List[_Site] = []
        partial_methods: Dict[str, List[_Site]] = {}
        for item in cls.body:
            if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if item.name in _EXEMPT_METHODS:
                continue
            scanner = _MethodScanner(item.name, locks, op_modules, op_funcs,
                                     flow=ctx.dataflow.function_flow(item))
            # generic_visit: enter the method body without tripping the
            # nested-def skip on the method node itself
            scanner.generic_visit(item)
            sites.extend(scanner.sites)
            if scanner.took_lock:
                partial_methods[item.name] = scanner.sites
        locked_attrs = {s.attr for s in sites if s.locked}
        flagged: Set[int] = set()
        for s in sites:
            if s.locked or s.attr not in locked_attrs:
                continue
            flagged.add(id(s.node))
            yield ctx.violation(
                self, s.node,
                f"{cls.name}.{s.attr} is mutated here without the lock but "
                f"mutated under `with self.{sorted(locks)[0]}` elsewhere in "
                "the class — a data race; take the lock around this "
                "mutation")
        for method, msites in partial_methods.items():
            for s in msites:
                if s.locked or id(s.node) in flagged:
                    continue
                yield ctx.violation(
                    self, s.node,
                    f"{cls.name}.{method} takes the class lock but mutates "
                    f"self.{s.attr} outside it — mutation after release is "
                    "an ordering race; move it inside the `with` block")
