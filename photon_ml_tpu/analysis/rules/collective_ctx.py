"""PL012 collective-without-mesh: collectives in traced code need a
binding context.

Why it matters here: ``jax.lax.psum``/``all_gather``/``ppermute`` only mean
something when the surrounding trace binds the named axis — a ``shard_map``
/ ``pmap`` / ``xmap`` target (or a ``vmap`` with ``axis_name=``).  A
collective that is reachable from a plain ``jax.jit`` root with NO such
binding anywhere on its call path raises ``NameError: unbound axis`` at
trace time — but only when that jit path actually executes, which for the
sharded serving kernels means "on the pod, under traffic", not in the CPU
unit tests.  The refactor hazard is real: hoisting a helper out of a
``shard_map`` target (or jitting a function that was only ever called from
inside one) silently severs the binding.

Using the dataflow layer this rule flags every collective call site that

  - executes under a jit trace (the per-module ``JitIndex`` walk, augmented
    with the ProgramIndex's cross-module traced roots), and
  - is NOT lexically inside a shard_map/pmap/xmap/vmap-with-axis_name
    target, NOT inside a ``with <mesh>:`` block, and NOT inside a function
    the (module-local or program-wide) call graph shows is only entered
    from such a target.

Unresolvable targets contribute exemptions, not findings — the usual
conservative direction.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional, Set

from photon_ml_tpu.analysis.framework import (ModuleContext, Rule, Violation,
                                              register)
from photon_ml_tpu.analysis.jit_index import (FunctionNode, _unwrap_transform,
                                              dotted_name)
from photon_ml_tpu.analysis.rules.mesh_axis import (_bare_lax_collectives,
                                                    _COLLECTIVES,
                                                    _def_in_scope_chain)

_MESH_BINDER_TERMINALS = {"shard_map", "pmap", "xmap"}
_MESH_WITH_TERMINALS = {"Mesh", "use_mesh", "set_mesh"}


def collective_call_name(node: ast.Call, bare) -> Optional[str]:
    """The collective's name when ``node`` is a collective call (axis
    argument present or not), else None."""
    name = dotted_name(node.func)
    if name is None:
        return None
    prefix, _, term = name.rpartition(".")
    if prefix:
        if not (prefix == "lax" or prefix.endswith(".lax")):
            return None
        return name if term in _COLLECTIVES else None
    return name if bare.get(name) else None


def _is_mesh_binder(call: ast.Call) -> bool:
    fname = dotted_name(call.func)
    term = (fname or "").rpartition(".")[2]
    if term in _MESH_BINDER_TERMINALS:
        return True
    return term == "vmap" and any(kw.arg == "axis_name"
                                  for kw in call.keywords)


def _mesh_with_context(item: ast.withitem) -> bool:
    """``with mesh:`` / ``with self.mesh:`` / ``with Mesh(...):`` /
    ``with jax.sharding.use_mesh(m):`` — loose on purpose (quietness
    bias)."""
    expr = item.context_expr
    if isinstance(expr, ast.Call):
        term = (dotted_name(expr.func) or "").rpartition(".")[2]
        return term in _MESH_WITH_TERMINALS
    leaf = (dotted_name(expr) or "").rpartition(".")[2].lower()
    return "mesh" in leaf


@register
class CollectiveContextRule(Rule):
    name = "collective-without-mesh"
    code = "PL012"
    severity = "error"
    description = ("collectives reachable from a jit root need an enclosing "
                   "shard_map/pmap/mesh context somewhere on the call path")

    def check(self, ctx: ModuleContext) -> Iterator[Violation]:
        if ctx.tree is None:
            return
        # findings anchor on collective calls — skip modules whose text
        # never names one
        if not any(c in ctx.source for c in _COLLECTIVES):
            return
        bare = _bare_lax_collectives(ctx.tree)
        traced = ctx.dataflow.traced_node_ids()
        if not traced:
            return
        exempt = self._exempt_ids(ctx)
        for node in ctx.nodes_of(ast.Call):
            name = collective_call_name(node, bare)
            if name is None:
                continue
            if id(node) not in traced or id(node) in exempt:
                continue
            yield ctx.violation(
                self, node,
                f"{name} is reachable from a jit root but no shard_map/"
                "pmap/mesh context binds its axis on this call path — the "
                "trace fails with an unbound axis name exactly when this "
                "path first runs on the real mesh; keep the collective "
                "inside the shard_map target (or bind the axis at this "
                "jit boundary)")

    def _exempt_ids(self, ctx: ModuleContext) -> Set[int]:
        """ids of nodes that DO have a binding context."""
        out: Set[int] = set()
        seeds = []
        for call in ctx.nodes_of(ast.With, ast.AsyncWith, ast.Call):
            is_with = isinstance(call, (ast.With, ast.AsyncWith))
            if is_with and any(_mesh_with_context(i) for i in call.items):
                for sub in ast.walk(call):
                    out.add(id(sub))
                continue
            if not (isinstance(call, ast.Call) and call.args
                    and _is_mesh_binder(call)):
                continue
            target = _unwrap_transform(call.args[0])
            if isinstance(target, ast.Name):
                target = _def_in_scope_chain(ctx, call, target.id)
            elif isinstance(target, ast.Attribute) \
                    and isinstance(target.value, ast.Name) \
                    and target.value.id == "self":
                target = ctx.dataflow.call_graph.resolve(target)
            if isinstance(target, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.Lambda)):
                seeds.append(target)
        # everything the binder targets transitively call is mesh-scoped
        scoped = ctx.dataflow.call_graph.reachable(seeds)
        fns: list = [fn for fn in ctx.dataflow.call_graph.fns
                     if id(fn) in scoped]
        fns.extend(s for s in seeds if isinstance(s, ast.Lambda))
        if ctx.program is not None:
            fns.extend(ctx.program.mesh_scoped_in(ctx.relpath))
        for fn in fns:
            for sub in ast.walk(fn):
                out.add(id(sub))
        return out
