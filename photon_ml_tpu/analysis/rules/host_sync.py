"""PL001 host-sync: device→host synchronization inside jit-traced code.

Why it matters here: the serving engine's AOT executables and the training
solvers' fused programs (serving/engine.py, game/fused.py, opt/) are built
on the premise that a traced function stays on-device end to end.  A
``.item()`` / ``float()`` / ``np.asarray`` on a traced value either raises
``ConcretizationTypeError`` at trace time or — worse, via callbacks or
pre-jit refactors that later get jitted — silently inserts a blocking
device→host transfer exactly where the paper's port lost its wins
(PAPERS.md: Flare, arXiv:1703.08219).

Flags, inside any jit-traced region (analysis/jit_index.py):
  - ``x.item()`` / ``x.tolist()`` — explicit sync;
  - ``np.asarray(...)`` / ``np.array(...)`` — host materialization (use
    ``jnp.asarray``);
  - ``float(p)`` / ``int(p)`` / ``bool(p)`` / ``complex(p)`` where ``p`` is
    a (non-static) parameter of the traced function — concretization;
  - ``print(...)`` referencing a traced parameter — executes at trace time,
    not per call (use ``jax.debug.print``); warning severity.
"""

from __future__ import annotations

import ast
from typing import Iterator

from photon_ml_tpu.analysis.framework import (ModuleContext, Rule, Violation,
                                              register)
from photon_ml_tpu.analysis.jit_index import (dotted_name, expr_references,
                                              walk_jit_code)

_NP_ALIASES = {"np", "numpy", "onp"}
_NP_HOST_FNS = {"asarray", "array"}
_CASTS = {"float", "int", "bool", "complex"}
_SYNC_METHODS = {"item", "tolist"}


@register
class HostSyncRule(Rule):
    name = "host-sync"
    code = "PL001"
    severity = "error"
    description = ("no host syncs (.item/.tolist/float()/np.asarray/print) "
                   "inside jit-traced code")

    def check(self, ctx: ModuleContext) -> Iterator[Violation]:
        for node, params in walk_jit_code(ctx.jit_index):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if (isinstance(func, ast.Attribute)
                    and func.attr in _SYNC_METHODS and not node.args):
                yield ctx.violation(
                    self, node,
                    f".{func.attr}() forces a device->host sync inside a "
                    "jit-traced function; keep the value on device (or move "
                    "the readback outside the traced region)")
                continue
            name = dotted_name(func)
            if name is not None and "." in name:
                alias, _, attr = name.rpartition(".")
                if alias in _NP_ALIASES and attr in _NP_HOST_FNS:
                    yield ctx.violation(
                        self, node,
                        f"{name}(...) materializes on host inside a "
                        "jit-traced function; use jnp.asarray (host numpy "
                        "breaks tracing and blocks the device stream)")
                    continue
            if isinstance(func, ast.Name):
                if (func.id in _CASTS and len(node.args) == 1
                        and expr_references(node.args[0], params)):
                    yield ctx.violation(
                        self, node,
                        f"{func.id}() concretizes a traced value (host sync "
                        "/ ConcretizationTypeError); use jnp casts or keep "
                        "it symbolic")
                elif func.id == "print" and any(
                        expr_references(a, params) for a in node.args):
                    yield ctx.violation(
                        self, node,
                        "print() of a traced value runs at trace time, not "
                        "per call; use jax.debug.print",
                        severity="warning")
