"""PL013 blocking-in-async: no blocking calls on the asyncio event loop.

Why it matters here: the serving frontend (``serving/frontend/server.py``)
and the replication plane (``online/replication/``) each run ONE event loop
that every connection shares.  A single ``time.sleep``, sync file read, or
``Future.result()`` on that loop stalls every in-flight request — the
Spark-ML performance literature's driver-bottleneck failure mode, ported to
asyncio.  These bugs pass every test (tests rarely run enough concurrent
load to notice a 10ms stall) and surface as fleet-wide p99 cliffs.

Flagged: calls from the blocking catalog —

  - ``time.sleep``, ``os.system``, ``subprocess.run/call/check_*``,
    ``socket.create_connection``, ``urllib.request.urlopen``,
    ``shutil.rmtree``/``copytree`` (dotted names);
  - the ``open(...)`` / ``input(...)`` builtins (sync file I/O);
  - ``<x>.result(...)`` (``concurrent.futures`` blocks until done) and
    ``<x>.acquire(...)`` (a sync lock) — except when directly awaited
    (``await lock.acquire()`` is the asyncio primitive);

when the call executes on the event loop, which the dataflow layer proves
three ways:

  - lexically inside an ``async def`` body;
  - inside a callback scheduled onto the loop (``loop.call_soon`` /
    ``call_soon_threadsafe`` / ``call_later`` / ``call_at`` targets);
  - inside a SYNC function the (module-local or cross-module) call graph
    shows is called from either of the above.

Hand-offs are exempt by construction: ``await loop.run_in_executor(None,
fn, ...)`` / ``asyncio.to_thread(fn)`` / ``Thread(target=fn)`` pass ``fn``
as a REFERENCE, not a call, so reachability never propagates into it.  The
sanctioned fixes are exactly those hand-offs (or ``call_soon_threadsafe``
from foreign threads).
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional

from photon_ml_tpu.analysis.dataflow import (_LOOP_SCHEDULERS, lexical_calls,
                                             loop_callback_exprs)
from photon_ml_tpu.analysis.framework import (ModuleContext, Rule, Violation,
                                              register)
from photon_ml_tpu.analysis.jit_index import FunctionNode, dotted_name

_BLOCKING_DOTTED = {
    "time.sleep", "os.system", "socket.create_connection",
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output", "urllib.request.urlopen",
    "shutil.rmtree", "shutil.copytree",
}
_BLOCKING_BUILTINS = {"open", "input"}
# attribute calls that block: Future.result() / Lock.acquire() — exempt
# when directly awaited (asyncio primitives)
_BLOCKING_ATTRS = {
    "result": "concurrent.futures result() blocks until the future settles",
    "acquire": "a synchronous lock acquire blocks the whole loop",
}


def _blocking_reason(node: ast.Call, ctx: ModuleContext) -> Optional[str]:
    f = node.func
    dn = dotted_name(f)
    if dn in _BLOCKING_DOTTED:
        return f"{dn}() is synchronous"
    if isinstance(f, ast.Name) and f.id in _BLOCKING_BUILTINS:
        return f"builtin {f.id}() does blocking I/O"
    if isinstance(f, ast.Attribute) and f.attr in _BLOCKING_ATTRS:
        if isinstance(ctx.resolver.parent(node), ast.Await):
            return None  # await x.acquire() — the asyncio form
        return _BLOCKING_ATTRS[f.attr]
    return None


@register
class BlockingInAsyncRule(Rule):
    name = "blocking-in-async"
    code = "PL013"
    severity = "error"
    description = ("no blocking calls (sleep/sync I/O/result()/acquire()) "
                   "on the asyncio event loop, through any call chain")

    def check(self, ctx: ModuleContext) -> Iterator[Violation]:
        if ctx.tree is None:
            return
        # textual precheck before building the call graph: module-local
        # loop seeds need `async def` or a scheduler call, and without
        # those only cross-module reachability can put a function on the
        # loop — ask the (once-per-run) program table directly
        src = ctx.source
        if "async" not in src and not any(s in src
                                          for s in _LOOP_SCHEDULERS):
            if ctx.program is None \
                    or not ctx.program.async_reachable_in(ctx.relpath):
                return
        on_loop = ctx.dataflow.event_loop_fns()
        if not on_loop:
            return
        # candidate bodies: every def in the module plus scheduled lambdas
        candidates: List[FunctionNode] = list(ctx.dataflow.call_graph.fns)
        candidates.extend(cb for cb in loop_callback_exprs(ctx.tree)
                          if isinstance(cb, ast.Lambda))
        seen = set()
        for fn in candidates:
            if id(fn) not in on_loop or id(fn) in seen:
                continue
            seen.add(id(fn))
            yield from self._check_fn(ctx, fn)

    def _check_fn(self, ctx: ModuleContext,
                  fn: FunctionNode) -> Iterator[Violation]:
        if isinstance(fn, ast.AsyncFunctionDef):
            where = f"inside `async def {fn.name}`"
        elif isinstance(fn, ast.Lambda):
            where = "in a callback scheduled onto the event loop"
        else:
            where = (f"in `{fn.name}`, which the call graph shows runs on "
                     "the event loop")
        for call in lexical_calls(fn):
            reason = _blocking_reason(call, ctx)
            if reason is None:
                continue
            yield ctx.violation(
                self, call,
                f"blocking call {where}: {reason} — it stalls every "
                "coroutine sharing this loop; hand it off with `await "
                "loop.run_in_executor(...)` / `asyncio.to_thread(...)` "
                "(threads signal back via `call_soon_threadsafe`)")
