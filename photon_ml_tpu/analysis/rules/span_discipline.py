"""PL010 span-discipline: trace spans that never close (or never open).

Why it matters here: a photonscope span (``obs.trace.span`` /
``Tracer.span``) is a context manager — the ring slot is claimed on
``__enter__`` and the duration is stamped on ``__exit__``.  Used any other
way it degrades silently: a span called and discarded records nothing at
all, a handle that escapes its function is entered on one code path and
leaked on another, and a manual ``__enter__`` without a paired
``__exit__`` leaves the per-thread span stack permanently deeper — every
LATER span in that thread then nests under a parent that never ended, which
corrupts the merged timeline photonpulse builds across processes.  None of
these raise; the trace just quietly lies, which is the one thing a tracing
layer must never do.

Flags, for any call whose callee is ``span`` or ``obs_span`` (module
function or method — ``tracer.span(...)`` counts):

  - **discarded** — the call is a bare expression statement: the context
    manager is created and dropped without ever being entered, so no span
    is recorded (``with span(...)``: was meant);
  - **escaping handle** — the call's result is assigned to a local name
    that is never used as a ``with`` item (and never explicitly
    ``__enter__``-ed) in the same function: the handle is being returned
    or stored, detaching the span's lifetime from any scope;
  - **begin-without-end** — ``h`` holds a span and ``h.__enter__()``
    appears in a function with no matching ``h.__exit__(...)``: the span
    opens and the thread's span stack never pops.

Exemption: none needed — ``with span(...)``, ``with span(...) as h:`` and
balanced manual enter/exit all pass; the tracer's own implementation
module (``obs/trace.py``) defines rather than misuses these names and
stays clean by construction.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set

from photon_ml_tpu.analysis.framework import (ModuleContext, Rule,
                                              Violation, register)

_SPAN_CALLEES = {"span", "obs_span"}


def _callee_name(node: ast.AST) -> Optional[str]:
    """Last path component of a call's callee (``a.b.span`` -> "span")."""
    if not isinstance(node, ast.Call):
        return None
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return None


def _is_span_call(node: ast.AST) -> bool:
    return _callee_name(node) in _SPAN_CALLEES


def _dunder_target(node: ast.AST, dunder: str) -> Optional[str]:
    """``name.__enter__()`` -> "name" (only simple-name receivers)."""
    if (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)
            and node.func.attr == dunder
            and isinstance(node.func.value, ast.Name)):
        return node.func.value.id
    return None


def _lexical_body(fn: ast.AST) -> Iterator[ast.AST]:
    """Nodes of ``fn``'s own body, not descending into nested defs."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


@register
class SpanDisciplineRule(Rule):
    name = "span-discipline"
    code = "PL010"
    severity = "error"
    description = ("trace span context managers discarded, escaping their "
                   "with scope, or __enter__-ed without __exit__")

    def check(self, ctx: ModuleContext) -> Iterator[Violation]:
        tree = ctx.tree
        if tree is None:
            return
        # every finding anchors on a call whose callee is named span /
        # obs_span — a module whose text never says "span" can't have one
        if "span" not in ctx.source:
            return
        # the tracer implementation module DEFINES span(); a module that
        # defines a function named span is the provider, not a misuser
        fns = ctx.nodes_of(ast.FunctionDef, ast.AsyncFunctionDef)
        if _SPAN_CALLEES & {n.name for n in fns}:
            return
        for fn in (tree, *fns):  # module level counts as a scope too
            yield from self._check_scope(ctx, fn)

    def _check_scope(self, ctx: ModuleContext, fn: ast.AST,
                     ) -> Iterator[Violation]:
        assigned: Dict[str, ast.AST] = {}   # name -> span-call assign node
        with_items: Set[str] = set()        # names used as `with h` items
        entered: Dict[str, ast.AST] = {}    # name -> __enter__ call node
        exited: Set[str] = set()            # names with an __exit__ call
        for node in _lexical_body(fn):
            if isinstance(node, ast.Expr):
                if _is_span_call(node.value):
                    yield ctx.violation(
                        self, node,
                        "span context manager created and discarded — no "
                        "span is recorded; use `with span(...):`")
                continue
            if isinstance(node, ast.Assign) and _is_span_call(node.value):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        assigned[tgt.id] = node
                continue
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    expr = item.context_expr
                    if isinstance(expr, ast.Name):
                        with_items.add(expr.id)
                continue
            name = _dunder_target(node, "__enter__")
            if name is not None:
                entered.setdefault(name, node)
            name = _dunder_target(node, "__exit__")
            if name is not None:
                exited.add(name)
        for name, node in assigned.items():
            if name in with_items or name in entered:
                continue
            yield ctx.violation(
                self, node,
                f"span handle {name!r} escapes its scope (never used as a "
                "`with` item): the span's lifetime is detached from any "
                "code region")
        for name, node in entered.items():
            if name not in assigned or name in exited:
                continue
            yield ctx.violation(
                self, node,
                f"{name}.__enter__() without a paired __exit__: the span "
                "never closes and every later span in this thread nests "
                "under it")
