"""PL008 sharding-annotation: mesh-path jits annotate output layouts, and
PartitionSpec axis strings must exist on the mesh they're paired with.

Why it matters here: on the ``parallel/`` mesh paths the output layout IS
the contract — ``fit_fixed_effect`` hands solver state between sweeps, and
multihost's score/residual kernels feed each other device-resident arrays.
A ``jax.jit`` without ``out_shardings`` leaves that layout to GSPMD
inference, which is free to change across jax versions or upstream edits
and silently inserts resharding collectives between stages (the TPU
distributed linear-algebra work, arxiv 2112.09017, pins every block layout
for the same reason).  And a ``NamedSharding``/``PartitionSpec`` naming an
axis the paired mesh does not have fails only when the mesh actually has
multiple axes — i.e. on the pod, not in the single-device CPU tests.

Flags:
  - (warning, ``parallel/`` modules only) a ``jax.jit(...)`` call,
    ``@jax.jit`` decorator, or ``functools.partial(jax.jit, ...)`` without
    an ``out_shardings`` annotation — annotate the layout, or suppress with
    the propagation rationale (sharding flowing from the inputs is a valid
    design, but it must be a DOCUMENTED one);
  - (error, anywhere) a string axis in a ``PartitionSpec(...)`` / ``P(...)``
    that is not an axis of the mesh it's paired with via
    ``NamedSharding(mesh, spec)`` (when the mesh expression resolves to a
    ``Mesh(...)`` construction), falling back to the program's mesh-axis
    universe from the ProgramIndex — unresolvable specs and an empty
    universe stay quiet.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Set, Tuple

from photon_ml_tpu.analysis.framework import (ModuleContext, Rule, Violation,
                                              register)
from photon_ml_tpu.analysis.jit_index import (JIT_NAMES, dotted_name,
                                              is_jit_call, is_partial_jit)
from photon_ml_tpu.analysis.resolve import mesh_axes_of_expr
from photon_ml_tpu.analysis.rules.mesh_axis import axis_universe

_MESH_PATH_DIRS: Tuple[str, ...] = ("parallel",)


def _on_mesh_path(relpath: str) -> bool:
    parts = relpath.replace("\\", "/").split("/")
    if "photon_ml_tpu" in parts:
        parts = parts[parts.index("photon_ml_tpu") + 1:]
    return bool(parts) and parts[0] in _MESH_PATH_DIRS


def _pspec_aliases(tree: ast.Module) -> Set[str]:
    """Local names bound to ``jax.sharding.PartitionSpec`` (``P`` et al.)."""
    out: Set[str] = set()
    for stmt in tree.body:
        if isinstance(stmt, ast.ImportFrom) \
                and (stmt.module or "").endswith("sharding"):
            for alias in stmt.names:
                if alias.name == "PartitionSpec":
                    out.add(alias.asname or alias.name)
    return out


def _is_pspec_call(node: ast.AST, aliases: Set[str]) -> bool:
    if not isinstance(node, ast.Call):
        return False
    name = dotted_name(node.func)
    if name is None:
        return False
    return name in aliases or name.rpartition(".")[2] == "PartitionSpec"


def _spec_axis_strings(ctx: ModuleContext,
                       spec: ast.Call) -> List[Tuple[str, ast.expr]]:
    """(axis-name, arg-expr) pairs for every resolvable string in the spec's
    arguments (a single argument may be a tuple of axes)."""
    out: List[Tuple[str, ast.expr]] = []
    for arg in spec.args:
        if isinstance(arg, ast.Starred):
            continue  # `*([None] * k)` padding idiom — nothing to check
        for s in ctx.resolver.strings(arg):
            out.append((s, arg))
    return out


def _has_out_shardings(call: ast.Call) -> bool:
    return any(kw.arg == "out_shardings" for kw in call.keywords)


@register
class ShardingAnnotationRule(Rule):
    name = "sharding-annotation"
    code = "PL008"
    severity = "error"
    description = ("parallel/ jits annotate out_shardings; PartitionSpec "
                   "axes must exist on their mesh")

    def check(self, ctx: ModuleContext) -> Iterator[Violation]:
        if ctx.tree is None:
            return
        aliases = _pspec_aliases(ctx.tree)
        universe = axis_universe(ctx)
        paired: Set[int] = set()  # P(...) nodes validated against their mesh
        # -- NamedSharding(mesh, spec): validate spec against THAT mesh ------
        for node in ctx.nodes_of(ast.Call):
            fname = dotted_name(node.func)
            if fname is None \
                    or fname.rpartition(".")[2] != "NamedSharding":
                continue
            mesh_expr = node.args[0] if node.args else None
            spec_expr = node.args[1] if len(node.args) >= 2 else None
            for kw in node.keywords:
                if kw.arg == "mesh":
                    mesh_expr = kw.value
                elif kw.arg == "spec":
                    spec_expr = kw.value
            if not (mesh_expr is not None
                    and _is_pspec_call(spec_expr, aliases)):
                continue
            axes = mesh_axes_of_expr(ctx.resolver, mesh_expr)
            if not axes:
                continue
            paired.add(id(spec_expr))
            for axis, arg in _spec_axis_strings(ctx, spec_expr):
                if axis not in axes:
                    yield ctx.violation(
                        self, arg,
                        f"PartitionSpec axis '{axis}' is not an axis of the "
                        f"mesh it is paired with (axes: {sorted(axes)}) — "
                        "this NamedSharding fails on any real mesh")
        # -- every other PartitionSpec: validate against the universe --------
        if universe:
            for node in ctx.nodes_of(ast.Call):
                if not _is_pspec_call(node, aliases) or id(node) in paired:
                    continue
                for axis, arg in _spec_axis_strings(ctx, node):
                    if axis not in universe:
                        yield ctx.violation(
                            self, arg,
                            f"PartitionSpec axis '{axis}', which no Mesh in "
                            "the program defines (known axes: "
                            f"{sorted(universe)}) — a stale or typo'd axis "
                            "that only fails on a multi-axis mesh")
        # -- parallel/ jits must annotate out_shardings ----------------------
        if not _on_mesh_path(ctx.relpath):
            return
        flagged: Set[int] = set()
        for node in ctx.nodes_of(ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Call):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if dotted_name(dec) in JIT_NAMES:
                        flagged.add(id(dec))
                        yield self._unannotated(ctx, dec)
                    elif isinstance(dec, ast.Call) \
                            and (is_jit_call(dec) or is_partial_jit(dec)) \
                            and not _has_out_shardings(dec):
                        flagged.add(id(dec))
                        yield self._unannotated(ctx, dec)
            elif isinstance(node, ast.Call) and id(node) not in flagged \
                    and (is_jit_call(node) or is_partial_jit(node)) \
                    and not _has_out_shardings(node):
                yield self._unannotated(ctx, node)

    def _unannotated(self, ctx: ModuleContext, node: ast.AST) -> Violation:
        return ctx.violation(
            self, node,
            "jax.jit on a mesh path without out_shardings — the output "
            "layout is left to GSPMD inference, which may reshard between "
            "pipeline stages; annotate it (or suppress with the "
            "sharding-propagation rationale)",
            severity="warning")
