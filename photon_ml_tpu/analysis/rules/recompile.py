"""PL002 recompile-hazard: jit construction patterns that defeat the cache.

Why it matters here: serving/engine.py guarantees zero recompiles after
``warm()`` — every (signature, bucket) executable is AOT-compiled once and a
recompile on the request path is a multi-second tail-latency cliff on TPU.
Training's regularization-path sweeps (opt/solve.py) make the same bet.
``jax.jit``'s cache is keyed by function identity: construct the wrapper
anew each iteration/call and every invocation retraces and recompiles.

Flags, anywhere in a module:
  - ``jax.jit(...)`` (or ``functools.partial(jax.jit, ...)``) constructed
    inside a ``for``/``while`` body — a fresh wrapper (and compile) per
    iteration; hoist the jit out of the loop;
  - immediately-invoked construction ``jax.jit(f)(args)`` inside a function
    body — a fresh wrapper per enclosing call (when ``f`` is a local
    closure, a guaranteed recompile per call); bind the jitted callable
    once (module level, ``__init__``, or an executable cache like
    serving/engine._executable);
  - ``jax.jit(...)`` where ``static_argnums``/``static_argnames`` is not a
    literal int/str/tuple/list — dynamic static-arg specs are how array
    values end up marked static (unhashable → TypeError, or worse a
    compile per distinct value).

Comprehensions are deliberately NOT treated as loops: the build-once
``{cid: jax.jit(...) for cid in ...}`` setup idiom (parallel/multihost.py)
constructs each wrapper exactly once and caches it.
"""

from __future__ import annotations

import ast
from typing import Iterator, List

from photon_ml_tpu.analysis.framework import (ModuleContext, Rule, Violation,
                                              register)
from photon_ml_tpu.analysis.jit_index import is_jit_call, is_partial_jit

_LITERAL_STATIC = (ast.Constant, ast.Tuple, ast.List)


def _static_spec_dynamic(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg not in ("static_argnums", "static_argnames"):
            continue
        v = kw.value
        if isinstance(v, ast.Constant):
            continue
        if isinstance(v, (ast.Tuple, ast.List)) and all(
                isinstance(e, ast.Constant) for e in v.elts):
            continue
        return True
    return False


@register
class RecompileHazardRule(Rule):
    name = "recompile-hazard"
    code = "PL002"
    severity = "error"
    description = ("no per-iteration/per-call jax.jit construction or "
                   "non-literal static-arg specs")

    def check(self, ctx: ModuleContext) -> Iterator[Violation]:
        if ctx.tree is None:
            return
        yield from self._walk(ctx, ctx.tree, loop_depth=0, fn_depth=0)

    def _walk(self, ctx: ModuleContext, node: ast.AST, loop_depth: int,
              fn_depth: int) -> Iterator[Violation]:
        for child in ast.iter_child_nodes(node):
            in_loop = loop_depth
            in_fn = fn_depth
            if isinstance(child, (ast.For, ast.AsyncFor, ast.While)):
                in_loop += 1
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                    ast.Lambda)):
                # a nested def's body only loops if IT is called in a loop —
                # reset; but it does run per call of the enclosing scope
                in_loop = 0
                in_fn += 1
            if isinstance(child, ast.Call):
                jit_like = is_jit_call(child) or is_partial_jit(child)
                if jit_like and loop_depth > 0:
                    yield ctx.violation(
                        self, child,
                        "jax.jit constructed inside a loop — a fresh wrapper "
                        "(and XLA compile) every iteration; hoist the jit "
                        "out of the loop and reuse it")
                elif jit_like and _static_spec_dynamic(child):
                    yield ctx.violation(
                        self, child,
                        "static_argnums/static_argnames is not a literal — "
                        "dynamic static-arg specs invite unhashable/array "
                        "statics (TypeError, or a compile per value); spell "
                        "the spec as a literal")
                elif (isinstance(child.func, ast.Call)
                        and is_jit_call(child.func) and fn_depth > 0):
                    yield ctx.violation(
                        self, child,
                        "jax.jit(f)(...) constructs and invokes a fresh "
                        "wrapper on every call of the enclosing function — "
                        "retrace + recompile each time; bind the jitted "
                        "callable once and reuse it")
            yield from self._walk(ctx, child, in_loop, in_fn)
