"""PL009 swallowed-exception: silent ``except`` in daemon workers.

Why it matters here: the serving stack runs its real work on daemon
threads and asyncio tasks — the batcher flush loop, the delta-log
follower, the replication subscriber, the hot-swap thread.  Nothing
joins these on the request path, so an ``except Exception: pass`` in
one of them converts a persistent failure (sick disk, corrupt log,
wedged socket) into permanent silent staleness: the thread keeps
spinning, metrics stay green, and no operator signal ever fires.  That
is precisely the failure mode the PR-14 catch-up hardening fixed
(``catchup_follow_errors_total`` + backoff) and the chaos watchdog
exists to surface.

Scope — only code that actually runs detached, where nobody observes a
raise:

  - the body of any ``async def`` function;
  - the body of any function or method referenced as a ``target=`` of a
    ``threading.Thread(...)`` construction anywhere in the module
    (``target=self._run`` marks the method ``_run``; ``target=run``
    marks the module function ``run``).

Within that scope, flags an ``except`` handler whose type is bare,
``Exception``, or ``BaseException`` (alone or in a tuple) and whose body
does NONE of the following:

  - re-raise (any ``raise``);
  - reference the bound exception name (``except ... as e`` where ``e``
    is read — stored on ``self``, passed to ``set_exception``,
    formatted into a reply);
  - log it (a call to ``debug``/``info``/``warning``/``error``/
    ``exception``/``critical``/``log`` on anything);
  - count it (a call to ``inc``/``increment``/``observe``/
    ``set_gauge``/``add_gauge``, or ``set_exception``).

Exemption: a handler guarding a Try whose body is nothing but
best-effort teardown calls (``close``/``cancel``/``stop``/
``shutdown``/``join``/``release``/``terminate``/``unlink``/
``remove``/``rmtree``) — ``try: writer.close() except Exception: pass``
during cleanup is the idiom, not the bug: there is no health signal to
emit about a socket that was already dying.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Set

from photon_ml_tpu.analysis.framework import (ModuleContext, Rule,
                                              Violation, register)
from photon_ml_tpu.analysis.jit_index import dotted_name

_LOG_METHODS = {"debug", "info", "warning", "error", "exception",
                "critical", "log"}
_METRIC_METHODS = {"inc", "increment", "observe", "set_gauge", "add_gauge",
                   "set_exception"}
_CLEANUP_METHODS = {"close", "cancel", "stop", "shutdown", "join",
                    "release", "terminate", "unlink", "remove", "rmtree",
                    "kill", "disarm"}


def _broad_types(handler: ast.ExceptHandler) -> bool:
    """Bare ``except``, or a type (tuple) naming Exception/BaseException."""
    t = handler.type
    if t is None:
        return True
    types = t.elts if isinstance(t, ast.Tuple) else [t]
    for node in types:
        name = (dotted_name(node) or "").rpartition(".")[2]
        if name in ("Exception", "BaseException"):
            return True
    return False


def _call_attr(node: ast.AST) -> Optional[str]:
    """``anything.attr(...)`` -> "attr" (else None)."""
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
        return node.func.attr
    return None


def _handles_it(handler: ast.ExceptHandler) -> bool:
    """Does the handler body raise, log, count, or use the exception?"""
    bound = handler.name
    for node in ast.walk(ast.Module(body=handler.body, type_ignores=[])):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Name) and node.id == bound:
            return True
        attr = _call_attr(node)
        if attr in _LOG_METHODS or attr in _METRIC_METHODS:
            return True
    return False


def _cleanup_only(try_node: ast.Try) -> bool:
    """Try body made exclusively of best-effort teardown expressions."""
    for stmt in try_node.body:
        if not isinstance(stmt, ast.Expr):
            return False
        call = stmt.value
        if isinstance(call, ast.Await):
            call = call.value
        if _call_attr(call) not in _CLEANUP_METHODS:
            return False
    return bool(try_node.body)


def _thread_targets(calls) -> Set[str]:
    """Function/method names passed as ``target=`` to a Thread(...)."""
    out: Set[str] = set()
    for node in calls:
        callee = (dotted_name(node.func) or "").rpartition(".")[2]
        if callee != "Thread":
            continue
        for kw in node.keywords:
            if kw.arg != "target":
                continue
            if isinstance(kw.value, ast.Name):
                out.add(kw.value.id)
            elif isinstance(kw.value, ast.Attribute):
                out.add(kw.value.attr)
    return out


@register
class SwallowedExceptionRule(Rule):
    name = "swallowed-exception"
    code = "PL009"
    severity = "error"
    description = ("broad except in a daemon-thread/async-task body that "
                   "neither logs, re-raises, nor increments a metric")

    def check(self, ctx: ModuleContext) -> Iterator[Violation]:
        tree = ctx.tree
        if tree is None:
            return
        targets = _thread_targets(ctx.nodes_of(ast.Call))
        for fn in ctx.nodes_of(ast.FunctionDef, ast.AsyncFunctionDef):
            if isinstance(fn, ast.AsyncFunctionDef):
                detached = True
            else:
                detached = fn.name in targets
            if not detached:
                continue
            yield from self._check_body(ctx, fn)

    def _check_body(self, ctx: ModuleContext, fn: ast.AST,
                    ) -> Iterator[Violation]:
        # lexical body only: nested defs get their own detached-or-not
        # decision in check()
        stack: List[ast.AST] = list(ast.iter_child_nodes(fn))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            if isinstance(node, ast.Try):
                for handler in node.handlers:
                    if (_broad_types(handler)
                            and not _handles_it(handler)
                            and not _cleanup_only(node)):
                        yield ctx.violation(
                            self, handler,
                            "broad except swallows failures in a detached "
                            f"worker body ({getattr(fn, 'name', '?')}): "
                            "log it, count it, re-raise, or use the bound "
                            "exception")
            stack.extend(ast.iter_child_nodes(node))
