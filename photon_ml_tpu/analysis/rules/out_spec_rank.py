"""PL017 out-spec-rank: each shard_map out_spec leaf must not name more
dimensions than the returned expression has.

Why it matters here: PL011 checks out_specs ARITY (tuple length vs the
target's return tuple), but a spec of the right arity can still be deeper
than the value it shards — ``out_specs=P("data", None)`` over a kernel
that returns ``x.sum()`` (rank 0) or a ``jnp.zeros((n,))`` accumulator
(rank 1).  jax rejects a PartitionSpec longer than the output's rank only
at trace time on the real mesh; on the CPU fallback path these sites pass
every test.  (A spec SHORTER than the rank is legal — trailing dimensions
replicate — so only the definite over-length case is flagged.)

Per-leaf ranks come from the v4 shape inference in ``analysis/dataflow``:
literal scalars, shape-literal constructors (``zeros``/``ones``/``full``),
axis-free reductions (``x.sum()``, ``jnp.mean(x)``), ``reshape`` with a
literal shape, ``ravel``, rank-preserving elementwise ops and collectives
(``psum``/``pmean``), closed over single-assignment locals — and, through
``ProgramSummaries``' return-rank fixpoint, over helper CALLS, so
``return _accumulate(x)`` resolves to the helper's inferred rank across
modules (a module-local resolver stands in when there is no program
index).  Anything not definitely known stays quiet.

Pairing mirrors jax's pytree-prefix semantics: a tuple out_specs pairs
element-wise with literal tuple returns; a single spec broadcasts to
every returned leaf.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Tuple

from photon_ml_tpu.analysis.dataflow import infer_rank, local_rank_env
from photon_ml_tpu.analysis.framework import (ModuleContext, Rule, Violation,
                                              register)
from photon_ml_tpu.analysis.jit_index import (FunctionNode, _unwrap_transform,
                                              dotted_name)
from photon_ml_tpu.analysis.rules.mesh_axis import (_def_in_scope_chain,
                                                    _SHARD_MAP_TERMINALS)
from photon_ml_tpu.analysis.rules.shard_spec import _arg_or_kw
from photon_ml_tpu.analysis.rules.sharding import (_is_pspec_call,
                                                   _pspec_aliases)


def _lexical_returns(fn: FunctionNode) -> List[ast.expr]:
    if isinstance(fn, ast.Lambda):
        return [fn.body]
    values: List[ast.expr] = []
    stack: List[ast.AST] = list(fn.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        if isinstance(node, ast.Return) and node.value is not None:
            values.append(node.value)
        stack.extend(ast.iter_child_nodes(node))
    return values


def _spec_rank(spec: ast.Call) -> Optional[int]:
    """Number of output dimensions a P(...)/PartitionSpec(...) literal
    names (None entries included — each positional argument addresses one
    dimension).  None when a Starred makes the length unknown."""
    if any(isinstance(a, ast.Starred) for a in spec.args):
        return None
    return len(spec.args)


def _local_rank_hook(ctx: ModuleContext):
    """Module-local callee return-rank resolver — the per-module stand-in
    for ProgramSummaries.call_rank."""
    graph = ctx.dataflow.call_graph
    memo: Dict[int, Optional[int]] = {}

    def fn_rank(fn: FunctionNode, depth: int = 0) -> Optional[int]:
        if id(fn) in memo:
            return memo[id(fn)]
        if depth > 6:
            return None
        memo[id(fn)] = None  # recursion/cycle guard
        values = _lexical_returns(fn)
        if values:
            def inner(call: ast.Call) -> Optional[int]:
                target = graph.resolve(call.func)
                return fn_rank(target, depth + 1) \
                    if target is not None else None
            env = local_rank_env(fn, inner)
            ranks = [infer_rank(v, env, inner) for v in values]
            if all(k is not None for k in ranks) and len(set(ranks)) == 1:
                memo[id(fn)] = ranks[0]
        return memo[id(fn)]

    def hook(call: ast.Call) -> Optional[int]:
        target = graph.resolve(call.func)
        return fn_rank(target) if target is not None else None

    return hook


@register
class OutSpecRankRule(Rule):
    name = "out-spec-rank"
    code = "PL017"
    severity = "error"
    description = ("no shard_map out_spec may name more dimensions than "
                   "the returned expression's (inferred) rank")

    def check(self, ctx: ModuleContext) -> Iterator[Violation]:
        if ctx.tree is None:
            return
        if not any(t in ctx.source for t in _SHARD_MAP_TERMINALS):
            return
        aliases = _pspec_aliases(ctx.tree)
        if ctx.program is not None:
            summ = ctx.program.summaries()
            hook = lambda call: summ.call_rank(ctx.relpath, call)  # noqa: E731
        else:
            hook = _local_rank_hook(ctx)
        for call in ctx.nodes_of(ast.Call):
            if not call.args:
                continue
            fname = dotted_name(call.func)
            if fname is None \
                    or fname.rpartition(".")[2] not in _SHARD_MAP_TERMINALS:
                continue
            yield from self._check_site(ctx, call, aliases, hook)

    def _check_site(self, ctx: ModuleContext, call: ast.Call, aliases,
                    hook) -> Iterator[Violation]:
        target = _unwrap_transform(call.args[0])
        if isinstance(target, ast.Name):
            target = _def_in_scope_chain(ctx, call, target.id)
        if not isinstance(target, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.Lambda)):
            return
        out_specs = _arg_or_kw(call, "out_specs", 3)
        if out_specs is None:
            return
        returns = _lexical_returns(target)
        if not returns:
            return
        env = local_rank_env(target, hook)
        tname = getattr(target, "name", "<lambda>")

        def leaf_pairs() -> Iterator[Tuple[ast.expr, ast.expr]]:
            if isinstance(out_specs, ast.Tuple):
                for ret in returns:
                    if isinstance(ret, ast.Tuple) \
                            and len(ret.elts) == len(out_specs.elts):
                        yield from zip(out_specs.elts, ret.elts)
            else:
                # single spec: a pytree prefix — broadcasts to every leaf
                for ret in returns:
                    leaves = ret.elts if isinstance(ret, ast.Tuple) else [ret]
                    for leaf in leaves:
                        yield out_specs, leaf

        seen: set = set()
        for spec, leaf in leaf_pairs():
            if not _is_pspec_call(spec, aliases):
                continue
            srank = _spec_rank(spec)
            if not srank:
                continue  # P() shards nothing — always legal
            lrank = infer_rank(leaf, env, hook)
            if lrank is None or lrank >= srank:
                continue
            key = (id(spec), id(leaf))
            if key in seen:
                continue
            seen.add(key)
            yield ctx.violation(
                self, spec,
                f"out_spec names {srank} dimension(s) but `{tname}` returns "
                f"an expression of rank {lrank} here (line {leaf.lineno}) — "
                "a PartitionSpec longer than the output rank is rejected at "
                "trace time on the real mesh; drop the extra entries or "
                "reshape the output")
