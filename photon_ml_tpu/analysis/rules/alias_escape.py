"""PL016 alias-escape: lock-protected state reached through a value
RETURNED by another function — PL005 upgraded from intraprocedural to
program-wide.

Why it matters here: the serving plane's discipline is "mutate under
``self._lock``, hand out snapshots" — ``coefficient_store.RandomCoordinate``
swaps ``self._hot`` under its lock and exposes it through unlocked
properties; ``swap.HotSwapper`` does the same with its base tuple.  PL005
polices mutations *inside the owning class*; nothing polices the caller
that does ``t = store.table; t[k] = v``.  That write lands on the same
object the swap thread replaces under the lock — a data race two modules
apart that no intraprocedural rule can connect.

The v4 summary layer computes, per function, which lock-protected
``self.<attr>`` objects its return value may alias (through the
``FunctionFlow`` alias state, so ``t = self._table; return t`` counts),
and ``ProgramSummaries`` closes the set over ``return f(...)`` chains
program-wide.  Two findings land on it:

  - **warning**, at the accessor: a ``return`` whose value aliases an attr
    mutated under the class lock — the escape hatch itself.  Legitimate
    snapshot-read APIs suppress with their documented contract.
  - **error**, at the caller: a mutation (attribute/item assignment,
    augmented assignment, mutating container method) through a name bound
    from an escape-returning call or property — resolved through the
    program call graph, with a program-wide unique-name fallback that only
    fires when exactly one def in the whole program carries the name.
    Mutations inside a ``with <lock-ish>:`` block are exempt (the caller
    took *a* lock; deciding whether it is the RIGHT lock is PL018's
    order-graph territory, not this rule's).

Whole-program mode only; per-module runs stay silent (like PL014).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Tuple

from photon_ml_tpu.analysis.dataflow import (MUTATOR_METHODS,
                                             _lockish_context)
from photon_ml_tpu.analysis.framework import (ModuleContext, Rule, Violation,
                                              register)


def _base_name(expr: ast.AST) -> Optional[str]:
    """Base ``Name`` of an attribute/subscript chain (``t.table[k]`` ->
    ``t``); None when the chain roots elsewhere (incl. ``self``)."""
    node: ast.AST = expr
    saw_chain = False
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        saw_chain = True
        node = node.value
    if isinstance(node, ast.Name) and node.id != "self" and saw_chain:
        return node.id
    return None


def _lockish_with(node: ast.AST) -> bool:
    return isinstance(node, (ast.With, ast.AsyncWith)) \
        and any(_lockish_context(i) for i in node.items)


@register
class AliasEscapeRule(Rule):
    name = "alias-escape"
    code = "PL016"
    severity = "error"
    description = ("no unlocked mutation through a value returned by an "
                   "accessor that aliases lock-protected state; accessors "
                   "leaking such aliases are flagged at the return")

    def check(self, ctx: ModuleContext) -> Iterator[Violation]:
        if ctx.tree is None or ctx.program is None:
            return
        summ = ctx.program.summaries()
        ms = summ.mod.get(ctx.relpath)
        if ms is None:
            return
        # (a) the escape hatches in THIS module: returns aliasing an attr
        # mutated under the class lock
        for fid, s in ms.by_id.items():
            if s.cls is None or not s.return_attr_sites:
                continue
            protected = ms.locked_attrs_of(s.cls)
            lock = ms.lock_display.get(s.cls, "_lock")
            for ret, attrs in s.return_attr_sites:
                hits = set(attrs) & protected
                if hits:
                    # attrs only ever assigned definitely-immutable values
                    # cannot be mutated through an alias — their accessors
                    # are clean (classified lazily: only on a hit)
                    hits -= ms.immutable_attrs_of(s.cls)
                hits = sorted(hits)
                if not hits:
                    continue
                listed = ", ".join(f"`self.{a}`" for a in hits)
                yield ctx.violation(
                    self, ret,
                    f"`{s.cls}.{s.name}` returns {listed}, mutated elsewhere "
                    f"under `self.{lock}` — the caller receives an unlocked "
                    "alias of lock-protected state; return a copy/snapshot, "
                    "or suppress with the documented read contract",
                    severity="warning")
        # (b) callers in THIS module mutating through an escaped alias
        for fid, s in ms.by_id.items():
            fn = ms.fn_of_id[fid]
            yield from self._scan_caller(ctx, summ, fn)

    def _scan_caller(self, ctx: ModuleContext, summ,
                     fn: ast.AST) -> Iterator[Violation]:
        # bound name -> (escape facts, source display, bind line)
        bound: Dict[str, Tuple[frozenset, str, int]] = {}

        def mutation_roots(node: ast.AST) -> List[Tuple[str, ast.AST]]:
            out: List[Tuple[str, ast.AST]] = []
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                tgts = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in tgts:
                    name = _base_name(t)
                    if name is not None:
                        out.append((name, t))
            elif isinstance(node, ast.AugAssign):
                name = _base_name(node.target)
                if name is not None:
                    out.append((name, node.target))
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    name = _base_name(t)
                    if name is not None:
                        out.append((name, t))
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in MUTATOR_METHODS:
                name = _base_name(node.func)
                if name is not None:
                    out.append((name, node))
            return out

        violations: List[Violation] = []

        def flag(name: str, site: ast.AST) -> None:
            got = bound.get(name)
            if got is None:
                return
            facts, src, line = got
            cls_key, attr, lock = sorted(facts)[0]
            violations.append(ctx.violation(
                self, site,
                f"`{name}` was returned by `{src}` (line {line}) and may "
                f"alias `{attr}` of {cls_key}, which is guarded by "
                f"`{lock}` — mutating it here bypasses the owner's lock; "
                "mutate through the owning API or under its lock"))

        def visit(node: ast.AST, locked: bool) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                return
            if bound:
                # mutation/lock tracking only matters once something IS
                # bound — before that the scan just looks for bindings
                if isinstance(node, ast.Name) and isinstance(node.ctx,
                                                             ast.Store):
                    bound.pop(node.id, None)  # a rebind kills the binding
                    return
                if _lockish_with(node):
                    locked = True
                if not locked:
                    for name, site in mutation_roots(node):
                        flag(name, site)
            elif _lockish_with(node):
                locked = True
            # `hot = store.hot` / `t = store.table()` — bind BEFORE the
            # statements that follow; skip the target so the generic
            # store-kill above doesn't immediately erase it
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                visit(node.value, locked)
                got = summ.resolve_escape_source(ctx.relpath, node.value)
                tname = node.targets[0].id
                if got is not None:
                    facts, src = got
                    bound[tname] = (facts, src, node.lineno)
                else:
                    bound.pop(tname, None)
                return
            for child in ast.iter_child_nodes(node):
                visit(child, locked)

        for stmt in getattr(fn, "body", []):
            visit(stmt, False)
        yield from violations
