"""photonlint rule catalog — importing this package registers every rule.

| code  | rule                | guards                                       |
|-------|---------------------|----------------------------------------------|
| PL001 | host-sync           | device→host syncs inside jit-traced code     |
| PL002 | recompile-hazard    | per-call / per-iteration jit construction    |
| PL003 | tracer-safety       | Python control flow on traced values         |
| PL004 | dtype-discipline    | float64 / numpy promotion on TPU hot paths   |
| PL005 | lock-discipline     | unlocked mutation of lock-protected state    |
| PL006 | donation-after-use  | reads of buffers already donated to jit      |
| PL007 | mesh-axis           | collective axis names absent from the mesh   |
| PL008 | sharding-annotation | unannotated mesh-path jits / bad spec axes   |
| PL009 | swallowed-exception | silent broad except in daemon/async workers  |
| PL010 | span-discipline     | trace spans discarded / escaping / unclosed  |

PL001/PL003/PL004 are trace-scoped: in whole-program mode (the default) the
ProgramIndex resolves functions jitted across module boundaries, so they
fire on helpers defined in one file and jitted in another.
"""

from photon_ml_tpu.analysis.rules.host_sync import HostSyncRule
from photon_ml_tpu.analysis.rules.recompile import RecompileHazardRule
from photon_ml_tpu.analysis.rules.tracer import TracerSafetyRule
from photon_ml_tpu.analysis.rules.dtype import DtypeDisciplineRule
from photon_ml_tpu.analysis.rules.locks import LockDisciplineRule
from photon_ml_tpu.analysis.rules.donation import DonationRule
from photon_ml_tpu.analysis.rules.mesh_axis import MeshAxisRule
from photon_ml_tpu.analysis.rules.sharding import ShardingAnnotationRule
from photon_ml_tpu.analysis.rules.swallowed import SwallowedExceptionRule
from photon_ml_tpu.analysis.rules.span_discipline import SpanDisciplineRule

__all__ = [
    "HostSyncRule",
    "RecompileHazardRule",
    "TracerSafetyRule",
    "DtypeDisciplineRule",
    "LockDisciplineRule",
    "DonationRule",
    "MeshAxisRule",
    "ShardingAnnotationRule",
    "SwallowedExceptionRule",
    "SpanDisciplineRule",
]
