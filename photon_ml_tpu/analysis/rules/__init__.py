"""photonlint rule catalog — importing this package registers every rule.

| code  | rule              | guards                                         |
|-------|-------------------|------------------------------------------------|
| PL001 | host-sync         | device→host syncs inside jit-traced code       |
| PL002 | recompile-hazard  | per-call / per-iteration jit construction      |
| PL003 | tracer-safety     | Python control flow on traced values           |
| PL004 | dtype-discipline  | float64 / numpy promotion on TPU hot paths     |
| PL005 | lock-discipline   | unlocked mutation of lock-protected state      |

Planned (ROADMAP): donation-after-use, sharding-annotation checks.
"""

from photon_ml_tpu.analysis.rules.host_sync import HostSyncRule
from photon_ml_tpu.analysis.rules.recompile import RecompileHazardRule
from photon_ml_tpu.analysis.rules.tracer import TracerSafetyRule
from photon_ml_tpu.analysis.rules.dtype import DtypeDisciplineRule
from photon_ml_tpu.analysis.rules.locks import LockDisciplineRule

__all__ = [
    "HostSyncRule",
    "RecompileHazardRule",
    "TracerSafetyRule",
    "DtypeDisciplineRule",
    "LockDisciplineRule",
]
