"""photonlint rule catalog — importing this package registers every rule.

| code  | rule                  | guards                                       |
|-------|-----------------------|----------------------------------------------|
| PL001 | host-sync             | device→host syncs inside jit-traced code     |
| PL002 | recompile-hazard      | per-call / per-iteration jit construction    |
| PL003 | tracer-safety         | Python control flow on traced values         |
| PL004 | dtype-discipline      | float64 / numpy promotion on TPU hot paths   |
| PL005 | lock-discipline       | unlocked mutation of lock-protected state    |
| PL006 | donation-after-use    | reads of buffers already donated to jit      |
| PL007 | mesh-axis             | collective axis names absent from the mesh   |
| PL008 | sharding-annotation   | unannotated mesh-path jits / bad spec axes   |
| PL009 | swallowed-exception   | silent broad except in daemon/async workers  |
| PL010 | span-discipline       | trace spans discarded / escaping / unclosed  |
| PL011 | shard-spec-arity      | shard_map specs vs target arity / site mesh  |
| PL012 | collective-without-mesh | collectives jit-reachable with no binder   |
| PL013 | blocking-in-async     | blocking calls on the asyncio event loop     |
| PL014 | cross-module-donation | donated-buffer reads across module imports   |
| PL015 | container-donation-taint | donated-buffer taint through containers / pytrees |
| PL016 | alias-escape          | unlocked mutation via accessor-returned aliases |
| PL017 | out-spec-rank         | shard_map out_specs deeper than returned rank |
| PL018 | lock-order            | cycles in the global lock acquisition order  |

PL001/PL003/PL004 are trace-scoped: in whole-program mode (the default) the
ProgramIndex resolves functions jitted across module boundaries, so they
fire on helpers defined in one file and jitted in another.

PL005/PL012/PL013 are dataflow-backed (analysis/dataflow.py): a per-function
CFG fixpoint supplies alias sets, and module/program call graphs supply
event-loop and mesh-scope reachability.  PL014 reuses PL006's taint scanner
over the ProgramIndex's program-wide donor table.

PL015–PL018 are summary-backed (v4): per-function interprocedural summaries
(return-value aliases, container provenance, definite return ranks, lock
acquisition order) joined to program-wide fixpoints by
``program_index.ProgramSummaries``.  PL016/PL018 need whole-program mode;
PL015/PL017 also run per-module with module-local resolution.
"""

from photon_ml_tpu.analysis.rules.host_sync import HostSyncRule
from photon_ml_tpu.analysis.rules.recompile import RecompileHazardRule
from photon_ml_tpu.analysis.rules.tracer import TracerSafetyRule
from photon_ml_tpu.analysis.rules.dtype import DtypeDisciplineRule
from photon_ml_tpu.analysis.rules.locks import LockDisciplineRule
from photon_ml_tpu.analysis.rules.donation import DonationRule
from photon_ml_tpu.analysis.rules.mesh_axis import MeshAxisRule
from photon_ml_tpu.analysis.rules.sharding import ShardingAnnotationRule
from photon_ml_tpu.analysis.rules.swallowed import SwallowedExceptionRule
from photon_ml_tpu.analysis.rules.span_discipline import SpanDisciplineRule
from photon_ml_tpu.analysis.rules.shard_spec import ShardSpecArityRule
from photon_ml_tpu.analysis.rules.collective_ctx import CollectiveContextRule
from photon_ml_tpu.analysis.rules.blocking_async import BlockingInAsyncRule
from photon_ml_tpu.analysis.rules.donation_flow import CrossModuleDonationRule
from photon_ml_tpu.analysis.rules.donation_containers import \
    ContainerDonationRule
from photon_ml_tpu.analysis.rules.alias_escape import AliasEscapeRule
from photon_ml_tpu.analysis.rules.out_spec_rank import OutSpecRankRule
from photon_ml_tpu.analysis.rules.lock_order import LockOrderRule

__all__ = [
    "HostSyncRule",
    "RecompileHazardRule",
    "TracerSafetyRule",
    "DtypeDisciplineRule",
    "LockDisciplineRule",
    "DonationRule",
    "MeshAxisRule",
    "ShardingAnnotationRule",
    "SwallowedExceptionRule",
    "SpanDisciplineRule",
    "ShardSpecArityRule",
    "CollectiveContextRule",
    "BlockingInAsyncRule",
    "CrossModuleDonationRule",
    "ContainerDonationRule",
    "AliasEscapeRule",
    "OutSpecRankRule",
    "LockOrderRule",
]
