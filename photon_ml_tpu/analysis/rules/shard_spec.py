"""PL011 shard-spec-arity: shard_map in_specs/out_specs must match the
target's signature and the mesh they are bound to.

Why it matters here: every sharded scoring/solve kernel in ``parallel/``
and ``serving/engine.py`` is a ``shard_map(local, mesh=..., in_specs=(...),
out_specs=(...))`` site.  jax checks the spec/args pytree match only when
the wrapped callable is CALLED — and the arity errors it raises at that
point name pytree paths, not source lines.  Worse, a spec tuple that is
the wrong LENGTH for the local function is often silently "fixed" during a
refactor by whoever adds the next argument, while the axis strings inside
drift from the mesh they run on (which only fails on the pod — same
failure class PL007/PL008 police).  This rule checks, statically, at the
shard_map call site:

  - ``in_specs``: when written as a literal tuple and the target function
    is resolvable (inline def/lambda, or a Name defined in an enclosing
    scope) with a fixed positional signature, the tuple length must equal
    the number of positional parameters;
  - ``out_specs``: when written as a literal tuple and every ``return`` of
    the target is a literal tuple of one consistent length, the lengths
    must agree (a single non-tuple out_spec is a valid pytree prefix and
    stays quiet);
  - each ``P(...)``/``PartitionSpec(...)`` inside the specs: no mesh axis
    may appear twice in one spec, and every definitely-resolved axis name
    must be an axis of the mesh bound at THIS site when that mesh
    expression resolves to a ``Mesh(...)`` construction (the program-wide
    universe membership check for unresolvable meshes is PL008's).

Resolution is best-effort through analysis/resolve.py and the ProgramIndex
mesh universe; anything unresolvable stays quiet.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Set, Tuple

from photon_ml_tpu.analysis.framework import (ModuleContext, Rule, Violation,
                                              register)
from photon_ml_tpu.analysis.jit_index import (FunctionNode, _unwrap_transform,
                                              dotted_name)
from photon_ml_tpu.analysis.resolve import mesh_axes_of_expr
from photon_ml_tpu.analysis.rules.mesh_axis import (_def_in_scope_chain,
                                                    _SHARD_MAP_TERMINALS)
from photon_ml_tpu.analysis.rules.sharding import (_is_pspec_call,
                                                   _pspec_aliases)


def _arg_or_kw(call: ast.Call, name: str, pos: int) -> Optional[ast.expr]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    if len(call.args) > pos:
        return call.args[pos]
    return None


def _positional_param_count(fn: FunctionNode) -> Optional[int]:
    a = fn.args
    if a.vararg is not None or a.kwarg is not None:
        return None  # variadic: any spec arity can be legal
    return len(a.posonlyargs) + len(a.args)


def _return_tuple_len(fn: FunctionNode) -> Optional[int]:
    """Length of the target's literal return tuple when EVERY lexical return
    is a tuple of the same length (None = unknown / inconsistent input —
    stay quiet)."""
    values: List[ast.expr] = []
    if isinstance(fn, ast.Lambda):
        values = [fn.body]
    else:
        stack: List[ast.AST] = list(fn.body)
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            if isinstance(node, ast.Return) and node.value is not None:
                values.append(node.value)
            stack.extend(ast.iter_child_nodes(node))
    if not values:
        return None
    lens: Set[int] = set()
    for v in values:
        if not isinstance(v, ast.Tuple):
            return None
        lens.add(len(v.elts))
    return lens.pop() if len(lens) == 1 else None


def _definite_spec_axes(ctx: ModuleContext,
                        spec: ast.Call) -> List[Tuple[str, ast.expr]]:
    """(axis, arg-expr) pairs for spec arguments whose resolution is
    DEFINITE (exactly one possible string) — ambiguous args are skipped so
    alternatives never manufacture duplicates."""
    out: List[Tuple[str, ast.expr]] = []
    for arg in spec.args:
        if isinstance(arg, ast.Starred):
            continue
        got = ctx.resolver.strings(arg)
        if len(got) == 1:
            out.append((got[0], arg))
    return out


@register
class ShardSpecArityRule(Rule):
    name = "shard-spec-arity"
    code = "PL011"
    severity = "error"
    description = ("shard_map in_specs/out_specs arity must match the "
                   "target signature and name axes of the bound mesh")

    def check(self, ctx: ModuleContext) -> Iterator[Violation]:
        if ctx.tree is None:
            return
        # findings anchor on shard_map call sites — skip modules whose
        # text never names one
        if not any(t in ctx.source for t in _SHARD_MAP_TERMINALS):
            return
        aliases = _pspec_aliases(ctx.tree)
        for call in ctx.nodes_of(ast.Call):
            if not call.args:
                continue
            fname = dotted_name(call.func)
            if fname is None \
                    or fname.rpartition(".")[2] not in _SHARD_MAP_TERMINALS:
                continue
            yield from self._check_site(ctx, call, aliases)

    def _check_site(self, ctx: ModuleContext, call: ast.Call,
                    aliases: Set[str]) -> Iterator[Violation]:
        target = _unwrap_transform(call.args[0])
        if isinstance(target, ast.Name):
            target = _def_in_scope_chain(ctx, call, target.id)
        fn = target if isinstance(target, (ast.FunctionDef,
                                           ast.AsyncFunctionDef,
                                           ast.Lambda)) else None
        in_specs = _arg_or_kw(call, "in_specs", 2)
        out_specs = _arg_or_kw(call, "out_specs", 3)
        tname = getattr(fn, "name", "<target>") if fn is not None else None

        if fn is not None and isinstance(in_specs, ast.Tuple):
            n_params = _positional_param_count(fn)
            if n_params is not None and len(in_specs.elts) != n_params:
                yield ctx.violation(
                    self, in_specs,
                    f"shard_map in_specs has {len(in_specs.elts)} spec(s) "
                    f"but `{tname}` takes {n_params} positional "
                    "argument(s) — the pytree/spec mismatch only surfaces "
                    "when the wrapped callable is invoked, far from this "
                    "site")
        if fn is not None and isinstance(out_specs, ast.Tuple):
            n_out = _return_tuple_len(fn)
            if n_out is not None and len(out_specs.elts) != n_out:
                yield ctx.violation(
                    self, out_specs,
                    f"shard_map out_specs has {len(out_specs.elts)} spec(s) "
                    f"but `{tname}` returns a {n_out}-tuple — every output "
                    "leaf needs a spec (or use a single pytree-prefix spec)")

        mesh_expr = _arg_or_kw(call, "mesh", 1)
        site_axes = (mesh_axes_of_expr(ctx.resolver, mesh_expr)
                     if mesh_expr is not None else set())
        for specs in (in_specs, out_specs):
            if specs is None:
                continue
            for node in ast.walk(specs):
                if not _is_pspec_call(node, aliases):
                    continue
                definite = _definite_spec_axes(ctx, node)
                seen: Set[str] = set()
                for axis, arg in definite:
                    if axis in seen:
                        yield ctx.violation(
                            self, arg,
                            f"mesh axis '{axis}' appears more than once in "
                            "this PartitionSpec — an axis may shard at most "
                            "one dimension; this spec is rejected on any "
                            "real mesh")
                    seen.add(axis)
                    if site_axes and axis not in site_axes:
                        yield ctx.violation(
                            self, arg,
                            f"PartitionSpec axis '{axis}' is not an axis of "
                            "the mesh bound at this shard_map site (axes: "
                            f"{sorted(site_axes)}) — the spec only fails "
                            "when this program runs on its real mesh")
