"""photonlint core: violations, rules, suppressions, and the analysis driver.

Photon ML reference counterpart: none directly — the JVM reference gets this
class of checking from scalac + Spark's static DAG.  A JAX port trades both
away (Python, dynamic tracing), so the repo's correctness/performance
invariants (no host syncs in hot paths, no recompile hazards, no float64 on
TPU paths, lock-protected mutation of shared serving state) are re-imposed
here as an AST pass over our own source, run by tier-1
(tests/test_photonlint.py) and ``python -m tools.photonlint``.

Design:
  - a ``Rule`` inspects one ``ModuleContext`` (source + AST + lazily built
    ``JitIndex``) and yields ``Violation``s;
  - ``# photonlint: disable=rule[,rule2] -- reason`` on the flagged line (or
    a standalone comment line directly above it) suppresses; ``disable=all``
    suppresses every rule; ``# photonlint: disable-file=rule`` anywhere in
    the first 10 lines suppresses for the whole file;
  - violations fingerprint on (rule, path, message, source-line text,
    same-line occurrence) — NOT the line number — so baselined debt stays
    matched while unrelated edits shift lines (analysis/baseline.py);
  - parse failures surface as ``parse-error`` violations instead of
    crashing the run, so a broken file fails the lint gate loudly.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import os
import re
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set, Tuple, Type

SEVERITIES = ("error", "warning")

# `# photonlint: disable=a,b` / `disable-file=a` with an optional
# `-- why this is intentional` trailer (the reason is required by review
# convention, not by the parser).
_SUPPRESS_RE = re.compile(
    r"#\s*photonlint:\s*disable(?P<scope>-file)?\s*=\s*"
    r"(?P<rules>[A-Za-z0-9_,\- ]+?)\s*(?:--.*)?$")
_FILE_SCOPE_SCAN_LINES = 10


@dataclasses.dataclass(frozen=True)
class Violation:
    """One finding.  ``snippet`` is the stripped source line — part of the
    baseline fingerprint so renumbering-only edits don't invalidate debt."""

    rule: str
    code: str
    path: str          # repo-relative, posix separators
    line: int
    col: int
    message: str
    severity: str = "error"
    snippet: str = ""
    occurrence: int = 0  # disambiguates identical findings on identical lines

    def fingerprint(self) -> str:
        key = "|".join((self.rule, self.path, self.message,
                        self.snippet.strip(), str(self.occurrence)))
        return hashlib.sha1(key.encode("utf-8")).hexdigest()[:16]

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["fingerprint"] = self.fingerprint()
        return d

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: "
                f"{self.code}[{self.rule}] {self.severity}: {self.message}")


class ModuleContext:
    """Everything a rule may inspect about one source file.

    ``program``: the whole-program :class:`~photon_ml_tpu.analysis.
    program_index.ProgramIndex` when linting in whole-program mode (None in
    per-module mode / ``--no-program-index``).  When the program index holds
    this module, its pre-parsed tree is reused so cross-module traced roots
    share node identity with the tree the rules walk.
    """

    def __init__(self, relpath: str, source: str, program=None):
        self.relpath = relpath.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.program = program
        self.tree: Optional[ast.Module] = None
        self.parse_error: Optional[SyntaxError] = None
        shared = program.tree_for(self.relpath) if program is not None else None
        if shared is not None:
            self.tree = shared
        else:
            try:
                self.tree = ast.parse(source)
            except SyntaxError as e:  # surfaced as a parse-error violation
                self.parse_error = e
        self._jit_index = None
        self._resolver = None
        self._dataflow = None
        self._walked: Optional[Tuple[ast.AST, ...]] = None
        self._node_buckets: Dict[tuple, Tuple[ast.AST, ...]] = {}

    @property
    def jit_index(self):
        """Lazily built once per module, shared by every rule.  In
        whole-program mode the per-module index is augmented with the
        cross-module traced roots the ProgramIndex resolved."""
        if self._jit_index is None:
            from photon_ml_tpu.analysis.jit_index import JitIndex
            # the ProgramIndex already built this module's index over the
            # SAME tree during construction and never re-reads it after —
            # adopt it instead of paying a second full-tree walk (augmenting
            # is idempotent: extra_roots skips roots the base already walks)
            info = (self.program.modules.get(self.relpath)
                    if self.program is not None else None)
            if info is not None and info.tree is self.tree:
                idx = info.jit_index
            else:
                idx = JitIndex(self.tree) if self.tree else JitIndex(None)
            if self.program is not None and self.tree is not None:
                for fn, params in self.program.extra_roots(self.relpath, idx):
                    idx.add_root(fn, params)
            self._jit_index = idx
        return self._jit_index

    @property
    def walked(self) -> Tuple[ast.AST, ...]:
        """The module's full preorder walk, computed once and shared by
        every rule — ``ast.walk`` per rule is the linter's dominant cost
        (a deque-driven traversal is ~7x slower than iterating this
        tuple)."""
        if self._walked is None:
            self._walked = (tuple(ast.walk(self.tree))
                            if self.tree is not None else ())
        return self._walked

    def nodes_of(self, *types: type) -> Tuple[ast.AST, ...]:
        """All nodes of the given AST types, bucketed once per type-key
        from the shared walk — the fast replacement for the
        ``for node in ast.walk(tree): if isinstance(node, T)`` loop."""
        got = self._node_buckets.get(types)
        if got is None:
            got = tuple(n for n in self.walked if isinstance(n, types))
            self._node_buckets[types] = got
        return got

    @property
    def resolver(self):
        """Shared best-effort literal resolver (analysis/resolve.py)."""
        if self._resolver is None:
            from photon_ml_tpu.analysis.resolve import Resolver
            self._resolver = Resolver(self)
        return self._resolver

    @property
    def dataflow(self):
        """Shared per-module dataflow facade (analysis/dataflow.py): cached
        per-function alias/reaching-def flows, the module call graph, and
        event-loop / lock-region / jit reachability sets."""
        if self._dataflow is None:
            from photon_ml_tpu.analysis.dataflow import ModuleDataflow
            self._dataflow = ModuleDataflow(self)
        return self._dataflow

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def violation(self, rule: "Rule", node: ast.AST, message: str,
                  severity: Optional[str] = None) -> Violation:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        return Violation(rule=rule.name, code=rule.code, path=self.relpath,
                         line=line, col=col, message=message,
                         severity=severity or rule.severity,
                         snippet=self.line_text(line).strip())


class Rule:
    """Base class: subclasses set metadata and implement ``check``."""

    name: str = ""
    code: str = ""
    severity: str = "error"
    description: str = ""

    def check(self, ctx: ModuleContext) -> Iterator[Violation]:
        raise NotImplementedError


class _ParseErrorRule(Rule):
    """Pseudo-rule used for files that fail to parse (never registered —
    a broken file must not be silently skipped by rule selection)."""

    name = "parse-error"
    code = "PL000"
    severity = "error"
    description = "file could not be parsed as Python"


_PARSE_RULE = _ParseErrorRule()

_REGISTRY: Dict[str, Type[Rule]] = {}


def register(cls: Type[Rule]) -> Type[Rule]:
    """Class decorator: add a rule to the global registry (keyed by name)."""
    if not cls.name or not cls.code:
        raise ValueError(f"rule {cls.__name__} must define name and code")
    if cls.name in _REGISTRY and _REGISTRY[cls.name] is not cls:
        raise ValueError(f"duplicate rule name {cls.name!r}")
    _REGISTRY[cls.name] = cls
    return cls


def registered_rules() -> Dict[str, Type[Rule]]:
    # import for the registration side effect; cheap after the first call
    import photon_ml_tpu.analysis.rules  # noqa: F401
    return dict(_REGISTRY)


def build_rules(names: Optional[Sequence[str]] = None) -> List[Rule]:
    registry = registered_rules()
    if names is None:
        names = sorted(registry, key=lambda n: registry[n].code)
    missing = [n for n in names if n not in registry]
    if missing:
        known = ", ".join(sorted(registry))
        raise KeyError(f"unknown rule(s) {missing} (known: {known})")
    return [registry[n]() for n in names]


# -- suppressions -----------------------------------------------------------

def _parse_suppressions(lines: Sequence[str]) -> Tuple[Dict[int, Set[str]],
                                                       Set[str]]:
    """Returns (per-line rule sets, file-wide rule set).  A suppression on a
    standalone comment line covers the next non-comment line too."""
    per_line: Dict[int, Set[str]] = {}
    file_wide: Set[str] = set()
    for i, text in enumerate(lines, start=1):
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        rules = {r.strip() for r in m.group("rules").split(",") if r.strip()}
        if m.group("scope"):
            if i <= _FILE_SCOPE_SCAN_LINES:
                file_wide |= rules
            continue
        per_line.setdefault(i, set()).update(rules)
        if text.lstrip().startswith("#"):
            # standalone comment: covers the rest of its comment block (a
            # multi-line reason) and the first code line below it
            j = i + 1
            while j <= len(lines) and lines[j - 1].lstrip().startswith("#"):
                per_line.setdefault(j, set()).update(rules)
                j += 1
            per_line.setdefault(j, set()).update(rules)
    return per_line, file_wide


def _is_suppressed(v: Violation, per_line: Dict[int, Set[str]],
                   file_wide: Set[str]) -> bool:
    if "all" in file_wide or v.rule in file_wide:
        return True
    rules = per_line.get(v.line, ())
    return "all" in rules or v.rule in rules


# -- driver -----------------------------------------------------------------

@dataclasses.dataclass
class AnalysisResult:
    violations: List[Violation]
    suppressed: List[Violation]
    files_scanned: int
    index_build_s: float = 0.0  # ProgramIndex build time (0 in per-module mode)
    dataflow_s: float = 0.0     # time spent in the dataflow engine this run
    summaries_s: float = 0.0    # time in the interprocedural summary layer
    summaries_cached: int = 0   # modules served from the digest summary cache
    whole_program: bool = False

    def by_rule(self) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        for v in self.violations:
            counts[v.rule] = counts.get(v.rule, 0) + 1
        return counts

    def by_severity(self) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        for v in self.violations:
            counts[v.severity] = counts.get(v.severity, 0) + 1
        return counts


def _iter_py_files(path: str) -> Iterator[str]:
    if os.path.isfile(path):
        yield path
        return
    for dirpath, dirnames, filenames in os.walk(path):
        dirnames[:] = sorted(d for d in dirnames
                             if d != "__pycache__" and not d.startswith("."))
        for f in sorted(filenames):
            if f.endswith(".py"):
                yield os.path.join(dirpath, f)


def _dedupe_occurrences(violations: List[Violation]) -> List[Violation]:
    """Number repeat findings that share a fingerprint key (same rule, path,
    message, and line text) so each gets a distinct baseline entry."""
    seen: Dict[Tuple, int] = {}
    out = []
    for v in sorted(violations, key=lambda v: (v.path, v.line, v.col, v.code)):
        key = (v.rule, v.path, v.message, v.snippet.strip())
        n = seen.get(key, 0)
        seen[key] = n + 1
        out.append(dataclasses.replace(v, occurrence=n) if n else v)
    return out


def analyze_source(relpath: str, source: str, rules: Sequence[Rule],
                   program=None) -> Tuple[List[Violation],
                                          List[Violation]]:
    """Lint one in-memory module; returns (kept, suppressed).  ``program``:
    optional ProgramIndex for whole-program (cross-module) resolution."""
    ctx = ModuleContext(relpath, source, program=program)
    found: List[Violation] = []
    if ctx.parse_error is not None:
        e = ctx.parse_error
        found.append(Violation(
            rule=_PARSE_RULE.name, code=_PARSE_RULE.code, path=ctx.relpath,
            line=e.lineno or 1, col=(e.offset or 1) - 1,
            message=f"syntax error: {e.msg}", severity="error",
            snippet=ctx.line_text(e.lineno or 1).strip()))
    else:
        for rule in rules:
            found.extend(rule.check(ctx))
    per_line, file_wide = _parse_suppressions(ctx.lines)
    kept = [v for v in found if not _is_suppressed(v, per_line, file_wide)]
    suppressed = [v for v in found if _is_suppressed(v, per_line, file_wide)]
    return _dedupe_occurrences(kept), suppressed


def run_analysis(paths: Sequence[str], rules: Optional[Sequence[Rule]] = None,
                 root: Optional[str] = None, whole_program: bool = True,
                 index_paths: Optional[Sequence[str]] = None
                 ) -> AnalysisResult:
    """Lint every ``.py`` under ``paths``.  ``root`` anchors the
    repo-relative paths used in reports and baseline fingerprints (default:
    the current working directory).

    ``whole_program``: build a ProgramIndex so trace-scoped rules resolve
    functions jitted across module boundaries and the sharding rules see
    every mesh in the program (default; ``False`` restores pure per-module
    analysis — the ``--no-program-index`` escape hatch).

    ``index_paths``: build the ProgramIndex over THESE paths instead of the
    lint paths — the incremental mode (``--paths``): lint a few files while
    indexing the whole package so cross-module results match a full run.
    """
    rules = list(rules) if rules is not None else build_rules()
    root = os.path.abspath(root or os.getcwd())
    from photon_ml_tpu.analysis import dataflow as _dataflow
    _dataflow.reset_cost()
    program = None
    index_build_s = 0.0
    if whole_program:
        from photon_ml_tpu.analysis.program_index import ProgramIndex
        program = ProgramIndex.from_paths(
            list(index_paths) if index_paths else list(paths), root)
        index_build_s = program.build_seconds
    violations: List[Violation] = []
    suppressed: List[Violation] = []
    n_files = 0
    for path in paths:
        for fpath in _iter_py_files(path):
            n_files += 1
            rel = os.path.relpath(os.path.abspath(fpath), root)
            with open(fpath, "r", encoding="utf-8") as f:
                source = f.read()
            kept, supp = analyze_source(rel, source, rules, program=program)
            violations.extend(kept)
            suppressed.extend(supp)
    violations.sort(key=lambda v: (v.path, v.line, v.col, v.code))
    return AnalysisResult(violations=violations, suppressed=suppressed,
                          files_scanned=n_files, index_build_s=index_build_s,
                          dataflow_s=_dataflow.cost_seconds(),
                          summaries_s=_dataflow.summary_seconds(),
                          summaries_cached=(
                              _dataflow.summaries_cached_count()),
                          whole_program=whole_program)
