"""Per-function forward dataflow + call-graph reachability for photonlint.

The v1/v2 rules are lexical: PL005 only sees mutations spelled ``self.X``,
PL007 only sees collectives lexically inside a shard_map target, and nothing
at all connects an ``async def`` body to the synchronous helpers it calls.
The concurrency and distributed-protocol rules (PL011–PL014, PL005v2) need
two things the lexical passes cannot answer:

  1. **"what does this name alias here?"** — a per-function forward dataflow
     over a CFG lowered from the AST (branches, loops run to convergence,
     try/except/finally with per-statement exception edges).  The abstract
     state maps each local name to (a) the set of ``self.<attr>`` objects it
     may alias and (b) the line numbers of the reaching definitions.
     ``a = self._store; b = a`` makes both ``a`` and ``b`` aliases of
     ``_store``; ``self._x = buf`` makes ``buf`` an alias of ``_x``; any
     other assignment kills.  Joins are set unions, so the analysis is
     monotone and the loop fixpoint terminates.

  2. **"is this call reachable from an async body / a jit root / a
     lock-held region?"** — a module-local call graph (``Name`` → module
     def, unique by-name fallback; ``self.method`` → unique method, the
     same convention ``ProgramIndex._resolve_callee`` uses) with seeded
     reachability: event-loop seeds are every ``async def`` plus the
     callback targets of ``loop.call_soon[_threadsafe]/call_later/call_at``;
     lock seeds are the callees invoked inside ``with self.<lock>:`` blocks;
     jit reachability reuses the (program-augmented) ``JitIndex`` walk.
     Propagation follows only real ``Call`` nodes — a function REFERENCE
     handed to ``run_in_executor``/``to_thread``/``Thread(target=...)`` is
     not a call, so executor hand-offs are exempt by construction.

Everything here is best-effort and conservative in the same direction as
the rest of the analysis stack: unresolvable facts contribute nothing, so
dataflow can only ADD precision, never invent phantom findings.  The time
spent in this module is accounted separately (``reset_cost``/
``cost_seconds``) so ``bench.py --lint`` can report the dataflow pass cost
next to the ProgramIndex build.
"""

from __future__ import annotations

import ast
import time
from typing import (Dict, FrozenSet, Iterable, Iterator, List, Optional,
                    Sequence, Set, Tuple)

from photon_ml_tpu.analysis.jit_index import FunctionNode, dotted_name

# -- cost accounting ---------------------------------------------------------

_COST = {"s": 0.0}


def reset_cost() -> None:
    _COST["s"] = 0.0


def cost_seconds() -> float:
    return _COST["s"]


class _timed:
    """Context manager accumulating wall time into the dataflow cost."""

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        _COST["s"] += time.perf_counter() - self._t0
        return False


# -- abstract state ----------------------------------------------------------
# name -> (frozenset of aliased self-attrs, frozenset of reaching-def lines)
VarFact = Tuple[FrozenSet[str], FrozenSet[int]]
AliasState = Dict[str, VarFact]

_EMPTY: FrozenSet = frozenset()


def _self_attr(node: ast.AST) -> Optional[str]:
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name) and node.value.id == "self"):
        return node.attr
    return None


def _value_aliases(state: AliasState, expr: ast.AST,
                   depth: int = 0) -> FrozenSet[str]:
    """Self-attrs the VALUE expression may alias under ``state``."""
    if depth > 6 or expr is None:
        return _EMPTY
    if isinstance(expr, ast.Name):
        return state.get(expr.id, (_EMPTY, _EMPTY))[0]
    attr = _self_attr(expr)
    if attr is not None:
        return frozenset((attr,))
    if isinstance(expr, ast.IfExp):
        return (_value_aliases(state, expr.body, depth + 1)
                | _value_aliases(state, expr.orelse, depth + 1))
    if isinstance(expr, ast.NamedExpr):
        return _value_aliases(state, expr.value, depth + 1)
    return _EMPTY


def _kill_target(new: AliasState, tgt: ast.AST, line: int) -> None:
    for sub in ast.walk(tgt):
        if isinstance(sub, ast.Name):
            new[sub.id] = (_EMPTY, frozenset((line,)))


def _apply_assign(new: AliasState, old: AliasState, tgt: ast.AST,
                  value: Optional[ast.AST], line: int) -> None:
    if isinstance(tgt, ast.Name):
        aliases = _value_aliases(old, value) if value is not None else _EMPTY
        new[tgt.id] = (aliases, frozenset((line,)))
    elif isinstance(tgt, (ast.Tuple, ast.List)):
        elts = tgt.elts
        if (isinstance(value, (ast.Tuple, ast.List))
                and len(value.elts) == len(elts)
                and not any(isinstance(e, ast.Starred) for e in elts)):
            for t, v in zip(elts, value.elts):
                _apply_assign(new, old, t, v, line)
        else:
            _kill_target(new, tgt, line)
    elif isinstance(tgt, ast.Starred):
        _kill_target(new, tgt.value, line)
    else:
        # attribute/subscript target: binds no local — but `self.X = name`
        # makes `name` an alias of X from here on (the object is shared)
        attr = _self_attr(tgt)
        if attr is not None and isinstance(value, ast.Name):
            aliases, defs = new.get(value.id, (_EMPTY, _EMPTY))
            new[value.id] = (aliases | {attr}, defs)


def _header_exprs(stmt: ast.AST) -> List[ast.AST]:
    """Expressions evaluated AT a CFG node for a compound statement (its
    body statements are separate CFG nodes)."""
    if isinstance(stmt, (ast.If, ast.While)):
        return [stmt.test]
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        return [stmt.iter]
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        return [item.context_expr for item in stmt.items]
    if isinstance(stmt, ast.Try) or stmt.__class__.__name__ == "TryStar":
        return []
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
                         ast.ExceptHandler)):
        return []
    return [stmt]  # simple statement: whole subtree


def _transfer(state: AliasState, stmt: ast.AST) -> AliasState:
    new = dict(state)
    line = getattr(stmt, "lineno", 0)
    if isinstance(stmt, ast.Assign):
        for tgt in stmt.targets:
            _apply_assign(new, state, tgt, stmt.value, line)
    elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
        _apply_assign(new, state, stmt.target, stmt.value, line)
    elif isinstance(stmt, ast.AugAssign):
        if isinstance(stmt.target, ast.Name):
            # x += v rebinds x for immutables; conservatively drop aliases
            _kill_target(new, stmt.target, line)
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        _kill_target(new, stmt.target, line)
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        for item in stmt.items:
            if item.optional_vars is not None:
                # `with self._lock as l:` — l aliases the context object
                _apply_assign(new, state, item.optional_vars,
                              item.context_expr, line)
    elif isinstance(stmt, ast.ExceptHandler):
        if stmt.name:
            new[stmt.name] = (_EMPTY, frozenset((line,)))
    elif isinstance(stmt, (ast.Import, ast.ImportFrom)):
        for alias in stmt.names:
            bound = alias.asname or alias.name.split(".")[0]
            new[bound] = (_EMPTY, frozenset((line,)))
    elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
        new[stmt.name] = (_EMPTY, frozenset((line,)))
    elif isinstance(stmt, ast.Delete):
        for tgt in stmt.targets:
            if isinstance(tgt, ast.Name):
                new[tgt.id] = (_EMPTY, frozenset((line,)))
    # walrus bindings in the expressions this node evaluates
    for expr in _header_exprs(stmt):
        for sub in ast.walk(expr):
            if isinstance(sub, ast.NamedExpr) \
                    and isinstance(sub.target, ast.Name):
                new[sub.target.id] = (_value_aliases(state, sub.value),
                                      frozenset((getattr(sub, "lineno",
                                                         line),)))
    return new


def _join(states: Iterable[AliasState]) -> AliasState:
    out: AliasState = {}
    for st in states:
        for name, (aliases, defs) in st.items():
            if name in out:
                a0, d0 = out[name]
                out[name] = (a0 | aliases, d0 | defs)
            else:
                out[name] = (aliases, defs)
    return out


# -- CFG ---------------------------------------------------------------------

class _Loop:
    __slots__ = ("header", "breaks")

    def __init__(self, header: int):
        self.header = header
        self.breaks: Set[int] = set()


class _CFG:
    """Statement-level control-flow graph of one function body.  Each
    statement (and each ``except`` handler head) is one node; edges follow
    branch/loop/try structure, with per-statement exception edges from try
    bodies to their handlers."""

    def __init__(self, body: Sequence[ast.stmt]):
        self.stmts: List[ast.AST] = []
        self.succ: List[Set[int]] = []
        self._seq(body, frontier=set(), loops=[], handlers=[])

    def _add(self, stmt: ast.AST) -> int:
        self.stmts.append(stmt)
        self.succ.append(set())
        return len(self.stmts) - 1

    def _seq(self, body: Sequence[ast.stmt], frontier: Set[int],
             loops: List[_Loop], handlers: List[int]) -> Set[int]:
        for stmt in body:
            idx = self._add(stmt)
            for f in frontier:
                self.succ[f].add(idx)
            for h in handlers:
                self.succ[idx].add(h)  # an exception may fire mid-statement
            frontier = self._stmt(stmt, idx, loops, handlers)
        return frontier

    def _stmt(self, stmt: ast.AST, idx: int, loops: List[_Loop],
              handlers: List[int]) -> Set[int]:
        if isinstance(stmt, (ast.Return, ast.Raise)):
            return set()
        if isinstance(stmt, ast.Break):
            if loops:
                loops[-1].breaks.add(idx)
            return set()
        if isinstance(stmt, ast.Continue):
            if loops:
                self.succ[idx].add(loops[-1].header)
            return set()
        if isinstance(stmt, ast.If):
            f_then = self._seq(stmt.body, {idx}, loops, handlers)
            f_else = (self._seq(stmt.orelse, {idx}, loops, handlers)
                      if stmt.orelse else {idx})
            return f_then | f_else
        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            loop = _Loop(header=idx)
            f_body = self._seq(stmt.body, {idx}, loops + [loop], handlers)
            for f in f_body:
                self.succ[f].add(idx)  # back edge — fixpoint converges it
            f_exit = (self._seq(stmt.orelse, {idx}, loops, handlers)
                      if stmt.orelse else {idx})
            return f_exit | loop.breaks
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            return self._seq(stmt.body, {idx}, loops, handlers)
        if isinstance(stmt, ast.Try) \
                or stmt.__class__.__name__ == "TryStar":
            region_lo = len(self.stmts)
            h_idx = [self._add(h) for h in stmt.handlers]
            for h in h_idx:
                self.succ[idx].add(h)
            f_body = self._seq(stmt.body, {idx}, loops, handlers + h_idx)
            f_handlers: Set[int] = set()
            for h, hi in zip(stmt.handlers, h_idx):
                f_handlers |= self._seq(h.body, {hi}, loops, handlers)
            f_else = (self._seq(stmt.orelse, f_body, loops, handlers)
                      if stmt.orelse else f_body)
            after = f_else | f_handlers
            if stmt.finalbody:
                # the finally runs whether or not the protected region
                # completed: feed it the Try head (pre-body state, for an
                # exception before the first assignment lands) and every
                # statement lowered in the region (mid-region exceptions),
                # not just the normal-completion frontier
                region = set(range(region_lo, len(self.stmts)))
                after = self._seq(stmt.finalbody, after | {idx} | region,
                                  loops, handlers)
            return after
        return {idx}


# -- per-function flow -------------------------------------------------------

class FunctionFlow:
    """Alias-set + reaching-definition facts for one function, queryable at
    any AST node inside it."""

    def __init__(self, fn: FunctionNode):
        with _timed():
            self.fn = fn
            if isinstance(fn, ast.Lambda):
                body: List[ast.stmt] = [ast.Expr(value=fn.body)]
            else:
                body = list(fn.body)
            self._cfg = _CFG(body)
            self._in: List[AliasState] = []
            self._fixpoint()
            # any node -> index of its (innermost) CFG statement.  Nodes are
            # visited in CFG order; inner statements were added after their
            # enclosing compound, so later writes win = innermost wins.
            self._stmt_of: Dict[int, int] = {}
            for i, s in enumerate(self._cfg.stmts):
                for sub in ast.walk(s):
                    self._stmt_of[id(sub)] = i

    def _entry_state(self) -> AliasState:
        a = getattr(self.fn, "args", None)
        state: AliasState = {}
        if a is None:
            return state
        line = getattr(self.fn, "lineno", 0)
        params = list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)
        if a.vararg:
            params.append(a.vararg)
        if a.kwarg:
            params.append(a.kwarg)
        for p in params:
            state[p.arg] = (_EMPTY, frozenset((line,)))
        return state

    def _fixpoint(self) -> None:
        cfg = self._cfg
        n = len(cfg.stmts)
        preds: List[List[int]] = [[] for _ in range(n)]
        for i, succs in enumerate(cfg.succ):
            for j in succs:
                preds[j].append(i)
        entry = self._entry_state()
        self._in = [{} for _ in range(n)]
        out: List[Optional[AliasState]] = [None] * n
        work: List[int] = list(range(n))
        guard = 0
        while work:
            guard += 1
            if guard > 50 * (n + 1):  # safety valve; cannot trip for
                break                 # monotone transfer, kept for hygiene
            i = work.pop(0)
            incoming = [out[p] for p in preds[i] if out[p] is not None]
            state = _join(incoming) if incoming else {}
            if not preds[i]:
                state = dict(entry)
            self._in[i] = state
            new_out = _transfer(state, cfg.stmts[i])
            if new_out != out[i]:
                out[i] = new_out
                for j in sorted(cfg.succ[i]):
                    if j not in work:
                        work.append(j)

    # -- queries -------------------------------------------------------------
    def state_at(self, node: ast.AST) -> AliasState:
        """Abstract state just BEFORE the statement enclosing ``node``
        ({} when the node is not inside this function)."""
        idx = self._stmt_of.get(id(node))
        return self._in[idx] if idx is not None else {}

    def attr_aliases(self, name: str, at: ast.AST) -> FrozenSet[str]:
        """``self.<attr>`` objects the local ``name`` may alias at ``at``."""
        return self.state_at(at).get(name, (_EMPTY, _EMPTY))[0]

    def reaching_defs(self, name: str, at: ast.AST) -> FrozenSet[int]:
        """Line numbers of the definitions of ``name`` reaching ``at``."""
        return self.state_at(at).get(name, (_EMPTY, _EMPTY))[1]


# -- module call graph -------------------------------------------------------

# loop.<scheduler>(callback, ...) — positional index of the callback
_LOOP_SCHEDULERS: Dict[str, int] = {
    "call_soon": 0, "call_soon_threadsafe": 0, "call_later": 1, "call_at": 1,
}
_LOCKISH = ("lock", "cond", "mutex")


def lexical_calls(fn: FunctionNode) -> Iterator[ast.Call]:
    """Call nodes in ``fn``'s own body, excluding nested function/lambda
    bodies (their execution context is their own)."""
    body = fn.body if isinstance(fn.body, list) else [fn.body]
    stack: List[ast.AST] = list(body)
    while stack:
        node = stack.pop()
        if isinstance(node, ast.Call):
            yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def loop_callback_exprs(tree: ast.AST) -> Iterator[ast.expr]:
    """Callback argument expressions of every event-loop scheduling call
    (``call_soon``/``call_soon_threadsafe``/``call_later``/``call_at``) —
    these callbacks RUN ON the loop, so they seed event-loop reachability."""
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)):
            continue
        pos = _LOOP_SCHEDULERS.get(node.func.attr)
        if pos is not None and len(node.args) > pos:
            yield node.args[pos]


def resolve_local_callee(func: ast.AST, defs: Dict[str, FunctionNode],
                         defs_by_name: Dict[str, List[FunctionNode]]
                         ) -> Optional[FunctionNode]:
    """Module-local callee resolution: ``Name`` -> module-level def (unique
    by-name fallback for nested/method helpers), ``self.attr`` -> unique
    method by name.  Mirrors ``ProgramIndex._resolve_callee``."""
    if isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
        return func
    if isinstance(func, ast.Name):
        fn = defs.get(func.id)
        if fn is not None:
            return fn
        cands = defs_by_name.get(func.id)
        if cands is not None and len(cands) == 1:
            return cands[0]
        return None
    if isinstance(func, ast.Attribute) and isinstance(func.value, ast.Name) \
            and func.value.id == "self":
        cands = defs_by_name.get(func.attr)
        if cands is not None and len(cands) == 1:
            return cands[0]
    return None


def _lockish_context(item: ast.withitem) -> bool:
    """Does a ``with`` item look like taking a lock (``self._lock`` /
    ``self.cv`` / a name bound to one — name-based heuristic)?"""
    expr = item.context_expr
    name = dotted_name(expr) or ""
    leaf = name.rpartition(".")[2].lower()
    return any(k in leaf for k in _LOCKISH)


class ModuleCallGraph:
    """Module-local call graph with seeded reachability queries."""

    def __init__(self, tree: Optional[ast.Module]):
        with _timed():
            self.tree = tree
            self.defs: Dict[str, FunctionNode] = {}
            self.defs_by_name: Dict[str, List[FunctionNode]] = {}
            self.fns: List[FunctionNode] = []
            self._edges: Dict[int, List[FunctionNode]] = {}
            if tree is None:
                return
            for node in ast.walk(tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self.fns.append(node)
                    self.defs_by_name.setdefault(node.name, []).append(node)
            for stmt in tree.body:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self.defs[stmt.name] = stmt

    def resolve(self, func: ast.AST) -> Optional[FunctionNode]:
        return resolve_local_callee(func, self.defs, self.defs_by_name)

    def callees(self, fn: FunctionNode) -> List[FunctionNode]:
        got = self._edges.get(id(fn))
        if got is None:
            got = []
            for call in lexical_calls(fn):
                target = self.resolve(call.func)
                if target is not None:
                    got.append(target)
            self._edges[id(fn)] = got
        return got

    def reachable(self, seeds: Iterable[FunctionNode]) -> Set[int]:
        """ids of every function reachable from ``seeds`` through module-
        local calls (seeds included)."""
        with _timed():
            out: Set[int] = set()
            stack: List[FunctionNode] = []
            for fn in seeds:
                if id(fn) not in out:
                    out.add(id(fn))
                    stack.append(fn)
            while stack:
                fn = stack.pop()
                for callee in self.callees(fn):
                    if id(callee) not in out:
                        out.add(id(callee))
                        stack.append(callee)
            return out

    def event_loop_fns(self) -> Set[int]:
        """ids of functions that run on the asyncio event loop: every
        ``async def``, every scheduled loop callback, and everything they
        transitively CALL.  Hand-offs (``run_in_executor``/``to_thread``/
        ``Thread(target=...)``) pass function references, not calls, so
        they do not propagate — the exemption the rules rely on."""
        if self.tree is None:
            return set()
        seeds: List[FunctionNode] = [fn for fn in self.fns
                                     if isinstance(fn, ast.AsyncFunctionDef)]
        for cb in loop_callback_exprs(self.tree):
            if isinstance(cb, ast.Lambda):
                seeds.append(cb)
                continue
            target = self.resolve(cb)
            if target is not None:
                seeds.append(target)
        return self.reachable(seeds)

    def lock_held_fns(self) -> Set[int]:
        """ids of functions invoked (transitively) from inside a
        ``with self.<lock>:`` region."""
        if self.tree is None:
            return set()
        seeds: List[FunctionNode] = []
        for node in ast.walk(self.tree):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            if not any(_lockish_context(i) for i in node.items):
                continue
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    target = self.resolve(sub.func)
                    if target is not None:
                        seeds.append(target)
        return self.reachable(seeds)


# -- module facade -----------------------------------------------------------

class ModuleDataflow:
    """Lazy per-module dataflow facade exposed as ``ctx.dataflow``: cached
    per-function flows, the module call graph, and reachability sets."""

    def __init__(self, ctx):
        self.ctx = ctx
        self._flows: Dict[int, FunctionFlow] = {}
        self._graph: Optional[ModuleCallGraph] = None
        self._traced_ids: Optional[Set[int]] = None
        self._loop_fns: Optional[Set[int]] = None
        self._lock_fns: Optional[Set[int]] = None

    def function_flow(self, fn: FunctionNode) -> FunctionFlow:
        flow = self._flows.get(id(fn))
        if flow is None:
            flow = FunctionFlow(fn)
            self._flows[id(fn)] = flow
        return flow

    @property
    def call_graph(self) -> ModuleCallGraph:
        if self._graph is None:
            self._graph = ModuleCallGraph(self.ctx.tree)
        return self._graph

    def traced_node_ids(self) -> Set[int]:
        """ids of every AST node that executes under a jit trace (per the
        program-augmented JitIndex) — "reachable from a jit root"."""
        if self._traced_ids is None:
            from photon_ml_tpu.analysis.jit_index import walk_jit_code
            with _timed():
                ids = {id(node) for node, _
                       in walk_jit_code(self.ctx.jit_index)}
                # a helper CALLED from traced code executes under the same
                # trace even though the JitIndex only walks root bodies —
                # close over the module call graph from the jit roots
                graph = self.call_graph
                reach = graph.reachable(
                    fn for fn, _ in self.ctx.jit_index.roots)
                for fn in graph.fns:
                    if id(fn) in reach:
                        for sub in ast.walk(fn):
                            ids.add(id(sub))
                self._traced_ids = ids
        return self._traced_ids

    def event_loop_fns(self) -> Set[int]:
        """ids of functions on the event loop — module-local seeds plus, in
        whole-program mode, functions proven reachable from another
        module's async code by the ProgramIndex."""
        if self._loop_fns is None:
            fns = set(self.call_graph.event_loop_fns())
            program = getattr(self.ctx, "program", None)
            if program is not None:
                fns |= {id(fn) for fn
                        in program.async_reachable_in(self.ctx.relpath)}
            self._loop_fns = fns
        return self._loop_fns

    def lock_held_fns(self) -> Set[int]:
        if self._lock_fns is None:
            self._lock_fns = self.call_graph.lock_held_fns()
        return self._lock_fns
