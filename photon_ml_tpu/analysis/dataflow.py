"""Per-function forward dataflow + call-graph reachability for photonlint.

The v1/v2 rules are lexical: PL005 only sees mutations spelled ``self.X``,
PL007 only sees collectives lexically inside a shard_map target, and nothing
at all connects an ``async def`` body to the synchronous helpers it calls.
The concurrency and distributed-protocol rules (PL011–PL014, PL005v2) need
two things the lexical passes cannot answer:

  1. **"what does this name alias here?"** — a per-function forward dataflow
     over a CFG lowered from the AST (branches, loops run to convergence,
     try/except/finally with per-statement exception edges).  The abstract
     state maps each local name to (a) the set of ``self.<attr>`` objects it
     may alias and (b) the line numbers of the reaching definitions.
     ``a = self._store; b = a`` makes both ``a`` and ``b`` aliases of
     ``_store``; ``self._x = buf`` makes ``buf`` an alias of ``_x``; any
     other assignment kills.  Joins are set unions, so the analysis is
     monotone and the loop fixpoint terminates.

  2. **"is this call reachable from an async body / a jit root / a
     lock-held region?"** — a module-local call graph (``Name`` → module
     def, unique by-name fallback; ``self.method`` → unique method, the
     same convention ``ProgramIndex._resolve_callee`` uses) with seeded
     reachability: event-loop seeds are every ``async def`` plus the
     callback targets of ``loop.call_soon[_threadsafe]/call_later/call_at``;
     lock seeds are the callees invoked inside ``with self.<lock>:`` blocks;
     jit reachability reuses the (program-augmented) ``JitIndex`` walk.
     Propagation follows only real ``Call`` nodes — a function REFERENCE
     handed to ``run_in_executor``/``to_thread``/``Thread(target=...)`` is
     not a call, so executor hand-offs are exempt by construction.

Everything here is best-effort and conservative in the same direction as
the rest of the analysis stack: unresolvable facts contribute nothing, so
dataflow can only ADD precision, never invent phantom findings.  The time
spent in this module is accounted separately (``reset_cost``/
``cost_seconds``) so ``bench.py --lint`` can report the dataflow pass cost
next to the ProgramIndex build.
"""

from __future__ import annotations

import ast
import dataclasses
import time
from typing import (Dict, FrozenSet, Iterable, Iterator, List, Optional,
                    Sequence, Set, Tuple)

from photon_ml_tpu.analysis.jit_index import FunctionNode, dotted_name

# -- cost accounting ---------------------------------------------------------

_COST = {"s": 0.0, "summary_s": 0.0, "summary_cached": 0}


def reset_cost() -> None:
    _COST["s"] = 0.0
    _COST["summary_s"] = 0.0
    _COST["summary_cached"] = 0


def cost_seconds() -> float:
    return _COST["s"]


def summary_seconds() -> float:
    """Time spent computing interprocedural function summaries (v4),
    reported as ``summaries_s`` next to ``dataflow_s``."""
    return _COST["summary_s"]


def summaries_cached_count() -> int:
    """Modules whose summary pass was skipped this run because the
    digest-keyed cache held them (``summaries_cached`` in BENCH_LINT)."""
    return int(_COST["summary_cached"])


class _timed:
    """Context manager accumulating wall time into the dataflow cost."""

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        _COST["s"] += time.perf_counter() - self._t0
        return False


class _timed_summary:
    """Accumulate wall time into the SUMMARY cost.  FunctionFlow fixpoints
    built while summarising self-report into the dataflow cost; their share
    is subtracted here so ``dataflow_s`` and ``summaries_s`` never double-
    count the same second."""

    def __enter__(self):
        self._t0 = time.perf_counter()
        self._d0 = _COST["s"]
        return self

    def __exit__(self, *exc):
        spent = time.perf_counter() - self._t0
        nested = _COST["s"] - self._d0
        _COST["summary_s"] += max(spent - nested, 0.0)
        return False


# -- abstract state ----------------------------------------------------------
# name -> (frozenset of aliased self-attrs, frozenset of reaching-def lines)
VarFact = Tuple[FrozenSet[str], FrozenSet[int]]
AliasState = Dict[str, VarFact]

_EMPTY: FrozenSet = frozenset()


def _self_attr(node: ast.AST) -> Optional[str]:
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name) and node.value.id == "self"):
        return node.attr
    return None


def _value_aliases(state: AliasState, expr: ast.AST,
                   depth: int = 0) -> FrozenSet[str]:
    """Self-attrs the VALUE expression may alias under ``state``."""
    if depth > 6 or expr is None:
        return _EMPTY
    if isinstance(expr, ast.Name):
        return state.get(expr.id, (_EMPTY, _EMPTY))[0]
    attr = _self_attr(expr)
    if attr is not None:
        return frozenset((attr,))
    if isinstance(expr, ast.IfExp):
        return (_value_aliases(state, expr.body, depth + 1)
                | _value_aliases(state, expr.orelse, depth + 1))
    if isinstance(expr, ast.NamedExpr):
        return _value_aliases(state, expr.value, depth + 1)
    return _EMPTY


def _kill_target(new: AliasState, tgt: ast.AST, line: int) -> None:
    for sub in ast.walk(tgt):
        if isinstance(sub, ast.Name):
            new[sub.id] = (_EMPTY, frozenset((line,)))


def _apply_assign(new: AliasState, old: AliasState, tgt: ast.AST,
                  value: Optional[ast.AST], line: int) -> None:
    if isinstance(tgt, ast.Name):
        aliases = _value_aliases(old, value) if value is not None else _EMPTY
        new[tgt.id] = (aliases, frozenset((line,)))
    elif isinstance(tgt, (ast.Tuple, ast.List)):
        elts = tgt.elts
        if (isinstance(value, (ast.Tuple, ast.List))
                and len(value.elts) == len(elts)
                and not any(isinstance(e, ast.Starred) for e in elts)):
            for t, v in zip(elts, value.elts):
                _apply_assign(new, old, t, v, line)
        else:
            _kill_target(new, tgt, line)
    elif isinstance(tgt, ast.Starred):
        _kill_target(new, tgt.value, line)
    else:
        # attribute/subscript target: binds no local — but `self.X = name`
        # makes `name` an alias of X from here on (the object is shared)
        attr = _self_attr(tgt)
        if attr is not None and isinstance(value, ast.Name):
            aliases, defs = new.get(value.id, (_EMPTY, _EMPTY))
            new[value.id] = (aliases | {attr}, defs)


def _header_exprs(stmt: ast.AST) -> List[ast.AST]:
    """Expressions evaluated AT a CFG node for a compound statement (its
    body statements are separate CFG nodes)."""
    if isinstance(stmt, (ast.If, ast.While)):
        return [stmt.test]
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        return [stmt.iter]
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        return [item.context_expr for item in stmt.items]
    if isinstance(stmt, ast.Try) or stmt.__class__.__name__ == "TryStar":
        return []
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
                         ast.ExceptHandler)):
        return []
    return [stmt]  # simple statement: whole subtree


def _transfer(state: AliasState, stmt: ast.AST) -> AliasState:
    new = dict(state)
    line = getattr(stmt, "lineno", 0)
    if isinstance(stmt, ast.Assign):
        for tgt in stmt.targets:
            _apply_assign(new, state, tgt, stmt.value, line)
    elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
        _apply_assign(new, state, stmt.target, stmt.value, line)
    elif isinstance(stmt, ast.AugAssign):
        if isinstance(stmt.target, ast.Name):
            # x += v rebinds x for immutables; conservatively drop aliases
            _kill_target(new, stmt.target, line)
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        _kill_target(new, stmt.target, line)
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        for item in stmt.items:
            if item.optional_vars is not None:
                # `with self._lock as l:` — l aliases the context object
                _apply_assign(new, state, item.optional_vars,
                              item.context_expr, line)
    elif isinstance(stmt, ast.ExceptHandler):
        if stmt.name:
            new[stmt.name] = (_EMPTY, frozenset((line,)))
    elif isinstance(stmt, (ast.Import, ast.ImportFrom)):
        for alias in stmt.names:
            bound = alias.asname or alias.name.split(".")[0]
            new[bound] = (_EMPTY, frozenset((line,)))
    elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
        new[stmt.name] = (_EMPTY, frozenset((line,)))
    elif isinstance(stmt, ast.Delete):
        for tgt in stmt.targets:
            if isinstance(tgt, ast.Name):
                new[tgt.id] = (_EMPTY, frozenset((line,)))
    # walrus bindings in the expressions this node evaluates
    for expr in _header_exprs(stmt):
        for sub in ast.walk(expr):
            if isinstance(sub, ast.NamedExpr) \
                    and isinstance(sub.target, ast.Name):
                new[sub.target.id] = (_value_aliases(state, sub.value),
                                      frozenset((getattr(sub, "lineno",
                                                         line),)))
    return new


def _join(states: Iterable[AliasState]) -> AliasState:
    out: AliasState = {}
    for st in states:
        for name, (aliases, defs) in st.items():
            if name in out:
                a0, d0 = out[name]
                out[name] = (a0 | aliases, d0 | defs)
            else:
                out[name] = (aliases, defs)
    return out


# -- CFG ---------------------------------------------------------------------

class _Loop:
    __slots__ = ("header", "breaks")

    def __init__(self, header: int):
        self.header = header
        self.breaks: Set[int] = set()


class _CFG:
    """Statement-level control-flow graph of one function body.  Each
    statement (and each ``except`` handler head) is one node; edges follow
    branch/loop/try structure, with per-statement exception edges from try
    bodies to their handlers."""

    def __init__(self, body: Sequence[ast.stmt]):
        self.stmts: List[ast.AST] = []
        self.succ: List[Set[int]] = []
        self._seq(body, frontier=set(), loops=[], handlers=[])

    def _add(self, stmt: ast.AST) -> int:
        self.stmts.append(stmt)
        self.succ.append(set())
        return len(self.stmts) - 1

    def _seq(self, body: Sequence[ast.stmt], frontier: Set[int],
             loops: List[_Loop], handlers: List[int]) -> Set[int]:
        for stmt in body:
            idx = self._add(stmt)
            for f in frontier:
                self.succ[f].add(idx)
            for h in handlers:
                self.succ[idx].add(h)  # an exception may fire mid-statement
            frontier = self._stmt(stmt, idx, loops, handlers)
        return frontier

    def _stmt(self, stmt: ast.AST, idx: int, loops: List[_Loop],
              handlers: List[int]) -> Set[int]:
        if isinstance(stmt, (ast.Return, ast.Raise)):
            return set()
        if isinstance(stmt, ast.Break):
            if loops:
                loops[-1].breaks.add(idx)
            return set()
        if isinstance(stmt, ast.Continue):
            if loops:
                self.succ[idx].add(loops[-1].header)
            return set()
        if isinstance(stmt, ast.If):
            f_then = self._seq(stmt.body, {idx}, loops, handlers)
            f_else = (self._seq(stmt.orelse, {idx}, loops, handlers)
                      if stmt.orelse else {idx})
            return f_then | f_else
        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            loop = _Loop(header=idx)
            f_body = self._seq(stmt.body, {idx}, loops + [loop], handlers)
            for f in f_body:
                self.succ[f].add(idx)  # back edge — fixpoint converges it
            f_exit = (self._seq(stmt.orelse, {idx}, loops, handlers)
                      if stmt.orelse else {idx})
            return f_exit | loop.breaks
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            return self._seq(stmt.body, {idx}, loops, handlers)
        if isinstance(stmt, ast.Try) \
                or stmt.__class__.__name__ == "TryStar":
            region_lo = len(self.stmts)
            h_idx = [self._add(h) for h in stmt.handlers]
            for h in h_idx:
                self.succ[idx].add(h)
            f_body = self._seq(stmt.body, {idx}, loops, handlers + h_idx)
            f_handlers: Set[int] = set()
            for h, hi in zip(stmt.handlers, h_idx):
                f_handlers |= self._seq(h.body, {hi}, loops, handlers)
            f_else = (self._seq(stmt.orelse, f_body, loops, handlers)
                      if stmt.orelse else f_body)
            after = f_else | f_handlers
            if stmt.finalbody:
                # the finally runs whether or not the protected region
                # completed: feed it the Try head (pre-body state, for an
                # exception before the first assignment lands) and every
                # statement lowered in the region (mid-region exceptions),
                # not just the normal-completion frontier
                region = set(range(region_lo, len(self.stmts)))
                after = self._seq(stmt.finalbody, after | {idx} | region,
                                  loops, handlers)
            return after
        return {idx}


# -- per-function flow -------------------------------------------------------

class FunctionFlow:
    """Alias-set + reaching-definition facts for one function, queryable at
    any AST node inside it."""

    def __init__(self, fn: FunctionNode):
        with _timed():
            self.fn = fn
            if isinstance(fn, ast.Lambda):
                body: List[ast.stmt] = [ast.Expr(value=fn.body)]
            else:
                body = list(fn.body)
            self._cfg = _CFG(body)
            self._in: List[AliasState] = []
            self._fixpoint()
            # any node -> index of its (innermost) CFG statement.  Nodes are
            # visited in CFG order; inner statements were added after their
            # enclosing compound, so later writes win = innermost wins.
            self._stmt_of: Dict[int, int] = {}
            for i, s in enumerate(self._cfg.stmts):
                for sub in ast.walk(s):
                    self._stmt_of[id(sub)] = i

    def _entry_state(self) -> AliasState:
        a = getattr(self.fn, "args", None)
        state: AliasState = {}
        if a is None:
            return state
        line = getattr(self.fn, "lineno", 0)
        params = list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)
        if a.vararg:
            params.append(a.vararg)
        if a.kwarg:
            params.append(a.kwarg)
        for p in params:
            state[p.arg] = (_EMPTY, frozenset((line,)))
        return state

    def _fixpoint(self) -> None:
        cfg = self._cfg
        n = len(cfg.stmts)
        preds: List[List[int]] = [[] for _ in range(n)]
        for i, succs in enumerate(cfg.succ):
            for j in succs:
                preds[j].append(i)
        entry = self._entry_state()
        self._in = [{} for _ in range(n)]
        out: List[Optional[AliasState]] = [None] * n
        work: List[int] = list(range(n))
        guard = 0
        while work:
            guard += 1
            if guard > 50 * (n + 1):  # safety valve; cannot trip for
                break                 # monotone transfer, kept for hygiene
            i = work.pop(0)
            incoming = [out[p] for p in preds[i] if out[p] is not None]
            state = _join(incoming) if incoming else {}
            if not preds[i]:
                state = dict(entry)
            self._in[i] = state
            new_out = _transfer(state, cfg.stmts[i])
            if new_out != out[i]:
                out[i] = new_out
                for j in sorted(cfg.succ[i]):
                    if j not in work:
                        work.append(j)

    # -- queries -------------------------------------------------------------
    def state_at(self, node: ast.AST) -> AliasState:
        """Abstract state just BEFORE the statement enclosing ``node``
        ({} when the node is not inside this function)."""
        idx = self._stmt_of.get(id(node))
        return self._in[idx] if idx is not None else {}

    def attr_aliases(self, name: str, at: ast.AST) -> FrozenSet[str]:
        """``self.<attr>`` objects the local ``name`` may alias at ``at``."""
        return self.state_at(at).get(name, (_EMPTY, _EMPTY))[0]

    def reaching_defs(self, name: str, at: ast.AST) -> FrozenSet[int]:
        """Line numbers of the definitions of ``name`` reaching ``at``."""
        return self.state_at(at).get(name, (_EMPTY, _EMPTY))[1]


# -- module call graph -------------------------------------------------------

# loop.<scheduler>(callback, ...) — positional index of the callback
_LOOP_SCHEDULERS: Dict[str, int] = {
    "call_soon": 0, "call_soon_threadsafe": 0, "call_later": 1, "call_at": 1,
}
_LOCKISH = ("lock", "cond", "mutex")


def lexical_calls(fn: FunctionNode) -> Iterator[ast.Call]:
    """Call nodes in ``fn``'s own body, excluding nested function/lambda
    bodies (their execution context is their own)."""
    body = fn.body if isinstance(fn.body, list) else [fn.body]
    stack: List[ast.AST] = list(body)
    while stack:
        node = stack.pop()
        if isinstance(node, ast.Call):
            yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def loop_callback_exprs(tree: ast.AST) -> Iterator[ast.expr]:
    """Callback argument expressions of every event-loop scheduling call
    (``call_soon``/``call_soon_threadsafe``/``call_later``/``call_at``) —
    these callbacks RUN ON the loop, so they seed event-loop reachability."""
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)):
            continue
        pos = _LOOP_SCHEDULERS.get(node.func.attr)
        if pos is not None and len(node.args) > pos:
            yield node.args[pos]


def resolve_local_callee(func: ast.AST, defs: Dict[str, FunctionNode],
                         defs_by_name: Dict[str, List[FunctionNode]]
                         ) -> Optional[FunctionNode]:
    """Module-local callee resolution: ``Name`` -> module-level def (unique
    by-name fallback for nested/method helpers), ``self.attr`` -> unique
    method by name.  Mirrors ``ProgramIndex._resolve_callee``."""
    if isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
        return func
    if isinstance(func, ast.Name):
        fn = defs.get(func.id)
        if fn is not None:
            return fn
        cands = defs_by_name.get(func.id)
        if cands is not None and len(cands) == 1:
            return cands[0]
        return None
    if isinstance(func, ast.Attribute) and isinstance(func.value, ast.Name) \
            and func.value.id == "self":
        cands = defs_by_name.get(func.attr)
        if cands is not None and len(cands) == 1:
            return cands[0]
    return None


def _lockish_context(item: ast.withitem) -> bool:
    """Does a ``with`` item look like taking a lock (``self._lock`` /
    ``self.cv`` / a name bound to one — name-based heuristic)?"""
    expr = item.context_expr
    name = dotted_name(expr) or ""
    leaf = name.rpartition(".")[2].lower()
    return any(k in leaf for k in _LOCKISH)


class ModuleCallGraph:
    """Module-local call graph with seeded reachability queries."""

    def __init__(self, tree: Optional[ast.Module]):
        with _timed():
            self.tree = tree
            self.defs: Dict[str, FunctionNode] = {}
            self.defs_by_name: Dict[str, List[FunctionNode]] = {}
            self.fns: List[FunctionNode] = []
            self._edges: Dict[int, List[FunctionNode]] = {}
            if tree is None:
                return
            for node in ast.walk(tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self.fns.append(node)
                    self.defs_by_name.setdefault(node.name, []).append(node)
            for stmt in tree.body:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self.defs[stmt.name] = stmt

    def resolve(self, func: ast.AST) -> Optional[FunctionNode]:
        return resolve_local_callee(func, self.defs, self.defs_by_name)

    def callees(self, fn: FunctionNode) -> List[FunctionNode]:
        got = self._edges.get(id(fn))
        if got is None:
            got = []
            for call in lexical_calls(fn):
                target = self.resolve(call.func)
                if target is not None:
                    got.append(target)
            self._edges[id(fn)] = got
        return got

    def reachable(self, seeds: Iterable[FunctionNode]) -> Set[int]:
        """ids of every function reachable from ``seeds`` through module-
        local calls (seeds included)."""
        with _timed():
            out: Set[int] = set()
            stack: List[FunctionNode] = []
            for fn in seeds:
                if id(fn) not in out:
                    out.add(id(fn))
                    stack.append(fn)
            while stack:
                fn = stack.pop()
                for callee in self.callees(fn):
                    if id(callee) not in out:
                        out.add(id(callee))
                        stack.append(callee)
            return out

    def event_loop_fns(self) -> Set[int]:
        """ids of functions that run on the asyncio event loop: every
        ``async def``, every scheduled loop callback, and everything they
        transitively CALL.  Hand-offs (``run_in_executor``/``to_thread``/
        ``Thread(target=...)``) pass function references, not calls, so
        they do not propagate — the exemption the rules rely on."""
        if self.tree is None:
            return set()
        seeds: List[FunctionNode] = [fn for fn in self.fns
                                     if isinstance(fn, ast.AsyncFunctionDef)]
        for cb in loop_callback_exprs(self.tree):
            if isinstance(cb, ast.Lambda):
                seeds.append(cb)
                continue
            target = self.resolve(cb)
            if target is not None:
                seeds.append(target)
        return self.reachable(seeds)

    def lock_held_fns(self) -> Set[int]:
        """ids of functions invoked (transitively) from inside a
        ``with self.<lock>:`` region."""
        if self.tree is None:
            return set()
        seeds: List[FunctionNode] = []
        for node in ast.walk(self.tree):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            if not any(_lockish_context(i) for i in node.items):
                continue
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    target = self.resolve(sub.func)
                    if target is not None:
                        seeds.append(target)
        return self.reachable(seeds)


# -- module facade -----------------------------------------------------------

class ModuleDataflow:
    """Lazy per-module dataflow facade exposed as ``ctx.dataflow``: cached
    per-function flows, the module call graph, and reachability sets."""

    def __init__(self, ctx):
        self.ctx = ctx
        self._flows: Dict[int, FunctionFlow] = {}
        self._graph: Optional[ModuleCallGraph] = None
        self._traced_ids: Optional[Set[int]] = None
        self._loop_fns: Optional[Set[int]] = None
        self._lock_fns: Optional[Set[int]] = None

    def function_flow(self, fn: FunctionNode) -> FunctionFlow:
        flow = self._flows.get(id(fn))
        if flow is None:
            flow = FunctionFlow(fn)
            self._flows[id(fn)] = flow
        return flow

    @property
    def call_graph(self) -> ModuleCallGraph:
        if self._graph is None:
            self._graph = ModuleCallGraph(self.ctx.tree)
        return self._graph

    def traced_node_ids(self) -> Set[int]:
        """ids of every AST node that executes under a jit trace (per the
        program-augmented JitIndex) — "reachable from a jit root"."""
        if self._traced_ids is None:
            from photon_ml_tpu.analysis.jit_index import walk_jit_code
            with _timed():
                ids = {id(node) for node, _
                       in walk_jit_code(self.ctx.jit_index)}
                # a helper CALLED from traced code executes under the same
                # trace even though the JitIndex only walks root bodies —
                # close over the module call graph from the jit roots
                graph = self.call_graph
                reach = graph.reachable(
                    fn for fn, _ in self.ctx.jit_index.roots)
                for fn in graph.fns:
                    if id(fn) in reach:
                        for sub in ast.walk(fn):
                            ids.add(id(sub))
                self._traced_ids = ids
        return self._traced_ids

    def event_loop_fns(self) -> Set[int]:
        """ids of functions on the event loop — module-local seeds plus, in
        whole-program mode, functions proven reachable from another
        module's async code by the ProgramIndex."""
        if self._loop_fns is None:
            fns = set(self.call_graph.event_loop_fns())
            program = getattr(self.ctx, "program", None)
            if program is not None:
                fns |= {id(fn) for fn
                        in program.async_reachable_in(self.ctx.relpath)}
            self._loop_fns = fns
        return self._loop_fns

    def lock_held_fns(self) -> Set[int]:
        if self._lock_fns is None:
            self._lock_fns = self.call_graph.lock_held_fns()
        return self._lock_fns


# -- interprocedural summaries (v4) ------------------------------------------
#
# Per-function facts cheap enough to compute once per module and join to a
# program-wide fixpoint through ProgramIndex's call graph (see
# program_index.ProgramSummaries):
#
#   * which lock-protected ``self.<attr>`` objects a return value may alias
#     (``t = self._table; return t`` — through the FunctionFlow alias state),
#   * the definite array rank of the return value where it can be inferred
#     syntactically (shape literals, full reductions, reshape, ...),
#   * which locks the function acquires, in what nesting order, and which
#     calls it makes while holding one.
#
# Lock identity is CLASS-level (``relpath::Class.attr``) — the classic
# static approximation that conflates instances; conservative for the
# deadlock rule because a real per-instance order inversion is a subset of
# the class-level one, and self-edges are excluded to avoid the reentrant /
# multi-instance false positives the approximation would otherwise invent.

LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore",
                  "BoundedSemaphore"}
MUTATOR_METHODS = {
    "append", "extend", "insert", "add", "update", "setdefault", "pop",
    "popitem", "remove", "discard", "clear", "move_to_end", "appendleft",
    "popleft", "sort", "reverse",
}


def chain_root_attr(expr: ast.AST) -> Optional[str]:
    """Innermost self-attr of an attribute/subscript chain:
    ``self._hot.table[k]`` -> ``"_hot"`` (None when not rooted at self)."""
    node: ast.AST = expr
    first: Optional[str] = None
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        if isinstance(node, ast.Attribute):
            first = node.attr
        node = node.value
    if isinstance(node, ast.Name) and node.id == "self" and first is not None:
        return first
    return None


def attr_chain_root(expr: ast.AST) -> Optional[str]:
    """Like :func:`chain_root_attr` but ATTRIBUTE links only: a subscript
    (``self._base[0]``) reads an *element*, a different object from the
    protected container, so it does not alias the root for escape
    purposes (mutation targets keep the subscript-including walk)."""
    node: ast.AST = expr
    first: Optional[str] = None
    while isinstance(node, ast.Attribute):
        first = node.attr
        node = node.value
    if isinstance(node, ast.Name) and node.id == "self" and first is not None:
        return first
    return None


def class_lock_info(cls: ast.ClassDef
                    ) -> Tuple[Set[str], Dict[str, str], Dict[str, str]]:
    """(lock attr names, canonical map wrapper->base lock for
    ``self._cond = threading.Condition(self._lock)``, factory name by
    canonical attr).  Superset of rules.locks._lock_names: also records
    WHICH factory built each lock so reentrant RLocks can be told apart.
    Memoized on the node — the summary layer and the lock rule both ask
    for the same class."""
    cached = getattr(cls, "_pl_lock_info", None)
    if cached is not None:
        return cached
    names: Set[str] = set()
    canon: Dict[str, str] = {}
    factory_of: Dict[str, str] = {}
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign):
            value_fn = (dotted_name(node.value.func)
                        if isinstance(node.value, ast.Call) else None)
            factory = (value_fn or "").rpartition(".")[2]
            if factory in LOCK_FACTORIES:
                for tgt in node.targets:
                    attr = _self_attr(tgt)
                    if attr is None:
                        continue
                    names.add(attr)
                    factory_of.setdefault(attr, factory)
                    if factory == "Condition" and node.value.args:
                        base = _self_attr(node.value.args[0])
                        if base is not None:
                            canon[attr] = base
        elif isinstance(node, ast.With):
            for item in node.items:
                attr = _self_attr(item.context_expr)
                if attr is not None and _lockish_context(item):
                    names.add(attr)
    # resolve wrapper chains (Condition(wraps) of Condition(wraps) ...)
    def resolve(a: str, depth: int = 0) -> str:
        nxt = canon.get(a)
        return a if nxt is None or depth > 4 else resolve(nxt, depth + 1)
    canon = {a: resolve(a) for a in names}
    cls._pl_lock_info = (names, canon, factory_of)
    return cls._pl_lock_info


def class_locked_attrs(cls: ast.ClassDef, lock_attrs: Set[str]
                       ) -> FrozenSet[str]:
    """self-attrs mutated anywhere in ``cls`` under a ``with self.<lock>:``
    region (syntactic chain roots — the conservative base the alias-escape
    fixpoint grows from)."""
    out: Set[str] = set()
    for node in ast.walk(cls):
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        if not any((_self_attr(i.context_expr) or "") in lock_attrs
                   for i in node.items):
            continue
        for sub in ast.walk(node):
            roots: List[Optional[str]] = []
            if isinstance(sub, (ast.Assign, ast.AnnAssign)):
                tgts = sub.targets if isinstance(sub, ast.Assign) \
                    else [sub.target]
                roots = [chain_root_attr(t) for t in tgts]
            elif isinstance(sub, ast.AugAssign):
                roots = [chain_root_attr(sub.target)]
            elif isinstance(sub, ast.Delete):
                roots = [chain_root_attr(t) for t in sub.targets]
            elif (isinstance(sub, ast.Call)
                  and isinstance(sub.func, ast.Attribute)
                  and sub.func.attr in MUTATOR_METHODS):
                roots = [chain_root_attr(sub.func)]
            for r in roots:
                if r is not None and r not in lock_attrs:
                    out.add(r)
    return frozenset(out)


_IMMUTABLE_TYPES = {"int", "float", "str", "bool", "bytes", "complex",
                    "frozenset"}
# builtins whose RESULT is immutable regardless of argument types
_IMMUTABLE_CALLS = {"int", "float", "str", "bool", "bytes", "len", "round",
                    "abs", "hash", "ord", "chr", "repr", "format", "id"}
# builtins that return ONE OF their arguments — immutable iff all args are
_ARG_SELECT_CALLS = {"min", "max"}


def immutable_valued_attrs(cls: ast.ClassDef) -> FrozenSet[str]:
    """self-attrs of ``cls`` whose EVERY write assigns a definitely
    immutable value (literal scalars/tuples of immutables, arithmetic over
    them, parameters annotated with immutable types, calls to
    value-constructing builtins).  An alias to such an attr cannot be
    mutated through — so accessor returns of these are not escapes,
    whatever the caller does with them.  Conservative: one unclassifiable
    write (or zero writes) disqualifies the attr."""
    writes: Dict[str, List[bool]] = {}

    def ann_name(a: Optional[ast.AST]) -> Optional[str]:
        # ``int`` / ``typing.Optional[int]`` -> "int" (Optional wrapping
        # keeps immutability — None is immutable too)
        if isinstance(a, ast.Subscript) \
                and (dotted_name(a.value) or "").rpartition(".")[2] \
                == "Optional":
            a = a.slice
        name = dotted_name(a)
        return name.rpartition(".")[2] if name else None

    def immut(expr: ast.AST, ann: Dict[str, Optional[str]],
              attr: str, depth: int = 0) -> bool:
        if depth > 6:
            return False
        if isinstance(expr, ast.Constant):
            return True
        if isinstance(expr, ast.JoinedStr):
            return True
        if isinstance(expr, ast.Compare):
            return True  # result is a bool
        if isinstance(expr, ast.Tuple):
            return all(immut(e, ann, attr, depth + 1) for e in expr.elts)
        if isinstance(expr, ast.Name):
            return ann.get(expr.id) in _IMMUTABLE_TYPES
        if isinstance(expr, ast.Attribute) and _self_attr(expr) == attr:
            return True  # coinductive: self-reference holds if the rest does
        if isinstance(expr, ast.BinOp):
            return immut(expr.left, ann, attr, depth + 1) \
                and immut(expr.right, ann, attr, depth + 1)
        if isinstance(expr, ast.UnaryOp):
            return immut(expr.operand, ann, attr, depth + 1)
        if isinstance(expr, ast.BoolOp):
            return all(immut(v, ann, attr, depth + 1) for v in expr.values)
        if isinstance(expr, ast.IfExp):
            return immut(expr.body, ann, attr, depth + 1) \
                and immut(expr.orelse, ann, attr, depth + 1)
        if isinstance(expr, ast.Call) and isinstance(expr.func, ast.Name):
            if expr.func.id in _IMMUTABLE_CALLS:
                return True
            if expr.func.id in _ARG_SELECT_CALLS:
                return bool(expr.args) and all(
                    immut(a, ann, attr, depth + 1) for a in expr.args)
        return False

    def scan(node: ast.AST, ann: Dict[str, Optional[str]]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            a = node.args
            ann = {p.arg: ann_name(p.annotation)
                   for p in (a.posonlyargs + a.args + a.kwonlyargs)}
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            tgts = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for tgt in tgts:
                attr = _self_attr(tgt)
                if attr is None:
                    # ``self._x[k] = v`` / ``self._x.y = v`` prove the
                    # held object mutable
                    root = chain_root_attr(tgt)
                    if root is not None:
                        writes.setdefault(root, []).append(False)
                    continue
                if isinstance(node, ast.AnnAssign) \
                        and ann_name(node.annotation) in _IMMUTABLE_TYPES:
                    writes.setdefault(attr, []).append(True)
                elif node.value is not None:
                    writes.setdefault(attr, []).append(
                        immut(node.value, ann, attr))
        elif isinstance(node, ast.AugAssign):
            attr = _self_attr(node.target)
            if attr is not None:
                # sound only together with the all-writes rule: an
                # immutable RHS augments in place when the attr holds a
                # mutable, but then some plain write already disqualified
                writes.setdefault(attr, []).append(
                    immut(node.value, ann, attr))
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in MUTATOR_METHODS:
            root = chain_root_attr(node.func)
            if root is not None:
                writes.setdefault(root, []).append(False)
        for child in ast.iter_child_nodes(node):
            scan(child, ann)

    for stmt in cls.body:
        scan(stmt, {})
    return frozenset(a for a, ws in writes.items() if all(ws))


# -- definite rank inference --------------------------------------------------

_FULL_REDUCERS = {"sum", "mean", "prod", "max", "min", "all", "any",
                  "std", "var"}
_SHAPE_BUILDERS = {"zeros", "ones", "empty", "full"}
_RANK_OF_FIRST_ARG = {"psum", "pmean", "pmax", "pmin", "abs", "exp", "log",
                      "negative", "tanh", "sqrt", "square", "where"}


def _literal_shape_rank(expr: ast.AST) -> Optional[int]:
    if isinstance(expr, (ast.Tuple, ast.List)):
        if any(isinstance(e, ast.Starred) for e in expr.elts):
            return None
        return len(expr.elts)
    if isinstance(expr, ast.Constant) and isinstance(expr.value, int):
        return 1  # zeros(8) -> rank 1
    return None


def infer_rank(expr: Optional[ast.AST],
               env: Optional[Dict[str, Optional[int]]] = None,
               rank_of_call=None, depth: int = 0) -> Optional[int]:
    """Definite array rank of ``expr``, or None when unknown.  Only facts
    that hold regardless of input shapes are reported: literal scalars,
    shape-literal constructors, full (axis-free) reductions, reshape with a
    literal shape, ravel/flatten, rank-preserving elementwise ops, and —
    via the ``rank_of_call`` hook — callee return ranks from the
    interprocedural summary fixpoint."""
    if expr is None or depth > 8:
        return None
    env = env or {}
    if isinstance(expr, ast.Constant):
        return 0 if isinstance(expr.value, (int, float, bool, complex)) \
            else None
    if isinstance(expr, ast.Name):
        return env.get(expr.id)
    if isinstance(expr, ast.NamedExpr):
        return infer_rank(expr.value, env, rank_of_call, depth + 1)
    if isinstance(expr, ast.UnaryOp):
        return infer_rank(expr.operand, env, rank_of_call, depth + 1)
    if isinstance(expr, ast.BinOp):
        l = infer_rank(expr.left, env, rank_of_call, depth + 1)
        r = infer_rank(expr.right, env, rank_of_call, depth + 1)
        if l is not None and r is not None:
            return max(l, r)  # broadcasting
        return None
    if isinstance(expr, ast.IfExp):
        l = infer_rank(expr.body, env, rank_of_call, depth + 1)
        r = infer_rank(expr.orelse, env, rank_of_call, depth + 1)
        return l if l == r else None
    if isinstance(expr, ast.Call):
        terminal = (dotted_name(expr.func) or "").rpartition(".")[2]
        kwnames = {k.arg for k in expr.keywords}
        if terminal == "reshape":
            # x.reshape(a, b) / x.reshape((a, b)) / jnp.reshape(x, shape)
            shape_args = list(expr.args)
            if (len(shape_args) >= 2 and isinstance(expr.func, ast.Attribute)
                    and (dotted_name(expr.func.value) or "")
                    in ("jnp", "np", "numpy", "jax.numpy")):
                shape_args = shape_args[1:]
            if len(shape_args) == 1:
                return _literal_shape_rank(shape_args[0]) \
                    if isinstance(shape_args[0], (ast.Tuple, ast.List)) \
                    else (1 if isinstance(shape_args[0], ast.Constant)
                          and isinstance(shape_args[0].value, int) else None)
            if shape_args and not any(isinstance(a, ast.Starred)
                                      for a in shape_args):
                return len(shape_args)
            return None
        if terminal in _SHAPE_BUILDERS and expr.args:
            return _literal_shape_rank(expr.args[0])
        if terminal in ("ravel", "flatten"):
            return 1
        if terminal in _FULL_REDUCERS and "axis" not in kwnames \
                and "keepdims" not in kwnames:
            if isinstance(expr.func, ast.Attribute) and not expr.args \
                    and isinstance(expr.func.value,
                                   (ast.Name, ast.Attribute, ast.Subscript)):
                return 0  # x.sum() with no axis — full reduction to scalar
            if len(expr.args) == 1 and isinstance(expr.func, ast.Attribute) \
                    and isinstance(expr.func.value, ast.Name):
                return 0  # jnp.sum(x); bare builtin max(x) is a Name func
            return None
        if terminal in _RANK_OF_FIRST_ARG and expr.args:
            return infer_rank(expr.args[0], env, rank_of_call, depth + 1)
        if terminal == "expand_dims" and expr.args:
            base = infer_rank(expr.args[0], env, rank_of_call, depth + 1)
            return None if base is None else base + 1
        if rank_of_call is not None:
            return rank_of_call(expr)
        return None
    return None


def local_rank_env(fn: FunctionNode, rank_of_call=None
                   ) -> Dict[str, Optional[int]]:
    """Name -> definite rank for single-assignment locals of ``fn``,
    computed in source order so chained definitions resolve."""
    counts: Dict[str, int] = {}
    assigns: List[ast.Assign] = []
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            counts[node.targets[0].id] = counts.get(node.targets[0].id,
                                                    0) + 1
            assigns.append(node)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            tgt = node.target
            if isinstance(tgt, ast.Name):
                counts[tgt.id] = counts.get(tgt.id, 0) + 2
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            for sub in ast.walk(node.target):
                if isinstance(sub, ast.Name):
                    counts[sub.id] = counts.get(sub.id, 0) + 2
    env: Dict[str, Optional[int]] = {}
    for node in sorted(assigns, key=lambda a: a.lineno):
        name = node.targets[0].id
        if counts.get(name, 0) == 1:
            env[name] = infer_rank(node.value, env, rank_of_call)
    return env


# -- per-function summary -----------------------------------------------------

@dataclasses.dataclass(frozen=True)
class FunctionSummary:
    """Interprocedural facts for one function, consumed by the program-wide
    fixpoints in ``program_index.ProgramSummaries``."""
    name: str
    cls: Optional[str]                       # enclosing class name
    is_property: bool
    return_attrs: FrozenSet[str]             # self-attrs the return may alias
    return_attr_sites: Tuple[Tuple[ast.Return, Tuple[str, ...]], ...]
    return_calls: Tuple[ast.Call, ...]       # `return f(...)` forms
    return_rank: Optional[int]               # definite rank of all returns
    return_rank_call: Optional[ast.Call]     # rank == rank of this callee
    lock_acquires: Tuple[str, ...]           # lock keys taken anywhere
    lock_pairs: Tuple[Tuple[str, str, ast.AST], ...]  # (outer, inner, site)
    held_calls: Tuple[Tuple[str, ast.Call], ...]      # calls under a lock
    calls: Tuple[ast.Call, ...]              # all lexical calls


def _is_property(fn: FunctionNode) -> bool:
    for dec in getattr(fn, "decorator_list", []):
        name = (dotted_name(dec) or "").rpartition(".")[2]
        if name in ("property", "cached_property"):
            return True
    return False


# relpath -> (source digest, tree object, ModuleSummaries).  Cross-run
# reuse: summaries key functions by ``id(fn)`` (AST node identity), so a
# hit additionally REQUIRES the caller's tree to be the SAME object the
# cached summaries were computed over — program_index's parse cache
# guarantees that for unchanged sources, and the identity check below
# makes a violated assumption a miss instead of silent corruption.
_SUMMARY_CACHE: Dict[str, Tuple[str, object, "ModuleSummaries"]] = {}


def cached_module_summaries(tree: Optional[ast.Module], relpath: str,
                            digest: Optional[str] = None
                            ) -> "ModuleSummaries":
    """``ModuleSummaries`` with a digest-keyed per-process cache.

    With a ``digest`` (the module source's content hash), an unchanged
    module's whole summary pass is skipped on every build after the first
    — the ``--diff`` fast path, where re-linting a handful of changed
    files no longer re-summarises the rest of the package.  Counted in
    ``summaries_cached_count()``; ``digest=None`` bypasses the cache."""
    if digest is not None:
        hit = _SUMMARY_CACHE.get(relpath)
        if hit is not None and hit[0] == digest and hit[1] is tree:
            _COST["summary_cached"] += 1
            return hit[2]
    ms = ModuleSummaries(tree, relpath)
    if digest is not None and tree is not None:
        _SUMMARY_CACHE[relpath] = (digest, tree, ms)
    return ms


class ModuleSummaries:
    """Per-module summary computation: one ``FunctionSummary`` per def, plus
    the class-level lock/locked-attr tables the summaries key against."""

    def __init__(self, tree: Optional[ast.Module], relpath: str):
        self.relpath = relpath
        self.by_id: Dict[int, FunctionSummary] = {}
        self.fn_of_id: Dict[int, FunctionNode] = {}
        self.lock_attrs: Dict[str, Set[str]] = {}       # class -> lock attrs
        self.lock_canon: Dict[str, Dict[str, str]] = {}
        self.lock_factory: Dict[str, str] = {}          # key -> factory
        self.locked_attrs: Dict[str, FrozenSet[str]] = {}     # lazy cache
        self.immutable_attrs: Dict[str, FrozenSet[str]] = {}  # lazy cache
        self._class_nodes: Dict[str, ast.ClassDef] = {}
        self.lock_display: Dict[str, str] = {}          # class -> main lock
        self.module_locks: Dict[str, str] = {}          # name -> factory
        self._flows: Dict[int, FunctionFlow] = {}
        if tree is None:
            return
        with _timed_summary():
            owned: List[Tuple[FunctionNode, Optional[str]]] = []
            self._enumerate(tree, None, owned)
            for stmt in tree.body:
                if isinstance(stmt, ast.Assign) \
                        and isinstance(stmt.value, ast.Call):
                    factory = (dotted_name(stmt.value.func) or "") \
                        .rpartition(".")[2]
                    if factory in LOCK_FACTORIES:
                        for tgt in stmt.targets:
                            if isinstance(tgt, ast.Name):
                                self.module_locks[tgt.id] = factory
                                self.lock_factory[
                                    f"{relpath}::{tgt.id}"] = factory
            for fn, cls_name in owned:
                self.by_id[id(fn)] = self._summarize(fn, cls_name)
                self.fn_of_id[id(fn)] = fn

    def _enumerate(self, root: ast.AST, cls: Optional[str],
                   out: List[Tuple[FunctionNode, Optional[str]]]) -> None:
        # defs and classes are statements: walking statement lists only
        # (never expression subtrees) finds every one at a fraction of a
        # full-node traversal
        stack: List[Tuple[ast.AST, Optional[str]]] = [(root, cls)]
        while stack:
            node, cls = stack.pop()
            if isinstance(node, ast.ClassDef):
                names, canon, factory_of = class_lock_info(node)
                self.lock_attrs[node.name] = names
                self.lock_canon[node.name] = canon
                canonical = sorted({canon.get(a, a) for a in names})
                if canonical:
                    self.lock_display[node.name] = canonical[0]
                for attr, fac in factory_of.items():
                    key = f"{self.relpath}::{node.name}.{canon.get(attr, attr)}"
                    # a Condition wrapping an RLock is reentrant with it
                    self.lock_factory.setdefault(key, fac)
                self._class_nodes[node.name] = node
                cls = node.name
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.append((node, cls))
            for field in ("handlers", "finalbody", "orelse", "body"):
                for child in reversed(getattr(node, field, ())):
                    stack.append((child, cls))

    def locked_attrs_of(self, cls_name: str) -> FrozenSet[str]:
        """Lazy :func:`class_locked_attrs` — only classes with an
        attr-returning method ever pay for the mutation scan."""
        got = self.locked_attrs.get(cls_name)
        if got is None:
            node = self._class_nodes.get(cls_name)
            got = (class_locked_attrs(node,
                                      self.lock_attrs.get(cls_name, set()))
                   if node is not None else frozenset())
            self.locked_attrs[cls_name] = got
        return got

    def immutable_attrs_of(self, cls_name: str) -> FrozenSet[str]:
        """Lazy :func:`immutable_valued_attrs` — only classes that produce
        an escape hit ever pay for the write classification."""
        got = self.immutable_attrs.get(cls_name)
        if got is None:
            node = self._class_nodes.get(cls_name)
            got = (immutable_valued_attrs(node) if node is not None
                   else frozenset())
            self.immutable_attrs[cls_name] = got
        return got

    def _flow(self, fn: FunctionNode) -> FunctionFlow:
        flow = self._flows.get(id(fn))
        if flow is None:
            flow = FunctionFlow(fn)
            self._flows[id(fn)] = flow
        return flow

    def _lock_key(self, cls_name: Optional[str], attr: str) -> str:
        if cls_name is None:
            return f"{self.relpath}::{attr}"
        canon = self.lock_canon.get(cls_name, {})
        return f"{self.relpath}::{cls_name}.{canon.get(attr, attr)}"

    def _resolve_lock_item(self, item: ast.withitem, fn: FunctionNode,
                           cls_name: Optional[str],
                           may_flow: bool) -> Optional[str]:
        expr = item.context_expr
        attr = _self_attr(expr)
        if attr is not None:
            if cls_name is not None \
                    and attr in self.lock_attrs.get(cls_name, set()):
                return self._lock_key(cls_name, attr)
            return None
        if isinstance(expr, ast.Name):
            if expr.id in self.module_locks:
                return f"{self.relpath}::{expr.id}"
            if may_flow and cls_name is not None and _lockish_context(item):
                aliases = self._flow(fn).attr_aliases(expr.id, expr)
                hits = sorted(aliases & self.lock_attrs.get(cls_name, set()))
                if hits:
                    return self._lock_key(cls_name, hits[0])
        return None

    def _resolve_lock_expr(self, expr: ast.AST,
                           cls_name: Optional[str]) -> Optional[str]:
        """Lock key for the receiver of a bare ``.acquire()``/``.release()``
        — ``self.<lock attr>`` (Condition wrappers canonicalise onto their
        base lock via ``_lock_key``) or a module-level lock name.  No alias
        flow: bare lock calls on a local alias are rare enough that the
        self-attr/module-name forms carry the rule."""
        attr = _self_attr(expr)
        if attr is not None:
            if cls_name is not None \
                    and attr in self.lock_attrs.get(cls_name, set()):
                return self._lock_key(cls_name, attr)
            return None
        if isinstance(expr, ast.Name) and expr.id in self.module_locks:
            return f"{self.relpath}::{expr.id}"
        return None

    def _return_roots(self, expr: ast.AST, fn: FunctionNode,
                      use_flow: bool, depth: int = 0) -> FrozenSet[str]:
        if expr is None or depth > 4:
            return _EMPTY
        root = attr_chain_root(expr)
        if root is not None:
            return frozenset((root,))
        if isinstance(expr, ast.Name) and use_flow:
            return self._flow(fn).attr_aliases(expr.id, expr)
        if isinstance(expr, ast.IfExp):
            return (self._return_roots(expr.body, fn, use_flow, depth + 1)
                    | self._return_roots(expr.orelse, fn, use_flow,
                                         depth + 1))
        if isinstance(expr, ast.NamedExpr):
            return self._return_roots(expr.value, fn, use_flow, depth + 1)
        return _EMPTY

    @staticmethod
    def _mentions_local(expr: ast.AST) -> bool:
        return any(isinstance(n, ast.Name) and n.id != "self"
                   for n in ast.walk(expr))

    def _summarize(self, fn: FunctionNode,
                   cls_name: Optional[str]) -> FunctionSummary:
        # one fused pass over the body collecting everything the summary
        # needs: returns, lexical calls, whether any with-block exists (the
        # expensive held-lock walk only runs when one does), whether any
        # local is bound FROM a self-attr (without one, a returned name
        # cannot alias self state, so no flow is needed), and the
        # single-assignment census the rank env is built from
        returns: List[ast.Return] = []
        fast_calls: List[ast.Call] = []
        rank_assigns: List[ast.Assign] = []
        name_counts: Dict[str, int] = {}
        has_with = False
        has_lock_calls = False
        has_self_src = False

        def _selfish(v: Optional[ast.AST]) -> bool:
            if isinstance(v, ast.IfExp):
                return (attr_chain_root(v.body) is not None
                        or attr_chain_root(v.orelse) is not None)
            if isinstance(v, (ast.Tuple, ast.List)):
                return any(attr_chain_root(e) is not None for e in v.elts)
            return v is not None and attr_chain_root(v) is not None

        stack: List[ast.AST] = list(fn.body)
        while stack:
            node = stack.pop()
            if isinstance(node, ast.Return):
                returns.append(node)
            elif isinstance(node, ast.Call):
                fast_calls.append(node)
                if not has_lock_calls \
                        and isinstance(node.func, ast.Attribute) \
                        and node.func.attr in ("acquire", "release"):
                    has_lock_calls = True
            elif isinstance(node, (ast.With, ast.AsyncWith)):
                has_with = True
            elif isinstance(node, ast.Assign):
                if len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name):
                    nm = node.targets[0].id
                    name_counts[nm] = name_counts.get(nm, 0) + 1
                    rank_assigns.append(node)
                if not has_self_src:
                    has_self_src = _selfish(node.value)
            elif isinstance(node, (ast.AnnAssign, ast.NamedExpr)):
                if isinstance(node.target, ast.Name):
                    name_counts[node.target.id] = \
                        name_counts.get(node.target.id, 0) + 2
                if not has_self_src:
                    has_self_src = _selfish(node.value)
            elif isinstance(node, ast.AugAssign):
                if isinstance(node.target, ast.Name):
                    name_counts[node.target.id] = \
                        name_counts.get(node.target.id, 0) + 2
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                for sub in ast.walk(node.target):
                    if isinstance(sub, ast.Name):
                        name_counts[sub.id] = name_counts.get(sub.id, 0) + 2
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.Lambda)):
                continue
            stack.extend(ast.iter_child_nodes(node))
        returns.sort(key=lambda r: r.lineno)

        # may-alias self-attrs of the return value.  A flow fixpoint is only
        # built when a bare-Name return makes it necessary.
        need_flow = cls_name is not None and has_self_src and any(
            isinstance(r.value, (ast.Name, ast.IfExp)) for r in returns)
        attr_sites: List[Tuple[ast.Return, Tuple[str, ...]]] = []
        all_attrs: Set[str] = set()
        for r in returns:
            if r.value is None:
                continue
            roots = self._return_roots(r.value, fn, need_flow)
            if roots:
                attr_sites.append((r, tuple(sorted(roots))))
                all_attrs |= roots

        return_calls = tuple(r.value for r in returns
                             if isinstance(r.value, ast.Call))

        # definite return rank.  The single-assignment env costs a full
        # fn walk, so it is only built when a return actually mentions a
        # local name — infer_rank consults env for nothing else.
        return_rank: Optional[int] = None
        return_rank_call: Optional[ast.Call] = None
        value_returns = [r for r in returns if r.value is not None]
        if value_returns:
            if len(value_returns) == 1 \
                    and isinstance(value_returns[0].value, ast.Call):
                return_rank_call = value_returns[0].value
            env: Optional[Dict[str, Optional[int]]] = None
            if any(self._mentions_local(r.value) for r in value_returns):
                # the env local_rank_env() would build, from the census the
                # fused pass already collected — no second body walk
                env = {}
                for a in sorted(rank_assigns, key=lambda a: a.lineno):
                    nm = a.targets[0].id
                    if name_counts.get(nm, 0) == 1:
                        env[nm] = infer_rank(a.value, env)
            ranks = [infer_rank(r.value, env) for r in value_returns]
            if all(k is not None for k in ranks) and len(set(ranks)) == 1:
                return_rank = ranks[0]

        # lock walk — only functions with a with-block or a bare
        # acquire()/release() call pay for it.  `bare` is the function-wide
        # document-order stack of locks taken by bare ``.acquire()`` and not
        # yet ``.release()``d: unlike with-blocks the hold outlives the
        # statement, so it participates in every pair/held-call formed after
        # it (branch-insensitive, like the rest of the walk).
        pairs: List[Tuple[str, str, ast.AST]] = []
        held_calls: List[Tuple[str, ast.Call]] = []
        acquires: List[str] = []
        calls: List[ast.Call] = fast_calls
        if has_with or has_lock_calls:
            calls = []
            bare: List[str] = []
            lockish_names = has_with and cls_name is not None and any(
                isinstance(i.context_expr, ast.Name) and _lockish_context(i)
                for n in ast.walk(fn)
                if isinstance(n, (ast.With, ast.AsyncWith))
                for i in n.items)

            def visit(node: ast.AST, held: List[str]) -> None:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.Lambda)):
                    return
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    got: List[str] = []
                    for item in node.items:
                        visit(item.context_expr, held + got)
                        key = self._resolve_lock_item(item, fn, cls_name,
                                                      lockish_names)
                        if key is not None:
                            if key not in acquires:
                                acquires.append(key)
                            for h in held + bare + got:
                                if h != key:
                                    pairs.append((h, key,
                                                  item.context_expr))
                            got.append(key)
                    for sub in node.body:
                        visit(sub, held + got)
                    return
                if isinstance(node, ast.Call):
                    calls.append(node)
                    for h in held + bare:
                        held_calls.append((h, node))
                    if isinstance(node.func, ast.Attribute) \
                            and node.func.attr in ("acquire", "release"):
                        key = self._resolve_lock_expr(node.func.value,
                                                      cls_name)
                        if key is not None:
                            if node.func.attr == "acquire":
                                if key not in acquires:
                                    acquires.append(key)
                                for h in held + bare:
                                    if h != key:
                                        pairs.append((h, key, node))
                                bare.append(key)
                            else:
                                # release the innermost matching hold
                                for i in range(len(bare) - 1, -1, -1):
                                    if bare[i] == key:
                                        del bare[i]
                                        break
                for child in ast.iter_child_nodes(node):
                    visit(child, held)

            for stmt in fn.body:
                visit(stmt, [])

        return FunctionSummary(
            name=fn.name, cls=cls_name, is_property=_is_property(fn),
            return_attrs=frozenset(all_attrs),
            return_attr_sites=tuple(attr_sites),
            return_calls=return_calls,
            return_rank=return_rank, return_rank_call=return_rank_call,
            lock_acquires=tuple(acquires), lock_pairs=tuple(pairs),
            held_calls=tuple(held_calls), calls=tuple(calls))
