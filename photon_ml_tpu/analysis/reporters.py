"""Render photonlint results as text (human/CI logs), JSON (tooling), or
SARIF 2.1.0 (code-scanning upload).

All reporters consume the same inputs: the violations split against the
baseline (analysis/baseline.py) plus scan counts, so the CLI and the tier-1
test print identical findings.  SARIF results reuse the baseline
fingerprint as ``partialFingerprints`` so code-scanning dedupes findings
across pushes exactly as the baseline does across runs; baselined and
in-source-suppressed findings are emitted WITH ``suppressions`` entries so
the upload reflects accepted debt instead of silently dropping it.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence

from photon_ml_tpu.analysis.framework import AnalysisResult, Violation


def render_text(new: Sequence[Violation], baselined: Sequence[Violation],
                stale: Sequence[str], result: AnalysisResult,
                verbose: bool = False) -> str:
    out: List[str] = []
    for v in new:
        out.append(v.render())
        if v.snippet:
            out.append(f"    {v.snippet}")
    if verbose and baselined:
        out.append("")
        out.append(f"baselined (accepted debt, {len(baselined)}):")
        out.extend(f"  {v.render()}" for v in baselined)
    if stale:
        out.append("")
        out.append(f"stale baseline entries ({len(stale)}) — debt fixed but "
                   "still baselined; remove with --prune-baseline:")
        out.extend(f"  {fp}" for fp in stale)
    out.append("")
    by_rule = {}
    for v in new:
        by_rule[v.rule] = by_rule.get(v.rule, 0) + 1
    detail = (" (" + ", ".join(f"{r}: {n}" for r, n in sorted(by_rule.items()))
              + ")") if by_rule else ""
    mode = (f", index {result.index_build_s:.2f}s, "
            f"dataflow {result.dataflow_s:.2f}s, "
            f"summaries {result.summaries_s:.2f}s, "
            f"{result.summaries_cached} summary cache hit(s)"
            if result.whole_program else ", per-module mode")
    out.append(
        f"photonlint: {result.files_scanned} files scanned, "
        f"{len(new)} new violation(s){detail}, {len(baselined)} baselined, "
        f"{len(result.suppressed)} suppressed{mode}")
    return "\n".join(out)


def _counts(violations: Sequence[Violation], key) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for v in violations:
        k = key(v)
        out[k] = out.get(k, 0) + 1
    return dict(sorted(out.items()))


def render_json(new: Sequence[Violation], baselined: Sequence[Violation],
                stale: Sequence[str], result: AnalysisResult) -> str:
    payload = {
        "files_scanned": result.files_scanned,
        "new": [v.to_dict() for v in new],
        "baselined": [v.to_dict() for v in baselined],
        "suppressed": [v.to_dict() for v in result.suppressed],
        "stale_baseline_fingerprints": list(stale),
        "summary": {
            "new": len(new),
            "baselined": len(baselined),
            "suppressed": len(result.suppressed),
            "stale": len(stale),
            "files_scanned": result.files_scanned,
            "whole_program": result.whole_program,
            "index_build_s": round(result.index_build_s, 4),
            "dataflow_s": round(result.dataflow_s, 4),
            "summaries_s": round(result.summaries_s, 4),
            "summaries_cached": result.summaries_cached,
            "by_rule": _counts(new, lambda v: v.rule),
            "by_severity": _counts(new, lambda v: v.severity),
        },
    }
    return json.dumps(payload, indent=2)


_SARIF_SCHEMA_URI = ("https://raw.githubusercontent.com/oasis-tcs/"
                     "sarif-spec/master/Schemata/sarif-schema-2.1.0.json")


def _sarif_result(v: Violation, rule_index: Dict[str, int],
                  suppression: Optional[str] = None) -> dict:
    out = {
        "ruleId": v.code,
        "ruleIndex": rule_index[v.code],
        "level": "error" if v.severity == "error" else "warning",
        "message": {"text": v.message},
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {"uri": v.path, "uriBaseId": "SRCROOT"},
                "region": {"startLine": max(v.line, 1),
                           "startColumn": max(v.col + 1, 1)},
            },
        }],
        # the baseline fingerprint: code-scanning dedupes on it across
        # pushes the same way analysis/baseline.py does across runs
        "partialFingerprints": {"photonlint/v1": v.fingerprint()},
    }
    if v.snippet:
        out["locations"][0]["physicalLocation"]["region"]["snippet"] = {
            "text": v.snippet}
    if suppression is not None:
        out["suppressions"] = [{"kind": suppression}]
    return out


def render_sarif(new: Sequence[Violation], baselined: Sequence[Violation],
                 stale: Sequence[str], result: AnalysisResult) -> str:
    """SARIF 2.1.0 for code-scanning upload: new findings active,
    baselined debt carried as externally-suppressed results, in-source
    ``# photonlint: disable`` sites as inSource-suppressed results."""
    from photon_ml_tpu.analysis.framework import (_ParseErrorRule,
                                                  registered_rules)
    registry = registered_rules()
    rules_sorted = sorted(registry.items(), key=lambda kv: kv[1].code)
    # PL000 parse failures are findings too — the pseudo-rule leads the
    # array so broken files upload instead of vanishing
    rules_sorted.insert(0, (_ParseErrorRule.name, _ParseErrorRule))
    rule_index = {cls.code: i for i, (_, cls) in enumerate(rules_sorted)}
    rules = [{
        "id": cls.code,
        "name": name,
        "shortDescription": {"text": cls.description},
        "defaultConfiguration": {
            "level": "error" if cls.severity == "error" else "warning"},
    } for name, cls in rules_sorted]
    results = [_sarif_result(v, rule_index) for v in new]
    results += [_sarif_result(v, rule_index, suppression="external")
                for v in baselined]
    results += [_sarif_result(v, rule_index, suppression="inSource")
                for v in result.suppressed]
    payload = {
        "$schema": _SARIF_SCHEMA_URI,
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "photonlint",
                "informationUri":
                    "https://github.com/photon-ml-tpu/photon-ml-tpu",
                "version": "4.0.0",
                "rules": rules,
            }},
            "results": results,
            "columnKind": "utf16CodeUnits",
            "properties": {
                "filesScanned": result.files_scanned,
                "wholeProgram": result.whole_program,
                "staleBaselineFingerprints": list(stale),
            },
        }],
    }
    return json.dumps(payload, indent=2)
