"""Render photonlint results as text (human/CI logs) or JSON (tooling).

Both reporters consume the same inputs: the violations split against the
baseline (analysis/baseline.py) plus scan counts, so the CLI and the tier-1
test print identical findings.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence

from photon_ml_tpu.analysis.framework import AnalysisResult, Violation


def render_text(new: Sequence[Violation], baselined: Sequence[Violation],
                stale: Sequence[str], result: AnalysisResult,
                verbose: bool = False) -> str:
    out: List[str] = []
    for v in new:
        out.append(v.render())
        if v.snippet:
            out.append(f"    {v.snippet}")
    if verbose and baselined:
        out.append("")
        out.append(f"baselined (accepted debt, {len(baselined)}):")
        out.extend(f"  {v.render()}" for v in baselined)
    if stale:
        out.append("")
        out.append(f"stale baseline entries ({len(stale)}) — debt fixed but "
                   "still baselined; remove with --prune-baseline:")
        out.extend(f"  {fp}" for fp in stale)
    out.append("")
    by_rule = {}
    for v in new:
        by_rule[v.rule] = by_rule.get(v.rule, 0) + 1
    detail = (" (" + ", ".join(f"{r}: {n}" for r, n in sorted(by_rule.items()))
              + ")") if by_rule else ""
    mode = (f", index {result.index_build_s:.2f}s, "
            f"dataflow {result.dataflow_s:.2f}s"
            if result.whole_program else ", per-module mode")
    out.append(
        f"photonlint: {result.files_scanned} files scanned, "
        f"{len(new)} new violation(s){detail}, {len(baselined)} baselined, "
        f"{len(result.suppressed)} suppressed{mode}")
    return "\n".join(out)


def _counts(violations: Sequence[Violation], key) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for v in violations:
        k = key(v)
        out[k] = out.get(k, 0) + 1
    return dict(sorted(out.items()))


def render_json(new: Sequence[Violation], baselined: Sequence[Violation],
                stale: Sequence[str], result: AnalysisResult) -> str:
    payload = {
        "files_scanned": result.files_scanned,
        "new": [v.to_dict() for v in new],
        "baselined": [v.to_dict() for v in baselined],
        "suppressed": [v.to_dict() for v in result.suppressed],
        "stale_baseline_fingerprints": list(stale),
        "summary": {
            "new": len(new),
            "baselined": len(baselined),
            "suppressed": len(result.suppressed),
            "stale": len(stale),
            "files_scanned": result.files_scanned,
            "whole_program": result.whole_program,
            "index_build_s": round(result.index_build_s, 4),
            "dataflow_s": round(result.dataflow_s, 4),
            "by_rule": _counts(new, lambda v: v.rule),
            "by_severity": _counts(new, lambda v: v.severity),
        },
    }
    return json.dumps(payload, indent=2)
