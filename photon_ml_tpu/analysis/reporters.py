"""Render photonlint results as text (human/CI logs) or JSON (tooling).

Both reporters consume the same inputs: the violations split against the
baseline (analysis/baseline.py) plus scan counts, so the CLI and the tier-1
test print identical findings.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence

from photon_ml_tpu.analysis.framework import AnalysisResult, Violation


def render_text(new: Sequence[Violation], baselined: Sequence[Violation],
                stale: Sequence[str], result: AnalysisResult,
                verbose: bool = False) -> str:
    out: List[str] = []
    for v in new:
        out.append(v.render())
        if v.snippet:
            out.append(f"    {v.snippet}")
    if verbose and baselined:
        out.append("")
        out.append(f"baselined (accepted debt, {len(baselined)}):")
        out.extend(f"  {v.render()}" for v in baselined)
    if stale:
        out.append("")
        out.append(f"stale baseline entries ({len(stale)}) — debt fixed; "
                   "prune with --write-baseline:")
        out.extend(f"  {fp}" for fp in stale)
    out.append("")
    by_rule = {}
    for v in new:
        by_rule[v.rule] = by_rule.get(v.rule, 0) + 1
    detail = (" (" + ", ".join(f"{r}: {n}" for r, n in sorted(by_rule.items()))
              + ")") if by_rule else ""
    out.append(
        f"photonlint: {result.files_scanned} files scanned, "
        f"{len(new)} new violation(s){detail}, {len(baselined)} baselined, "
        f"{len(result.suppressed)} suppressed")
    return "\n".join(out)


def render_json(new: Sequence[Violation], baselined: Sequence[Violation],
                stale: Sequence[str], result: AnalysisResult) -> str:
    payload = {
        "files_scanned": result.files_scanned,
        "new": [v.to_dict() for v in new],
        "baselined": [v.to_dict() for v in baselined],
        "suppressed": [v.to_dict() for v in result.suppressed],
        "stale_baseline_fingerprints": list(stale),
        "summary": {
            "new": len(new),
            "baselined": len(baselined),
            "suppressed": len(result.suppressed),
            "stale": len(stale),
        },
    }
    return json.dumps(payload, indent=2)
