"""Best-effort literal resolution inside one module, for rule checks.

PL006 (donation) needs ``donate_argnums=donate`` resolved to concrete
positions; PL007/PL008 need axis names like ``axis`` / ``self.feature_axis``
resolved to strings before validating them against the mesh universe.  The
repo's idiom chains several hops deep::

    class ShardSparseObjective:
        def __init__(self, ..., feature_axis: str = FEATURE_AXIS):
            self.feature_axis = feature_axis          # param default
        def hvp(self, ...):
            obj, data, feat = self.obj, self.data_axis, self.feature_axis
            ... jax.lax.psum(..., feat)               # tuple unpack

so the resolver follows: constants, Name bindings in enclosing function
scopes (including tuple-unpack assignments), parameter DEFAULTS, ``self.X``
attributes assigned in ``__init__``/other methods, module-level constants,
and — when a :class:`~photon_ml_tpu.analysis.program_index.ProgramIndex`
is attached — constants imported from other modules.

``values(node)`` returns the LIST of possible literal values (an ``IfExp``
contributes both branches; an empty list means "unknown").  Unknown always
means "stay quiet" for the rules built on top — resolution failures must
never invent findings.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

_MAX_DEPTH = 10
_SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef,
           ast.Module)


class Resolver:
    def __init__(self, ctx):
        """``ctx``: a framework.ModuleContext (tree + optional .program)."""
        self.ctx = ctx
        self.tree = ctx.tree
        self.program = getattr(ctx, "program", None)
        self._parents: Dict[int, ast.AST] = {}
        self._constants: Dict[str, ast.expr] = {}
        if self.tree is None:
            return
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self._parents[id(child)] = node
        for stmt in self.tree.body:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                self._constants[stmt.targets[0].id] = stmt.value
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None \
                    and isinstance(stmt.target, ast.Name):
                self._constants[stmt.target.id] = stmt.value

    # -- scope walking -------------------------------------------------------
    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parents.get(id(node))

    def enclosing_scopes(self, node: ast.AST) -> List[ast.AST]:
        """Innermost-out chain of function/class/module scopes above node."""
        out: List[ast.AST] = []
        cur = self.parent(node)
        while cur is not None:
            if isinstance(cur, _SCOPES):
                out.append(cur)
            cur = self.parent(cur)
        return out

    def enclosing_class(self, node: ast.AST) -> Optional[ast.ClassDef]:
        for scope in self.enclosing_scopes(node):
            if isinstance(scope, ast.ClassDef):
                return scope
        return None

    # -- resolution ----------------------------------------------------------
    def values(self, node: ast.AST, at: Optional[ast.AST] = None,
               depth: int = 0) -> List[object]:
        """Possible literal values of ``node`` ([] = unknown).  ``at``
        anchors Name lookups to the scope chain of that node (defaults to
        ``node`` itself)."""
        if depth > _MAX_DEPTH or node is None:
            return []
        at = at if at is not None else node
        if isinstance(node, ast.Constant):
            return [node.value]
        if isinstance(node, ast.IfExp):
            return _dedupe(self.values(node.body, at, depth + 1)
                           + self.values(node.orelse, at, depth + 1))
        if isinstance(node, (ast.Tuple, ast.List)):
            elts = [self.values(e, at, depth + 1) for e in node.elts]
            if any(not v for v in elts):
                return []
            # cap the cross product: one alternative per element beyond the
            # first keeps this bounded and is plenty for donate/axis specs
            out = [tuple(v[0] for v in elts)]
            for i, alts in enumerate(elts):
                for alt in alts[1:3]:
                    combo = list(out[0])
                    combo[i] = alt
                    out.append(tuple(combo))
            return _dedupe(out)
        if isinstance(node, ast.Name):
            return self._name_values(node.id, at, depth)
        if isinstance(node, ast.Attribute):
            if isinstance(node.value, ast.Name) and node.value.id == "self":
                return self._self_attr_values(node.attr, at, depth)
            return self._imported_const(node, depth)
        return []

    def _name_values(self, name: str, at: ast.AST, depth: int) -> List[object]:
        for scope in self.enclosing_scopes(at):
            if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                got: List[object] = []
                for expr in self._bindings_in(scope, name):
                    got.extend(self.values(expr, expr, depth + 1))
                default = self._param_default(scope, name)
                if default is not None:
                    # defaults evaluate in the scope ENCLOSING the function
                    got.extend(self.values(default, scope, depth + 1))
                if got or self._binds(scope, name):
                    return _dedupe(got)
            elif isinstance(scope, ast.ClassDef):
                continue  # class bodies don't scope into methods
        if name in self._constants:
            return self.values(self._constants[name], self.tree, depth + 1)
        return self._imported_name_const(name, depth)

    def _self_attr_values(self, attr: str, at: ast.AST,
                          depth: int) -> List[object]:
        cls = self.enclosing_class(at)
        if cls is None:
            return []
        got: List[object] = []
        for item in cls.body:
            if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for stmt in ast.walk(item):
                for tgt, expr in _assign_pairs(stmt):
                    if (isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self" and tgt.attr == attr):
                        got.extend(self.values(expr, expr, depth + 1))
        return _dedupe(got)

    def _bindings_in(self, scope, name: str) -> List[ast.expr]:
        """Expressions assigned to ``name`` anywhere in ``scope``'s own body
        (nested defs excluded — their bindings are theirs)."""
        out: List[ast.expr] = []
        body = scope.body if isinstance(scope.body, list) else []
        stack = list(body)
        while stack:
            stmt = stack.pop()
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda, ast.ClassDef)):
                continue
            for tgt, expr in _assign_pairs(stmt):
                if isinstance(tgt, ast.Name) and tgt.id == name:
                    out.append(expr)
            stack.extend(ast.iter_child_nodes(stmt))
        return out

    def _binds(self, scope, name: str) -> bool:
        """Is ``name`` a parameter of ``scope`` (shadowing outer scopes)?"""
        a = scope.args
        names = [p.arg for p in
                 list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)]
        if a.vararg:
            names.append(a.vararg.arg)
        if a.kwarg:
            names.append(a.kwarg.arg)
        return name in names

    def _param_default(self, scope, name: str) -> Optional[ast.expr]:
        a = scope.args
        ordered = list(a.posonlyargs) + list(a.args)
        defaults = list(a.defaults)
        # defaults align to the TAIL of the positional params
        for param, default in zip(ordered[len(ordered) - len(defaults):],
                                  defaults):
            if param.arg == name:
                return default
        for param, default in zip(a.kwonlyargs, a.kw_defaults):
            if param.arg == name and default is not None:
                return default
        return None

    def _imported_name_const(self, name: str, depth: int) -> List[object]:
        if self.program is None:
            return []
        info = self.program.modules.get(self.ctx.relpath)
        if info is None:
            return []
        val = self.program.const_value(info, ast.Name(id=name, ctx=ast.Load()),
                                       depth)
        return [val] if val is not None else []

    def _imported_const(self, node: ast.Attribute, depth: int) -> List[object]:
        if self.program is None:
            return []
        info = self.program.modules.get(self.ctx.relpath)
        if info is None:
            return []
        val = self.program.const_value(info, node, depth)
        return [val] if val is not None else []

    # -- convenience ---------------------------------------------------------
    def strings(self, node: ast.AST) -> List[str]:
        """Flattened possible axis-name strings of node (strings and
        tuples-of-strings both contribute their members)."""
        out: List[str] = []
        for v in self.values(node):
            if isinstance(v, str):
                out.append(v)
            elif isinstance(v, tuple):
                out.extend(x for x in v if isinstance(x, str))
        return _dedupe(out)


def _assign_pairs(stmt: ast.AST):
    """(target, value-expr) pairs of an assignment statement, tuple-unpacks
    expanded elementwise when both sides are tuples."""
    if isinstance(stmt, ast.Assign):
        for tgt in stmt.targets:
            if isinstance(tgt, (ast.Tuple, ast.List)) \
                    and isinstance(stmt.value, (ast.Tuple, ast.List)) \
                    and len(tgt.elts) == len(stmt.value.elts):
                yield from zip(tgt.elts, stmt.value.elts)
            else:
                yield tgt, stmt.value
    elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
        yield stmt.target, stmt.value


def _dedupe(items: List) -> List:
    out = []
    for x in items:
        if x not in out:
            out.append(x)
    return out


def mesh_axes_in_module(resolver: Resolver) -> Set[str]:
    """Axis names of every ``Mesh(...)`` constructed in THIS module (the
    no-program-index fallback for PL007/PL008)."""
    axes: Set[str] = set()
    if resolver.tree is None:
        return axes
    for node in ast.walk(resolver.tree):
        got = mesh_axes_of_call(resolver, node)
        if got:
            axes.update(got)
    return axes


def mesh_axes_of_call(resolver: Resolver, node: ast.AST) -> Set[str]:
    """Axis names when ``node`` is a ``Mesh(...)`` construction (else {})."""
    from photon_ml_tpu.analysis.jit_index import dotted_name

    if not isinstance(node, ast.Call):
        return set()
    fname = dotted_name(node.func)
    if fname is None or fname.rpartition(".")[2] != "Mesh":
        return set()
    axes_expr = None
    for kw in node.keywords:
        if kw.arg == "axis_names":
            axes_expr = kw.value
    if axes_expr is None and len(node.args) >= 2:
        axes_expr = node.args[1]
    if axes_expr is None:
        return set()
    return set(resolver.strings(axes_expr))


def mesh_axes_of_expr(resolver: Resolver, expr: ast.AST) -> Set[str]:
    """Resolve a mesh-valued EXPRESSION to its axis names when statically
    visible: a direct ``Mesh(...)`` call, or a Name bound to one in an
    enclosing scope.  {} = unknown."""
    direct = mesh_axes_of_call(resolver, expr)
    if direct:
        return direct
    if isinstance(expr, ast.Name):
        for scope in resolver.enclosing_scopes(expr):
            if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for bound in resolver._bindings_in(scope, expr.id):
                    got = mesh_axes_of_call(resolver, bound)
                    if got:
                        return got
        if expr.id in resolver._constants:
            return mesh_axes_of_call(resolver, resolver._constants[expr.id])
    return set()
