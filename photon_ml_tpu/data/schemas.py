"""Avro schemas matching the reference's wire/storage formats.

Reference: photon-avro-schemas/src/main/avro/*.avsc — field names, types and
union shapes mirror the reference so files written by either implementation
are mutually readable:
  - TrainingExampleAvro(uid?, response, label?, features[FeatureAvro],
    weight?, offset?, metadataMap?)
  - FeatureAvro(name, term, value)
  - BayesianLinearModelAvro(modelId, modelClass?, modelType?,
    means[NameTermValueAvro], variances?, lossFunction?)
  - NameTermValueAvro(name, term, value)
  - ScoringResultAvro(uid?, predictionScore, label?, metadataMap?)
"""

from __future__ import annotations

NAMESPACE = "com.linkedin.photon.avro.generated"

FEATURE = {
    "type": "record",
    "name": "FeatureAvro",
    "namespace": NAMESPACE,
    "fields": [
        {"name": "name", "type": "string"},
        {"name": "term", "type": "string", "default": ""},
        {"name": "value", "type": "double"},
    ],
}

TRAINING_EXAMPLE = {
    "type": "record",
    "name": "TrainingExampleAvro",
    "namespace": NAMESPACE,
    "fields": [
        {"name": "uid", "type": ["null", "string", "long", "int"], "default": None},
        {"name": "response", "type": "double"},
        {"name": "label", "type": ["null", "double"], "default": None},
        {"name": "features", "type": {"type": "array", "items": FEATURE}},
        {"name": "weight", "type": ["null", "double"], "default": None},
        {"name": "offset", "type": ["null", "double"], "default": None},
        {"name": "metadataMap", "type": ["null", {"type": "map", "values": "string"}],
         "default": None},
    ],
}

NAME_TERM_VALUE = {
    "type": "record",
    "name": "NameTermValueAvro",
    "namespace": NAMESPACE,
    "fields": [
        {"name": "name", "type": "string"},
        {"name": "term", "type": "string", "default": ""},
        {"name": "value", "type": "double"},
    ],
}

BAYESIAN_LINEAR_MODEL = {
    "type": "record",
    "name": "BayesianLinearModelAvro",
    "namespace": NAMESPACE,
    "fields": [
        {"name": "modelId", "type": "string"},
        {"name": "modelClass", "type": ["null", "string"], "default": None},
        {"name": "means", "type": {"type": "array", "items": NAME_TERM_VALUE}},
        {"name": "variances",
         "type": ["null", {"type": "array", "items": "NameTermValueAvro"}],
         "default": None},
        {"name": "lossFunction", "type": ["null", "string"], "default": None},
    ],
}

SCORING_RESULT = {
    "type": "record",
    "name": "ScoringResultAvro",
    "namespace": NAMESPACE,
    "fields": [
        {"name": "uid", "type": ["null", "string", "long", "int"], "default": None},
        {"name": "predictionScore", "type": "double"},
        {"name": "label", "type": ["null", "double"], "default": None},
        {"name": "metadataMap", "type": ["null", {"type": "map", "values": "string"}],
         "default": None},
    ],
}

# Feature-summarization output (reference FeatureSummarizationResultAvro)
FEATURE_SUMMARY = {
    "type": "record",
    "name": "FeatureSummarizationResultAvro",
    "namespace": NAMESPACE,
    "fields": [
        {"name": "name", "type": "string"},
        {"name": "term", "type": "string", "default": ""},
        {"name": "metrics", "type": {"type": "map", "values": "double"}},
    ],
}

# Truncated response-prediction input (reference ResponsePredictionAvro.avsc:
# "the only field[s] photon is expecting").
RESPONSE_PREDICTION = {
    "type": "record",
    "name": "SimplifiedResponsePrediction",
    "namespace": NAMESPACE,
    "fields": [
        {"name": "response", "type": "double"},
        {"name": "features", "type": {"type": "array", "items": FEATURE}},
        {"name": "weight", "type": "double", "default": 1.0},
        {"name": "offset", "type": "double", "default": 0.0},
    ],
}

# Matrix-factorization latent factor (reference LatentFactorAvro.avsc — a
# schema stub with no implementation behind it in the reference either,
# SURVEY.md §2.5).
LATENT_FACTOR = {
    "type": "record",
    "name": "LatentFactorAvro",
    "namespace": NAMESPACE,
    "fields": [
        {"name": "effectId", "type": "string"},
        {"name": "latentFactor", "type": {"type": "array", "items": "double"}},
    ],
}

# The reference encodes an intercept as name=(INTERCEPT), term=""
# (Constants.scala INTERCEPT_KEY).
INTERCEPT_NAME = "(INTERCEPT)"
INTERCEPT_TERM = ""
