"""Off-heap feature index store (PalDB replacement) — Python side.

Reference: photon-api .../index/PalDBIndexMap.scala:16-278 (off-heap store,
binary-search reverse lookup) + PalDBIndexMapBuilder/Loader.  Here the store
is one mmap'd file with a precomputed open-addressing table
(native/index_store.cpp); this module provides:

- ``StoreIndexMap``: IndexMap-compatible reader backed by the C++ library
  when g++ is available, else a pure-Python mmap prober on the SAME file —
  either way the key data stays off the Python heap (contrast
  ``IndexMap.load`` which materializes a dict).
- ``build_store``: writer (from an IndexMap or an iterable of keys).
"""

from __future__ import annotations

import ctypes
import mmap
import os
import struct
from typing import Iterable, List, Optional, Tuple, Union

import numpy as np

from photon_ml_tpu.data.index_map import (IndexMap, feature_key, split_key,
                                          try_feature_key)
from photon_ml_tpu.data.schemas import INTERCEPT_NAME, INTERCEPT_TERM
from photon_ml_tpu.native.build import compile_library

MAGIC2 = b"PHIDX002"

_lib = None
_lib_tried = False


def _native_lib():
    global _lib, _lib_tried
    if _lib_tried:
        return _lib
    _lib_tried = True
    path = compile_library("index_store")
    if path is None:
        return None
    lib = ctypes.CDLL(path)
    lib.phidx_build.restype = ctypes.c_int64
    lib.phidx_build.argtypes = [ctypes.c_char_p, ctypes.c_void_p,
                                ctypes.c_void_p, ctypes.c_int64]
    lib.phidx_open.restype = ctypes.c_void_p
    lib.phidx_open.argtypes = [ctypes.c_char_p]
    lib.phidx_size.restype = ctypes.c_int64
    lib.phidx_size.argtypes = [ctypes.c_void_p]
    lib.phidx_get.restype = ctypes.c_int64
    lib.phidx_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64]
    lib.phidx_get_batch.restype = None
    lib.phidx_get_batch.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                    ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p]
    lib.phidx_name.restype = ctypes.c_int64
    lib.phidx_name.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                               ctypes.POINTER(ctypes.c_void_p),
                               ctypes.POINTER(ctypes.c_int64)]
    lib.phidx_close.restype = None
    lib.phidx_close.argtypes = [ctypes.c_void_p]
    _lib = lib
    return _lib


def _pack_keys(keys: List[bytes]) -> Tuple[np.ndarray, np.ndarray]:
    offsets = np.zeros(len(keys) + 1, np.int64)
    for i, k in enumerate(keys):
        offsets[i + 1] = offsets[i] + len(k)
    blob = np.frombuffer(b"".join(keys), np.uint8) if keys else np.zeros(0, np.uint8)
    return blob.copy(), offsets


def build_store(path: str, source: Union[IndexMap, Iterable[str]]) -> None:
    """Write a PHIDX002 store from an IndexMap (id order preserved) or an
    iterable of keys (ids assigned in iteration order)."""
    if isinstance(source, IndexMap):
        rev: List[Optional[str]] = [None] * source.size
        for k, i in source.items():
            rev[i] = k
        keys = [k.encode("utf-8") for k in rev]  # type: ignore[union-attr]
    else:
        keys = [k.encode("utf-8") for k in source]
    blob, offsets = _pack_keys(keys)

    lib = _native_lib()
    if lib is not None:
        rc = lib.phidx_build(path.encode(), blob.ctypes.data, offsets.ctypes.data,
                             len(keys))
        if rc != 0:
            raise ValueError(f"phidx_build failed with code {rc} (duplicate keys?)")
        return
    _py_build(path, blob, offsets, len(keys))


# -- pure-python writer/reader on the same format ------------------------------

_FNV_OFF, _FNV_PRIME, _MASK64 = 1469598103934665603, 1099511628211, (1 << 64) - 1


def _fnv1a(data: bytes) -> int:
    h = _FNV_OFF
    for b in data:
        h = ((h ^ b) * _FNV_PRIME) & _MASK64
    return h


def _py_build(path: str, blob: np.ndarray, offsets: np.ndarray, n: int) -> None:
    table_size = 8
    while table_size < max(8, 2 * n):
        table_size <<= 1
    mask = table_size - 1
    slots = np.full(table_size, -1, np.int64)
    raw = blob.tobytes()
    for idx in range(n):
        key = raw[offsets[idx]: offsets[idx + 1]]
        i = _fnv1a(key) & mask
        while slots[i] >= 0:
            other = slots[i]
            if raw[offsets[other]: offsets[other + 1]] == key:
                raise ValueError(f"duplicate key {key!r}")
            i = (i + 1) & mask
        slots[i] = idx
    with open(path, "wb") as f:
        f.write(MAGIC2)
        f.write(struct.pack("<qq", n, table_size))
        f.write(slots.tobytes())
        f.write(offsets[: n + 1].tobytes())
        f.write(raw[: int(offsets[n])])


class StoreIndexMap:
    """IndexMap-compatible reader over a PHIDX002 store.

    Native path: C++ mmap + ctypes (zero-copy, off-heap).  Fallback: Python
    mmap with the same probing — still off-heap (no dict materialization).
    """

    def __init__(self, path: str):
        self._path = path
        self._handle = None
        self._mm: Optional[mmap.mmap] = None
        lib = _native_lib()
        if lib is not None:
            handle = lib.phidx_open(path.encode())
            if not handle:
                raise ValueError(f"{path}: cannot open PHIDX002 store")
            self._handle = handle
            self._n = int(lib.phidx_size(handle))
            return
        f = open(path, "rb")
        self._mm = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
        f.close()
        if self._mm[:8] != MAGIC2:
            raise ValueError(f"{path}: not a PHIDX002 store")
        self._n, self._table_size = struct.unpack_from("<qq", self._mm, 8)
        self._slots_off = 24
        self._offsets_off = self._slots_off + 8 * self._table_size
        self._blob_off = self._offsets_off + 8 * (self._n + 1)
        # reject truncated/corrupt stores (same checks as phidx_open)
        ts, n = self._table_size, self._n
        if n < 0 or ts < 8 or ts & (ts - 1) or n > ts or self._blob_off > len(self._mm):
            raise ValueError(f"{path}: corrupt PHIDX002 store header")
        (blob_len,) = struct.unpack_from("<q", self._mm, self._offsets_off + 8 * n)
        if blob_len < 0 or self._blob_off + blob_len > len(self._mm):
            raise ValueError(f"{path}: truncated PHIDX002 store")

    # -- IndexMap contract --------------------------------------------------

    @property
    def size(self) -> int:
        return self._n

    def get_index(self, name: str, term: str = "") -> int:
        key = try_feature_key(name, term)
        return -1 if key is None else self.get_key(key)

    def get_key(self, key: str) -> int:
        kb = key.encode("utf-8")
        if self._handle is not None:
            return int(_native_lib().phidx_get(self._handle, kb, len(kb)))
        return self._py_probe(kb)

    def get_indices(self, keys: Iterable[str]) -> np.ndarray:
        """Vectorized lookup (the data-load hot path: every (name, term) of
        every record resolves through this)."""
        enc = [k.encode("utf-8") for k in keys]
        if self._handle is not None:
            blob, offsets = _pack_keys(enc)
            out = np.empty(len(enc), np.int64)
            _native_lib().phidx_get_batch(self._handle, blob.ctypes.data,
                                          offsets.ctypes.data, len(enc),
                                          out.ctypes.data)
            return out
        return np.asarray([self._py_probe(k) for k in enc], np.int64)

    def key_blob(self):
        """(utf-8 key blob, offsets[n+1] int64) ordered by index, read
        straight out of the store's mmap — zero copies of the 1e7+ keys
        (the arrays view the mapping; numpy keeps it alive)."""
        mm = self._mm
        if mm is None:
            # native-handle instances never built the python-side view;
            # map the (already phidx_open-validated) file lazily once
            f = open(self._path, "rb")
            mm = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
            f.close()
            if mm[:8] != MAGIC2:
                raise ValueError(f"{self._path}: not a PHIDX002 store")
            n, table_size = struct.unpack_from("<qq", mm, 8)
            if n != self._n:
                raise ValueError(f"{self._path}: store changed on disk "
                                 f"({n} keys now, opened with {self._n})")
            slots_off = 24
            offsets_off = slots_off + 8 * table_size
            blob_off = offsets_off + 8 * (n + 1)
            if (n < 0 or table_size < 8 or table_size & (table_size - 1)
                    or n > table_size or blob_off > len(mm)):
                raise ValueError(f"{self._path}: corrupt PHIDX002 store header")
            (blob_len,) = struct.unpack_from("<q", mm, offsets_off + 8 * n)
            if blob_len < 0 or blob_off + blob_len > len(mm):
                raise ValueError(f"{self._path}: truncated PHIDX002 store")
            self._mm = mm
            self._table_size = table_size
            self._slots_off = slots_off
            self._offsets_off = offsets_off
            self._blob_off = blob_off
        offsets = np.frombuffer(mm, np.int64, self._n + 1,
                                offset=self._offsets_off)
        blob = np.frombuffer(mm, np.uint8, int(offsets[-1]),
                             offset=self._blob_off)
        return blob, offsets

    def get_indices_blob(self, blob: np.ndarray, offsets: np.ndarray) -> np.ndarray:
        """Batch lookup over an already-packed key blob (the native codec's
        output format) — no python strings at any point."""
        n = len(offsets) - 1
        if self._handle is not None:
            out = np.empty(n, np.int64)
            _native_lib().phidx_get_batch(
                self._handle, blob.ctypes.data, offsets.ctypes.data, n,
                out.ctypes.data)
            return out
        raw = blob.tobytes()
        return np.asarray(
            [self._py_probe(raw[offsets[i]:offsets[i + 1]]) for i in range(n)],
            np.int64)

    def get_feature_name(self, idx: int) -> Optional[Tuple[str, str]]:
        if not 0 <= idx < self._n:
            return None
        if self._handle is not None:
            lib = _native_lib()
            ptr, ln = ctypes.c_void_p(), ctypes.c_int64()
            if not lib.phidx_name(self._handle, idx, ctypes.byref(ptr), ctypes.byref(ln)):
                return None
            raw = ctypes.string_at(ptr.value, ln.value)
        else:
            o0, o1 = struct.unpack_from("<qq", self._mm, self._offsets_off + 8 * idx)
            raw = self._mm[self._blob_off + o0: self._blob_off + o1]
        return split_key(raw.decode("utf-8"))

    @property
    def intercept_index(self) -> Optional[int]:
        i = self.get_index(INTERCEPT_NAME, INTERCEPT_TERM)
        return None if i < 0 else i

    def __contains__(self, key: str) -> bool:
        return self.get_key(key) >= 0

    def _py_probe(self, key: bytes) -> int:
        mask = self._table_size - 1
        i = _fnv1a(key) & mask
        while True:
            (idx,) = struct.unpack_from("<q", self._mm, self._slots_off + 8 * i)
            if idx < 0:
                return -1
            o0, o1 = struct.unpack_from("<qq", self._mm, self._offsets_off + 8 * idx)
            if self._mm[self._blob_off + o0: self._blob_off + o1] == key:
                return int(idx)
            i = (i + 1) & mask

    def save(self, path: str) -> None:
        """Persist = copy the backing store file (drivers re-save maps next
        to trained models; IndexMap.save parity)."""
        import shutil

        if os.path.abspath(path) != os.path.abspath(self._path):
            shutil.copyfile(self._path, path)

    def close(self) -> None:
        if self._handle is not None:
            _native_lib().phidx_close(self._handle)
            self._handle = None
        if self._mm is not None:
            self._mm.close()
            self._mm = None

    def __enter__(self) -> "StoreIndexMap":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
