"""GameData -> TrainingExampleAvro export.

Reference: photon-client data/avro/AvroDataWriter.scala:159 (DataFrame ->
TrainingExample-style Avro out, re-expanding shard vectors into (name, term,
value) feature bags through the index maps).

Round-trips with ``data.reader.read_game_data_avro``: features come back
through the same index maps, id tags through the same entity indexes.
"""

from __future__ import annotations

from typing import Dict, Iterator, Optional

import numpy as np

from photon_ml_tpu.data import avro as avro_io
from photon_ml_tpu.data.index_map import IndexMap
from photon_ml_tpu.data.reader import EntityIndex
from photon_ml_tpu.data.schemas import TRAINING_EXAMPLE
from photon_ml_tpu.game.data import GameData, SparseShard


def write_game_data_avro(
    data: GameData,
    path: str,
    index_maps: Dict[str, IndexMap],
    entity_indexes: Optional[Dict[str, EntityIndex]] = None,
    shard: Optional[str] = None,
) -> int:
    """Write ``data`` as TrainingExampleAvro records; returns record count.

    ``shard``: which feature shard to expand into the record's feature bag
    (defaults to the only shard; required when several are present — the
    reference's writer likewise emits one flattened feature bag).
    Intercept columns are skipped: readers re-add them from the index map.
    """
    entity_indexes = entity_indexes or {}
    if shard is None:
        if len(index_maps) != 1:
            raise ValueError(
                f"several feature shards {sorted(index_maps)}; pass shard=")
        shard = next(iter(index_maps))
    imap = index_maps[shard]
    x = data.features[shard]
    intercept = imap.intercept_index

    def feature_bag(i: int) -> list:
        feats = []
        if isinstance(x, SparseShard):
            idxs = np.asarray(x.indices[i])
            vals = np.asarray(x.values[i])
            cols = [(int(j), float(v)) for j, v in zip(idxs, vals) if v != 0.0]
        else:
            row = np.asarray(x[i])
            cols = [(int(j), float(row[j])) for j in np.nonzero(row)[0]]
        for j, v in cols:
            if j == intercept:
                continue
            name_term = imap.get_feature_name(j)
            if name_term is None:
                continue
            feats.append({"name": name_term[0], "term": name_term[1],
                          "value": v})
        return feats

    tag_names = {tag: entity_indexes.get(tag) for tag in data.id_tags}

    def records() -> Iterator[dict]:
        for i in range(data.num_samples):
            meta = {}
            for tag, ids in data.id_tags.items():
                eid = int(ids[i])
                if eid < 0:
                    continue
                eidx = tag_names[tag]
                name = eidx.name_of(eid) if eidx is not None else None
                # None-check, not truthiness: "" is a legal entity name
                meta[tag] = name if name is not None else str(eid)
            uid = None if data.uids is None else data.uids[i]
            if uid is not None and not isinstance(uid, (str, int)):
                # numpy scalars match no Avro union branch
                uid = int(uid) if np.issubdtype(type(uid), np.integer) else str(uid)
            yield {
                "uid": uid,
                "response": float(data.y[i]),
                "label": None,
                "features": feature_bag(i),
                "weight": float(data.weight[i]),
                "offset": float(data.offset[i]),
                "metadataMap": meta or None,
            }

    n = data.num_samples
    avro_io.write_container(path, TRAINING_EXAMPLE, records())
    return n
