"""Readers producing GameData from Avro / libsvm sources.

Reference: photon-client .../data/avro/AvroDataReader.scala:54-475 (Avro ->
rows with per-shard vectors via index maps), GameConverters.scala:173
(rows -> GameDatum with id tags), io/deprecated/GLMSuite (libsvm for the
legacy driver).

Host-side, columnar output: the device only ever sees the dense design
matrices and integer id columns that GameData carries.
"""

from __future__ import annotations

import dataclasses
import json
import threading
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

from photon_ml_tpu.data.index_map import IndexMap, feature_key
from photon_ml_tpu.data.schemas import INTERCEPT_NAME
from photon_ml_tpu.game.data import GameData


class EntityIndex:
    """String entity ids -> dense int ids (grow-on-first-sight).

    The reference keeps REIds as strings everywhere; on TPU the id columns
    must be integers, so each id-tag column owns one of these.

    Thread-safe: ``get_or_add``'s grow-on-first-sight is a read-check-write
    over two fields (the dict insert and the next-id counter implied by
    ``len``), so two threads racing on a NEW key could both claim the same
    dense id — one lock around the grow (and around ``name_of``'s lazy
    reverse-table rebuild, which reads ``_fwd`` while growers mutate it)
    makes the index safe under the stream decode pool.
    """

    def __init__(self, ids: Optional[Dict[str, int]] = None):
        self._fwd: Dict[str, int] = dict(ids or {})
        self._rev: Optional[List[str]] = None
        self._lock = threading.Lock()

    def get_or_add(self, key: str) -> int:
        i = self._fwd.get(key)
        if i is None:
            with self._lock:
                i = self._fwd.get(key)  # re-check: another thread may have won
                if i is None:
                    i = len(self._fwd)
                    self._fwd[key] = i
                    self._rev = None
        return i

    def get(self, key: str) -> int:
        return self._fwd.get(key, -1)

    def name_of(self, idx: int) -> Optional[str]:
        if self._rev is None:
            with self._lock:
                rev = [""] * len(self._fwd)
                for k, i in self._fwd.items():
                    rev[i] = k
                self._rev = rev
        return self._rev[idx] if 0 <= idx < len(self._rev) else None

    @property
    def size(self) -> int:
        return len(self._fwd)

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self._fwd, f)

    @classmethod
    def load(cls, path: str) -> "EntityIndex":
        with open(path) as f:
            return cls(json.load(f))


#: Logical -> physical record field names (reference InputColumnsNames.scala:
#: the reserved columns {uid, response, offset, weight, metadataMap} may be
#: remapped to arbitrary input field names).
DEFAULT_INPUT_COLUMNS = {
    "uid": "uid",
    "response": "response",
    "offset": "offset",
    "weight": "weight",
    "metadataMap": "metadataMap",
    "features": "features",
}


def parse_input_columns(spec: str) -> Dict[str, str]:
    """Parse a remap spec 'response=clicked,features=feats' against the
    reserved logical names; identity entries are dropped (so they don't
    disable the native fast path).  Raises ValueError on unknown keys or
    physical-name collisions (two logical columns reading one field would
    silently train on the wrong data)."""
    out: Dict[str, str] = {}
    for kv in (spec or "").split(","):
        if not kv:
            continue
        k, _, v = kv.partition("=")
        if k not in DEFAULT_INPUT_COLUMNS or not v:
            raise ValueError(f"bad input-columns entry: {kv!r} "
                             f"(keys: {sorted(DEFAULT_INPUT_COLUMNS)})")
        if v != DEFAULT_INPUT_COLUMNS[k]:
            out[k] = v
    merged = {**DEFAULT_INPUT_COLUMNS, **out}
    seen: Dict[str, str] = {}
    for k, v in merged.items():
        if v in seen:
            raise ValueError(
                f"input columns {seen[v]!r} and {k!r} both read field {v!r}")
        seen[v] = k
    return out


def read_game_data_avro(
    paths: Iterable[str],
    index_maps: Dict[str, IndexMap],
    id_tag_names: Iterable[str] = (),
    entity_indexes: Optional[Dict[str, EntityIndex]] = None,
    dtype=np.float32,
    records: Optional[List[dict]] = None,
    sparse_shards: Optional[Iterable[str]] = None,
    input_columns: Optional[Dict[str, str]] = None,
) -> Tuple[GameData, Dict[str, EntityIndex]]:
    """TrainingExampleAvro files -> GameData.

    Every feature shard in ``index_maps`` gets a dense [n, d_shard] design
    matrix (intercept column filled with 1 when the map has one) — except
    shards named in ``sparse_shards``, which become row-padded SparseShard
    layouts (O(n*k) not O(n*d); the 1e6-feature scale path, SURVEY §2.7).
    ``id_tag`` values come from metadataMap[tag] (reference GameConverters
    id-tag extraction); entity string ids pass through EntityIndex.
    """
    from photon_ml_tpu.data.avro import read_directory

    cols = {**DEFAULT_INPUT_COLUMNS, **(input_columns or {})}
    default_cols = cols == DEFAULT_INPUT_COLUMNS
    sparse_shards = set(sparse_shards or ())
    if records is None:
        if default_cols:  # the native columnar loader reads reserved names
            fast = _read_game_data_columnar(paths, index_maps, id_tag_names,
                                            entity_indexes, dtype, sparse_shards)
            if fast is not None:
                return fast
        records = []
        for path in paths:
            records.extend(read_directory(path))
    n = len(records)

    y = np.zeros(n, dtype)
    offset = np.zeros(n, dtype)
    weight = np.ones(n, dtype)
    uids = np.empty(n, object)
    # Shards sharing one IndexMap object get ONE matrix filled once and
    # aliased (read-only downstream) — k identical shards would otherwise cost
    # k decode passes and k copies of an [n, d] dense block.
    groups, group_maps, group_sparse = _shard_groups(index_maps, sparse_shards)
    group_mats = {gid: np.zeros((n, m.size), dtype)
                  for gid, m in group_maps.items() if not group_sparse[gid]}
    id_tag_names = list(id_tag_names)
    entity_indexes = entity_indexes or {}
    for tag in id_tag_names:
        entity_indexes.setdefault(tag, EntityIndex())
    tags = {tag: np.full(n, -1, np.int64) for tag in id_tag_names}

    for i, rec in enumerate(records):
        fill_record_row(rec, cols, i, i, y, offset, weight, uids, tags,
                        entity_indexes, id_tag_names, group_maps,
                        group_sparse, group_mats)

    mats: Dict[str, object] = {}
    for gid, shards_of in groups.items():
        m = group_maps[gid]
        if group_sparse[gid]:
            sparse = _sparse_from_records(records, m, dtype, cols["features"])
            for shard in shards_of:
                mats[shard] = sparse
        else:
            for shard in shards_of:
                mats[shard] = group_mats[gid]

    data = GameData(y=y, features=mats, offset=offset, weight=weight, id_tags=tags,
                    uids=uids)
    return data, entity_indexes


def fill_record_row(rec, cols, row, mat_row, y, offset, weight, uids, tags,
                    entity_indexes, id_tag_names, group_maps, group_sparse,
                    mats) -> None:
    """Decode ONE TrainingExampleAvro record into row ``row`` of the columnar
    arrays and row ``mat_row`` of the per-group dense design matrices.

    The ONE place record->row semantics live: ``read_game_data_avro`` passes
    ``mat_row == row`` (matrices span the whole dataset); the streaming
    ingest (stream/ingest.py) passes a batch-local ``mat_row`` because its
    design-matrix buffers only span one device-feed batch.  Sharing the fill
    keeps the two paths bitwise-identical by construction — same float
    accumulation order, same entity-id assignment order."""
    uids[row] = rec.get(cols["uid"])
    y[row] = rec[cols["response"]]
    if rec.get(cols["offset"]) is not None:
        offset[row] = rec[cols["offset"]]
    if rec.get(cols["weight"]) is not None:
        weight[row] = rec[cols["weight"]]
    meta = rec.get(cols["metadataMap"]) or {}
    for tag in id_tag_names:
        if tag in meta:
            tags[tag][row] = entity_indexes[tag].get_or_add(str(meta[tag]))
    for gid, m in group_maps.items():
        if group_sparse[gid]:
            continue
        x = mats[gid]
        ii = m.intercept_index
        if ii is not None:
            x[mat_row, ii] = 1.0
        for feat in rec.get(cols["features"], []):
            j = m.get_index(feat["name"], feat.get("term") or "")
            if j >= 0:
                x[mat_row, j] += feat["value"]


def _shard_groups(index_maps, sparse_shards):
    """Group shards sharing one IndexMap object (one matrix per group);
    a group is sparse when any of its shards was requested sparse."""
    groups: Dict[int, List[str]] = {}
    for shard, m in index_maps.items():
        groups.setdefault(id(m), []).append(shard)
    group_maps = {gid: index_maps[shards[0]] for gid, shards in groups.items()}
    group_sparse = {gid: any(sh in sparse_shards for sh in shards)
                    for gid, shards in groups.items()}
    return groups, group_maps, group_sparse


def _sparse_from_records(records, m, dtype, features_col="features"):
    """Row-padded COO from decoded records (fallback path)."""
    from photon_ml_tpu.game.data import SparseShard

    n = len(records)
    ii = m.intercept_index
    extra = 1 if ii is not None else 0
    k = max((len(r.get(features_col) or ()) for r in records), default=0) + extra
    k = max(k, 1)
    idx = np.zeros((n, k), np.int32)
    vals = np.zeros((n, k), dtype)
    for i, rec in enumerate(records):
        p = 0
        for feat in rec.get(features_col, []):
            j = m.get_index(feat["name"], feat.get("term") or "")
            if j >= 0:
                idx[i, p] = j
                vals[i, p] = feat["value"]
                p += 1
        if ii is not None:
            idx[i, p] = ii
            vals[i, p] = 1.0
    return SparseShard(indices=idx, values=vals, dim=m.size)


def _read_game_data_columnar(paths, index_maps, id_tag_names, entity_indexes,
                             dtype, sparse_shards=frozenset()
                             ) -> Optional[Tuple[GameData, Dict[str, EntityIndex]]]:
    """Native-loader fast path: columnar decode (native/avro_loader.cpp) +
    fully vectorized assembly.  Feature keys resolve through the index map
    ONCE per unique key; the design matrices fill with one np.add.at per
    file.  Returns None (caller falls back to the record loop) when the
    native library or an eligible schema is unavailable."""
    from photon_ml_tpu.data.avro import list_avro_files
    from photon_ml_tpu.data.native_avro import load_columnar, native_available

    if not native_available():
        return None
    files = [f for p in paths for f in list_avro_files(p)]
    cols = []
    for f in files:
        c = load_columnar(f, cache=True)  # shared with index building
        if c is None:
            return None  # ineligible schema: single decode via fallback
        cols.append(c)

    n = sum(c.n for c in cols)
    y = np.zeros(n, dtype)
    offset = np.zeros(n, dtype)
    weight = np.ones(n, dtype)
    uids = np.empty(n, object)

    # shards sharing one IndexMap share one matrix (see caller docstring)
    groups, group_maps, group_sparse = _shard_groups(index_maps, sparse_shards)
    group_mats = {gid: np.zeros((n, m.size), dtype)
                  for gid, m in group_maps.items() if not group_sparse[gid]}
    # sparse groups: one row-padded COO per group, k = global max active + intercept
    k_raw = max((int(c.feat_counts.max()) if len(c.feat_counts) else 0)
                for c in cols) if cols else 0
    group_coo = {}
    for gid, m in group_maps.items():
        if group_sparse[gid]:
            extra = 1 if m.intercept_index is not None else 0
            k = max(k_raw + extra, 1)
            group_coo[gid] = (np.zeros((n, k), np.int32), np.zeros((n, k), dtype))

    id_tag_names = list(id_tag_names)
    entity_indexes = entity_indexes or {}
    for tag in id_tag_names:
        entity_indexes.setdefault(tag, EntityIndex())
    tags = {tag: np.full(n, -1, np.int64) for tag in id_tag_names}

    base = 0
    for c in cols:
        sl = slice(base, base + c.n)
        rv, lv = c.numeric_valid["response"], c.numeric_valid["label"]
        y[sl] = np.where(rv, c.numeric["response"],
                         np.where(lv, c.numeric["label"], 0.0)).astype(dtype)
        offset[sl] = np.where(c.numeric_valid["offset"], c.numeric["offset"], 0.0)
        weight[sl] = np.where(c.numeric_valid["weight"], c.numeric["weight"], 1.0)
        uids[sl] = c.uids

        rec_of_feat = base + np.repeat(np.arange(c.n), c.feat_counts)
        starts = np.concatenate([[0], np.cumsum(c.feat_counts)])
        pos_in_rec = (np.arange(len(c.feat_ids))
                      - np.repeat(starts[:-1], c.feat_counts))
        for gid, m in group_maps.items():
            ii = m.intercept_index
            col_of = m.get_indices(c.feat_table)  # UNIQUE keys only
            feat_cols = col_of[c.feat_ids] if len(c.feat_ids) else np.zeros(0, np.int64)
            ok = feat_cols >= 0
            if group_sparse[gid]:
                idx, vals = group_coo[gid]
                # padded COO: place valid features at their raw slot; invalid
                # ones stay (0, 0) which is inert (SparseBatch contract)
                idx[rec_of_feat[ok], pos_in_rec[ok]] = feat_cols[ok]
                vals[rec_of_feat[ok], pos_in_rec[ok]] = c.feat_values[ok].astype(dtype)
                if ii is not None:
                    idx[sl, -1] = ii
                    vals[sl, -1] = 1.0
            else:
                x = group_mats[gid]
                if ii is not None:
                    x[sl, ii] = 1.0
                # += accumulation for duplicate (row, col) pairs (fallback parity)
                np.add.at(x, (rec_of_feat[ok], feat_cols[ok]),
                          c.feat_values[ok].astype(dtype))

        if id_tag_names and len(c.meta_keys):
            rec_of_meta = base + np.repeat(np.arange(c.n), c.meta_counts)
            key_strs = np.asarray(c.meta_table, object)
            for tag in id_tag_names:
                matches = np.flatnonzero(key_strs == tag)
                if len(matches) == 0:
                    continue
                hit = (c.meta_keys == matches[0]) & (c.meta_vals >= 0)
                vals = c.meta_vals[hit]
                uniq = np.unique(vals)
                eidx = entity_indexes[tag]
                remap = {int(v): eidx.get_or_add(c.meta_table[v]) for v in uniq}
                tags[tag][rec_of_meta[hit]] = [remap[int(v)] for v in vals]
        base += c.n

    from photon_ml_tpu.game.data import SparseShard

    mats: Dict[str, object] = {}
    for gid, shards_of in groups.items():
        if group_sparse[gid]:
            idx, vals = group_coo[gid]
            shard_data = SparseShard(indices=idx, values=vals,
                                     dim=group_maps[gid].size)
        else:
            shard_data = group_mats[gid]
        for shard in shards_of:
            mats[shard] = shard_data

    data = GameData(y=y, features=mats, offset=offset, weight=weight,
                    id_tags=tags, uids=uids)
    return data, entity_indexes


def unique_feature_keys(paths) -> Optional[Dict[str, None]]:
    """Distinct feature keys across files via the native loader (insertion
    order preserved); None when unavailable — used by index building."""
    from photon_ml_tpu.data.avro import list_avro_files
    from photon_ml_tpu.data.native_avro import load_columnar, native_available

    if not native_available():
        return None
    out: Dict[str, None] = {}
    for p in paths:
        for f in list_avro_files(p):
            c = load_columnar(f, cache=True)  # shared with GameData assembly
            if c is None:
                return None
            for k in c.feat_table:
                out.setdefault(k)
    return out


def read_libsvm(path: str, num_features: Optional[int] = None,
                add_intercept: bool = True, binary_labels_01: bool = True,
                dtype=np.float32) -> Tuple[np.ndarray, np.ndarray, Optional[int]]:
    """Read a libsvm file (e.g. a1a): returns (X dense, y, intercept_index).

    Labels -1/+1 are mapped to 0/1 when ``binary_labels_01`` (the losses here
    use {0,1}, core/losses.py).  Indices are 1-based in the format.
    """
    rows: List[List[Tuple[int, float]]] = []
    labels: List[float] = []
    max_idx = 0
    with open(path) as f:
        for line in f:
            parts = line.split()
            if not parts:
                continue
            labels.append(float(parts[0]))
            row = []
            for tok in parts[1:]:
                k, _, v = tok.partition(":")
                j = int(k)
                max_idx = max(max_idx, j)
                row.append((j, float(v)))
            rows.append(row)
    d = max_idx if num_features is None else num_features
    if d < max_idx:
        raise ValueError(
            f"{path}: feature index {max_idx} exceeds num_features={num_features}")
    extra = 1 if add_intercept else 0
    x = np.zeros((len(rows), d + extra), dtype)
    if add_intercept:
        x[:, 0] = 1.0
    for i, row in enumerate(rows):
        for j, v in row:
            x[i, j - 1 + extra] = v
    y = np.asarray(labels, dtype)
    if binary_labels_01 and set(np.unique(y)) <= {-1.0, 1.0}:
        y = (y > 0).astype(dtype)
    return x, y, (0 if add_intercept else None)


def index_map_for_libsvm(dim: int, add_intercept: bool = True) -> IndexMap:
    """Positional index map for libsvm features (feature name = column number).

    Built directly so 1-based feature j lands at dense column j-1+intercept —
    IndexMap.build would sort keys LEXICOGRAPHICALLY ('10' < '2') and disagree
    with read_libsvm's positional layout for dim >= 10.
    """
    from photon_ml_tpu.data.schemas import INTERCEPT_NAME, INTERCEPT_TERM

    fwd = {}
    extra = 1 if add_intercept else 0
    if add_intercept:
        fwd[feature_key(INTERCEPT_NAME, INTERCEPT_TERM)] = 0
    for j in range(dim):
        fwd[feature_key(str(j + 1), "")] = j + extra
    return IndexMap(fwd)
