"""Seeded synthetic data generators.

Reference: photon-test-utils .../SparkTestUtils.scala:86-120+ (seeded draws of
dense/sparse features for binary/poisson/linear problems) and GameTestUtils
(per-entity GAME datasets).  Used by tests and benchmarks.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

from photon_ml_tpu.game.data import GameData


def generate_binary_classification(n: int, d: int, seed: int = 0, intercept: bool = True,
                                   dtype=np.float32) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Returns (x, y, w_true); logits = x @ w_true."""
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(dtype)
    if intercept:
        x[:, 0] = 1.0
    w = (rng.normal(size=d) * 0.5).astype(dtype)
    y = (rng.random(n) < 1.0 / (1.0 + np.exp(-(x @ w)))).astype(dtype)
    return x, y, w


def generate_poisson(n: int, d: int, seed: int = 0, dtype=np.float32
                     ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    rng = np.random.default_rng(seed)
    x = (rng.normal(size=(n, d)) * 0.3).astype(dtype)
    w = (rng.normal(size=d) * 0.3).astype(dtype)
    lam = np.exp(np.clip(x @ w, -10, 3))
    y = rng.poisson(lam).astype(dtype)
    return x, y, w


def generate_linear(n: int, d: int, noise: float = 0.1, seed: int = 0, dtype=np.float32
                    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(dtype)
    w = rng.normal(size=d).astype(dtype)
    y = (x @ w + noise * rng.normal(size=n)).astype(dtype)
    return x, y, w


def generate_glmix(
    n_users: int = 64,
    per_user: int = 128,
    d_global: int = 32,
    d_user: int = 8,
    n_items: Optional[int] = None,
    d_item: int = 8,
    seed: int = 0,
    dtype=np.float32,
) -> Tuple[GameData, Dict[str, np.ndarray]]:
    """2- or 3-coordinate GLMix data (fixed + per-user [+ per-item]),
    logistic response.  Returns (GameData, true parameter dict)."""
    rng = np.random.default_rng(seed)
    n = n_users * per_user
    xg = rng.normal(size=(n, d_global)).astype(dtype)
    xu = rng.normal(size=(n, d_user)).astype(dtype)
    uid = np.repeat(np.arange(n_users, dtype=np.int64), per_user)
    wg = (rng.normal(size=d_global) * 0.5).astype(dtype)
    wu = (rng.normal(size=(n_users, d_user))).astype(dtype)
    logits = xg @ wg + np.einsum("nd,nd->n", xu, wu[uid])

    features = {"global": xg, "per_user": xu}
    id_tags = {"userId": uid}
    truth = {"wg": wg, "wu": wu}

    if n_items is not None:
        xi = rng.normal(size=(n, d_item)).astype(dtype)
        iid = rng.integers(0, n_items, size=n).astype(np.int64)
        wi = rng.normal(size=(n_items, d_item)).astype(dtype)
        logits = logits + np.einsum("nd,nd->n", xi, wi[iid])
        features["per_item"] = xi
        id_tags["itemId"] = iid
        truth["wi"] = wi

    perm = rng.permutation(n)
    y = (rng.random(n) < 1.0 / (1.0 + np.exp(-logits))).astype(dtype)
    data = GameData(
        y=y[perm],
        features={k: v[perm] for k, v in features.items()},
        id_tags={k: v[perm] for k, v in id_tags.items()},
    )
    return data, truth
