"""Feature index maps: (name, term) <-> contiguous integer id.

Reference: photon-api .../index/IndexMap.scala:54 (getIndex/getFeatureName),
DefaultIndexMap/DefaultIndexMapLoader (in-memory from distinct features),
PalDBIndexMap (off-heap partitioned store for ~1e8-feature vocabularies,
PalDBIndexMap.scala:16-278) and the FeatureIndexingDriver
(photon-client .../index/FeatureIndexingDriver.scala:41-320).

TPU-native stance: the DEVICE only ever sees dense integer ids; the map is a
host-side dictionary with a compact binary file format (sorted key blob +
offsets, mmap-friendly — the PalDB replacement; a C++ loader can consume the
same format).  Keys are "name\\x1fterm" (the reference joins name.term with a
separator for model files).
"""

from __future__ import annotations

import json
import os
import struct
from typing import Dict, Iterable, Iterator, List, Optional, Tuple

from photon_ml_tpu.data.schemas import INTERCEPT_NAME, INTERCEPT_TERM

SEP = "\x1f"
MAGIC = b"PHIDX001"


def feature_key(name: str, term: str = "") -> str:
    # The separator inside a NAME would make the key ambiguous under
    # split_key/partition (term may legitimately be anything after the first
    # SEP).  Reject loudly — found by hypothesis, not a theoretical case.
    if SEP in name:
        raise ValueError(
            f"feature name {name!r} contains the reserved key separator "
            f"U+001F (index_map.SEP); rename the feature")
    return f"{name}{SEP}{term}"


def try_feature_key(name: str, term: str = "") -> Optional[str]:
    """feature_key, or None when the name is un-keyable (reserved separator).

    Lookup paths use this: a name that cannot be keyed can never be IN a map,
    so it is absent (-1) under the reference's IndexMap.NULL_KEY contract —
    only map-construction/keying paths keep feature_key's loud rejection."""
    try:
        return feature_key(name, term)
    except ValueError:
        return None


def split_key(key: str) -> Tuple[str, str]:
    name, _, term = key.partition(SEP)
    return name, term


class IndexMap:
    """Immutable feature index map (reference IndexMap contract)."""

    def __init__(self, key_to_id: Dict[str, int]):
        self._fwd = key_to_id
        self._rev: Optional[List[str]] = None

    @property
    def size(self) -> int:
        return len(self._fwd)

    def get_index(self, name: str, term: str = "") -> int:
        """-1 if absent (reference IndexMap.NULL_KEY semantics)."""
        key = try_feature_key(name, term)
        return -1 if key is None else self._fwd.get(key, -1)

    def get_feature_name(self, idx: int) -> Optional[Tuple[str, str]]:
        if self._rev is None:
            rev = [""] * len(self._fwd)
            for k, i in self._fwd.items():
                rev[i] = k
            self._rev = rev
        if 0 <= idx < len(self._rev):
            return split_key(self._rev[idx])
        return None

    @property
    def intercept_index(self) -> Optional[int]:
        i = self.get_index(INTERCEPT_NAME, INTERCEPT_TERM)
        return None if i < 0 else i

    def __contains__(self, key: str) -> bool:
        return key in self._fwd

    def get_indices(self, keys) -> "np.ndarray":
        """Vectorized key lookup (-1 missing) — same surface as the native
        StoreIndexMap, so readers can batch-resolve either kind."""
        import numpy as np

        get = self._fwd.get
        return np.asarray([get(k, -1) for k in keys], np.int64)

    def items(self) -> Iterator[Tuple[str, int]]:
        return iter(self._fwd.items())

    def key_blob(self):
        """(utf-8 key blob, offsets[n+1] int64) ordered by index — the bulk
        boundary format shared with the native store/codec (one flat buffer
        instead of n python strings); cached per instance."""
        import numpy as np

        cached = getattr(self, "_key_blob", None)
        if cached is not None:
            return cached
        rev = [b""] * len(self._fwd)
        for k, i in self._fwd.items():
            rev[i] = k.encode("utf-8")
        offs = np.zeros(len(rev) + 1, np.int64)
        np.cumsum([len(b) for b in rev], out=offs[1:])
        blob = np.frombuffer(b"".join(rev), np.uint8)
        self._key_blob = (blob, offs)
        return self._key_blob

    # -- builders -----------------------------------------------------------

    @classmethod
    def build(cls, keys: Iterable[str], add_intercept: bool = True) -> "IndexMap":
        """Deterministic map: intercept first (if requested), then sorted keys
        (the reference sorts per-partition then offsets; sorted-global is the
        single-host equivalent and is reproducible)."""
        uniq = sorted(set(keys))
        fwd: Dict[str, int] = {}
        if add_intercept:
            fwd[feature_key(INTERCEPT_NAME, INTERCEPT_TERM)] = 0
        for k in uniq:
            if k not in fwd:
                fwd[k] = len(fwd)
        return cls(fwd)

    @classmethod
    def from_features(cls, features: Iterable[Tuple[str, str]], add_intercept: bool = True
                      ) -> "IndexMap":
        return cls.build((feature_key(n, t) for n, t in features), add_intercept)

    # -- binary store (PalDB replacement) -----------------------------------

    def save(self, path: str) -> None:
        """Compact binary layout: header, id-ordered key blob + offset table."""
        rev = [""] * len(self._fwd)
        for k, i in self._fwd.items():
            rev[i] = k
        blob = bytearray()
        offsets = []
        for k in rev:
            offsets.append(len(blob))
            blob.extend(k.encode("utf-8"))
        offsets.append(len(blob))
        with open(path, "wb") as f:
            f.write(MAGIC)
            f.write(struct.pack("<q", len(rev)))
            f.write(struct.pack(f"<{len(offsets)}q", *offsets))
            f.write(bytes(blob))

    @classmethod
    def load(cls, path: str) -> "IndexMap":
        with open(path, "rb") as f:
            data = f.read()
        if data[:8] != MAGIC:
            raise ValueError(f"{path}: not a photon index map")
        (n,) = struct.unpack_from("<q", data, 8)
        offsets = struct.unpack_from(f"<{n + 1}q", data, 16)
        base = 16 + 8 * (n + 1)
        fwd = {}
        for i in range(n):
            fwd[data[base + offsets[i]: base + offsets[i + 1]].decode("utf-8")] = i
        return cls(fwd)


def load_index(path: str):
    """Open an index file of either format, dispatching on its magic bytes:
    PHIDX001 (compact, dict-loaded) or PHIDX002 (mmap off-heap store —
    the PalDB-equivalent, data/native_index.py)."""
    with open(path, "rb") as f:
        magic = f.read(8)
    if magic == MAGIC:
        return IndexMap.load(path)
    from photon_ml_tpu.data.native_index import MAGIC2, StoreIndexMap

    if magic == MAGIC2:
        return StoreIndexMap(path)
    raise ValueError(f"{path}: unknown index map format {magic!r}")


def build_index_maps_from_records(
    records: Iterable[dict],
    shards: Iterable[str],
    add_intercept: bool = True,
    features_col: str = "features",
) -> Dict[str, IndexMap]:
    """Build per-shard IndexMaps from already-decoded TrainingExampleAvro
    records.  The single-bag Avro layout puts every feature in every shard,
    so ONE map is built and shared (IndexMap is immutable); per-bag shard
    filtering (reference FeatureShardConfiguration) lands with the multi-bag
    reader."""
    seen: set = set()
    for rec in records:
        for feat in rec.get(features_col, []):
            seen.add(feature_key(feat["name"], feat.get("term") or ""))
    shared = IndexMap.build(seen, add_intercept)
    return {shard: shared for shard in shards}


def build_index_maps_from_avro(
    paths: Iterable[str],
    feature_bags: Dict[str, List[str]],
    add_intercept: bool = True,
) -> Dict[str, IndexMap]:
    """Scan TrainingExampleAvro files and build IndexMaps (see
    build_index_maps_from_records; ``feature_bags`` keys = shard names)."""
    from photon_ml_tpu.data.avro import read_directory
    from photon_ml_tpu.data.reader import unique_feature_keys

    keys = unique_feature_keys(paths)  # native columnar scan when available
    if keys is not None:
        shared = IndexMap.build(keys, add_intercept)
        return {shard: shared for shard in feature_bags}

    def all_records():
        for path in paths:
            yield from read_directory(path)

    return build_index_maps_from_records(all_records(), list(feature_bags), add_intercept)
