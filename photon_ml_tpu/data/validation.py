"""Row-level input validation.

Reference: photon-client .../data/DataValidators.scala:405 — per-task checks
(finite features/offset/weight, binary labels for logistic/hinge, non-negative
labels for poisson) with modes VALIDATE_FULL / VALIDATE_SAMPLE /
VALIDATE_DISABLED (DataValidationType.scala:23).
"""

from __future__ import annotations

import enum
from typing import List

import numpy as np

from photon_ml_tpu.game.data import GameData
from photon_ml_tpu.types import TaskType


class DataValidationType(enum.Enum):
    VALIDATE_FULL = "validate_full"
    VALIDATE_SAMPLE = "validate_sample"
    VALIDATE_DISABLED = "validate_disabled"


SAMPLE_FRACTION = 0.1


def _is_device_array(x) -> bool:
    import jax

    return isinstance(x, jax.Array)


def validate_game_data(data: GameData, task: TaskType,
                       mode: DataValidationType = DataValidationType.VALIDATE_FULL,
                       seed: int = 0,
                       allow_zero_weight: bool = False) -> List[str]:
    """Returns a list of human-readable violations (empty = valid).

    Raises nothing itself — drivers decide (the reference throws on the first
    failed check; CLI callers here do the same on a non-empty list).

    ``allow_zero_weight`` relaxes the positive-weight rule to nonnegative:
    the streamed skip policy marks rows lost to malformed chunks inert at
    weight 0 (so ``n`` never silently shrinks), and those rows must not fail
    the run the policy just saved.
    """
    if mode == DataValidationType.VALIDATE_DISABLED:
        return []
    n = data.num_samples
    if mode == DataValidationType.VALIDATE_SAMPLE and n > 0:
        rng = np.random.default_rng(seed)
        idx = rng.choice(n, size=max(1, int(n * SAMPLE_FRACTION)), replace=False)
    else:
        idx = slice(None)

    errors: List[str] = []
    y = np.asarray(data.y)[idx]
    offset = np.asarray(data.offset)[idx]
    weight = np.asarray(data.weight)[idx]

    if not np.all(np.isfinite(y)):
        errors.append("labels contain non-finite values")
    if not np.all(np.isfinite(offset)):
        errors.append("offsets contain non-finite values")
    if not np.all(np.isfinite(weight)):
        errors.append("weights contain non-finite values")
    if allow_zero_weight:
        if np.any(weight < 0):
            errors.append("weights must be nonnegative")
    elif np.any(weight <= 0):
        errors.append("weights must be positive (reference: zero/negative weight rows rejected)")

    for shard, x in data.features.items():
        if _is_device_array(x):
            # streamed device-assembled shard: pulling [n, d] to host here
            # would defeat out-of-core ingest — it was finite-checked per
            # chunk at decode time (stream/ingest.py validate=True)
            continue
        arr = x.values if hasattr(x, "indices") else np.asarray(x)  # SparseShard
        if not np.all(np.isfinite(np.asarray(arr)[idx])):
            errors.append(f"feature shard {shard!r} contains non-finite values")

    if task in (TaskType.LOGISTIC_REGRESSION, TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM):
        if not np.all(np.isin(y, (0.0, 1.0))):
            errors.append(f"{task.value}: labels must be binary 0/1")
    elif task == TaskType.POISSON_REGRESSION:
        if np.any(y < 0):
            errors.append("poisson_regression: labels must be non-negative")
    return errors
