from photon_ml_tpu.data.index_map import IndexMap, feature_key, split_key  # noqa: F401
from photon_ml_tpu.data.reader import (  # noqa: F401
    EntityIndex,
    read_game_data_avro,
    read_libsvm,
    index_map_for_libsvm,
)
from photon_ml_tpu.data.validation import (  # noqa: F401
    DataValidationType,
    validate_game_data,
)
from photon_ml_tpu.data.synthetic import (  # noqa: F401
    generate_binary_classification,
    generate_poisson,
    generate_linear,
    generate_glmix,
)
from photon_ml_tpu.data.writer import write_game_data_avro  # noqa: F401
