"""Self-contained Avro binary codec + object-container-file reader/writer.

The execution image has no avro library, and the reference's wire/storage
formats are Avro (photon-avro-schemas/src/main/avro/*.avsc; AvroUtils.scala,
AvroDataReader.scala) — so the codec lives here, implemented from the Avro
1.x specification: zigzag-varint ints/longs, little-endian float/double,
length-prefixed strings/bytes, index-prefixed unions, block-encoded
arrays/maps, and the ``Obj\\x01`` container framing with a metadata map and
16-byte sync markers.  Supports null/deflate codecs, generic schema-driven
decode (reader uses the writer schema embedded in the header, as the spec
requires).

This is the Python fallback; the C++ extension (native/) accelerates the
hot TrainingExample decode path when built.
"""

from __future__ import annotations

import dataclasses
import io
import json
import os
import struct
import zlib
from typing import Any, BinaryIO, Dict, Iterable, Iterator, List, Optional, Union

Schema = Union[str, dict, list]

MAGIC = b"Obj\x01"


# ---------------------------------------------------------------------------
# primitive codecs
# ---------------------------------------------------------------------------


def _encode_long(n: int, out: bytearray) -> None:
    n = (n << 1) ^ (n >> 63)  # zigzag
    while (n & ~0x7F) != 0:
        out.append((n & 0x7F) | 0x80)
        n >>= 7
    out.append(n & 0x7F)


def _decode_long(buf: memoryview, pos: int) -> tuple[int, int]:
    shift = 0
    acc = 0
    while True:
        b = buf[pos]
        pos += 1
        acc |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
    return (acc >> 1) ^ -(acc & 1), pos


class _Reader:
    __slots__ = ("buf", "pos")

    def __init__(self, data: bytes):
        self.buf = memoryview(data)
        self.pos = 0

    def long(self) -> int:
        v, self.pos = _decode_long(self.buf, self.pos)
        return v

    def raw(self, n: int) -> bytes:
        b = bytes(self.buf[self.pos: self.pos + n])
        self.pos += n
        return b

    def string(self) -> str:
        return self.raw(self.long()).decode("utf-8")

    def bytes_(self) -> bytes:
        return self.raw(self.long())

    def float_(self) -> float:
        (v,) = struct.unpack_from("<f", self.buf, self.pos)
        self.pos += 4
        return v

    def double(self) -> float:
        (v,) = struct.unpack_from("<d", self.buf, self.pos)
        self.pos += 8
        return v

    def boolean(self) -> bool:
        b = self.buf[self.pos]
        self.pos += 1
        return b != 0


# ---------------------------------------------------------------------------
# schema-driven decode / encode
# ---------------------------------------------------------------------------


def _schema_type(schema: Schema) -> str:
    if isinstance(schema, str):
        return schema
    if isinstance(schema, list):
        return "union"
    return schema["type"]


def decode(schema: Schema, r: _Reader, named: Dict[str, dict]) -> Any:
    t = _schema_type(schema)
    if t == "null":
        return None
    if t == "boolean":
        return r.boolean()
    if t in ("int", "long"):
        return r.long()
    if t == "float":
        return r.float_()
    if t == "double":
        return r.double()
    if t == "string":
        return r.string()
    if t == "bytes":
        return r.bytes_()
    if t == "union":
        idx = r.long()
        return decode(schema[idx], r, named)
    if t == "record":
        _register(schema, named)
        return {f["name"]: decode(f["type"], r, named) for f in schema["fields"]}
    if t == "array":
        out: List[Any] = []
        while True:
            count = r.long()
            if count == 0:
                break
            if count < 0:
                r.long()  # block byte size, unused
                count = -count
            for _ in range(count):
                out.append(decode(schema["items"], r, named))
        return out
    if t == "map":
        m: Dict[str, Any] = {}
        while True:
            count = r.long()
            if count == 0:
                break
            if count < 0:
                r.long()
                count = -count
            for _ in range(count):
                k = r.string()
                m[k] = decode(schema["values"], r, named)
        return m
    if t == "enum":
        _register(schema, named)
        return schema["symbols"][r.long()]
    if t == "fixed":
        _register(schema, named)
        return r.raw(schema["size"])
    # named-type reference
    if t in named:
        return decode(named[t], r, named)
    raise ValueError(f"unsupported avro schema type {t!r}")


def _register(schema: dict, named: Dict[str, dict]) -> None:
    name = schema.get("name")
    if name:
        ns = schema.get("namespace")
        named[name] = schema
        if ns:
            named[f"{ns}.{name}"] = schema


def _union_index(schema: list, value: Any) -> int:
    def matches(s: Schema, v: Any) -> bool:
        t = _schema_type(s)
        if t == "null":
            return v is None
        if t == "boolean":
            return isinstance(v, bool)
        if t in ("int", "long"):
            return isinstance(v, int) and not isinstance(v, bool)
        if t in ("float", "double"):
            return isinstance(v, float) or (isinstance(v, int) and not isinstance(v, bool))
        if t == "string":
            return isinstance(v, str)
        if t == "bytes":
            return isinstance(v, bytes)
        if t == "record":
            return isinstance(v, dict)
        if t == "array":
            return isinstance(v, list)
        if t == "map":
            return isinstance(v, dict)
        if t == "enum":
            return isinstance(v, str)
        return False

    for i, s in enumerate(schema):
        if matches(s, value):
            return i
    raise ValueError(f"value {value!r} matches no branch of union {schema!r}")


def encode(schema: Schema, value: Any, out: bytearray, named: Dict[str, dict]) -> None:
    t = _schema_type(schema)
    if t == "null":
        return
    if t == "boolean":
        out.append(1 if value else 0)
        return
    if t in ("int", "long"):
        _encode_long(int(value), out)
        return
    if t == "float":
        out.extend(struct.pack("<f", value))
        return
    if t == "double":
        out.extend(struct.pack("<d", value))
        return
    if t == "string":
        b = value.encode("utf-8")
        _encode_long(len(b), out)
        out.extend(b)
        return
    if t == "bytes":
        _encode_long(len(value), out)
        out.extend(value)
        return
    if t == "union":
        idx = _union_index(schema, value)
        _encode_long(idx, out)
        encode(schema[idx], value, out, named)
        return
    if t == "record":
        _register(schema, named)
        for f in schema["fields"]:
            if f["name"] not in value and "default" in f:
                encode(f["type"], f["default"], out, named)
            else:
                encode(f["type"], value[f["name"]], out, named)
        return
    if t == "array":
        if value:
            _encode_long(len(value), out)
            for item in value:
                encode(schema["items"], item, out, named)
        _encode_long(0, out)
        return
    if t == "map":
        if value:
            _encode_long(len(value), out)
            for k, v in value.items():
                encode("string", k, out, named)
                encode(schema["values"], v, out, named)
        _encode_long(0, out)
        return
    if t == "enum":
        _register(schema, named)
        _encode_long(schema["symbols"].index(value), out)
        return
    if t == "fixed":
        out.extend(value)
        return
    if t in named:
        encode(named[t], value, out, named)
        return
    raise ValueError(f"unsupported avro schema type {t!r}")


# ---------------------------------------------------------------------------
# object container files
# ---------------------------------------------------------------------------

_META_SCHEMA: Schema = {"type": "map", "values": "bytes"}


def read_container(path: str) -> Iterator[dict]:
    """Iterate records of an Avro object container file (null/deflate codecs)."""
    schema, blocks = read_container_raw(path)
    named: Dict[str, dict] = {}
    for count, block in blocks:
        br = _Reader(block)
        for _ in range(count):
            yield decode(schema, br, named)


def read_schema(path: str) -> dict:
    """Read just the writer schema from a container file header."""
    with open(path, "rb") as f:
        data = f.read(1 << 20)
    r = _Reader(data)
    if r.raw(4) != MAGIC:
        raise ValueError(f"{path}: not an Avro container file")
    meta = decode(_META_SCHEMA, r, {})
    raw = meta["avro.schema"]
    return json.loads(raw if isinstance(raw, (str, bytes)) else bytes(raw))


def _write_header(f, schema: Schema, codec: str, sync: bytes, named) -> None:
    """Container header framing — the ONE home shared by write_container
    and write_container_raw."""
    f.write(MAGIC)
    header = bytearray()
    meta = {"avro.schema": json.dumps(schema).encode(),
            "avro.codec": codec.encode()}
    encode(_META_SCHEMA, meta, header, named)
    f.write(bytes(header))
    f.write(sync)


def write_container(path: str, schema: Schema, records: Iterable[dict],
                    codec: str = "deflate", sync: bytes = b"photon-ml-tpu-sm",
                    block_records: int = 4096) -> int:
    """Write records to an Avro object container file; returns record count."""
    assert len(sync) == 16
    named: Dict[str, dict] = {}
    n_total = 0
    with open(path, "wb") as f:
        _write_header(f, schema, codec, sync, named)

        block = bytearray()
        n_block = 0

        def flush():
            nonlocal block, n_block
            if n_block == 0:
                return
            payload = bytes(block)
            if codec == "deflate":
                comp = zlib.compressobj(wbits=-15)
                payload = comp.compress(payload) + comp.flush()
            head = bytearray()
            _encode_long(n_block, head)
            _encode_long(len(payload), head)
            f.write(bytes(head))
            f.write(payload)
            f.write(sync)
            block = bytearray()
            n_block = 0

        for rec in records:
            encode(schema, rec, block, named)
            n_block += 1
            n_total += 1
            if n_block >= block_records:
                flush()
        flush()
    return n_total


def list_avro_files(path: str) -> List[str]:
    """A file itself, or the sorted .avro part-files under a directory."""
    if os.path.isfile(path):
        return [path]
    return [os.path.join(path, name) for name in sorted(os.listdir(path))
            if name.endswith(".avro")]


def read_directory(path: str) -> Iterator[dict]:
    """Read all .avro files under a directory (the reference reads
    part-files from an HDFS dir, AvroUtils.readAvroFiles)."""
    for f in list_avro_files(path):
        yield from read_container(f)


def write_container_raw(path: str, schema: Schema, encoded_records,
                        codec: str = "deflate",
                        sync: bytes = b"photon-ml-tpu-sm",
                        block_records: int = 4096) -> int:
    """Write PRE-ENCODED record bodies (bytes each) into a container file —
    the native-codec fast path's framing half (the generic ``write_container``
    encodes python dicts; this skips straight to block assembly).  Bodies
    batch into blocks of ``block_records`` like the generic writer (one
    deflate stream + sync marker per block, not per record)."""
    assert len(sync) == 16
    named: Dict[str, dict] = {}
    n_total = 0
    with open(path, "wb") as f:
        _write_header(f, schema, codec, sync, named)
        block = bytearray()
        n_block = 0

        def flush():
            nonlocal block, n_block
            if n_block == 0:
                return
            payload = bytes(block)
            if codec == "deflate":
                comp = zlib.compressobj(wbits=-15)
                payload = comp.compress(payload) + comp.flush()
            head = bytearray()
            _encode_long(n_block, head)
            _encode_long(len(payload), head)
            f.write(bytes(head))
            f.write(payload)
            f.write(sync)
            block = bytearray()
            n_block = 0

        for body in encoded_records:
            block += body
            n_block += 1
            n_total += 1
            if n_block >= block_records:
                flush()
        flush()
    return n_total


@dataclasses.dataclass
class BlockSpan:
    """One container block located by ``scan_container_blocks``.

    ``offset``/``size`` frame the COMPRESSED payload (the count/size varints
    precede ``offset``; the 16-byte sync marker follows ``offset + size``).
    ``count`` is the record count from the block header, or -1 when the
    header itself is truncated (record count unknowable).  ``torn`` marks a
    block whose header or payload extends past end-of-file.
    """

    offset: int
    size: int
    count: int
    torn: bool = False


@dataclasses.dataclass
class ContainerInfo:
    """Header + block map of one container file (``scan_container_blocks``)."""

    path: str
    schema: dict
    codec: str
    sync: bytes
    blocks: List[BlockSpan]

    @property
    def num_records(self) -> int:
        """Records with a KNOWN count (torn-header blocks excluded)."""
        return sum(b.count for b in self.blocks if b.count >= 0)


def scan_container_blocks(path: str) -> ContainerInfo:
    """Seek-based block-span scan: header + per-block (offset, size, count)
    WITHOUT reading payloads — the streaming reader's shard map.

    Unlike ``read_container_raw`` (whole file in memory), this walks only the
    ~20-byte block headers, so a multi-GB part-file costs a few KB of reads.
    Truncation surfaces as a ``torn`` final span instead of an exception:
    EOF inside the count/size varints gives ``count == -1`` (rows
    unknowable), EOF inside the payload/sync keeps the header's count (the
    skip policy can then preserve the dataset row count).  The scan stops at
    the first torn block — whatever follows a truncation is unframed bytes.
    """
    file_size = os.path.getsize(path)
    with open(path, "rb") as f:
        head = f.read(min(file_size, 1 << 20))
        r = _Reader(head)
        if r.raw(4) != MAGIC:
            raise ValueError(f"{path}: not an Avro container file")
        try:
            meta = decode(_META_SCHEMA, r, {})
            sync = r.raw(16)
        except IndexError:
            # metadata map longer than the 1MB probe: decode from the full
            # file (rare — the header holds a schema, not data)
            f.seek(0)
            r = _Reader(f.read())
            r.raw(4)
            meta = decode(_META_SCHEMA, r, {})
            sync = r.raw(16)
        raw = meta["avro.schema"]
        schema = json.loads(raw if isinstance(raw, (str, bytes)) else bytes(raw))
        codec = meta.get("avro.codec", b"null").decode()
        if len(sync) != 16:
            raise ValueError(f"{path}: truncated container header")

        blocks: List[BlockSpan] = []
        pos = r.pos
        while pos < file_size:
            f.seek(pos)
            hr = _Reader(f.read(32))  # two varints: at most 20 bytes
            try:
                count = hr.long()
                size = hr.long()
            except IndexError:
                blocks.append(BlockSpan(offset=pos, size=file_size - pos,
                                        count=-1, torn=True))
                break
            data_off = pos + hr.pos
            if count < 0 or size < 0:
                blocks.append(BlockSpan(offset=data_off, size=size,
                                        count=-1, torn=True))
                break
            if data_off + size + 16 > file_size:
                # payload or sync truncated: the count survives, the bytes
                # don't — downstream policy decides raise vs skip-with-count
                blocks.append(BlockSpan(offset=data_off, size=size,
                                        count=count, torn=True))
                break
            blocks.append(BlockSpan(offset=data_off, size=size, count=count))
            pos = data_off + size + 16
    return ContainerInfo(path=path, schema=schema, codec=codec, sync=sync,
                         blocks=blocks)


def read_block(path: str, span: BlockSpan, codec: str, sync: bytes) -> bytes:
    """One block's DECOMPRESSED record bytes, sync-verified.

    The streaming decode worker's read: seek + bounded read of exactly one
    block, so concurrent workers never share file state and host memory
    holds only in-flight blocks.  Raises ValueError for torn spans, sync
    mismatches, and unknown codecs — one block's corruption is one chunk's
    error, never a whole-file abort (that policy lives in the pipeline).
    """
    if span.torn:
        raise ValueError(f"{path}: torn block at offset {span.offset} "
                         f"({span.count if span.count >= 0 else 'unknown'}"
                         " records lost to truncation)")
    with open(path, "rb") as f:
        f.seek(span.offset)
        payload = f.read(span.size)
        marker = f.read(16)
    if len(payload) < span.size or marker != sync:
        raise ValueError(f"{path}: sync marker mismatch at offset "
                         f"{span.offset} (corrupt block)")
    if codec == "deflate":
        return zlib.decompress(payload, -15)
    if codec != "null":
        raise ValueError(f"unsupported avro codec {codec!r}")
    return payload


def read_container_raw(path: str):
    """Yield decompressed (record_count, raw_block_bytes) pairs plus the
    writer schema: returns (schema, iterator) — the native-codec fast
    path's read half.  Callers must decode records out of each block
    themselves (records are concatenated with no framing)."""
    with open(path, "rb") as f:
        data = f.read()
    r = _Reader(data)
    if r.raw(4) != MAGIC:
        raise ValueError(f"{path}: not an Avro container file")
    meta = decode(_META_SCHEMA, r, {})
    schema = json.loads(meta["avro.schema"])
    codec = meta.get("avro.codec", b"null").decode()
    sync = r.raw(16)

    def blocks():
        while r.pos < len(data):
            count = r.long()
            size = r.long()
            block = r.raw(size)
            if codec == "deflate":
                block = zlib.decompress(block, -15)
            elif codec != "null":
                raise ValueError(f"unsupported avro codec {codec!r}")
            if r.raw(16) != sync:
                raise ValueError(f"{path}: sync marker mismatch (corrupt file)")
            yield count, block

    return schema, blocks()
