"""Native Avro data-loader — Python side.

Compiles the container file's embedded WRITER SCHEMA into the int32 pre-order
tree the C++ decoder walks (native/avro_loader.cpp), tagging the
TrainingExample-shaped fields with capture roles.  Decoding returns columnar
numpy arrays with all strings interned — the per-record Python work of the
fallback codec (data/avro.py) disappears, and feature-name -> column-id
resolution becomes one vectorized lookup over UNIQUE keys.

Eligibility is structural, not by name matching the full schema: any
top-level record qualifies; recognized field names (uid/response/label/
offset/weight/features/metadataMap) capture, everything else is decoded
generically and discarded.  Ineligible shapes (recursive named types) fall
back to the Python codec.
"""

from __future__ import annotations

import ctypes
import dataclasses
from typing import Dict, List, Optional

import numpy as np

from photon_ml_tpu.data.avro import read_schema
from photon_ml_tpu.native.build import compile_library

# ---- type codes / roles (keep in sync with native/avro_loader.cpp) ----------
T_NULL, T_BOOL, T_INT, T_LONG, T_FLOAT, T_DOUBLE, T_STRING, T_BYTES = range(8)
T_UNION, T_ARRAY, T_MAP, T_RECORD, T_ENUM, T_FIXED = range(8, 14)

R_NONE = 0
# numeric capture columns (role = R_NUM0 + column)
R_NUM0 = 1
NUM_FIELDS = {"response": 0, "label": 1, "offset": 2, "weight": 3}
R_UID_LONG, R_UID_STR = 10, 11
R_FEAT_ARRAY, R_FEAT_NAME, R_FEAT_TERM, R_FEAT_VALUE = 20, 21, 22, 23
R_META_MAP, R_META_KEY, R_META_VALUE = 30, 31, 32

_PRIMS = {"null": T_NULL, "boolean": T_BOOL, "int": T_INT, "long": T_LONG,
          "float": T_FLOAT, "double": T_DOUBLE, "string": T_STRING,
          "bytes": T_BYTES}

_lib = None
_lib_tried = False


def _native_lib():
    global _lib, _lib_tried
    if _lib_tried:
        return _lib
    _lib_tried = True
    path = compile_library("avro_loader")
    if path is None:
        return None
    lib = ctypes.CDLL(path)
    lib.avl_open.restype = ctypes.c_void_p
    lib.avl_open.argtypes = [ctypes.c_char_p, ctypes.c_void_p, ctypes.c_int64]
    lib.avl_num_records.restype = ctypes.c_int64
    lib.avl_num_records.argtypes = [ctypes.c_void_p]
    pp_d = ctypes.POINTER(ctypes.c_double)
    pp_u8 = ctypes.POINTER(ctypes.c_uint8)
    pp_i32 = ctypes.POINTER(ctypes.c_int32)
    pp_i64 = ctypes.POINTER(ctypes.c_int64)
    lib.avl_numeric_col.restype = ctypes.c_int64
    lib.avl_numeric_col.argtypes = [ctypes.c_void_p, ctypes.c_int32,
                                    ctypes.POINTER(pp_d), ctypes.POINTER(pp_u8)]
    lib.avl_uid.restype = ctypes.c_int64
    lib.avl_uid.argtypes = [ctypes.c_void_p, ctypes.POINTER(pp_i64),
                            ctypes.POINTER(pp_u8)]
    lib.avl_features.restype = ctypes.c_int64
    lib.avl_features.argtypes = [ctypes.c_void_p, ctypes.POINTER(pp_i32),
                                 ctypes.POINTER(pp_i32), ctypes.POINTER(pp_d)]
    lib.avl_feature_table.restype = ctypes.c_int64
    lib.avl_feature_table.argtypes = [ctypes.c_void_p, ctypes.POINTER(pp_u8),
                                      ctypes.POINTER(pp_i64)]
    lib.avl_meta.restype = ctypes.c_int64
    lib.avl_meta.argtypes = [ctypes.c_void_p, ctypes.POINTER(pp_i32),
                             ctypes.POINTER(pp_i32), ctypes.POINTER(pp_i32)]
    lib.avl_meta_table.restype = ctypes.c_int64
    lib.avl_meta_table.argtypes = [ctypes.c_void_p, ctypes.POINTER(pp_u8),
                                   ctypes.POINTER(pp_i64)]
    lib.avl_uid_table.restype = ctypes.c_int64
    lib.avl_uid_table.argtypes = [ctypes.c_void_p, ctypes.POINTER(pp_u8),
                                  ctypes.POINTER(pp_i64)]
    lib.avl_close.restype = None
    lib.avl_close.argtypes = [ctypes.c_void_p]
    _lib = lib
    return _lib


def native_available() -> bool:
    return _native_lib() is not None


# (path, mtime, size) -> ColumnarFile.  Lets index building and GameData
# assembly share ONE decode per file (the "decode training data ONCE"
# invariant); callers clear it when the data is assembled.
_cache: Dict[tuple, "ColumnarFile"] = {}


def clear_columnar_cache() -> None:
    _cache.clear()


def schema_eligible(path: str) -> bool:
    """Cheap check (header read + tree compile only — no decode)."""
    if not native_available():
        return False
    try:
        compile_schema(read_schema(path))
        return True
    except Exception:
        return False


# ---- schema -> int32 tree ----------------------------------------------------


class _Ineligible(Exception):
    pass


def _compile_type(schema, out: List[int], role: int, named: Dict[str, dict],
                  seen: tuple) -> None:
    if isinstance(schema, str):
        if schema in _PRIMS:
            out.extend([_PRIMS[schema], role])
            return
        if schema in named:
            if schema in seen:
                raise _Ineligible(f"recursive named type {schema}")
            _compile_type(named[schema], out, role, named, seen + (schema,))
            return
        raise _Ineligible(f"unknown type {schema!r}")
    if isinstance(schema, list):
        out.extend([T_UNION, role, len(schema)])
        for branch in schema:
            # roles distribute over union branches (e.g. nullable numerics)
            _compile_type(branch, out, role, named, seen)
        return
    t = schema["type"]
    if t in _PRIMS and len(schema) <= 2:
        out.extend([_PRIMS[t], role])
        return
    if t in ("record", "error"):
        name = schema.get("name")
        if name:
            if name in seen:
                raise _Ineligible(f"recursive named type {name}")
            named.setdefault(name, schema)
            seen = seen + (name,)
        fields = schema.get("fields", [])
        out.extend([T_RECORD, role, len(fields)])
        for f in fields:
            _compile_type(f["type"], out, R_NONE, named, seen)
        return
    if t == "array":
        out.extend([T_ARRAY, role])
        _compile_type(schema["items"], out, R_NONE, named, seen)
        return
    if t == "map":
        out.extend([T_MAP, role])
        _compile_type(schema["values"], out, R_NONE, named, seen)
        return
    if t == "enum":
        named.setdefault(schema.get("name", ""), schema)
        out.extend([T_ENUM, role])
        return
    if t == "fixed":
        named.setdefault(schema.get("name", ""), schema)
        out.extend([T_FIXED, role, int(schema["size"])])
        return
    _compile_type(t, out, role, named, seen)  # {"type": <nested>}


def _resolve(schema, named: Dict[str, dict]):
    """Follow string references / {"type": ...} wrappers to a concrete node."""
    while True:
        if isinstance(schema, str) and schema in named:
            schema = named[schema]
        elif isinstance(schema, dict) and isinstance(schema.get("type"), (dict, list)) \
                and len(schema) == 1:
            schema = schema["type"]
        else:
            return schema


def compile_schema(schema: dict) -> np.ndarray:
    """Writer schema -> role-tagged int32 tree; raises _Ineligible on shapes
    the C++ walker cannot handle (recursion)."""
    named: Dict[str, dict] = {}
    schema = _resolve(schema, named)
    if not (isinstance(schema, dict) and schema.get("type") == "record"):
        raise _Ineligible("top-level schema is not a record")
    if schema.get("name"):
        named.setdefault(schema["name"], schema)

    out: List[int] = []
    fields = schema.get("fields", [])
    out.extend([T_RECORD, R_NONE, len(fields)])
    for f in fields:
        fname, ftype = f["name"], f["type"]
        if fname in NUM_FIELDS:
            _compile_with_role(ftype, out, R_NUM0 + NUM_FIELDS[fname],
                               {"long": None, "int": None}, named)
        elif fname == "uid":
            _compile_uid(ftype, out, named)
        elif fname == "features":
            _compile_features(ftype, out, named)
        elif fname == "metadataMap":
            _compile_meta(ftype, out, named)
        else:
            _compile_type(ftype, out, R_NONE, named, ())
    return np.asarray(out, np.int32)


def _compile_with_role(ftype, out, role, _unused, named) -> None:
    """Numeric field (possibly nullable union): role lands on every numeric
    branch; null branches capture nothing."""
    if isinstance(ftype, list):
        out.extend([T_UNION, R_NONE, len(ftype)])
        for b in ftype:
            b_res = _resolve(b, named)
            is_num = b_res in ("int", "long", "float", "double", "boolean")
            _compile_type(b, out, role if is_num else R_NONE, named, ())
        return
    _compile_type(ftype, out, role, named, ())


def _compile_uid(ftype, out, named) -> None:
    if isinstance(ftype, list):
        out.extend([T_UNION, R_NONE, len(ftype)])
        for b in ftype:
            b_res = _resolve(b, named)
            if b_res in ("int", "long"):
                _compile_type(b, out, R_UID_LONG, named, ())
            elif b_res == "string":
                _compile_type(b, out, R_UID_STR, named, ())
            else:
                _compile_type(b, out, R_NONE, named, ())
        return
    res = _resolve(ftype, named)
    role = R_UID_LONG if res in ("int", "long") else (
        R_UID_STR if res == "string" else R_NONE)
    _compile_type(ftype, out, role, named, ())


def _compile_features(ftype, out, named) -> None:
    res = _resolve(ftype, named)
    if isinstance(res, list):  # nullable array
        out.extend([T_UNION, R_NONE, len(res)])
        for b in res:
            br = _resolve(b, named)
            if isinstance(br, dict) and br.get("type") == "array":
                _compile_feature_array(br, out, named)
            else:
                _compile_type(b, out, R_NONE, named, ())
        return
    if isinstance(res, dict) and res.get("type") == "array":
        _compile_feature_array(res, out, named)
        return
    _compile_type(ftype, out, R_NONE, named, ())


def _compile_feature_array(arr_schema, out, named) -> None:
    item = _resolve(arr_schema["items"], named)
    if not (isinstance(item, dict) and item.get("type") == "record"):
        _compile_type(arr_schema, out, R_NONE, named, ())
        return
    out.extend([T_ARRAY, R_FEAT_ARRAY])
    fields = item.get("fields", [])
    if item.get("name"):
        named.setdefault(item["name"], item)
    out.extend([T_RECORD, R_NONE, len(fields)])
    for f in fields:
        fname = f["name"]
        if fname == "name":
            _compile_string_role(f["type"], out, R_FEAT_NAME, named)
        elif fname == "term":
            _compile_string_role(f["type"], out, R_FEAT_TERM, named)
        elif fname == "value":
            _compile_with_role(f["type"], out, R_FEAT_VALUE, None, named)
        else:
            _compile_type(f["type"], out, R_NONE, named, ())


def _compile_string_role(ftype, out, role, named) -> None:
    if isinstance(ftype, list):
        out.extend([T_UNION, R_NONE, len(ftype)])
        for b in ftype:
            _compile_type(b, out, role if _resolve(b, named) == "string" else R_NONE,
                          named, ())
        return
    _compile_type(ftype, out, role if _resolve(ftype, named) == "string" else R_NONE,
                  named, ())


def _compile_meta(ftype, out, named) -> None:
    res = _resolve(ftype, named)
    if isinstance(res, list):
        out.extend([T_UNION, R_NONE, len(res)])
        for b in res:
            br = _resolve(b, named)
            if isinstance(br, dict) and br.get("type") == "map":
                out.extend([T_MAP, R_META_MAP])
                _compile_string_role(br["values"], out, R_META_VALUE, named)
            else:
                _compile_type(b, out, R_NONE, named, ())
        return
    if isinstance(res, dict) and res.get("type") == "map":
        out.extend([T_MAP, R_META_MAP])
        _compile_string_role(res["values"], out, R_META_VALUE, named)
        return
    _compile_type(ftype, out, R_NONE, named, ())


# ---- decode ------------------------------------------------------------------


@dataclasses.dataclass
class ColumnarFile:
    """One container file decoded to columns (all numpy, zero per-record
    Python objects)."""

    n: int
    numeric: Dict[str, np.ndarray]       # field -> f64 values
    numeric_valid: Dict[str, np.ndarray]  # field -> bool present-mask
    uids: np.ndarray                     # object array (int/str/None)
    feat_counts: np.ndarray              # [n] int32
    feat_ids: np.ndarray                 # [total] int32 into feat_table
    feat_values: np.ndarray              # [total] f64
    feat_table: List[str]                # interned "name\x1fterm" keys
    meta_counts: np.ndarray              # [n] int32
    meta_keys: np.ndarray                # [entries] int32 into meta_table
    meta_vals: np.ndarray                # [entries] int32 (-1 = null value)
    meta_table: List[str]


def _table(lib, fn, handle) -> List[str]:
    blob = ctypes.POINTER(ctypes.c_uint8)()
    offs = ctypes.POINTER(ctypes.c_int64)()
    count = fn(handle, ctypes.byref(blob), ctypes.byref(offs))
    if count == 0:
        return []
    offsets = np.ctypeslib.as_array(offs, shape=(count + 1,))
    raw = bytes(np.ctypeslib.as_array(blob, shape=(int(offsets[-1]),))) if offsets[-1] else b""
    return [raw[offsets[i]: offsets[i + 1]].decode("utf-8") for i in range(count)]


def load_columnar(path: str, cache: bool = False) -> Optional[ColumnarFile]:
    """Decode one container file natively; None when the library is missing
    or the schema shape is ineligible (callers fall back to data/avro.py).

    ``cache=True`` memoizes by (path, mtime, size) so a pipeline that needs
    both the feature vocabulary and the data pays ONE decode per file."""
    lib = _native_lib()
    if lib is None:
        return None
    key = None
    if cache:
        import os

        st = os.stat(path)
        key = (path, st.st_mtime_ns, st.st_size)
        hit = _cache.get(key)
        if hit is not None:
            return hit
    try:
        tree = compile_schema(read_schema(path))
    except _Ineligible:
        return None
    handle = lib.avl_open(path.encode(), tree.ctypes.data, len(tree))
    if not handle:
        return None
    try:
        n = int(lib.avl_num_records(handle))

        numeric, valid = {}, {}
        for field, col in NUM_FIELDS.items():
            pv = ctypes.POINTER(ctypes.c_double)()
            pm = ctypes.POINTER(ctypes.c_uint8)()
            lib.avl_numeric_col(handle, col, ctypes.byref(pv), ctypes.byref(pm))
            numeric[field] = (np.ctypeslib.as_array(pv, shape=(n,)).copy()
                              if n else np.zeros(0))
            valid[field] = (np.ctypeslib.as_array(pm, shape=(n,)).copy().astype(bool)
                            if n else np.zeros(0, bool))

        pu = ctypes.POINTER(ctypes.c_int64)()
        pk = ctypes.POINTER(ctypes.c_uint8)()
        lib.avl_uid(handle, ctypes.byref(pu), ctypes.byref(pk))
        uid_raw = np.ctypeslib.as_array(pu, shape=(n,)).copy() if n else np.zeros(0, np.int64)
        uid_kind = np.ctypeslib.as_array(pk, shape=(n,)).copy() if n else np.zeros(0, np.uint8)

        pc = ctypes.POINTER(ctypes.c_int32)()
        pi = ctypes.POINTER(ctypes.c_int32)()
        pvv = ctypes.POINTER(ctypes.c_double)()
        total = int(lib.avl_features(handle, ctypes.byref(pc), ctypes.byref(pi),
                                     ctypes.byref(pvv)))
        feat_counts = np.ctypeslib.as_array(pc, shape=(n,)).copy() if n else np.zeros(0, np.int32)
        feat_ids = np.ctypeslib.as_array(pi, shape=(total,)).copy() if total else np.zeros(0, np.int32)
        feat_values = np.ctypeslib.as_array(pvv, shape=(total,)).copy() if total else np.zeros(0)
        feat_table = _table(lib, lib.avl_feature_table, handle)

        pmc = ctypes.POINTER(ctypes.c_int32)()
        pmk = ctypes.POINTER(ctypes.c_int32)()
        pmv = ctypes.POINTER(ctypes.c_int32)()
        entries = int(lib.avl_meta(handle, ctypes.byref(pmc), ctypes.byref(pmk),
                                   ctypes.byref(pmv)))
        meta_counts = np.ctypeslib.as_array(pmc, shape=(n,)).copy() if n else np.zeros(0, np.int32)
        meta_keys = np.ctypeslib.as_array(pmk, shape=(entries,)).copy() if entries else np.zeros(0, np.int32)
        meta_vals = np.ctypeslib.as_array(pmv, shape=(entries,)).copy() if entries else np.zeros(0, np.int32)
        meta_table = _table(lib, lib.avl_meta_table, handle)
        uid_table = _table(lib, lib.avl_uid_table, handle)

        uids = np.empty(n, object)
        for i in range(n):  # small: uid decode only (kinds are rare-branch)
            k = uid_kind[i]
            uids[i] = (int(uid_raw[i]) if k == 1
                       else uid_table[uid_raw[i]] if k == 2 else None)

        out = ColumnarFile(
            n=n, numeric=numeric, numeric_valid=valid, uids=uids,
            feat_counts=feat_counts, feat_ids=feat_ids, feat_values=feat_values,
            feat_table=feat_table, meta_counts=meta_counts, meta_keys=meta_keys,
            meta_vals=meta_vals, meta_table=meta_table)
        if key is not None:
            _cache[key] = out
        return out
    finally:
        lib.avl_close(handle)
