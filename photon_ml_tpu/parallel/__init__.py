from photon_ml_tpu.parallel.mesh import (  # noqa: F401
    DATA_AXIS,
    ENTITY_AXIS,
    FEATURE_AXIS,
    make_mesh,
    padded_dim,
    shard_batch,
    shard_coefficients,
    replicate,
)
from photon_ml_tpu.parallel.fixed import fit_fixed_effect  # noqa: F401
from photon_ml_tpu.parallel.bucketing import (  # noqa: F401
    EntityBuckets,
    bucket_by_entity,
    fit_random_effects,
    score_random_effects,
)
from photon_ml_tpu.parallel.multihost import (  # noqa: F401
    build_re_scoring,
    export_local_random_effects,
    global_batch_from_local,
    global_entity_buckets,
    global_mesh,
    initialize,
    local_entity_rows,
    multihost_glmix_sweep,
    pad_local_rows,
    padded_per_host_rows,
    process_entity_assignment,
    process_row_range,
)
