from photon_ml_tpu.parallel.mesh import (  # noqa: F401
    DATA_AXIS,
    ENTITY_AXIS,
    make_mesh,
    shard_batch,
    replicate,
)
from photon_ml_tpu.parallel.fixed import fit_fixed_effect  # noqa: F401
from photon_ml_tpu.parallel.bucketing import (  # noqa: F401
    EntityBuckets,
    bucket_by_entity,
    fit_random_effects,
    score_random_effects,
)
