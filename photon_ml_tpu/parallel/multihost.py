"""Multi-host (multi-process) SPMD: initialization, global meshes, and
process-local data placement.

Reference analog: the Spark driver/executor cluster — Netty RPC broadcast +
treeAggregate over the cluster network (SURVEY.md §5 "Distributed
communication backend").  TPU-native shape: every host runs THIS SAME
program under ``jax.distributed``; collectives ride ICI within a slice and
DCN across slices, inserted by XLA from the sharding annotations.  There is
no driver process — the "driver loop" (coordinate descent) runs identically
on every host, operating on globally-sharded arrays.

Data loading is split by sample id BEFORE reading (each host reads only its
row range — the reference's executor-partitioned Avro read), PADDED to the
balanced per-host row count (padding rows carry weight 0, so they are inert
in every objective/metric), then assembled into global arrays with
``jax.make_array_from_process_local_data``.

The recipe (each host runs the same code):

    initialize(...)                      # no-op for a single process
    mesh = global_mesh()
    rows = padded_per_host_rows(n, mesh)
    start, stop = process_row_range(n)
    block = load_rows(start, stop)       # host-local read
    block = pad_local_rows(block, rows)  # weight column padded with 0
    g = global_batch_from_local(block, mesh)

Cross-process scope (tested in tests/test_parallel.py
::test_multihost_two_processes and ::test_multihost_glmix_four_processes):
the fixed-effect solve runs multihost both data-parallel
(ShardMapObjective — the one DCN all-reduce) and FEATURE-SHARDED
(ShardSparseObjective, w blocked over the within-process feature axis).
RANDOM-EFFECT coordinates run multihost via ENTITY-sharded reads: every
entity's samples are owned by exactly one host
(``process_entity_assignment`` — the deterministic-hash analog of the
reference's shuffle into balanced entity partitions,
RandomEffectDatasetPartitioner.scala:30-171), each host buckets its own
entities locally (``parallel/bucketing.py`` with global ``row_ids``), the
hosts agree on global bucket shapes with one tiny metadata all-gather
(``global_entity_buckets``), and the entity-lane arrays assemble into
globally-sharded buckets with ``jax.make_array_from_process_local_data``.
``multihost_glmix_sweep`` then runs residual coordinate descent (fixed +
random effects) with every score vector a global device array."""

from __future__ import annotations

import logging
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from photon_ml_tpu.parallel.mesh import DATA_AXIS, ENTITY_AXIS, FEATURE_AXIS

Array = jax.Array
logger = logging.getLogger(__name__)


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None,
               expected_processes: Optional[int] = None) -> None:
    """Bring up the jax.distributed runtime.

    Explicit ``num_processes <= 1`` is a no-op.  With no arguments,
    auto-detection is attempted (TPU pods infer everything from the
    environment); if no cluster environment is found this degenerates to
    single-process — at WARNING level, because on a real pod that means N
    independent jobs training divergent models.  Pass
    ``expected_processes`` to turn a short job into a hard error (the
    recommended pod setting)."""
    if num_processes is not None and num_processes <= 1:
        if expected_processes is not None and expected_processes != num_processes:
            raise RuntimeError(
                f"expected {expected_processes} processes but launched with "
                f"num_processes={num_processes}")
        return
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    try:
        jax.distributed.initialize(**kwargs)
    except (RuntimeError, ValueError) as e:
        if kwargs:
            raise  # explicit cluster config that fails must be loud
        logger.warning("no cluster environment detected (%s); running "
                       "single-process", e)
    got = jax.process_count()
    want = expected_processes if expected_processes is not None else num_processes
    if want is not None and got != want:
        raise RuntimeError(
            f"expected {want} processes but jax.process_count() == {got}: "
            "the cluster did not form (check coordinator address / pod env)")


def global_mesh(n_entity: int = 1, n_feature: int = 1) -> Mesh:
    """A (data, entity, feature) mesh over ALL processes' devices, laid out
    so collectives ride the right interconnect tier.

    ICI/DCN mapping (the multi-slice story, SURVEY §5): the ``entity`` and
    ``feature`` axes are placed INNERMOST WITHIN each process's (slice's)
    devices, so their collectives — the per-evaluation feature-axis margin
    psum of the sharded sparse objective, the entity-lane layouts — always
    ride ICI.  Only the ``data`` axis strides ACROSS processes, so the one
    gradient all-reduce per objective evaluation is the only collective that
    ever touches DCN — exactly the reference's cluster-network role
    (treeAggregate over Spark executors), and DP gradient all-reduce is the
    one collective that amortizes DCN latency well.

    Within a single process this degenerates to ``make_mesh``'s layout.
    """
    devices = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
    n = len(devices)
    n_proc = jax.process_count()
    local = n // n_proc
    cell = n_entity * n_feature
    if n % cell:
        raise ValueError(
            f"{n} global devices not divisible by entity*feature = {cell}")
    if n_proc > 1 and local % cell:
        raise ValueError(
            f"entity*feature = {cell} does not fit within one process's "
            f"{local} devices — entity/feature collectives must stay on ICI "
            "(within a slice); shrink those axes or grow the slice")
    arr = (np.asarray(devices)
           .reshape(n // cell, n_entity, n_feature))
    return Mesh(arr, (DATA_AXIS, ENTITY_AXIS, FEATURE_AXIS))


def process_row_range(n: int,
                      process_id: Optional[int] = None,
                      num_processes: Optional[int] = None) -> Tuple[int, int]:
    """[start, stop) of the global sample rows THIS host should read.

    Contiguous row split by process id; the last host's range is short when
    ``n`` doesn't divide (pad with ``pad_local_rows`` before assembly).
    """
    pid = jax.process_index() if process_id is None else process_id
    np_ = jax.process_count() if num_processes is None else num_processes
    if not 0 <= pid < np_:
        raise ValueError(f"process id {pid} out of range for {np_} processes")
    per = -(-n // np_)  # ceil: every host but the last reads `per` rows
    start = min(pid * per, n)
    stop = min(start + per, n)
    return start, stop


def padded_per_host_rows(n: int, mesh: Mesh,
                         num_processes: Optional[int] = None) -> int:
    """Per-host row count every host must pad its block to: ceil(n / hosts)
    rounded up so each host's rows divide its share of the data axis."""
    np_ = jax.process_count() if num_processes is None else num_processes
    per = -(-n // np_)
    data_size = mesh.shape[DATA_AXIS]
    if data_size % np_:
        raise ValueError(
            f"data axis ({data_size}) must be divisible by the process "
            f"count ({np_}) — one host cannot own a fraction of a device row")
    local_devices = data_size // np_
    return -(-per // local_devices) * local_devices


def pad_local_rows(block: Dict[str, np.ndarray], rows: int) -> Dict[str, np.ndarray]:
    """Zero-pad every column's leading dim to ``rows`` (weight columns pad
    with 0, making the extra rows inert everywhere)."""
    from photon_ml_tpu.parallel.mesh import _pad_rows

    out = {}
    for name, a in block.items():
        try:
            out[name] = _pad_rows(np.asarray(a), rows)
        except ValueError as e:
            raise ValueError(f"column {name!r}: {e}") from e
    return out


def global_batch_from_local(
    local: Dict[str, np.ndarray],
    mesh: Mesh,
    specs: Optional[Dict[str, PartitionSpec]] = None,
) -> Dict[str, Array]:
    """Host-local row blocks -> globally data-sharded device arrays.

    Every host must pass the same keys with the SAME per-host row count
    (use ``padded_per_host_rows`` + ``pad_local_rows``); rows concatenate
    across hosts in process order.  ``specs`` overrides the default
    row-sharded PartitionSpec per key (e.g. ``{"x": P(DATA_AXIS,
    FEATURE_AXIS)}`` for a feature-sharded design matrix).
    """
    specs = specs or {}
    n_proc = jax.process_count()
    out: Dict[str, Array] = {}
    for name, a in local.items():
        a = np.asarray(a)
        spec = specs.get(name,
                         PartitionSpec(DATA_AXIS, *([None] * (a.ndim - 1))))
        sharding = NamedSharding(mesh, spec)
        global_shape = (a.shape[0] * n_proc,) + a.shape[1:]
        out[name] = jax.make_array_from_process_local_data(
            sharding, a, global_shape=global_shape)
    return out


# ---------------------------------------------------------------------------
# Random effects across hosts: entity-sharded reads -> host-local bucketing
# -> globally-sharded entity lanes.  Reference analog: the shuffle of
# per-entity data into balanced partitions (RandomEffectDataset.scala:302-341,
# RandomEffectDatasetPartitioner.scala:30-171).  TPU-native shape: there is
# no shuffle fabric — ownership is decided BEFORE the read by a deterministic
# hash of the entity id, every host keeps only its entities' rows (carrying
# their GLOBAL sample ids), and the per-host buckets concatenate into global
# [E, S, d] lane arrays whose entity axis is sharded over the whole mesh.
# ---------------------------------------------------------------------------


def process_entity_assignment(entity_ids: np.ndarray,
                              num_processes: Optional[int] = None,
                              seed: int = 0) -> np.ndarray:
    """Owning process id per sample, by deterministic hash of the entity id.

    Every host computes the same assignment with no global view — the
    shuffle-free analog of the reference's entity partitioner; with many
    entities the load balances statistically (the reference balances by
    exact counts because a Spark shuffle is already paying for the global
    pass, RandomEffectDatasetPartitioner.scala:68-117)."""
    from photon_ml_tpu.parallel.bucketing import _splitmix64

    np_ = jax.process_count() if num_processes is None else num_processes
    ids = np.asarray(entity_ids, np.int64).astype(np.uint64)
    return (_splitmix64(ids ^ np.uint64(seed)) % np.uint64(np_)).astype(np.int64)


def local_entity_rows(entity_ids: np.ndarray,
                      process_id: Optional[int] = None,
                      num_processes: Optional[int] = None,
                      seed: int = 0) -> np.ndarray:
    """GLOBAL row ids of the samples THIS host owns for a random-effect
    coordinate (its entities' rows).  Feed the filtered columns plus these
    ids into ``bucket_by_entity(..., row_ids=..., num_samples=n_global)``."""
    pid = jax.process_index() if process_id is None else process_id
    owner = process_entity_assignment(entity_ids, num_processes, seed)
    return np.nonzero(owner == pid)[0].astype(np.int64)


def global_entity_buckets(local, mesh: Mesh, projections=None):
    """Host-local EntityBuckets -> globally-sharded EntityBuckets.

    Every host calls this with ITS entities' buckets (built with global
    ``row_ids``/``num_samples``).  One metadata all-gather agrees on the
    union of capacity classes, the per-host lane count of each, and — for
    COMPACT buckets — the class's compact width; then every field assembles
    via ``make_array_from_process_local_data`` with the entity lane sharded
    over ALL mesh devices (the layout ``fit_random_effects`` solves under).
    The returned ``lane_of`` maps THIS host's entities to (bucket, GLOBAL
    lane); ``num_entities`` is the global total.  Hosts missing a capacity
    class contribute all-padding lanes (weight 0, entity -1) — inert by the
    core masking contract.

    ``projections``: the per-bucket BucketProjection list from
    ``bucket_by_entity_sparse`` (wide-vocabulary compact buckets: design
    blocks are [E, S, d_obs], never [E, S, vocab]).  Per-host compact
    widths differ, so the agreement pass takes the max per class and each
    host zero-pads its blocks (padded columns carry index -1 / value 0 —
    margin-inert).  Returns ``(global_buckets, padded_projections)`` in
    this mode; projections stay HOST-LOCAL (publish back-projects each
    host's own lanes — ``export_local_random_effects``)."""
    from jax.experimental import multihost_utils

    from photon_ml_tpu.parallel.bucketing import Bucket, EntityBuckets

    n_proc = jax.process_count()
    pid = jax.process_index()
    n_dev = mesh.size
    if n_dev % n_proc:
        raise ValueError(f"{n_dev} devices not divisible by {n_proc} processes")
    ldc = n_dev // n_proc  # per-host device share of the entity lane

    # 1. agree on capacity classes + per-host lane counts + compact widths
    #    (tiny all-gather: two ints per log2-capacity per host)
    MAXLOG = 33
    vec = np.zeros((MAXLOG, 2), np.int64)
    by_cap = {}
    for local_bi, b in enumerate(local.buckets):
        c = int(b.capacity)
        log = c.bit_length() - 1
        if (1 << log) != c:
            raise ValueError(f"bucket capacity {c} is not a power of two")
        vec[log, 0] = b.num_lanes
        vec[log, 1] = b.x.shape[2]
        by_cap[c] = (local_bi, b)
    if local.compact and projections is None:
        # the explicit marker, NOT width comparison: a padded compact width
        # can equal dim while lane column j still means "j-th observed
        # feature" (EntityBuckets.compact docstring)
        raise ValueError(
            "compact buckets need their projections: pass "
            "bucket_by_entity_sparse's BucketProjection list so the "
            "agreement pass can align per-host compact widths and export "
            "can back-project to the full vocabulary")
    # process_allgather returns the input shape unchanged when n_proc == 1
    # (no leading process axis is prepended) — normalize both gathers to
    # [n_proc, ...] so the per-host indexing below holds either way
    all_vec = np.asarray(multihost_utils.process_allgather(vec)
                         ).reshape((n_proc,) + vec.shape)
    ent_counts = np.asarray(multihost_utils.process_allgather(
        np.asarray([local.num_entities], np.int64))).reshape(n_proc, 1)
    num_entities_global = int(ent_counts.sum())

    shard = NamedSharding(mesh, PartitionSpec(tuple(mesh.axis_names)))
    buckets = []
    padded_projections = []
    lane_of: Dict[int, Tuple[int, int]] = {}
    dtype = (local.buckets[0].x.dtype if local.buckets else np.float32)
    for log in range(MAXLOG):
        host_lanes = all_vec[:, log, 0]
        if not host_lanes.any():
            continue
        cap = 1 << log
        per_host = int(-(-host_lanes.max() // ldc) * ldc)
        local_bi, b = by_cap.get(cap, (None, None))
        d = (int(all_vec[:, log, 1].max()) if projections is not None
             else local.dim)

        def _pad2(a, fill, shape_tail, dt):
            """Pad lanes AND (for 3-d design blocks) trailing compact dim."""
            out = np.full((per_host,) + shape_tail, fill, dt)
            if a is not None:
                if a.ndim == 3:
                    out[: a.shape[0], :, : a.shape[2]] = a
                elif a.ndim == 2:
                    out[: a.shape[0], : a.shape[1]] = a
                else:
                    out[: a.shape[0]] = a
            return out

        fields = dict(
            x=_pad2(b.x if b else None, 0, (cap, d), dtype),
            y=_pad2(b.y if b else None, 0, (cap,), dtype),
            offset=_pad2(b.offset if b else None, 0, (cap,), dtype),
            weight=_pad2(b.weight if b else None, 0, (cap,), dtype),
            rows=_pad2(b.rows if b else None, -1, (cap,), np.int32),
            counts=_pad2(b.counts if b else None, 0, (), np.int32),
            entity_lanes=_pad2(b.entity_lanes if b else None, -1, (),
                               np.int64),
        )
        g = {
            k: jax.make_array_from_process_local_data(
                shard, a, global_shape=(per_host * n_proc,) + a.shape[1:])
            for k, a in fields.items()
        }
        bi = len(buckets)
        if b is not None:
            for eid, (lbi, lane) in local.lane_of.items():
                if lbi == local_bi:
                    lane_of[eid] = (bi, pid * per_host + lane)
        buckets.append(Bucket(**g))
        if projections is not None:
            from photon_ml_tpu.parallel.projection import BucketProjection

            p = projections[local_bi] if b is not None else None
            idx = _pad2(p.indices if p is not None else None, -1, (d,),
                        np.int32)
            padded_projections.append(
                BucketProjection(indices=idx, d_full=local.dim))
    out = EntityBuckets(buckets=buckets, lane_of=lane_of, dim=local.dim,
                        num_entities=num_entities_global,
                        num_samples=local.num_samples,
                        compact=local.compact)
    if projections is not None:
        return out, padded_projections
    return out


def build_re_scoring(global_train, local_scoring, mesh: Mesh):
    """Multihost analog of the reference's PASSIVE data path: samples capped
    out of an entity's training reservoir still get scored with the entity's
    model (RandomEffectDataset passiveData; RandomEffectCoordinate.scala:
    210-231).  ``local_scoring``: THIS host's UNCAPPED buckets (same entity
    filter, ``active_cap=None``, global ``row_ids``).  Returns
    ``(global_scoring_buckets, coeff_idx)`` where ``coeff_idx[bi]`` maps each
    scoring lane to its entity's row in the CONCATENATED training lane
    arrays (-1 for padding lanes) — the cross-bucket coefficient gather
    ``multihost_glmix_sweep`` scores with."""
    if global_train.compact:
        raise ValueError(
            "passive scoring does not compose with COMPACT training buckets "
            "(each lane's coefficients live in its own observed-column "
            "basis); omit the reservoir cap for compact multihost "
            "coordinates, so the training buckets score every sample")
    bases = np.cumsum([0] + [b.num_lanes for b in global_train.buckets])
    flat_of = {eid: int(bases[bi] + lane)
               for eid, (bi, lane) in global_train.lane_of.items()}
    gs = global_entity_buckets(local_scoring, mesh)
    n_proc = jax.process_count()
    shard = NamedSharding(mesh, PartitionSpec(tuple(mesh.axis_names)))
    coeff_idx = []
    for b in gs.buckets:
        per_host = b.num_lanes // n_proc
        local_lanes = np.full((per_host,), -1, np.int32)
        for eid, (bi2, glane) in gs.lane_of.items():
            if gs.buckets[bi2] is b:
                local_lanes[glane - jax.process_index() * per_host] = \
                    flat_of.get(eid, -1)
        coeff_idx.append(jax.make_array_from_process_local_data(
            shard, local_lanes, global_shape=(b.num_lanes,)))
    return gs, coeff_idx


def multihost_glmix_sweep(
    mesh: Mesh,
    fixed_batch,
    re_buckets,
    fixed_objective,
    re_objective,
    num_iterations: int = 2,
    optimizer=None,
    config=None,
    re_scoring=None,
    num_samples: Optional[int] = None,
    on_iteration=None,
    initial=None,
    start_iteration: int = 0,
):
    """Residual coordinate descent (one fixed + one random-effect
    coordinate) where EVERY score vector is a global device array — the
    multihost GLMix training loop (reference CoordinateDescent.scala:197-204
    run on a cluster; here the same program runs on every host and XLA's
    collectives replace the shuffle/broadcast).

    ``fixed_batch``: globally row-sharded DenseBatch (``global_batch_from_
    local``); its ``offset`` is the base offset.  ``re_buckets``: globally
    entity-sharded EntityBuckets (``global_entity_buckets``) whose
    ``Bucket.rows`` carry GLOBAL sample ids into the fixed batch's row
    space.  Update order per iteration: fixed (offsets += RE scores), then
    random effects (offsets += fixed margins) — the 2-coordinate residual
    schedule of game/descent.py.

    ``re_scoring``: optional ``build_re_scoring`` result — under a
    reservoir cap, RE scores come from the UNCAPPED scoring buckets (the
    reference's passive-data path), not just the training rows; without it
    the training buckets score (exact when no cap drops rows).

    ``num_samples``: the TRUE global sample count ``n``.  ``Bucket.rows``
    carry ORIGINAL global row ids (so reservoir decisions stay
    topology-invariant), but the fixed batch lives in the PADDED per-host
    layout — whenever ceil(n/nproc) is not a multiple of the per-host
    data-device count the two row spaces differ, and every gather/scatter
    here translates original -> padded ids.  Required; the two tests'
    sizes aligning by accident is exactly the trap.

    MULTIPLE random-effect coordinates (the reference's per-user +
    per-item GLMix shape): pass ``re_buckets`` as an ORDERED dict
    {cid: EntityBuckets} — ``re_objective`` then takes a matching dict (or
    one shared objective) and ``re_scoring`` a dict of ``build_re_scoring``
    results; the update schedule becomes fixed, then each RE coordinate in
    dict order, every one training against the residual of ALL others
    (CoordinateDescent.scala:197-204).  Returns dicts in this mode.

    Checkpoint/resume (the multihost twin of storage/checkpoint.py's
    mid-job resume): ``on_iteration(it, w_fixed, re_coeffs)`` fires after
    every completed iteration with the live device values — the CLI driver
    writes per-host npz checkpoints from it.  To resume, pass
    ``initial=(w_fixed_host, {cid: [host-local lane blocks per bucket]})``
    (each host ITS OWN addressable blocks, as saved) plus
    ``start_iteration``; RE scores are recomputed from the loaded
    coefficients, so the resumed trajectory equals the uninterrupted one.

    Normalization rides the objectives (shared contexts, the reference's
    NormalizationContextBroadcast semantics): solves run transformed,
    every exchanged score carries eff(w) + the margin shift (margins are
    invariant), and the returned coefficients stay in SOLVER space — the
    caller publishes original-space via
    ``norm.model_to_original_space`` / ``export_local_random_effects(
    norm=...)``.  Compact buckets refuse non-identity normalization
    (per-lane projected contexts are the single-process path's domain).

    Returns ``(w_fixed, re_coeffs, re_scores)`` — replicated fixed
    coefficients, per-bucket GLOBAL [E, d] lane coefficients, and the
    final replicated RE score vector(s)."""
    import functools

    from photon_ml_tpu.opt.solve import make_solver
    from photon_ml_tpu.parallel.fixed import ShardMapObjective
    from photon_ml_tpu.types import OptimizerType

    single = not isinstance(re_buckets, dict)
    re_b = {"__re__": re_buckets} if single else dict(re_buckets)
    if isinstance(re_objective, dict):
        if set(re_objective) != set(re_b):
            raise ValueError("re_objective keys must match re_buckets keys")
        re_obj = dict(re_objective)
    else:
        re_obj = {cid: re_objective for cid in re_b}
    if re_scoring is None:
        re_sc = {}
    elif single:
        re_sc = {"__re__": re_scoring}
    else:
        re_sc = dict(re_scoring)
        unknown = set(re_sc) - set(re_b)
        if unknown:
            # a misspelled key would silently fall back to scoring with the
            # CAPPED training buckets — the exact failure mode the passive
            # path exists to prevent
            raise ValueError(f"re_scoring keys {sorted(unknown)} not in "
                             f"re_buckets {sorted(re_b)}")

    # Normalization rides the objectives (the single-process shared-context
    # semantics: solve transformed, margins invariant): the fixed margins
    # and RE scores below carry eff(w) + the margin shift, and the CALLER
    # publishes original-space coefficients (export_local_random_effects
    # norm=/model_to_original_space).  Compact buckets would need PER-LANE
    # projected contexts — refused, like the sparse feature-sharded fixed
    # objective refuses shifts.
    for cid, rb in re_b.items():
        o = re_obj[cid]
        if rb.compact and (o.norm.factors is not None
                           or o.norm.shifts is not None):
            raise ValueError(
                f"multihost coordinate {cid!r}: normalization with COMPACT "
                "(observed-column) buckets needs per-lane projected "
                "contexts — use dense buckets or identity normalization")
    optimizer = OptimizerType.LBFGS if optimizer is None else optimizer
    n_pad = int(fixed_batch.y.shape[0])
    d_fixed = int(fixed_batch.x.shape[1])
    dtype = fixed_batch.y.dtype
    rep = NamedSharding(mesh, PartitionSpec())
    row_sharded = NamedSharding(mesh, PartitionSpec(DATA_AXIS))
    # per-entity lanes over ALL devices — the exact placement
    # global_entity_buckets gave every bucket array
    entity_shard = NamedSharding(mesh, PartitionSpec(tuple(mesh.axis_names)))

    if num_samples is None:
        raise ValueError(
            "multihost_glmix_sweep needs num_samples (the true global n) to "
            "translate original row ids into the padded fixed-batch layout")
    n_proc = jax.process_count()
    per = -(-num_samples // n_proc)       # process_row_range's host stride
    rows_per = n_pad // n_proc            # padded_per_host_rows's stride
    if per == rows_per:
        to_padded = lambda rows: rows
    else:
        # original global id r lives in host r // per at padded position
        # (r // per) * rows_per + r % per; -1 padding slots pass through
        to_padded = lambda rows: jnp.where(
            rows >= 0, (rows // per) * rows_per + rows % per, rows)

    zeros_n = jax.jit(lambda: jnp.zeros((n_pad,), dtype), out_shardings=rep)

    add_offsets = jax.jit(lambda base, s: base + s, out_shardings=row_sharded)
    fnorm = fixed_objective.norm
    fixed_margin = jax.jit(
        lambda w, b: b.margins(fnorm.effective_coefficients(w))
        + fnorm.margin_shift(w), out_shardings=rep)

    def _lane_margins(norm, w, x):
        """[E, S] margins of lane models under the coordinate's shared
        context: x·eff(w) plus each lane's own margin shift."""
        eff = norm.effective_coefficients(w)
        m = jnp.einsum("esd,ed->es", x, eff)
        if norm.shifts is not None:
            m = m - (eff @ norm.shifts)[:, None]
        return m
    # residual bookkeeping on replicated [n_pad] vectors (the descent loop's
    # numpy adds in game/descent.py, kept on device)
    rep_other = jax.jit(lambda m, t, s: m + t - s, out_shardings=rep)
    rep_swap = jax.jit(lambda t, old, new: t - old + new, out_shardings=rep)

    @functools.partial(jax.jit, out_shardings=entity_shard)
    def bucket_offset(off0, rows, margins):
        rows = to_padded(rows)
        safe = jnp.where(rows >= 0, rows, 0)
        return off0 + jnp.where(rows >= 0, margins[safe], 0.0)

    def _make_scorer(norm):
        @functools.partial(jax.jit, out_shardings=rep)
        def re_score(ws, xs, rows_list):
            total = jnp.zeros((n_pad,), dtype)
            # photonlint: disable=tracer-safety -- zip over tuple pytrees:
            # one lane per capacity bucket, a static structure deliberately
            # unrolled (bucket count is small and fixed per model)
            for w, x, rows in zip(ws, xs, rows_list):
                rows = to_padded(rows)
                margins = _lane_margins(norm, w, x)
                valid = rows >= 0
                safe = jnp.where(valid, rows, 0)
                total = total.at[safe.ravel()].add(
                    jnp.where(valid, margins, 0.0).ravel())
            return total
        return re_score

    def _make_passive_scorer(norm):
        @functools.partial(jax.jit, out_shardings=rep)
        def re_score_passive(ws, xs, rows_list, idx_list):
            # cross-bucket coefficient gather: scoring lanes look their
            # entity's trained row up in the concatenated training arrays
            flat = jnp.concatenate(ws, axis=0)
            total = jnp.zeros((n_pad,), dtype)
            # photonlint: disable=tracer-safety -- zip over tuple pytrees:
            # static per-bucket lane structure, deliberately unrolled
            for x, rows, idx in zip(xs, rows_list, idx_list):
                rows = to_padded(rows)
                wl = flat[jnp.clip(idx, 0, flat.shape[0] - 1)]
                wl = jnp.where((idx >= 0)[:, None], wl, 0.0)
                margins = _lane_margins(norm, wl, x)
                valid = rows >= 0
                safe = jnp.where(valid, rows, 0)
                total = total.at[safe.ravel()].add(
                    jnp.where(valid, margins, 0.0).ravel())
            return total
        return re_score_passive

    scorers = {cid: _make_scorer(re_obj[cid].norm) for cid in re_b}
    passive_scorers = {cid: _make_passive_scorer(re_obj[cid].norm)
                       for cid in re_b}

    # photonlint: disable=sharding-annotation -- SolverResult is a pytree of
    # [E, ...] entity lanes whose layout follows w0/batch (both placed
    # entity-sharded by global_entity_buckets); one broadcast spec would
    # also pin the result's scalar diagnostics, so propagation IS the
    # annotation here
    vsolves = {cid: jax.jit(jax.vmap(make_solver(re_obj[cid], optimizer,
                                                 config)))
               for cid in re_b}
    # ONE compile for the fixed solve (the same explicit-SPMD path
    # fit_fixed_effect takes), reused across descent iterations
    solve_fixed = jax.jit(
        make_solver(ShardMapObjective(fixed_objective, mesh), optimizer,
                    config), out_shardings=rep)

    import dataclasses as _dc

    from photon_ml_tpu.core.batch import DenseBatch

    def _score_of(cid, coeffs):
        if cid in re_sc and re_sc[cid] is not None:
            gs, coeff_idx = re_sc[cid]
            return passive_scorers[cid](
                tuple(coeffs), tuple(b.x for b in gs.buckets),
                tuple(b.rows for b in gs.buckets), tuple(coeff_idx))
        rb = re_b[cid]
        return scorers[cid](tuple(coeffs), tuple(b.x for b in rb.buckets),
                            tuple(b.rows for b in rb.buckets))

    if initial is not None:
        w0_host, re_blocks = initial
        w_fixed = jax.make_array_from_process_local_data(
            rep, np.asarray(w0_host, dtype), global_shape=(d_fixed,))
        if single and not isinstance(re_blocks, dict):
            re_blocks = {"__re__": re_blocks}
        re_coeffs = {
            cid: [jax.make_array_from_process_local_data(
                      entity_shard, np.asarray(blk),
                      global_shape=(b.num_lanes,) + np.asarray(blk).shape[1:])
                  for b, blk in zip(re_b[cid].buckets, re_blocks[cid])]
            for cid in re_b
        }
        # scores recomputed from the loaded coefficients — the resumed
        # trajectory equals the uninterrupted one
        re_scores = {cid: _score_of(cid, re_coeffs[cid]) for cid in re_b}
    else:
        # photonlint: disable=recompile-hazard -- one-shot cold-start init:
        # runs once per training job; jit is the supported way to build a
        # sharded zeros array across processes
        w_fixed = jax.jit(lambda: jnp.zeros((d_fixed,), dtype),
                          out_shardings=rep)()
        # per-bucket solve width = the bucket's design width (compact
        # buckets solve in their observed-column space, not the vocabulary)
        re_coeffs = {
            # photonlint: disable=recompile-hazard -- one-shot cold-start
            # init, one compile per bucket shape per training job
            cid: [jax.jit(functools.partial(jnp.zeros,
                                            (b.num_lanes, int(b.x.shape[2])),
                                            dtype),
                          out_shardings=entity_shard)()
                  for b in rb.buckets]
            for cid, rb in re_b.items()
        }
        re_scores = {cid: zeros_n() for cid in re_b}
    total_re = zeros_n()
    for s in re_scores.values():
        total_re = rep_swap(total_re, zeros_n(), s)
    base_offset = fixed_batch.offset
    for it in range(start_iteration, num_iterations):
        batch_f = _dc.replace(fixed_batch,
                              offset=add_offsets(base_offset, total_re))
        w_fixed = solve_fixed(w_fixed, batch_f).w
        margins = fixed_margin(w_fixed, fixed_batch)
        for cid, rb in re_b.items():
            # everything the OTHER coordinates explain becomes this one's
            # offset (fresh scores from coordinates already updated this
            # iteration — the game/descent.py schedule)
            other = rep_other(margins, total_re, re_scores[cid])
            new_coeffs = []
            for b, w0 in zip(rb.buckets, re_coeffs[cid]):
                off = bucket_offset(b.offset, b.rows, other)
                dbatch = DenseBatch(x=b.x, y=b.y, offset=off, weight=b.weight)
                new_coeffs.append(vsolves[cid](w0, dbatch).w)
            re_coeffs[cid] = new_coeffs
            new_score = _score_of(cid, new_coeffs)
            total_re = rep_swap(total_re, re_scores[cid], new_score)
            re_scores[cid] = new_score
        if on_iteration is not None:
            on_iteration(it, w_fixed,
                         re_coeffs["__re__"] if single else re_coeffs)
    if single:
        return w_fixed, re_coeffs["__re__"], re_scores["__re__"]
    return w_fixed, re_coeffs, re_scores


def host_lane_blocks(re_coeffs) -> "list[np.ndarray]":
    """THIS host's addressable [per_host, d] block of each global entity-lane
    array — the unit the CLI checkpoints and ``initial=`` resumes from."""
    out = []
    for arr in re_coeffs:
        shards = sorted(arr.addressable_shards, key=lambda s: s.index[0].start)
        out.append(np.concatenate([np.asarray(s.data) for s in shards])
                   if shards else np.zeros((0, arr.shape[1])))
    return out


def export_local_random_effects(re_coeffs, re_buckets, mesh: Mesh,
                                projections=None, norm=None,
                                intercept_index=None) -> Dict[int, np.ndarray]:
    """THIS host's entities' coefficient vectors from globally-sharded lane
    arrays — each host publishes its own entity range (the reference writes
    the RandomEffectModel RDD partition-wise the same way).

    ``projections``: the padded host-local BucketProjection list from
    ``global_entity_buckets(..., projections=...)`` — compact lanes
    back-project through THIS host's observed-column maps to full
    vocabulary width before export.

    ``norm``/``intercept_index``: the coordinate's shared
    NormalizationContext — solver-space lanes map to ORIGINAL-space
    coefficients per lane (NormalizationContext.scala:73-99), like the
    single-process publish path."""
    n_proc = jax.process_count()
    pid = jax.process_index()
    out: Dict[int, np.ndarray] = {}
    blocks = host_lane_blocks(re_coeffs)
    for bi, (arr, block) in enumerate(zip(re_coeffs, blocks)):
        if norm is not None and not norm.is_identity:
            if norm.shifts is not None and intercept_index is None:
                raise ValueError("shift normalization needs "
                                 "intercept_index to publish")
            # the ONE definition of the coefficient-space map
            # (NormalizationContext.scala:73-99), vmapped over lanes
            block = np.asarray(jax.vmap(
                lambda r: norm.model_to_original_space(r, intercept_index)
            )(jnp.asarray(block))).astype(block.dtype)
        if projections is not None:
            block = projections[bi].back_project(block)
        per_host = arr.shape[0] // n_proc
        base = pid * per_host
        for eid, (ebi, lane) in re_buckets.lane_of.items():
            if ebi == bi:
                out[eid] = block[lane - base]
    return out
