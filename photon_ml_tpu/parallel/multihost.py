"""Multi-host (multi-process) SPMD: initialization, global meshes, and
process-local data placement.

Reference analog: the Spark driver/executor cluster — Netty RPC broadcast +
treeAggregate over the cluster network (SURVEY.md §5 "Distributed
communication backend").  TPU-native shape: every host runs THIS SAME
program under ``jax.distributed``; collectives ride ICI within a slice and
DCN across slices, inserted by XLA from the sharding annotations.  There is
no driver process — the "driver loop" (coordinate descent) runs identically
on every host, operating on globally-sharded arrays.

Data loading is split by sample id BEFORE reading (each host reads only its
row range — the reference's executor-partitioned Avro read), PADDED to the
balanced per-host row count (padding rows carry weight 0, so they are inert
in every objective/metric), then assembled into global arrays with
``jax.make_array_from_process_local_data``.

The recipe (each host runs the same code):

    initialize(...)                      # no-op for a single process
    mesh = global_mesh()
    rows = padded_per_host_rows(n, mesh)
    start, stop = process_row_range(n)
    block = load_rows(start, stop)       # host-local read
    block = pad_local_rows(block, rows)  # weight column padded with 0
    g = global_batch_from_local(block, mesh)

Cross-process scope (tested in tests/test_parallel.py
::test_multihost_two_processes): the fixed-effect solve runs multihost
both data-parallel (ShardMapObjective — the one DCN all-reduce) and
FEATURE-SHARDED (ShardSparseObjective, w blocked over the within-process
feature axis).  RANDOM-EFFECT coordinates are currently single-process:
their bucketing groups rows by entity GLOBALLY, so a row-split read
cannot feed them — a multihost RE run must give every host the full
dataset for those shards and keep the entity axis within one process
(the reference instead shuffles per-entity across the cluster,
RandomEffectDatasetPartitioner.scala:30-171; the TPU-native equivalent —
entity-lane arrays assembled per process from a host-sharded entity
range — is future work)."""

from __future__ import annotations

import logging
from typing import Dict, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from photon_ml_tpu.parallel.mesh import DATA_AXIS, ENTITY_AXIS, FEATURE_AXIS

Array = jax.Array
logger = logging.getLogger(__name__)


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None,
               expected_processes: Optional[int] = None) -> None:
    """Bring up the jax.distributed runtime.

    Explicit ``num_processes <= 1`` is a no-op.  With no arguments,
    auto-detection is attempted (TPU pods infer everything from the
    environment); if no cluster environment is found this degenerates to
    single-process — at WARNING level, because on a real pod that means N
    independent jobs training divergent models.  Pass
    ``expected_processes`` to turn a short job into a hard error (the
    recommended pod setting)."""
    if num_processes is not None and num_processes <= 1:
        if expected_processes is not None and expected_processes != num_processes:
            raise RuntimeError(
                f"expected {expected_processes} processes but launched with "
                f"num_processes={num_processes}")
        return
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    try:
        jax.distributed.initialize(**kwargs)
    except (RuntimeError, ValueError) as e:
        if kwargs:
            raise  # explicit cluster config that fails must be loud
        logger.warning("no cluster environment detected (%s); running "
                       "single-process", e)
    got = jax.process_count()
    want = expected_processes if expected_processes is not None else num_processes
    if want is not None and got != want:
        raise RuntimeError(
            f"expected {want} processes but jax.process_count() == {got}: "
            "the cluster did not form (check coordinator address / pod env)")


def global_mesh(n_entity: int = 1, n_feature: int = 1) -> Mesh:
    """A (data, entity, feature) mesh over ALL processes' devices, laid out
    so collectives ride the right interconnect tier.

    ICI/DCN mapping (the multi-slice story, SURVEY §5): the ``entity`` and
    ``feature`` axes are placed INNERMOST WITHIN each process's (slice's)
    devices, so their collectives — the per-evaluation feature-axis margin
    psum of the sharded sparse objective, the entity-lane layouts — always
    ride ICI.  Only the ``data`` axis strides ACROSS processes, so the one
    gradient all-reduce per objective evaluation is the only collective that
    ever touches DCN — exactly the reference's cluster-network role
    (treeAggregate over Spark executors), and DP gradient all-reduce is the
    one collective that amortizes DCN latency well.

    Within a single process this degenerates to ``make_mesh``'s layout.
    """
    devices = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
    n = len(devices)
    n_proc = jax.process_count()
    local = n // n_proc
    cell = n_entity * n_feature
    if n % cell:
        raise ValueError(
            f"{n} global devices not divisible by entity*feature = {cell}")
    if n_proc > 1 and local % cell:
        raise ValueError(
            f"entity*feature = {cell} does not fit within one process's "
            f"{local} devices — entity/feature collectives must stay on ICI "
            "(within a slice); shrink those axes or grow the slice")
    arr = (np.asarray(devices)
           .reshape(n // cell, n_entity, n_feature))
    return Mesh(arr, (DATA_AXIS, ENTITY_AXIS, FEATURE_AXIS))


def process_row_range(n: int,
                      process_id: Optional[int] = None,
                      num_processes: Optional[int] = None) -> Tuple[int, int]:
    """[start, stop) of the global sample rows THIS host should read.

    Contiguous row split by process id; the last host's range is short when
    ``n`` doesn't divide (pad with ``pad_local_rows`` before assembly).
    """
    pid = jax.process_index() if process_id is None else process_id
    np_ = jax.process_count() if num_processes is None else num_processes
    if not 0 <= pid < np_:
        raise ValueError(f"process id {pid} out of range for {np_} processes")
    per = -(-n // np_)  # ceil: every host but the last reads `per` rows
    start = min(pid * per, n)
    stop = min(start + per, n)
    return start, stop


def padded_per_host_rows(n: int, mesh: Mesh,
                         num_processes: Optional[int] = None) -> int:
    """Per-host row count every host must pad its block to: ceil(n / hosts)
    rounded up so each host's rows divide its share of the data axis."""
    np_ = jax.process_count() if num_processes is None else num_processes
    per = -(-n // np_)
    data_size = mesh.shape[DATA_AXIS]
    if data_size % np_:
        raise ValueError(
            f"data axis ({data_size}) must be divisible by the process "
            f"count ({np_}) — one host cannot own a fraction of a device row")
    local_devices = data_size // np_
    return -(-per // local_devices) * local_devices


def pad_local_rows(block: Dict[str, np.ndarray], rows: int) -> Dict[str, np.ndarray]:
    """Zero-pad every column's leading dim to ``rows`` (weight columns pad
    with 0, making the extra rows inert everywhere)."""
    from photon_ml_tpu.parallel.mesh import _pad_rows

    out = {}
    for name, a in block.items():
        try:
            out[name] = _pad_rows(np.asarray(a), rows)
        except ValueError as e:
            raise ValueError(f"column {name!r}: {e}") from e
    return out


def global_batch_from_local(
    local: Dict[str, np.ndarray],
    mesh: Mesh,
    specs: Optional[Dict[str, PartitionSpec]] = None,
) -> Dict[str, Array]:
    """Host-local row blocks -> globally data-sharded device arrays.

    Every host must pass the same keys with the SAME per-host row count
    (use ``padded_per_host_rows`` + ``pad_local_rows``); rows concatenate
    across hosts in process order.  ``specs`` overrides the default
    row-sharded PartitionSpec per key (e.g. ``{"x": P(DATA_AXIS,
    FEATURE_AXIS)}`` for a feature-sharded design matrix).
    """
    specs = specs or {}
    n_proc = jax.process_count()
    out: Dict[str, Array] = {}
    for name, a in local.items():
        a = np.asarray(a)
        spec = specs.get(name,
                         PartitionSpec(DATA_AXIS, *([None] * (a.ndim - 1))))
        sharding = NamedSharding(mesh, spec)
        global_shape = (a.shape[0] * n_proc,) + a.shape[1:]
        out[name] = jax.make_array_from_process_local_data(
            sharding, a, global_shape=global_shape)
    return out
