"""Device mesh + sharding helpers.

Replaces the reference's Spark communication substrate (SURVEY.md §2.7):
  - broadcast of coefficients per evaluation (DistributedObjectiveFunction.scala:61)
      -> weights live REPLICATED in HBM; nothing is re-shipped per step.
  - treeAggregate gradient reductions (ValueAndGradientAggregator.scala:248)
      -> XLA all-reduce over the ``data`` mesh axis, inserted by GSPMD when the
         batch is sharded on ``data`` and outputs are replicated.  ICI
         all-reduce is already tree/torus-optimal, so the reference's
         ``treeAggregateDepth`` knob has no analog.
  - shuffle/groupBy for per-entity data (RandomEffectDataset.scala:302-341)
      -> one-time host-side bucketing (parallel/bucketing.py) + ``entity``-axis
         sharding.

Mesh axes:
  - ``data``    : examples of the fixed-effect batch (DP)
  - ``entity``  : independent random-effect problems (the reference's
                  "per-entity model parallelism", RandomEffectCoordinate.scala:109-127)
  - ``feature`` : model/feature-axis sharding for huge-d fixed effects — the
                  TPU counterpart of the reference's feature-axis scaling story
                  (PalDB 1e8-feature index maps + treeAggregateDepth keeping
                  driver merge memory flat, SURVEY.md §5): w and the per-feature
                  gradient partial sums are sharded so no single device holds
                  the full coefficient vector, and the feature-axis reduction of
                  margins rides ICI (GSPMD inserts the psum from the shardings).
Multi-host later slices these over DCN by constructing the mesh from
``jax.devices()`` spanning hosts; the code below is agnostic.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from photon_ml_tpu.core.batch import Batch, DenseBatch, SparseBatch

DATA_AXIS = "data"
ENTITY_AXIS = "entity"
FEATURE_AXIS = "feature"
SHARD_AXIS = "shard"  # serving-side coefficient-table entity partition


def make_mesh(n_data: Optional[int] = None, n_entity: int = 1, n_feature: int = 1,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Create a (data, entity, feature) mesh over the available devices.

    Default: all devices on the data axis.  A single-device mesh is valid and
    produces the exact same program (collectives become no-ops), so every code
    path is mesh-agnostic — the chip-count-invariance property the tests rely
    on (SURVEY.md §4).
    """
    devices = list(devices if devices is not None else jax.devices())
    if n_data is None:
        n_data = len(devices) // (n_entity * n_feature)
    need = n_data * n_entity * n_feature
    if need > len(devices):
        raise ValueError(
            f"mesh {n_data}x{n_entity}x{n_feature} needs {need} devices, have {len(devices)}")
    arr = np.asarray(devices[:need]).reshape(n_data, n_entity, n_feature)
    return Mesh(arr, (DATA_AXIS, ENTITY_AXIS, FEATURE_AXIS))


def serving_mesh(n_shards: int,
                 devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """A 1-axis ``(shard,)`` mesh over the first ``n_shards`` devices — the
    serving-side coefficient-table partition (serving/coefficient_store.py
    slices each random-effect table's entity axis over it; the engine's AOT
    kernels psum shard-local margins across it).  Kept separate from
    ``make_mesh``'s training axes: serving never shards data or features,
    only the entity rows of the hot tables."""
    devices = list(devices if devices is not None else jax.devices())
    if n_shards < 1:
        raise ValueError(f"serving mesh needs n_shards >= 1, got {n_shards}")
    if n_shards > len(devices):
        raise ValueError(
            f"serving mesh over {n_shards} shards needs {n_shards} devices, "
            f"have {len(devices)}")
    return Mesh(np.asarray(devices[:n_shards]), (SHARD_AXIS,))


def replicate(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def _pad_rows(a: np.ndarray, target: int) -> np.ndarray:
    pad = target - a.shape[0]
    if pad < 0:
        raise ValueError(f"array has {a.shape[0]} rows > target {target}")
    if pad == 0:
        return a
    return np.concatenate([a, np.zeros((pad,) + a.shape[1:], a.dtype)], axis=0)


def _pad_cols(a: np.ndarray, target: int) -> np.ndarray:
    pad = target - a.shape[1]
    if pad == 0:
        return a
    return np.concatenate([a, np.zeros((a.shape[0], pad), a.dtype)], axis=1)


def padded_dim(d: int, mesh: Mesh, axis: str = FEATURE_AXIS) -> int:
    """Feature count padded up to a multiple of the feature-axis size."""
    size = mesh.shape[axis]
    return ((d + size - 1) // size) * size


def shard_coefficients(w, mesh: Mesh, axis: str = FEATURE_AXIS):
    """Place a coefficient vector sharded over the feature axis (zero-padded).

    Padded slots see only zero feature columns, so their gradient is exactly
    the regularization term at w=0, which is 0 — they stay 0 through any solve.

    Device arrays stay on device (pad + reshard, no host round-trip) so
    warm-starting from a previous sweep's sharded w never all-gathers the
    full vector to the host.
    """
    import jax.numpy as jnp

    sharding = NamedSharding(mesh, P(axis))
    if jax.process_count() > 1 and getattr(w, "is_fully_addressable", True):
        # multihost: any PROCESS-LOCAL input (host numpy or a
        # fully-addressable jax.Array — e.g. the coordinate's jnp.zeros
        # cold start) becomes a GLOBAL sharded array via the per-shard
        # callback (device_put of process-local data to a multi-process
        # sharding is not portable).  Every host passes the same w, and the
        # feature axis lives within each process (multihost.global_mesh),
        # so each callback index is addressable.  An already-global array
        # (is_fully_addressable False) takes the reshard path below.
        w_np = np.asarray(w)
        pad = padded_dim(w_np.shape[0], mesh, axis) - w_np.shape[0]
        if pad:
            w_np = np.concatenate([w_np, np.zeros(pad, w_np.dtype)])
        return jax.make_array_from_callback(
            w_np.shape, sharding, lambda idx: w_np[idx])
    w = jnp.asarray(w)
    pad = padded_dim(w.shape[0], mesh, axis) - w.shape[0]
    if pad:
        w = jnp.pad(w, (0, pad))
    return jax.device_put(w, sharding)


def shard_batch(batch: Batch, mesh: Mesh, axis: str = DATA_AXIS,
                feature_axis: Optional[str] = None) -> Batch:
    """Place a batch with its example dimension sharded over ``axis``.

    Pads the example count up to a multiple of the axis size with weight-0
    rows (inert by the core masking contract), then device_puts each leaf with
    a NamedSharding.  This is the one-time data layout step that replaces the
    reference's per-step broadcast + shuffle choreography.

    ``feature_axis``: additionally shard the feature dimension of a dense
    design matrix (zero-padding d up to a multiple of the axis size) so the
    margin matmul contracts over a sharded axis — GSPMD turns the row of
    per-shard partial margins into one psum over ``feature_axis``.  Sparse
    batches address w by global index and are deliberately left unsharded on
    features (their w stays replicated; see parallel/fixed.py).
    """
    size = mesh.shape[axis]
    n = batch.num_examples
    target = ((n + size - 1) // size) * size

    def place(x, spec):
        return jax.device_put(x, NamedSharding(mesh, spec))

    row = P(axis)

    if isinstance(batch, DenseBatch):
        x = _pad_rows(np.asarray(batch.x), target)
        if feature_axis is not None:
            x = _pad_cols(x, padded_dim(x.shape[1], mesh, feature_axis))
        return DenseBatch(
            x=place(x, P(axis, feature_axis)),
            y=place(_pad_rows(np.asarray(batch.y), target), row),
            offset=place(_pad_rows(np.asarray(batch.offset), target), row),
            weight=place(_pad_rows(np.asarray(batch.weight), target), row),
        )
    if isinstance(batch, SparseBatch):
        return SparseBatch(
            indices=place(_pad_rows(np.asarray(batch.indices), target), P(axis, None)),
            values=place(_pad_rows(np.asarray(batch.values), target), P(axis, None)),
            y=place(_pad_rows(np.asarray(batch.y), target), row),
            offset=place(_pad_rows(np.asarray(batch.offset), target), row),
            weight=place(_pad_rows(np.asarray(batch.weight), target), row),
            dim=batch.dim,
        )
    raise TypeError(f"unknown batch type {type(batch)!r}")
