"""JAX version-compat shims for the parallel layer.

``shard_map`` graduated from ``jax.experimental.shard_map`` to a
top-level ``jax.shard_map`` export only in newer JAX releases; the
pinned toolchain (0.4.x) still ships it under experimental.  Every
photon-ml-tpu call site imports the symbol from HERE so the whole
repo tracks the migration in exactly one place.
"""

from __future__ import annotations

import jax

if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:  # JAX < 0.6: the experimental home
    from jax.experimental.shard_map import shard_map  # noqa: F401

__all__ = ["shard_map"]
