"""Entity bucketing: the TPU-native replacement for the reference's
random-effect data layout (groupByKey shuffle -> RDD[(REId, LocalDataset)]).

Reference machinery being replaced (SURVEY.md §2.2):
  - RandomEffectDataset.apply: groupBy REId shuffle, deterministic reservoir
    cap with weight rescale count/cap (RandomEffectDataset.scala:358-420)
  - RandomEffectDatasetPartitioner: balanced entity->partition assignment
    (RandomEffectDatasetPartitioner.scala:30-171)
  - RandomEffectCoordinate.updateModel: per-entity serial solves inside
    mapValues (RandomEffectCoordinate.scala:104-153)

TPU-native design: entities are grouped ONCE on host into statically-shaped
buckets — all entities in a bucket share a sample capacity S (next power of
two of their active count) — then every entity in a bucket is solved
SIMULTANEOUSLY by ``vmap``-ing the jittable solver over the entity lane, with
the entity lane sharded across the whole mesh.  Padding rows carry weight 0
(inert by the core masking contract); padding lanes are whole fake entities
whose solves are discarded.  Millions of serial executor-core solves become a
handful of dense [E, S, d] batched programs on the MXU.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from photon_ml_tpu.core.batch import DenseBatch
from photon_ml_tpu.core.objective import GLMObjective
from photon_ml_tpu.opt.solve import make_solver
from photon_ml_tpu.opt.types import SolverConfig, SolverResult
from photon_ml_tpu.types import OptimizerType

Array = jax.Array


def _splitmix64(x: np.ndarray) -> np.ndarray:
    """Deterministic 64-bit mix for reservoir keys (the reference uses
    byteswap64(hash ^ uniqueId), RandomEffectDataset.scala:394-401 — any
    fixed avalanche mix gives the same recompute-stable property)."""
    x = (x + np.uint64(0x9E3779B97F4A7C15)).astype(np.uint64)
    x = ((x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)).astype(np.uint64)
    x = ((x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)).astype(np.uint64)
    return x ^ (x >> np.uint64(31))


@dataclasses.dataclass
class Bucket:
    """One capacity class of entities, device-ready.

    Arrays: x [E, S, d], y/offset/weight [E, S], rows [E, S] int32 (original
    sample row of each slot, -1 for padding), counts [E] int32 (real samples
    per entity), entity_lanes [E] int64 (original entity id per lane, -1 for
    padding lanes).
    """

    x: np.ndarray
    y: np.ndarray
    offset: np.ndarray
    weight: np.ndarray
    rows: np.ndarray
    counts: np.ndarray
    entity_lanes: np.ndarray

    @property
    def num_lanes(self) -> int:
        return self.x.shape[0]

    @property
    def capacity(self) -> int:
        return self.x.shape[1]

    def batch(self) -> DenseBatch:
        return DenseBatch(
            x=jnp.asarray(self.x), y=jnp.asarray(self.y),
            offset=jnp.asarray(self.offset), weight=jnp.asarray(self.weight),
        )


@dataclasses.dataclass
class EntityBuckets:
    """All buckets for one random-effect coordinate + the entity directory.

    ``lane_of``: entity id -> (bucket index, lane) for model lookup/update.
    ``compact``: design blocks are per-lane OBSERVED-column bases (the
    sparse bucketer), not the shared full-vocabulary basis — an explicit
    marker because the padded compact width can EQUAL ``dim`` while lane
    column j still means "the lane's j-th observed feature", so width
    comparison cannot detect compactness.
    """

    buckets: List[Bucket]
    lane_of: Dict[int, Tuple[int, int]]
    dim: int
    num_entities: int
    num_samples: int  # original sample-row count (scores vector length)
    compact: bool = False

    def entity_ids(self) -> np.ndarray:
        return np.asarray(sorted(self.lane_of), np.int64)


def _group_rows(
    entity_ids: np.ndarray,
    active_cap: Optional[int],
    min_active_samples: int,
    seed: int,
    existing_model_keys: Optional[frozenset] = None,
    row_ids: Optional[np.ndarray] = None,
) -> Tuple[List[np.ndarray], List[int], List[float]]:
    """Group sample rows by entity with the deterministic reservoir cap +
    weight rescale count/cap (reference RandomEffectDataset.scala:358-420)
    and the min-active lower bound (:319-341).  Shared by the dense and
    row-sparse bucketers.

    ``existing_model_keys`` (warm start): the reference's lower-bound filter
    drops an under-bound entity only when a prior model already covers it
    (that model then passes through unchanged — RandomEffectCoordinate
    .updateModel's leftOuterJoin :114-127); an under-bound NEW entity still
    trains, else it would never get a model at all
    (RandomEffectDataset.scala:322-333).

    ``row_ids``: GLOBAL sample-row id per local row (multihost entity-sharded
    reads, parallel/multihost.py).  Reservoir keys mix the global id, so an
    entity keeps the SAME samples no matter how many hosts the data is split
    over — the recompute-stable property the reference gets from hashing
    uniqueId (RandomEffectDataset.scala:394-401), extended across topology."""
    uniq, inverse, counts = np.unique(entity_ids, return_inverse=True,
                                      return_counts=True)
    order = np.argsort(inverse, kind="stable")  # rows grouped by entity
    starts = np.concatenate([[0], np.cumsum(counts)])

    kept_rows: List[np.ndarray] = []
    kept_entities: List[int] = []
    rescale: List[float] = []
    for e in range(len(uniq)):
        rows = order[starts[e]: starts[e + 1]]
        if len(rows) < min_active_samples and (
                existing_model_keys is None
                or int(uniq[e]) in existing_model_keys):
            continue
        scale = 1.0
        if active_cap is not None and len(rows) > active_cap:
            gids = rows if row_ids is None else row_ids[rows]
            keys = _splitmix64(gids.astype(np.uint64) ^ np.uint64(seed))
            rows = rows[np.argsort(keys, kind="stable")[:active_cap]]
            scale = len(keys) / active_cap  # weight rescale count/cap
        kept_rows.append(np.sort(rows))
        kept_entities.append(int(uniq[e]))
        rescale.append(scale)
    return kept_rows, kept_entities, rescale


def _capacity_classes(kept_rows: List[np.ndarray]) -> np.ndarray:
    """Per-entity bucket capacity: next power of two of the active count —
    ONE rounding rule for the dense and sparse bucketers."""
    return np.asarray([max(1, 1 << (len(r) - 1).bit_length())
                       for r in kept_rows])


def _pack_lane_meta(n_lanes, cap, idxs, kept_rows, kept_entities, rescale,
                    y, offset, weight, dtype, lane_of, bucket_index,
                    row_ids=None):
    """Fill one capacity class's NON-design lane arrays (labels, offsets,
    rescaled weights, row map, counts, entity directory) — identical between
    the dense and row-sparse bucketers, factored so their padding/rescale
    semantics cannot diverge.  Returns (by, boff, bw, brows, bcounts,
    blanes); ``lane_of`` is updated in place.  ``row_ids`` maps local row
    positions to the GLOBAL sample-row ids stored in ``brows`` (multihost)."""
    by = np.zeros((n_lanes, cap), dtype)
    boff = np.zeros((n_lanes, cap), dtype)
    bw = np.zeros((n_lanes, cap), dtype)
    brows = np.full((n_lanes, cap), -1, np.int32)
    bcounts = np.zeros((n_lanes,), np.int32)
    blanes = np.full((n_lanes,), -1, np.int64)
    for lane, ei in enumerate(idxs):
        rows = kept_rows[ei]
        k = len(rows)
        by[lane, :k] = y[rows]
        boff[lane, :k] = offset[rows]
        bw[lane, :k] = weight[rows] * rescale[ei]
        brows[lane, :k] = rows if row_ids is None else row_ids[rows]
        bcounts[lane] = k
        blanes[lane] = kept_entities[ei]
        lane_of[kept_entities[ei]] = (bucket_index, lane)
    return by, boff, bw, brows, bcounts, blanes


def bucket_by_entity(
    entity_ids: np.ndarray,
    x: np.ndarray,
    y: np.ndarray,
    offset: Optional[np.ndarray] = None,
    weight: Optional[np.ndarray] = None,
    active_cap: Optional[int] = None,
    min_active_samples: int = 1,
    lane_multiple: int = 1,
    seed: int = 0,
    dtype=np.float32,
    existing_model_keys: Optional[frozenset] = None,
    row_ids: Optional[np.ndarray] = None,
    num_samples: Optional[int] = None,
    groups: Optional[Tuple[List[np.ndarray], List[int], List[float]]] = None,
) -> EntityBuckets:
    """Group samples by entity into power-of-two-capacity buckets.

    - ``active_cap``: deterministic reservoir cap per entity with weight
      rescale count/cap (reference RandomEffectDataset.scala:358-420).
      Overflow samples are DROPPED from training here; the score-only
      "passive" path keeps them via score_random_effects on the full data.
    - ``min_active_samples``: entities with fewer samples are excluded
      (reference lower-bound filter, RandomEffectDataset.scala:319-341).
    - ``lane_multiple``: pad each bucket's entity count to a multiple (set to
      the mesh device count so the entity axis shards evenly).
    - ``row_ids`` / ``num_samples``: multihost entity-sharded reads — the
      local rows' GLOBAL sample ids (stored in ``Bucket.rows`` and mixed
      into reservoir keys so decisions are topology-invariant) and the
      GLOBAL score-vector length (parallel/multihost.py).
    - ``groups``: a precomputed ``(kept_rows, kept_entities, rescale)``
      triple (stream.EntityStats accumulated chunk-by-chunk) replacing the
      ``_group_rows`` scan; it must have been built with the SAME cap /
      min-active / seed / warm-start arguments (EntityStats.groups enforces
      the cap+seed half and returns None on mismatch).

    ``x`` may be a device-resident ``jax.Array`` (streaming ingest
    assembles design shards on device): the per-lane design blocks are then
    built by an on-device gather — bit-identical to the host fill, since a
    gather copies rows and the padding is exact zeros either way — and the
    [n, d] array never materializes on host.
    """
    n = len(entity_ids)
    entity_ids = np.asarray(entity_ids, np.int64)
    x_is_device = isinstance(x, jax.Array)
    if x_is_device:
        if row_ids is not None:
            raise NotImplementedError(
                "device-resident design shards do not support multihost "
                "row_ids yet (ROADMAP item 5 follow-on)")
        if x.dtype != np.dtype(dtype):
            x = x.astype(dtype)  # on-device cast: never host-materialize
    else:
        x = np.asarray(x, dtype)
    y = np.asarray(y, dtype)
    offset = np.zeros(n, dtype) if offset is None else np.asarray(offset, dtype)
    weight = np.ones(n, dtype) if weight is None else np.asarray(weight, dtype)
    d = x.shape[1]
    if row_ids is not None:
        row_ids = np.asarray(row_ids, np.int64)

    if groups is not None:
        kept_rows, kept_entities, rescale = groups
    else:
        kept_rows, kept_entities, rescale = _group_rows(
            entity_ids, active_cap, min_active_samples, seed,
            existing_model_keys=existing_model_keys, row_ids=row_ids)

    # Capacity classes: next power of two of the active count.
    caps = _capacity_classes(kept_rows)
    buckets: List[Bucket] = []
    lane_of: Dict[int, Tuple[int, int]] = {}
    for cap in sorted(set(caps.tolist())):
        idxs = np.nonzero(caps == cap)[0]
        n_lanes = ((len(idxs) + lane_multiple - 1) // lane_multiple) * lane_multiple
        by, boff, bw, brows, bcounts, blanes = _pack_lane_meta(
            n_lanes, cap, idxs, kept_rows, kept_entities, rescale,
            y, offset, weight, dtype, lane_of, len(buckets), row_ids=row_ids)
        if x_is_device:
            # on-device lane gather: rows copy exactly, padding lanes/slots
            # are exact zeros — bitwise-equal to the host fill below
            valid = brows >= 0
            safe = np.where(valid, brows, 0).astype(np.int64)
            bx = jnp.where(jnp.asarray(valid)[..., None],
                           x[jnp.asarray(safe)], jnp.zeros((), x.dtype))
        else:
            bx = np.zeros((n_lanes, cap, d), dtype)
            for lane, ei in enumerate(idxs):
                rows = kept_rows[ei]
                bx[lane, :len(rows)] = x[rows]
        buckets.append(Bucket(x=bx, y=by, offset=boff, weight=bw, rows=brows,
                              counts=bcounts, entity_lanes=blanes))

    return EntityBuckets(buckets=buckets, lane_of=lane_of, dim=d,
                         num_entities=len(kept_entities),
                         num_samples=n if num_samples is None else num_samples)


def bucket_by_entity_sparse(
    entity_ids: np.ndarray,
    indices: np.ndarray,
    values: np.ndarray,
    dim: int,
    y: np.ndarray,
    offset: Optional[np.ndarray] = None,
    weight: Optional[np.ndarray] = None,
    active_cap: Optional[int] = None,
    min_active_samples: int = 1,
    lane_multiple: int = 1,
    seed: int = 0,
    dtype=np.float32,
    features_to_samples_ratio: Optional[float] = None,
    intercept_index: Optional[int] = None,
    existing_model_keys: Optional[frozenset] = None,
    row_ids: Optional[np.ndarray] = None,
    num_samples: Optional[int] = None,
):
    """Compact per-entity buckets built DIRECTLY from row-sparse features.

    The reference keeps per-entity SPARSE Breeze vectors
    (data/LocalDataset.scala:35-247), so wide sparse random-effect feature
    bags never densify to the full vocabulary.  The TPU equivalent: each
    entity solves in the compact space of its OBSERVED columns (the
    IndexMapProjectorRDD.scala:222-261 set, built here straight from the
    row-sparse (indices, values) pairs), so the bucket design blocks are
    [E, S, d_obs] — never [E, S, d_full] — and HBM scales with observed
    features per entity, not vocabulary size.  Margin-exact: an unobserved
    feature has zero data gradient and stays at exactly 0 under L2/L1 from a
    zero init (same fact the reference's projection relies on).

    ``indices``/``values``: the SparseShard row-padded COO arrays [n, k]
    (padded slots carry value 0 and are ignored; duplicate indices within a
    row ACCUMULATE, matching core/batch.SparseBatch margins).
    ``features_to_samples_ratio``/``intercept_index``: per-entity top-k
    |Pearson| feature filter exactly as build_observed_indices applies it to
    dense buckets (LocalDataset.scala:185-247).

    Returns ``(EntityBuckets, projections)`` — compact buckets plus one
    BucketProjection per bucket mapping compact columns back to the full
    vocabulary (``EntityBuckets.dim`` stays the FULL dimension).
    """
    from photon_ml_tpu.parallel.projection import (BucketProjection,
                                                   _pow2_at_least,
                                                   pearson_top_k)

    n = len(entity_ids)
    entity_ids = np.asarray(entity_ids, np.int64)
    indices = np.asarray(indices, np.int64)
    values = np.asarray(values, dtype)
    y = np.asarray(y, dtype)
    offset = np.zeros(n, dtype) if offset is None else np.asarray(offset, dtype)
    weight = np.ones(n, dtype) if weight is None else np.asarray(weight, dtype)

    if row_ids is not None:
        row_ids = np.asarray(row_ids, np.int64)
    kept_rows, kept_entities, rescale = _group_rows(
        entity_ids, active_cap, min_active_samples, seed,
        existing_model_keys=existing_model_keys, row_ids=row_ids)

    def _compact_lane(rows: np.ndarray):
        """(observed columns, compact dense block [len(rows), n_obs])."""
        iv, vv = indices[rows], values[rows]
        nz_r, nz_c = np.nonzero(vv != 0)
        obs = np.unique(iv[nz_r, nz_c]) if nz_r.size else np.empty(0, np.int64)
        x = np.zeros((len(rows), len(obs)), dtype)
        if nz_r.size:
            pos = np.searchsorted(obs, iv[nz_r, nz_c])
            np.add.at(x, (nz_r, pos), vv[nz_r, nz_c])  # duplicates accumulate
        if features_to_samples_ratio is not None and obs.size:
            keep_n = max(1, int(np.ceil(features_to_samples_ratio * len(rows))))
            if obs.size > keep_n:
                top = pearson_top_k(x, y[rows], weight[rows], obs, keep_n,
                                    intercept_index)
                obs, x = obs[top], x[:, top]
        return obs.astype(np.int32), x

    caps = _capacity_classes(kept_rows)
    buckets: List[Bucket] = []
    projections: List[object] = []
    lane_of: Dict[int, Tuple[int, int]] = {}
    for cap in sorted(set(caps.tolist())):
        idxs = np.nonzero(caps == cap)[0]
        compacted = [_compact_lane(kept_rows[ei]) for ei in idxs]
        d_proj = _pow2_at_least(max((len(o) for o, _ in compacted),
                                    default=1))
        d_proj = min(d_proj, dim)
        n_lanes = ((len(idxs) + lane_multiple - 1) // lane_multiple) * lane_multiple
        by, boff, bw, brows, bcounts, blanes = _pack_lane_meta(
            n_lanes, cap, idxs, kept_rows, kept_entities, rescale,
            y, offset, weight, dtype, lane_of, len(buckets), row_ids=row_ids)
        bx = np.zeros((n_lanes, cap, d_proj), dtype)
        bidx = np.full((n_lanes, d_proj), -1, np.int32)
        for lane, ei in enumerate(idxs):
            k = len(kept_rows[ei])
            obs, x = compacted[lane]
            bx[lane, :k, :len(obs)] = x
            bidx[lane, :len(obs)] = obs
        buckets.append(Bucket(x=bx, y=by, offset=boff, weight=bw, rows=brows,
                              counts=bcounts, entity_lanes=blanes))
        projections.append(BucketProjection(indices=bidx, d_full=dim))

    ents = EntityBuckets(buckets=buckets, lane_of=lane_of, dim=dim,
                         num_entities=len(kept_entities),
                         num_samples=n if num_samples is None else num_samples,
                         compact=True)
    return ents, projections


def _entity_sharding(mesh: Optional[Mesh]):
    if mesh is None:
        return None
    return NamedSharding(mesh, P(tuple(mesh.axis_names)))  # E over ALL devices


def fit_random_effects(
    objective: GLMObjective,
    buckets: EntityBuckets,
    mesh: Optional[Mesh] = None,
    optimizer: OptimizerType = OptimizerType.LBFGS,
    config: Optional[SolverConfig] = None,
    init: Optional[List[Array]] = None,
) -> Tuple[List[Array], List[SolverResult]]:
    """Solve every entity's GLM; returns per-bucket coefficients [E, d].

    The reference solves each entity SERIALLY inside a Spark mapValues
    (RandomEffectCoordinate.scala:114-127); here each capacity class is one
    vmapped solver launch with the entity lane sharded over the mesh.
    ``init``: per-bucket warm-start coefficients (e.g. from the previous
    coordinate-descent iteration).
    """
    solve = make_solver(objective, optimizer, config)
    # photonlint: disable=sharding-annotation -- mesh is Optional here: the
    # same jit serves the mesh-less single-device path, and when a mesh IS
    # given the [E, ...] lane layout propagates from the device_put of
    # w0/batch below (one broadcast spec would also pin scalar leaves)
    vsolve = jax.jit(jax.vmap(lambda w0, batch: solve(w0, batch)))
    shard = _entity_sharding(mesh)

    coeffs: List[Array] = []
    results: List[SolverResult] = []
    for bi, b in enumerate(buckets.buckets):
        w0 = (init[bi] if init is not None
              else jnp.zeros((b.num_lanes, buckets.dim), b.batch().x.dtype))
        batch = b.batch()
        if shard is not None:
            w0 = jax.device_put(w0, shard)
            batch = jax.tree.map(lambda a: jax.device_put(a, _spec_for(mesh, a)), batch)
        res = vsolve(w0, batch)
        coeffs.append(res.w)
        results.append(res)
    return coeffs, results


def _spec_for(mesh: Mesh, a: Array) -> NamedSharding:
    axes = tuple(mesh.axis_names)
    spec = P(axes, *([None] * (a.ndim - 1)))
    return NamedSharding(mesh, spec)


def score_random_effects(
    coeffs: Sequence[Array],
    buckets: EntityBuckets,
) -> Array:
    """Per-sample raw scores w_entity · x for every ACTIVE sample.

    Returns scores[num_samples] aligned with the original sample-row order
    (reference RandomEffectCoordinate.score:167-196, which shuffles scored
    tuples back to the uniqueId partitioner — here a scatter by row index).
    Samples of excluded/capped-out entities get 0.
    """
    total = jnp.zeros((buckets.num_samples,), coeffs[0].dtype if coeffs else jnp.float32)
    for b, w in zip(buckets.buckets, coeffs):
        margins = jnp.einsum("esd,ed->es", jnp.asarray(b.x), w)
        valid = b.rows >= 0
        safe_rows = jnp.where(valid, b.rows, 0)
        total = total.at[safe_rows.ravel()].add(
            jnp.where(valid, margins, 0.0).ravel()
        )
    return total


def stacked_coefficients(
    coeffs: Sequence[Array], buckets: EntityBuckets
) -> Tuple[Array, Dict[int, int]]:
    """Stack per-bucket lane coefficients into W[num_entities, d] + id->slot map.

    The dense W is the device-resident form of the reference's
    RDD[(REId, GLM)] model (RandomEffectModel.scala) — scoring any sample set
    becomes a gather + row-wise dot (see score_samples), covering the
    reference's "passive data" path (samples capped out of training still get
    scored, RandomEffectDataset passiveData / RandomEffectCoordinate.scala:210-231).
    """
    # ONE host transfer per bucket, then numpy gathers — indexing device
    # arrays per entity would issue thousands of tiny dispatches.
    host = [np.asarray(c) for c in coeffs]
    slot_of: Dict[int, int] = {}
    parts = []
    for eid in sorted(buckets.lane_of):
        bi, lane = buckets.lane_of[eid]
        slot_of[eid] = len(slot_of)
        parts.append(host[bi][lane])
    w = jnp.asarray(np.stack(parts)) if parts else jnp.zeros((0, buckets.dim))
    return w, slot_of


def stack_bucket_lanes(lane_ws: Sequence[Array], slot_idx: Sequence[Array],
                       num_entities: int) -> Array:
    """Traceable stacked_coefficients: scatter per-bucket lane coefficient
    rows into W[num_entities, d].  ``slot_idx[bi][lane]`` is the stacked row
    (out-of-range for invalid/padded lanes, which the 'drop' scatter
    discards).  Device-side counterpart of ``stacked_coefficients`` for
    fully-jitted sweeps (game/fused.py)."""
    d = lane_ws[0].shape[-1]
    w = jnp.zeros((num_entities, d), lane_ws[0].dtype)
    for idx, lw in zip(slot_idx, lane_ws):
        w = w.at[idx].set(lw, mode="drop")
    return w


def score_samples(w_stack: Array, slots: Array, x: Array) -> Array:
    """Raw per-sample scores (x_i · w_entity(i)) for ANY sample set.

    ``slots``: per-sample row index into w_stack, -1 for samples whose entity
    has no model (score 0 — reference scores missing random effects as 0).
    """
    safe = jnp.where(slots >= 0, slots, 0)
    margins = jnp.einsum("nd,nd->n", x, w_stack[safe])
    return jnp.where(slots >= 0, margins, 0.0)


NARROW_SCORE_DIM_MAX = 32  # [d, n] layout only ever helps below this width
# Measured crossover for the transposed layout (v5e, round-5 shipped-code
# checklist vs the run-1 pre-swap numbers, TPU_CHECKLIST.json):
#   - glmix2  [524288, 16] f32  -> padded [n, d] is 268 MB; the einsum row
#     layout is 1.56x FASTER (0.47s vs 0.73s per sweep) — the pad fits HBM
#     and XLA fuses the single gather+einsum better than d serial passes.
#   - glmix_chip [8.39M, 4] bf16 -> padded [n, d] is 2.1 GB and the scoring
#     HLO materializes two of them: OOM on a 16 GB chip. Transposed layout
#     is the only way this config EXISTS on the v5e.
# So the gate is the padded-HBM footprint (n x 128 lanes x itemsize), not
# the width alone: transpose only when the pad is an actual memory threat.
NARROW_SCORE_PAD_BYTES_MIN = 1 << 30


def use_transposed_scoring(n: int, d: int, itemsize: int) -> bool:
    """True when full-sample dense scoring should use the [d, n]
    samples-on-lanes layout (``score_samples_t``) instead of row-major
    [n, d] (``score_samples``).  See the crossover note above."""
    return (d <= NARROW_SCORE_DIM_MAX
            and n * 128 * itemsize >= NARROW_SCORE_PAD_BYTES_MIN)


def score_samples_t(w_stack: Array, slots: Array, x_t: Array) -> Array:
    """``score_samples`` for a TRANSPOSED [d, n] full-sample array.

    TPU tiling pads an array's minor axis to 128 lanes, so a narrow [n, d]
    design (random-effect shards are typically d<=16 wide) occupies 128/d x
    its logical bytes in HBM and so does every [n, d] gather from it — 32x
    at d=4, which turned glmix_chip's 8.39M-sample scoring into 2 x 4GB of
    HLO temp and OOMed a 16GB v5e (bench round 5).  Samples-on-lanes layout
    keeps every large intermediate 1-D over n: d static gathers of [E]
    coefficient columns, and no padded [n, d] array ever exists.
    """
    safe = jnp.where(slots >= 0, slots, 0)
    w_t = w_stack.T  # [d, E]: entities on lanes, tiny either way
    acc = jnp.zeros(x_t.shape[1],
                    jnp.promote_types(x_t.dtype, w_stack.dtype))
    for j in range(x_t.shape[0]):  # d is static and small by contract
        acc = acc + x_t[j] * w_t[j][safe]
    return jnp.where(slots >= 0, acc, 0.0)


def score_samples_sparse(w_stack: Array, slots: Array, indices: Array,
                         values: Array) -> Array:
    """Raw per-sample scores for ROW-SPARSE features:
    sum_k w_stack[slot_i, indices[i,k]] * values[i,k].

    The sparse twin of ``score_samples`` — no [n, d_full] densification, an
    O(n*k) two-level gather instead.  Padded COO slots carry value 0
    (SparseShard contract), so whatever coefficient they gather is inert;
    samples with slot -1 (entity without a model) score 0.
    """
    safe = jnp.where(slots >= 0, slots, 0)
    gathered = w_stack[safe[:, None], indices]  # [n, k]
    margins = jnp.sum(gathered * values, axis=-1)
    return jnp.where(slots >= 0, margins, 0.0)


def gather_entity_coefficients(
    coeffs: Sequence[Array], buckets: EntityBuckets
) -> Dict[int, np.ndarray]:
    """Entity id -> coefficient vector (host-side model export)."""
    host = [np.asarray(c) for c in coeffs]
    return {eid: host[bi][lane] for eid, (bi, lane) in buckets.lane_of.items()}
