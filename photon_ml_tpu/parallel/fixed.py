"""Distributed fixed-effect GLM fitting — the DP hot path.

Reference call stack (SURVEY.md §3.2): FixedEffectCoordinate.updateModel ->
DistributedOptimizationProblem.run -> Optimizer.optimize, where every
objective evaluation costs one driver->executor coefficient broadcast + one
treeAggregate reduction.

TPU-native shape: the ENTIRE solver (L-BFGS/TRON while_loop included) is one
jitted SPMD program over the mesh.  The batch arrives sharded on the ``data``
axis, w0 replicated; GSPMD partitions the margin matmul by rows and inserts
one all-reduce per value+grad evaluation over ICI — the exact communication
pattern of the reference's treeAggregate but with zero per-step weight
shipping and no host round-trip between optimizer iterations.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
from jax.sharding import Mesh

from photon_ml_tpu.core.batch import Batch
from photon_ml_tpu.core.objective import GLMObjective
from photon_ml_tpu.opt.solve import make_solver
from photon_ml_tpu.opt.types import SolverConfig, SolverResult
from photon_ml_tpu.parallel.mesh import replicate, shard_batch
from photon_ml_tpu.types import OptimizerType

Array = jax.Array


def fit_fixed_effect(
    objective: GLMObjective,
    batch: Batch,
    w0: Array,
    mesh: Mesh,
    optimizer: OptimizerType = OptimizerType.LBFGS,
    config: Optional[SolverConfig] = None,
    box: Optional[Tuple[Array, Array]] = None,
    batch_presharded: bool = False,
) -> SolverResult:
    """Fit one fixed-effect GLM coordinate over the mesh.

    ``batch_presharded``: skip the device_put when the caller already laid the
    batch out (the coordinate-descent loop places data once and reuses it).
    """
    if not batch_presharded:
        batch = shard_batch(batch, mesh)
    rep = replicate(mesh)
    w0 = jax.device_put(w0, rep)
    solve = make_solver(objective, optimizer, config, box=box)
    # Replicated outputs force GSPMD to all-reduce the sharded loss/grad
    # reductions inside the solver loop.
    fitted = jax.jit(solve, out_shardings=rep)
    return fitted(w0, batch)
