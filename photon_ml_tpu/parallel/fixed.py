"""Distributed fixed-effect GLM fitting — the DP hot path.

Reference call stack (SURVEY.md §3.2): FixedEffectCoordinate.updateModel ->
DistributedOptimizationProblem.run -> Optimizer.optimize, where every
objective evaluation costs one driver->executor coefficient broadcast + one
treeAggregate reduction.

TPU-native shape: the ENTIRE solver (L-BFGS/TRON while_loop included) is one
jitted SPMD program over the mesh.  The batch arrives sharded on the ``data``
axis, w0 replicated; GSPMD partitions the margin matmul by rows and inserts
one all-reduce per value+grad evaluation over ICI — the exact communication
pattern of the reference's treeAggregate but with zero per-step weight
shipping and no host round-trip between optimizer iterations.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from photon_ml_tpu.core.batch import Batch, DenseBatch
from photon_ml_tpu.core.objective import GLMObjective
from photon_ml_tpu.opt.solve import make_solver
from photon_ml_tpu.opt.types import SolverConfig, SolverResult
from photon_ml_tpu.parallel.mesh import (
    DATA_AXIS,
    FEATURE_AXIS,
    padded_dim,
    replicate,
    shard_batch,
    shard_coefficients,
)
from photon_ml_tpu.types import OptimizerType

Array = jax.Array


class ShardMapObjective:
    """GLMObjective computed as EXPLICIT SPMD: per-shard raw sums + psum.

    The psum over the ``data`` mesh axis is the reference's treeAggregate
    (ValueAndGradientAggregator.scala:248-252) mapped onto ICI.  Two reasons
    to be explicit rather than letting GSPMD partition the math:

    - pallas kernels (ops/fused_glm.py) are custom calls GSPMD cannot
      auto-partition; under shard_map each device runs the kernel on its
      LOCAL rows, so the fused path works multi-chip;
    - the communication pattern is pinned (exactly one all-reduce per
      objective evaluation), not left to the partitioner's cost model.

    Presents the same (reg / value_and_grad / hvp) surface the solvers bind
    (opt/solve.make_solver), so it drops into any of them.  The batch must
    arrive sharded on the leading example axis (parallel/mesh.shard_batch).
    """

    def __init__(self, objective: GLMObjective, mesh: Mesh, axis: str = DATA_AXIS):
        self.obj = objective
        self.mesh = mesh
        self.axis = axis

    @property
    def reg(self):
        return self.obj.reg

    def with_reg(self, reg) -> "ShardMapObjective":
        """Reg-overridden copy (see GLMObjective.with_reg); used inside a
        trace, so plain construction is fine."""
        return ShardMapObjective(self.obj.with_reg(reg), self.mesh, self.axis)

    def _specs(self, batch: Batch):
        row_sharded = lambda a: P(self.axis, *([None] * (a.ndim - 1)))
        return jax.tree.map(row_sharded, batch)

    def value_and_grad(self, w: Array, batch: Batch) -> Tuple[Array, Array]:
        obj, axis = self.obj, self.axis

        def local(w, b):
            # one psum call over the tuple = one pinned fused all-reduce
            return jax.lax.psum(obj.raw_value_and_grad(w, b), axis)

        rv, gr, rs = jax.shard_map(
            local, mesh=self.mesh, in_specs=(P(), self._specs(batch)),
            out_specs=(P(), P(), P()))(w, batch)
        return obj.finish_value_and_grad(w, rv, gr, rs)

    def hvp(self, w: Array, batch: Batch, v: Array) -> Array:
        obj, axis = self.obj, self.axis

        def local(w, b, v):
            return jax.lax.psum(obj.raw_hvp(w, b, v), axis)

        hv, qs = jax.shard_map(
            local, mesh=self.mesh, in_specs=(P(), self._specs(batch), P()),
            out_specs=(P(), P()))(w, batch, v)
        return obj.finish_hvp(v, hv, qs)

    # Variance computation (opt/solve.compute_variances) needs the Hessian
    # diagonal / matrix.  Both are sums over examples followed by elementwise
    # (linear) normalization maps, so per-shard values psum exactly — except
    # the L2 term, which every shard adds once; subtract it locally and re-add
    # after the reduction (reference treeAggregate reduces UN-regularized
    # aggregators for the same reason, HessianDiagonalAggregator.scala:128).

    def hessian_diag(self, w: Array, batch: Batch) -> Array:
        obj, axis = self.obj, self.axis

        def local(w, b):
            return jax.lax.psum(obj.hessian_diag(w, b) - obj.reg.l2, axis)

        return jax.shard_map(
            local, mesh=self.mesh, in_specs=(P(), self._specs(batch)),
            out_specs=P())(w, batch) + obj.reg.l2

    def hessian(self, w: Array, batch: Batch) -> Array:
        obj, axis = self.obj, self.axis
        d = w.shape[-1]

        def local(w, b):
            eye = jnp.eye(d, dtype=w.dtype)
            return jax.lax.psum(obj.hessian(w, b) - obj.reg.l2 * eye, axis)

        h = jax.shard_map(
            local, mesh=self.mesh, in_specs=(P(), self._specs(batch)),
            out_specs=P())(w, batch)
        return h + obj.reg.l2 * jnp.eye(d, dtype=h.dtype)


def fit_fixed_effect(
    objective: GLMObjective,
    batch: Batch,
    w0: Array,
    mesh: Mesh,
    optimizer: OptimizerType = OptimizerType.LBFGS,
    config: Optional[SolverConfig] = None,
    box: Optional[Tuple[Array, Array]] = None,
    batch_presharded: bool = False,
    feature_sharded: bool = False,
) -> SolverResult:
    """Fit one fixed-effect GLM coordinate over the mesh.

    ``batch_presharded``: skip the device_put when the caller already laid the
    batch out (the coordinate-descent loop places data once and reuses it).

    ``feature_sharded``: shard w (and the dense design matrix's columns) over
    the mesh's ``feature`` axis for huge-d problems — no device holds the full
    coefficient vector, and each objective evaluation's margin contraction /
    per-feature gradient partial sums become GSPMD-inserted collectives over
    ICI.  This is the TPU analog of the reference keeping 1e8-feature models
    out of any single JVM heap (PalDB index maps + treeAggregate, SURVEY §5).
    The returned w is sliced back to the caller's d (padding is trimmed).
    """
    d = int(w0.shape[0])
    if feature_sharded and not isinstance(batch, DenseBatch):
        # Sparse batches address w by global index; a feature-sharded w would
        # force an all-gather per lookup.  Shard-local-id sparse layouts are
        # the data layer's job — refuse loudly rather than silently
        # replicating a vector the caller asked to keep sharded.
        raise ValueError(
            "feature_sharded=True requires a DenseBatch; sparse batches use "
            "global feature ids (project/densify first, or keep w replicated)")
    if not batch_presharded:
        batch = shard_batch(batch, mesh,
                            feature_axis=FEATURE_AXIS if feature_sharded else None)
    rep = replicate(mesh)
    if feature_sharded:
        d_pad = padded_dim(d, mesh)
        if batch.x.shape[-1] != d_pad:
            raise ValueError(
                f"feature-sharded batch has {batch.x.shape[-1]} feature "
                f"columns but w pads to {d_pad}; preshard with "
                f"shard_batch(..., feature_axis=FEATURE_AXIS)")
        if d_pad != d:
            # Pad every (d,)-shaped companion of w so padded slots stay
            # pinned at 0: box bounds pad with [0, 0], normalization factors
            # with 1 (identity scale) and shifts with 0 (no shift).
            pad = d_pad - d
            if box is not None:
                box = (jnp.pad(box[0], (0, pad)), jnp.pad(box[1], (0, pad)))
            norm = objective.norm
            if norm.factors is not None or norm.shifts is not None:
                objective = objective.replace(norm=norm.replace(
                    factors=None if norm.factors is None
                    else jnp.pad(norm.factors, (0, pad), constant_values=1.0),
                    shifts=None if norm.shifts is None
                    else jnp.pad(norm.shifts, (0, pad)),
                ))
        w0 = shard_coefficients(w0, mesh)
    else:
        w0 = jax.device_put(w0, rep)
    if feature_sharded:
        # w stays P("feature") throughout; sharding propagates from inputs
        # and GSPMD inserts the feature-axis contractions.
        solve = make_solver(objective, optimizer, config, box=box)
        fitted = jax.jit(solve)
    else:
        # Explicit SPMD (one psum per evaluation); the caller's fused flag is
        # honored as-is — under shard_map the pallas kernels run per-device
        # on local rows, so fused=True works multi-chip too.
        sm = ShardMapObjective(objective, mesh)
        solve = make_solver(sm, optimizer, config, box=box)
        fitted = jax.jit(solve, out_shardings=rep)
    result = fitted(w0, batch)
    if feature_sharded and result.w.shape[0] != d:
        result = result.replace(w=result.w[:d])
    return result
