"""Distributed fixed-effect GLM fitting — the DP hot path.

Reference call stack (SURVEY.md §3.2): FixedEffectCoordinate.updateModel ->
DistributedOptimizationProblem.run -> Optimizer.optimize, where every
objective evaluation costs one driver->executor coefficient broadcast + one
treeAggregate reduction.

TPU-native shape: the ENTIRE solver (L-BFGS/TRON while_loop included) is one
jitted SPMD program over the mesh.  The batch arrives sharded on the ``data``
axis, w0 replicated; GSPMD partitions the margin matmul by rows and inserts
one all-reduce per value+grad evaluation over ICI — the exact communication
pattern of the reference's treeAggregate but with zero per-step weight
shipping and no host round-trip between optimizer iterations.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from photon_ml_tpu.core.batch import Batch, DenseBatch, SparseBatch
from photon_ml_tpu.parallel.compat import shard_map
from photon_ml_tpu.core.objective import GLMObjective
from photon_ml_tpu.opt.solve import make_solver
from photon_ml_tpu.opt.types import SolverConfig, SolverResult
from photon_ml_tpu.parallel.mesh import (
    DATA_AXIS,
    FEATURE_AXIS,
    padded_dim,
    replicate,
    shard_batch,
    shard_coefficients,
)
from photon_ml_tpu.types import OptimizerType

Array = jax.Array


class ShardMapObjective:
    """GLMObjective computed as EXPLICIT SPMD: per-shard raw sums + psum.

    The psum over the ``data`` mesh axis is the reference's treeAggregate
    (ValueAndGradientAggregator.scala:248-252) mapped onto ICI.  Two reasons
    to be explicit rather than letting GSPMD partition the math:

    - pallas kernels (ops/fused_glm.py) are custom calls GSPMD cannot
      auto-partition; under shard_map each device runs the kernel on its
      LOCAL rows, so the fused path works multi-chip;
    - the communication pattern is pinned (exactly one all-reduce per
      objective evaluation), not left to the partitioner's cost model.

    Presents the same (reg / value_and_grad / hvp) surface the solvers bind
    (opt/solve.make_solver), so it drops into any of them.  The batch must
    arrive sharded on the leading example axis (parallel/mesh.shard_batch).
    """

    def __init__(self, objective: GLMObjective, mesh: Mesh, axis: str = DATA_AXIS):
        self.obj = objective
        self.mesh = mesh
        self.axis = axis

    @property
    def reg(self):
        return self.obj.reg

    def with_reg(self, reg) -> "ShardMapObjective":
        """Reg-overridden copy (see GLMObjective.with_reg); used inside a
        trace, so plain construction is fine."""
        return ShardMapObjective(self.obj.with_reg(reg), self.mesh, self.axis)

    def _specs(self, batch: Batch):
        row_sharded = lambda a: P(self.axis, *([None] * (a.ndim - 1)))
        return jax.tree.map(row_sharded, batch)

    def value_and_grad(self, w: Array, batch: Batch) -> Tuple[Array, Array]:
        obj, axis = self.obj, self.axis

        def local(w, b):
            # one psum call over the tuple = one pinned fused all-reduce
            return jax.lax.psum(obj.raw_value_and_grad(w, b), axis)

        rv, gr, rs = shard_map(
            local, mesh=self.mesh, in_specs=(P(), self._specs(batch)),
            out_specs=(P(), P(), P()))(w, batch)
        return obj.finish_value_and_grad(w, rv, gr, rs)

    def hvp(self, w: Array, batch: Batch, v: Array) -> Array:
        obj, axis = self.obj, self.axis

        def local(w, b, v):
            return jax.lax.psum(obj.raw_hvp(w, b, v), axis)

        hv, qs = shard_map(
            local, mesh=self.mesh, in_specs=(P(), self._specs(batch), P()),
            out_specs=(P(), P()))(w, batch, v)
        return obj.finish_hvp(v, hv, qs)

    # Variance computation (opt/solve.compute_variances) needs the Hessian
    # diagonal / matrix.  Both are sums over examples followed by elementwise
    # (linear) normalization maps, so per-shard values psum exactly — except
    # the L2 term, which every shard adds once; subtract it locally and re-add
    # after the reduction (reference treeAggregate reduces UN-regularized
    # aggregators for the same reason, HessianDiagonalAggregator.scala:128).

    def hessian_diag(self, w: Array, batch: Batch) -> Array:
        obj, axis = self.obj, self.axis

        def local(w, b):
            return jax.lax.psum(obj.hessian_diag(w, b) - obj.reg.l2, axis)

        return shard_map(
            local, mesh=self.mesh, in_specs=(P(), self._specs(batch)),
            out_specs=P())(w, batch) + obj.reg.l2

    def hessian(self, w: Array, batch: Batch) -> Array:
        obj, axis = self.obj, self.axis
        d = w.shape[-1]

        def local(w, b):
            eye = jnp.eye(d, dtype=w.dtype)
            return jax.lax.psum(obj.hessian(w, b) - obj.reg.l2 * eye, axis)

        h = shard_map(
            local, mesh=self.mesh, in_specs=(P(), self._specs(batch)),
            out_specs=P())(w, batch)
        return h + obj.reg.l2 * jnp.eye(d, dtype=h.dtype)


class ShardSparseObjective:
    """Sparse GLM objective with w sharded over the ``feature`` mesh axis.

    The huge-vocabulary path (reference scale story: sparse vectors over
    PalDB 1e8-feature index maps, PalDBIndexMap.scala:16-60): no device holds
    the full coefficient vector.  Each device owns a contiguous block of
    ``shard_d`` coefficients and the batch rows of its ``data`` shard
    (indices stay GLOBAL — the data layout is identical to the replicated-w
    case, so the data path needs no shard-local reindexing pass):

      margins   masked gather from the LOCAL w block (out-of-block slots
                contribute 0) -> one psum over ``feature`` assembles the full
                margin for the shard's rows;
      gradient  per-block masked scatter-add -> one psum over ``data``; the
                result STAYS feature-sharded (P('feature')) — the per-feature
                partial-sum layout the reference gets from treeAggregate
                segments, mapped onto ICI.

    Communication per value+grad evaluation: exactly one feature-axis
    all-reduce of an [n_local] vector + one data-axis all-reduce of the
    [shard_d] block (vs the replicated-w path's single [d] all-reduce — for
    d >> n/D this is the cheaper direction, which is the point).

    All normalization/regularization algebra runs OUTSIDE the shard_map at
    GSPMD level on sharded (d_pad,) vectors (elementwise ops keep the
    sharding; dots psum over ICI).  Scaling-only normalization is supported;
    shifts would densify sparse margins, so they raise — same reason the
    reference recommends scaling-only normalization for sparse data
    (NormalizationType SCALE_WITH_*).
    """

    def __init__(self, objective: GLMObjective, mesh: Mesh, shard_d: int,
                 data_axis: str = DATA_AXIS, feature_axis: str = FEATURE_AXIS):
        if objective.norm.shifts is not None:
            raise ValueError(
                "feature-sharded sparse fitting supports scaling-only "
                "normalization (shifts densify sparse margins)")
        self.obj = objective
        self.mesh = mesh
        self.shard_d = shard_d
        self.data_axis = data_axis
        self.feature_axis = feature_axis

    @property
    def reg(self):
        return self.obj.reg

    def with_reg(self, reg) -> "ShardSparseObjective":
        return ShardSparseObjective(self.obj.with_reg(reg), self.mesh,
                                    self.shard_d, self.data_axis,
                                    self.feature_axis)

    def _specs(self, batch: SparseBatch):
        row_sharded = lambda a: P(self.data_axis, *([None] * (a.ndim - 1)))
        return jax.tree.map(row_sharded, batch)

    def _local_margins(self, blk: Array, b: SparseBatch):
        """(raw margins x·w for local rows — psum over feature, no offset —,
        masked values, local ids).  The ONE definition of the shard-local
        gather/mask rule, shared by every objective pass and by margins()."""
        lo = jax.lax.axis_index(self.feature_axis) * self.shard_d
        lid = b.indices - lo
        ok = (lid >= 0) & (lid < self.shard_d)
        vals = jnp.where(ok, b.values.astype(blk.dtype), 0)
        lid = jnp.clip(lid, 0, self.shard_d - 1)
        z = jax.lax.psum(jnp.sum(vals * blk[lid], axis=-1), self.feature_axis)
        return z, vals, lid

    def _local_parts(self, blk: Array, b: SparseBatch):
        """(full margins incl. offset for local rows, masked values, local ids)."""
        z, vals, lid = self._local_margins(blk, b)
        return z + b.offset, vals, lid

    def _scatter(self, vals: Array, lid: Array, r: Array) -> Array:
        """Local block of X^T r (masked vals make clamped ids contribute 0)."""
        contrib = vals * r[..., None]
        return jnp.zeros((self.shard_d,), contrib.dtype).at[lid].add(contrib)

    def value_and_grad(self, w: Array, batch: SparseBatch) -> Tuple[Array, Array]:
        obj, data, feat = self.obj, self.data_axis, self.feature_axis
        eff = obj.norm.effective_coefficients(w)  # elementwise: stays sharded

        def local(eff_blk, b):
            z, vals, lid = self._local_parts(eff_blk, b)
            z = jnp.where(b.weight > 0, z, 0.0)  # core masking contract
            l, d1 = obj.loss.loss_and_d1(z, b.y)
            r = b.weight * d1
            return (jax.lax.psum(jnp.sum(b.weight * l), data),
                    jax.lax.psum(self._scatter(vals, lid, r), data),
                    jax.lax.psum(jnp.sum(r), data))

        rv, gr, rs = shard_map(
            local, mesh=self.mesh, in_specs=(P(feat), self._specs(batch)),
            out_specs=(P(), P(feat), P()))(eff, batch)
        return obj.finish_value_and_grad(w, rv, gr, rs)

    def hvp(self, w: Array, batch: SparseBatch, v: Array) -> Array:
        obj, data, feat = self.obj, self.data_axis, self.feature_axis
        eff_w = obj.norm.effective_coefficients(w)
        eff_v = obj.norm.effective_coefficients(v)

        def local(ew_blk, ev_blk, b):
            z, vals, lid = self._local_parts(ew_blk, b)
            z = jnp.where(b.weight > 0, z, 0.0)
            mv = jax.lax.psum(jnp.sum(vals * ev_blk[lid], axis=-1), feat)
            q = b.weight * obj.loss.d2(z, b.y) * mv
            return (jax.lax.psum(self._scatter(vals, lid, q), data),
                    jax.lax.psum(jnp.sum(q), data))

        hv, qs = shard_map(
            local, mesh=self.mesh,
            in_specs=(P(feat), P(feat), self._specs(batch)),
            out_specs=(P(feat), P()))(eff_w, eff_v, batch)
        return obj.finish_hvp(v, hv, qs)

    def margins(self, w: Array, batch: SparseBatch) -> Array:
        """Raw margins x·w of a feature-sharded w (same contract as
        Batch.margins: no offset, no normalization shift).  One [n_local]
        psum over the feature axis — the pinned-communication alternative to
        letting GSPMD all-gather the full [d_pad] coefficient vector for the
        gather in SparseBatch.margins.  Used by the fused sweep's re-scoring
        of a feature-sharded coordinate (game/coordinate.trace_update)."""
        def local(blk, b):
            return self._local_margins(blk, b)[0]

        return shard_map(
            local, mesh=self.mesh,
            in_specs=(P(self.feature_axis), self._specs(batch)),
            out_specs=P(self.data_axis))(w, batch)

    def hessian_diag(self, w: Array, batch: SparseBatch) -> Array:
        obj, data, feat = self.obj, self.data_axis, self.feature_axis
        eff = obj.norm.effective_coefficients(w)

        def local(eff_blk, b):
            z, vals, lid = self._local_parts(eff_blk, b)
            z = jnp.where(b.weight > 0, z, 0.0)
            q = b.weight * obj.loss.d2(z, b.y)
            return jax.lax.psum(self._scatter(vals * vals, lid, q), data)

        diag = shard_map(
            local, mesh=self.mesh, in_specs=(P(feat), self._specs(batch)),
            out_specs=P(feat))(eff, batch)
        if obj.norm.factors is not None:
            diag = diag * obj.norm.factors * obj.norm.factors
        return diag + obj.reg.l2

    def hessian(self, w: Array, batch: SparseBatch) -> Array:
        raise NotImplementedError(
            "FULL variance needs the dense d x d Hessian — not meaningful at "
            "feature-sharded scale; use SIMPLE (diagonal) variances")


def fit_fixed_effect(
    objective: GLMObjective,
    batch: Batch,
    w0: Array,
    mesh: Mesh,
    optimizer: OptimizerType = OptimizerType.LBFGS,
    config: Optional[SolverConfig] = None,
    box: Optional[Tuple[Array, Array]] = None,
    batch_presharded: bool = False,
    feature_sharded: bool = False,
) -> SolverResult:
    """Fit one fixed-effect GLM coordinate over the mesh.

    ``batch_presharded``: skip the device_put when the caller already laid the
    batch out (the coordinate-descent loop places data once and reuses it).

    ``feature_sharded``: shard w (and the dense design matrix's columns) over
    the mesh's ``feature`` axis for huge-d problems — no device holds the full
    coefficient vector, and each objective evaluation's margin contraction /
    per-feature gradient partial sums become GSPMD-inserted collectives over
    ICI.  This is the TPU analog of the reference keeping 1e8-feature models
    out of any single JVM heap (PalDB index maps + treeAggregate, SURVEY §5).
    The returned w is sliced back to the caller's d (padding is trimmed).
    """
    d = int(w0.shape[0])
    if not batch_presharded:
        batch = shard_batch(batch, mesh,
                            feature_axis=FEATURE_AXIS if feature_sharded else None)
    rep = replicate(mesh)
    if feature_sharded:
        d_pad = padded_dim(d, mesh)
        if isinstance(batch, DenseBatch) and batch.x.shape[-1] != d_pad:
            raise ValueError(
                f"feature-sharded batch has {batch.x.shape[-1]} feature "
                f"columns but w pads to {d_pad}; preshard with "
                f"shard_batch(..., feature_axis=FEATURE_AXIS)")
        if d_pad != d:
            # Pad every (d,)-shaped companion of w so padded slots stay
            # pinned at 0: box bounds pad with [0, 0], normalization factors
            # with 1 (identity scale) and shifts with 0 (no shift).
            pad = d_pad - d
            if box is not None:
                box = (jnp.pad(box[0], (0, pad)), jnp.pad(box[1], (0, pad)))
            norm = objective.norm
            if norm.factors is not None or norm.shifts is not None:
                objective = objective.replace(norm=norm.replace(
                    factors=None if norm.factors is None
                    else jnp.pad(norm.factors, (0, pad), constant_values=1.0),
                    shifts=None if norm.shifts is None
                    else jnp.pad(norm.shifts, (0, pad)),
                ))
        w0 = shard_coefficients(w0, mesh)
    else:
        w0 = jax.device_put(w0, rep)
    if feature_sharded:
        if isinstance(batch, SparseBatch):
            # Global-id sparse rows + blocked w: explicit shard_map objective
            # (masked gather/scatter per block — see ShardSparseObjective).
            # Solver state stays P("feature") via propagation from w0.
            sm = ShardSparseObjective(objective, mesh,
                                      d_pad // mesh.shape[FEATURE_AXIS])
            solve = make_solver(sm, optimizer, config, box=box)
            # photonlint: disable=sharding-annotation -- solver state stays
            # P("feature") via propagation from the sharded w0; the result
            # pytree mixes [d_pad] lanes with scalar diagnostics, so one
            # broadcast out_shardings spec cannot express the layout
            fitted = jax.jit(solve)
        else:
            # w stays P("feature") throughout; sharding propagates from
            # inputs and GSPMD inserts the feature-axis contractions.
            solve = make_solver(objective, optimizer, config, box=box)
            # photonlint: disable=sharding-annotation -- same propagation
            # contract as the sparse branch above: w0 pins P("feature")
            fitted = jax.jit(solve)
    else:
        # Explicit SPMD (one psum per evaluation); the caller's fused flag is
        # honored as-is — under shard_map the pallas kernels run per-device
        # on local rows, so fused=True works multi-chip too.
        sm = ShardMapObjective(objective, mesh)
        solve = make_solver(sm, optimizer, config, box=box)
        fitted = jax.jit(solve, out_shardings=rep)
    result = fitted(w0, batch)
    if feature_sharded and result.w.shape[0] != d:
        result = result.replace(w=result.w[:d])
    return result
