"""Per-entity feature projection for random-effect coordinates.

Reference machinery being replaced (SURVEY.md §2.2 "Projectors", ~776 LoC):
  - IndexMapProjector / IndexMapProjectorRDD: per-entity observed-feature
    compaction — each entity's local problem is solved in the subspace of
    features it has actually seen (projector/IndexMapProjectorRDD.scala:34-262,
    build: 222-261).
  - ProjectionMatrix / ProjectionMatrixBroadcast: shared Gaussian random
    projection to a fixed low dimension (projector/ProjectionMatrix.scala:127,
    ProjectionMatrixBroadcast.scala:150).
  - LocalDataset.filterFeaturesByPearsonCorrelationScore: per-entity top-k
    feature selection by |Pearson correlation| with the label
    (data/LocalDataset.scala:185-247), driven by
    RandomEffectDataConfiguration.featuresToSamplesRatio.

TPU-native design: the reference keeps a projector OBJECT per entity inside an
RDD and maps every vector through it.  Here projection is a static data-layout
step over the already-bucketed entity arrays:

  - INDEX_MAP: per-lane gather indices ``idx[E, d_proj]`` (−1 = padding);
    projected design block ``x[E, S, d_proj] = x_full[..., idx]`` built once on
    host; solvers run vmapped in the small d_proj space (a dense [E, S, d_proj]
    MXU program instead of [E, S, d_full]); trained coefficients are scattered
    back to full dimension, so margins are EXACTLY preserved and scoring stays
    full-dimensional.
  - RANDOM: one shared Gaussian matrix A[d_full, d_proj] (the reference
    broadcasts one ProjectionMatrix per coordinate too); x' = x·A, and
    back-projection w = A·w' preserves margins by construction
    (w'ᵀ(Aᵀx) = (Aw')ᵀx).

Solving in the observed subspace is loss-identical to the full-space solve for
GLMs: an unobserved feature has zero data gradient, and with zero-initialised
coefficients L2/L1 keep it at exactly 0 — the reference relies on the same
fact when it projects.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import numpy as np

from photon_ml_tpu.parallel.bucketing import Bucket, EntityBuckets
from photon_ml_tpu.types import ProjectorType


def _pow2_at_least(k: int) -> int:
    return max(1, 1 << (max(0, k - 1)).bit_length())


def pearson_scores(x: np.ndarray, y: np.ndarray, weight: np.ndarray) -> np.ndarray:
    """|Pearson correlation| of each column of x with y over weighted samples.

    Reference LocalDataset.scala:185-247 computes the same score per entity to
    rank features.  Near-constant columns carry no per-entity signal and score
    0; the intercept's guaranteed survival is handled by the caller pinning
    ``intercept_index`` (build_observed_indices), not by guessing which
    constant column is the intercept — an entity-constant attribute feature
    would otherwise hijack the carve-out.
    """
    w = weight / max(float(weight.sum()), 1e-12)
    mx = w @ x
    my = float(w @ y)
    dx = x - mx
    dy = y - my
    cov = (w * dy) @ dx
    vx = w @ (dx * dx)
    vy = float(w @ (dy * dy))
    denom = np.sqrt(np.maximum(vx * vy, 0.0))
    near_const = vx <= 1e-12 * np.maximum(1.0, np.abs(mx) ** 2)
    with np.errstate(invalid="ignore", divide="ignore"):
        score = np.abs(cov) / np.where(denom > 0, denom, 1.0)
    out = np.where(denom > 0, score, 0.0)
    out[near_const] = 0.0
    return out


def pearson_top_k(x: np.ndarray, y: np.ndarray, w: np.ndarray,
                  obs: np.ndarray, keep_n: int,
                  intercept_index: Optional[int] = None) -> np.ndarray:
    """Sorted positions (into ``obs``) of the top-``keep_n`` |Pearson|
    columns of ``x`` (LocalDataset.scala:185-247) — THE per-entity feature
    filter, shared by the dense bucket path (build_observed_indices) and the
    row-sparse one (bucket_by_entity_sparse) so tie-breaking and the
    intercept pin cannot diverge.  The intercept column (located via its
    full-dim id in ``obs``) always survives."""
    scores = pearson_scores(x, y, w)
    if intercept_index is not None:
        at = np.nonzero(obs == intercept_index)[0]
        if at.size:
            scores[at[0]] = np.inf  # intercept always survives
    return np.sort(np.argsort(-scores, kind="stable")[:keep_n])


@dataclasses.dataclass
class BucketProjection:
    """INDEX_MAP projection of one bucket: per-lane gather indices."""

    indices: np.ndarray  # [E, d_proj] int32, -1 padding
    d_full: int

    @property
    def d_proj(self) -> int:
        return self.indices.shape[1]

    def project_x(self, x: np.ndarray) -> np.ndarray:
        """[E, S, d_full] -> [E, S, d_proj]; padding columns are zero."""
        safe = np.where(self.indices < 0, 0, self.indices)  # [E, d_proj]
        out = np.take_along_axis(x, safe[:, None, :], axis=2)
        return np.where((self.indices >= 0)[:, None, :], out, 0.0).astype(x.dtype)

    def back_project(self, w_proj: np.ndarray,
                     fill: Optional[np.ndarray] = None) -> np.ndarray:
        """[E, d_proj] -> [E, d_full] scatter (margin-exact).

        ``fill``: per-feature value [d_full] every UNOBSERVED slot takes
        (default 0).  Box-constrained compact solves pass clip(0, lo, hi):
        the reference solves in full space and projects every iterate into
        the box (OptimizationUtils.projectCoefficientsToSubspace), so an
        unobserved feature — whose full-space optimum is the box projection
        of the L2 pull toward 0 — publishes clip(0, lo, hi), not 0."""
        e = w_proj.shape[0]
        if fill is None:
            out = np.zeros((e, self.d_full), w_proj.dtype)
        else:
            out = np.broadcast_to(np.asarray(fill, w_proj.dtype),
                                  (e, self.d_full)).copy()
        lanes = np.repeat(np.arange(e), self.d_proj)
        idx = self.indices.reshape(-1)
        vals = np.asarray(w_proj).reshape(-1)
        keep = idx >= 0
        out[lanes[keep], idx[keep]] = vals[keep]
        # padding lanes (bucket slots past the real entity count carry an
        # all -1 index row) must stay zero — a fill row there would publish
        # clip(0, lo, hi) coefficients for entities that don't exist
        if fill is not None:
            invalid = ~(self.indices >= 0).any(axis=1)
            if invalid.any():
                out[invalid] = 0.0
        return out


@dataclasses.dataclass
class RandomProjection:
    """Shared Gaussian projection (reference ProjectionMatrix.scala:127).

    ``intercept_index``: original-space intercept column, when the matrix
    carries the reference's intercept pass-through (an extra projected slot
    that copies the intercept exactly — the "dummy row" of
    ProjectionMatrix.buildGaussianRandomProjectionMatrix:112-120, a column
    here under the transposed [d_full, d_proj] convention).  The projected
    intercept is then the LAST projected coordinate
    (ProjectionMatrix.scala:43 projectedInterceptId)."""

    matrix: np.ndarray  # [d_full, d_proj]
    intercept_index: Optional[int] = None

    @property
    def d_full(self) -> int:
        return self.matrix.shape[0]

    @property
    def d_proj(self) -> int:
        return self.matrix.shape[1]

    @property
    def projected_intercept(self) -> Optional[int]:
        return None if self.intercept_index is None else self.d_proj - 1

    def project_x(self, x: np.ndarray) -> np.ndarray:
        return (x @ self.matrix).astype(x.dtype)

    def back_project(self, w_proj: np.ndarray) -> np.ndarray:
        return (np.asarray(w_proj) @ self.matrix.T).astype(w_proj.dtype)

    def project_normalization(self, norm) -> tuple:
        """Reference ProjectionMatrixBroadcast.projectNormalizationContext
        (:102-112): push factors AND shifts through projectFeatures; the
        projected intercept id is the pass-through slot.  Returns
        ``(projected NormalizationContext, projected intercept index)``."""
        from photon_ml_tpu.core.normalization import NormalizationContext

        fac = (None if norm.factors is None
               else (np.asarray(norm.factors) @ self.matrix).astype(
                   self.matrix.dtype))
        shifts = (None if norm.shifts is None
                  else (np.asarray(norm.shifts) @ self.matrix).astype(
                      self.matrix.dtype))
        return (NormalizationContext(factors=fac, shifts=shifts),
                self.projected_intercept)


def build_random_projection(d_full: int, d_proj: int, seed: int = 0,
                            dtype=np.float32,
                            intercept_index: Optional[int] = None
                            ) -> RandomProjection:
    """``intercept_index``: append the intercept pass-through slot (the
    reference builds every random-effect projection with
    isKeepingInterceptTerm=true, RandomEffectProjector.scala:80) — the
    projected design gets d_proj+1 columns, the last being the original
    intercept column copied exactly."""
    rng = np.random.default_rng(seed)
    m = rng.normal(scale=1.0 / np.sqrt(d_proj), size=(d_full, d_proj))
    m = m.astype(dtype)
    if intercept_index is not None:
        e = np.zeros((d_full, 1), dtype)
        e[intercept_index, 0] = 1.0
        # zero the Gaussian mass on the intercept column so the pass-through
        # slot is the ONLY place its signal lands (the reference's dummy row
        # coexists with Gaussian rows that also see the intercept; zeroing
        # keeps the projected intercept exact AND non-duplicated)
        m[intercept_index, :] = 0.0
        m = np.concatenate([m, e], axis=1)
    return RandomProjection(matrix=m, intercept_index=intercept_index)


def build_observed_indices(
    bucket: Bucket,
    d_full: int,
    features_to_samples_ratio: Optional[float] = None,
    intercept_index: Optional[int] = None,
) -> BucketProjection:
    """Observed-feature gather indices for every lane of one bucket.

    A feature is "observed" for an entity when any of its active samples has a
    nonzero value in that column (the reference builds the same set from
    active+passive indices, IndexMapProjectorRDD.scala:222-261).  When
    ``features_to_samples_ratio`` is set, each entity keeps at most
    ``ratio * active_count`` features, ranked by |Pearson| with the label
    (LocalDataset.scala:185-247); the intercept column is always kept.
    """
    e, s, _ = bucket.x.shape
    per_lane: List[np.ndarray] = []
    for lane in range(e):
        k = int(bucket.counts[lane])
        if k == 0:
            per_lane.append(np.empty(0, np.int32))
            continue
        x = bucket.x[lane, :k]
        observed = np.nonzero(np.any(x != 0.0, axis=0))[0]
        if features_to_samples_ratio is not None and observed.size > 0:
            keep_n = max(1, int(np.ceil(features_to_samples_ratio * k)))
            if observed.size > keep_n:
                top = pearson_top_k(x[:, observed], bucket.y[lane, :k],
                                    bucket.weight[lane, :k], observed,
                                    keep_n, intercept_index)
                observed = observed[top]
        per_lane.append(observed.astype(np.int32))

    d_proj = _pow2_at_least(max((len(o) for o in per_lane), default=1))
    d_proj = min(d_proj, d_full)
    indices = np.full((e, d_proj), -1, np.int32)
    for lane, obs in enumerate(per_lane):
        obs = obs[:d_proj]
        indices[lane, : len(obs)] = obs
    return BucketProjection(indices=indices, d_full=d_full)


@dataclasses.dataclass
class ProjectedBuckets:
    """Entity buckets re-laid-out in projected feature space.

    ``buckets[i]`` has design blocks of width ``projections[i].d_proj``;
    everything else (lanes, rows, weights, directory) is unchanged, so the
    descent/score plumbing in RandomEffectCoordinate applies as-is.
    """

    base: EntityBuckets
    buckets: List[Bucket]
    projections: List[object]  # BucketProjection | RandomProjection per bucket

    def back_project(self, coeffs: List[np.ndarray],
                     fill: Optional[np.ndarray] = None) -> List[np.ndarray]:
        kw = {} if fill is None else {"fill": fill}
        return [p.back_project(np.asarray(w), **kw)
                for p, w in zip(self.projections, coeffs)]


def project_buckets(
    buckets: EntityBuckets,
    kind: ProjectorType,
    projected_dim: Optional[int] = None,
    features_to_samples_ratio: Optional[float] = None,
    intercept_index: Optional[int] = None,
    seed: int = 0,
) -> ProjectedBuckets:
    """Apply a ProjectorType to every bucket (host-side, one-time layout)."""
    if kind == ProjectorType.IDENTITY:
        raise ValueError("IDENTITY projection needs no ProjectedBuckets")
    if kind == ProjectorType.RANDOM and features_to_samples_ratio is not None:
        raise ValueError(
            "features_to_samples_ratio applies only to INDEX_MAP projection; "
            "RANDOM would silently ignore it")
    if kind == ProjectorType.INDEX_MAP and projected_dim is not None:
        raise ValueError(
            "projected_dim applies only to RANDOM projection; INDEX_MAP "
            "derives its dimension from observed features per entity")
    new_buckets: List[Bucket] = []
    projections: List[object] = []
    shared: Optional[RandomProjection] = None
    for b in buckets.buckets:
        if kind == ProjectorType.INDEX_MAP:
            proj: object = build_observed_indices(
                b, buckets.dim, features_to_samples_ratio, intercept_index)
        elif kind == ProjectorType.RANDOM:
            if projected_dim is None:
                raise ValueError("RANDOM projection requires projected_dim")
            if shared is None:
                shared = build_random_projection(buckets.dim, projected_dim, seed,
                                                 dtype=b.x.dtype,
                                                 intercept_index=intercept_index)
            proj = shared
        else:
            raise ValueError(f"unknown projector {kind!r}")
        new_buckets.append(dataclasses.replace(b, x=proj.project_x(b.x)))
        projections.append(proj)
    return ProjectedBuckets(base=buckets, buckets=new_buckets, projections=projections)
