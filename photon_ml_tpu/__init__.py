"""photon_ml_tpu — a TPU-native framework with the capabilities of LinkedIn Photon ML.

Photon ML (reference: /root/reference) is a Spark/Scala library for Generalized
Linear Models and GLMix / GAME (Generalized Additive Mixed Effects) models trained
by block coordinate descent. This package re-designs those capabilities TPU-first:

- ``core``      pure-JAX pointwise losses, GLM objectives, normalization algebra
                (reference: photon-lib .../function, .../normalization)
- ``opt``       jittable + vmappable L-BFGS / OWLQN / TRON solvers
                (reference: photon-lib .../optimization)
- ``parallel``  device mesh, shard_map'd SPMD objective reductions, entity bucketing
                (reference substrate: Spark treeAggregate / broadcast / shuffle)
- ``game``      coordinates + coordinate descent + estimator/transformer
                (reference: photon-lib .../algorithm, photon-api estimators)
- ``models``    GLM + GAME model containers
                (reference: photon-api supervised/**, model/**)
- ``evaluation``AUC/RMSE/... evaluators and suites (reference: .../evaluation)
- ``tune``      Sobol random search + Gaussian-process Bayesian optimization
                (reference: photon-lib .../hyperparameter)
- ``data``      Avro/libsvm readers, feature index maps, synthetic generators
                (reference: photon-client .../data, .../index)
- ``utils``     logging, timing, linalg helpers (reference: .../util)
- ``obs``       photonscope: span tracer (Chrome trace export), unified
                metrics registry (Prometheus/JSON), JAX runtime probe
                (reference: .../util PhotonLogger/Timed + event/* — unified)

Everything device-side is functional JAX: static shapes, ``lax``-control flow,
collectives via ``shard_map`` over a ``jax.sharding.Mesh``.
"""

__version__ = "0.1.0"

from photon_ml_tpu.types import TaskType  # noqa: F401
