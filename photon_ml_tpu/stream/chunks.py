"""Chunk sources: shard input files into independently decodable pieces.

A chunk is the unit of parallel decode and of error isolation — one Avro
container block (record count known from the block header without touching
the payload) or one libsvm line range.  Sources do a cheap metadata-only
scan up front so the TOTAL row count is known before the first record
decodes (the device-side design matrices are preallocated [n, d]) and torn
files surface at scan time as explicitly-marked torn chunks rather than as
a mid-epoch surprise.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

from photon_ml_tpu.data import avro as _avro


@dataclasses.dataclass
class Chunk:
    """One decodable shard of the input.

    ``n_rows`` is the record count known WITHOUT decoding (Avro block
    header / libsvm line scan), or -1 when a torn Avro block header made it
    unknowable.  ``torn`` chunks fail in ``decode_chunk`` by construction —
    they exist so the row-count accounting and the error policy both see
    truncation explicitly instead of a silently short epoch.
    """

    index: int
    path: str
    n_rows: int
    torn: bool = False
    span: Optional[_avro.BlockSpan] = None          # Avro
    byte_range: Optional[Tuple[int, int]] = None    # libsvm


class AvroStreamSource:
    """Avro container files -> block-aligned chunks.

    ``paths`` may be files or directories (directories expand via
    ``list_avro_files``, sorted — the same file order as the eager reader,
    which the bitwise-parity guarantee depends on).
    """

    def __init__(self, paths):
        if isinstance(paths, str):
            paths = [paths]
        self.files: List[str] = [f for p in paths
                                 for f in _avro.list_avro_files(p)]
        self._info = {}
        self.chunks: List[Chunk] = []
        for path in self.files:
            info = _avro.scan_container_blocks(path)
            self._info[path] = info
            for span in info.blocks:
                self.chunks.append(Chunk(
                    index=len(self.chunks), path=path,
                    n_rows=span.count if span.count >= 0 else -1,
                    torn=span.torn, span=span))

    @property
    def num_rows(self) -> int:
        """Rows with a KNOWN count.  Payload-torn blocks are included (the
        header survived; skip policy keeps their rows, inert); header-torn
        blocks are excluded — their count is unknowable, and they are
        surfaced as chunk errors, never silently absorbed."""
        return sum(c.n_rows for c in self.chunks if c.n_rows >= 0)

    def schema(self, path: Optional[str] = None) -> dict:
        return self._info[path or self.files[0]].schema

    def decode_chunk(self, chunk: Chunk) -> List[dict]:
        """Decode one block to records (thread-safe: bounded seek+read, no
        shared mutable state).  Raises ValueError with file+offset context
        for torn spans, sync mismatches, bad compression, or decode errors
        — the pipeline's per-chunk error unit."""
        info = self._info[chunk.path]
        raw = _avro.read_block(chunk.path, chunk.span, info.codec, info.sync)
        br = _avro._Reader(raw)
        named: dict = {}  # fresh per block: decode() mutates it
        try:
            return [_avro.decode(info.schema, br, named)
                    for _ in range(chunk.span.count)]
        except ValueError:
            raise
        except Exception as e:
            raise ValueError(f"{chunk.path}: corrupt block at offset "
                             f"{chunk.span.offset}: {e!r}") from e


#: One parsed libsvm row: (label, [(1-based index, value), ...]).
LibsvmRow = Tuple[float, List[Tuple[int, float]]]


class LibsvmStreamSource:
    """A libsvm file -> line-range chunks of ``rows_per_chunk`` rows.

    The scan walks the file once counting non-empty lines and recording
    chunk byte ranges — O(1) memory.  (This is also why streaming libsvm
    needs an explicit ``num_features``: the eager reader's max-index
    default would cost a full parse pass.)
    """

    def __init__(self, path: str, rows_per_chunk: int = 4096):
        self.path = path
        self.chunks: List[Chunk] = []
        with open(path, "rb") as f:
            start, count = 0, 0
            while True:
                line = f.readline()
                if not line:
                    break
                if line.split():
                    count += 1
                if count >= rows_per_chunk:
                    self.chunks.append(Chunk(index=len(self.chunks),
                                             path=path, n_rows=count,
                                             byte_range=(start, f.tell())))
                    start, count = f.tell(), 0
            if count:
                self.chunks.append(Chunk(index=len(self.chunks), path=path,
                                         n_rows=count,
                                         byte_range=(start, f.tell())))

    @property
    def num_rows(self) -> int:
        return sum(c.n_rows for c in self.chunks)

    def decode_chunk(self, chunk: Chunk) -> List[LibsvmRow]:
        """Parse one line range — token-for-token the ``read_libsvm`` parse,
        so the streamed design matrix matches the eager one bitwise."""
        lo, hi = chunk.byte_range
        with open(self.path, "rb") as f:
            f.seek(lo)
            blob = f.read(hi - lo)
        out: List[LibsvmRow] = []
        try:
            for line in blob.decode().splitlines():
                parts = line.split()
                if not parts:
                    continue
                label = float(parts[0])
                row = []
                for tok in parts[1:]:
                    k, _, v = tok.partition(":")
                    row.append((int(k), float(v)))
                out.append((label, row))
        except (UnicodeDecodeError, ValueError) as e:
            raise ValueError(f"{self.path}: corrupt libsvm chunk at bytes "
                             f"[{lo}, {hi}): {e}") from e
        if len(out) != chunk.n_rows:
            raise ValueError(f"{self.path}: chunk at bytes [{lo}, {hi}) "
                             f"parsed {len(out)} rows, scan counted "
                             f"{chunk.n_rows}")
        return out
