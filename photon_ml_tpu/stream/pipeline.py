"""Bounded-parallel ordered chunk decode.

``ChunkPipeline`` runs a source's chunks through a thread pool (decode is
zlib + Avro varint walking — it releases the GIL in zlib and is the hot
host cost the reference stack pays in Spark serialization) while the
consumer receives chunks strictly IN SUBMISSION ORDER.  Ordered delivery
is a correctness property, not a convenience: the consumer assigns dense
entity ids grow-on-first-sight and fills global row ranges, and both must
see records in exactly the eager reader's order for the bitwise-parity
guarantee.

The submission window (``workers + depth``) bounds host memory to ~that
many decoded chunks regardless of dataset size, and doubles as the
prefetch depth that hides decode latency behind the consumer's fill+upload
work.

Error policy (the malformed-input knob): ``raise`` re-raises the first
chunk's error; ``skip`` yields the chunk with ``records=None`` and the
error, counts it (``stream_chunk_errors_total``), and keeps going — the
consumer decides what a lost chunk means (the GameData ingest keeps its
row range, inert).  Either way the pool is shut down with futures
cancelled on exit, so a torn file can never hang the epoch.
"""

from __future__ import annotations

import collections
import concurrent.futures
import logging
import time
from typing import Iterator, Optional, Tuple

from photon_ml_tpu.chaos.injector import fault as _chaos_fault
from photon_ml_tpu.obs import trace as _trace
from photon_ml_tpu.obs.registry import get_registry
from photon_ml_tpu.stream.chunks import Chunk

_LOG = logging.getLogger("photon_ml_tpu.stream")


class ChunkPipeline:
    """Ordered bounded decode over ``source.chunks`` (see module docstring).

    Iterating yields ``(chunk, records, error)``: exactly one of
    ``records`` / ``error`` is None.  ``stall_seconds`` accumulates time
    the consumer spent blocked on not-yet-decoded chunks — the pipeline-
    stall axis the stream bench reports (0 means decode fully hidden).
    """

    def __init__(self, source, workers: int = 2, depth: int = 2,
                 on_error: str = "raise"):
        if on_error not in ("raise", "skip"):
            raise ValueError(f"on_error must be 'raise' or 'skip', "
                             f"got {on_error!r}")
        self.source = source
        self.workers = max(1, int(workers))
        self.depth = max(0, int(depth))
        self.on_error = on_error
        self.stall_seconds = 0.0
        self.error_count = 0

    def _decode(self, chunk: Chunk):
        act = _chaos_fault("stream.decode")
        if act is not None:
            # "slow"/"stall_dist" exercise the pipeline-stall accounting
            # (stall_dist holds come pre-sampled by the injector);
            # "corrupt" exercises the on_error raise/skip contract — both
            # flow through the exact paths a real bad chunk would take
            if act.kind in ("slow", "stall_dist"):
                time.sleep(float(act.data.get("stall_s", 0.05)))
            else:
                raise ValueError(
                    f"injected {act.kind} chunk at index {chunk.index}")
        with _trace.span("stream.decode", chunk=chunk.index,
                         rows=chunk.n_rows):
            return self.source.decode_chunk(chunk)

    def __iter__(self) -> Iterator[Tuple[Chunk, Optional[list],
                                         Optional[Exception]]]:
        chunks = list(self.source.chunks)
        if not chunks:
            return
        registry = get_registry()
        window = self.workers + self.depth
        pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=self.workers, thread_name_prefix="photonstream")
        pending: collections.deque = collections.deque()
        nxt = 0
        try:
            while nxt < len(chunks) and len(pending) < window:
                pending.append((chunks[nxt],
                                pool.submit(self._decode, chunks[nxt])))
                nxt += 1
            while pending:
                registry.set_gauge("stream_buffer_depth", len(pending))
                chunk, fut = pending.popleft()
                t0 = time.perf_counter()
                try:
                    records, err = fut.result(), None
                except Exception as e:  # noqa: BLE001 — per-chunk policy unit
                    records, err = None, e
                self.stall_seconds += time.perf_counter() - t0
                registry.inc("stream_chunks_total")
                if err is not None:
                    self.error_count += 1
                    registry.inc("stream_chunk_errors_total")
                    if self.on_error == "raise":
                        raise err
                    _LOG.warning("stream: skipping chunk %d (%s): %s",
                                 chunk.index, chunk.path, err)
                # refill BEFORE yielding: the consumer's fill+upload work
                # overlaps the next decode
                if nxt < len(chunks):
                    pending.append((chunks[nxt],
                                    pool.submit(self._decode, chunks[nxt])))
                    nxt += 1
                yield chunk, records, err
        finally:
            registry.set_gauge("stream_buffer_depth", 0)
            # cumulative consumer-blocked time, visible to metrics exports
            # and the stream bench even when the pipeline object is internal
            registry.add_gauge("stream_stall_seconds", self.stall_seconds)
            pool.shutdown(wait=False, cancel_futures=True)
