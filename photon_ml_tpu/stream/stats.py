"""Streaming per-entity sufficient statistics for random-effect grouping.

The eager path groups sample rows by entity with ``parallel/bucketing
._group_rows`` — one pass over the FULL id column with the deterministic
splitmix64 reservoir cap.  A streaming epoch never holds the full dataset,
but the id columns are scalar (8 bytes/row) and stay host-resident, and the
reservoir selection is a running min-``cap`` over a total order, so the
same grouping can be accumulated chunk by chunk.  ``EntityStats``
reproduces ``_group_rows``' output EXACTLY — same kept rows, same entity
order, same ``count / cap`` rescale floats — which is what lets streamed
random-effect solves match the in-memory path bitwise.

Two accumulation modes:

- ``active_cap=None`` (full): keeps every row index per entity.  Memory is
  O(total rows) of int64 — same order as the host id column itself — and
  any ``(active_cap, seed)`` can be answered later by recomputing keys.
- ``active_cap=k`` (capped): keeps at most ``k`` ``(row, key)`` pairs per
  entity — the running cap-smallest by ``(key, row)``, exactly the set
  ``argsort(keys, kind="stable")[:cap]`` selects over ascending rows.
  Memory is O(entities * cap); only the matching ``(active_cap, seed)``
  can be answered (``groups`` returns None otherwise and the coordinate
  falls back to ``_group_rows`` over the host id column).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from photon_ml_tpu.parallel.bucketing import _splitmix64

Groups = Tuple[List[np.ndarray], List[int], List[float]]


class EntityStats:
    """Chunk-incremental replica of ``_group_rows`` (see module docstring)."""

    def __init__(self, active_cap: Optional[int] = None, seed: int = 0):
        self.active_cap = active_cap
        self.seed = seed
        self._counts: Dict[int, int] = {}
        self._rows: Dict[int, np.ndarray] = {}
        self._keys: Dict[int, np.ndarray] = {}  # capped mode only

    @property
    def num_entities(self) -> int:
        return len(self._counts)

    def update(self, entity_ids: np.ndarray, row_base: int) -> None:
        """Fold one chunk's id column (GLOBAL rows ``row_base ..``).

        Chunks must arrive in row order (the pipeline is ordered), so
        full-mode row lists stay globally ascending — the property
        ``_group_rows`` gets from its stable argsort and that the capped
        mode's ``(key, row)`` tie-break reproduces.  Missing-tag rows
        (id -1) are NOT filtered: ``_group_rows`` groups them too.
        """
        eids = np.asarray(entity_ids, np.int64)
        if eids.size == 0:
            return
        uniq, inverse, counts = np.unique(eids, return_inverse=True,
                                          return_counts=True)
        order = np.argsort(inverse, kind="stable")
        starts = np.concatenate([[0], np.cumsum(counts)])
        cap = self.active_cap
        for e in range(len(uniq)):
            eid = int(uniq[e])
            rows = (order[starts[e]: starts[e + 1]]
                    + row_base).astype(np.int64)
            self._counts[eid] = self._counts.get(eid, 0) + len(rows)
            prev = self._rows.get(eid)
            if cap is None:
                self._rows[eid] = rows if prev is None \
                    else np.concatenate([prev, rows])
                continue
            keys = _splitmix64(rows.astype(np.uint64) ^ np.uint64(self.seed))
            if prev is not None:
                rows = np.concatenate([prev, rows])
                keys = np.concatenate([self._keys[eid], keys])
            if len(rows) > cap:
                # running min-cap by (key, row): the same set a one-shot
                # stable argsort over keys selects, since rows are unique
                # and ascending within each incoming chunk
                sel = np.lexsort((rows, keys))[:cap]
                rows, keys = rows[sel], keys[sel]
            self._rows[eid] = rows
            self._keys[eid] = keys

    def groups(self, active_cap: Optional[int], min_active_samples: int,
               seed: int, existing_model_keys: Optional[frozenset] = None,
               ) -> Optional[Groups]:
        """The ``(kept_rows, kept_entities, rescale)`` triple
        ``_group_rows`` would produce over the full id column, or None when
        this accumulator was capped for a DIFFERENT ``(active_cap, seed)``
        (the capped selection is irrecoverable; caller falls back)."""
        if self.active_cap is not None and (
                active_cap != self.active_cap or seed != self.seed):
            return None
        kept_rows: List[np.ndarray] = []
        kept_entities: List[int] = []
        rescale: List[float] = []
        for eid in sorted(self._counts):
            total = self._counts[eid]
            if total < min_active_samples and (
                    existing_model_keys is None
                    or eid in existing_model_keys):
                continue
            rows = self._rows[eid]
            scale = 1.0
            if active_cap is not None and total > active_cap:
                if self.active_cap is None:
                    keys = _splitmix64(rows.astype(np.uint64)
                                       ^ np.uint64(seed))
                    rows = rows[np.argsort(keys, kind="stable")[:active_cap]]
                # same float operands as _group_rows' len(keys) / active_cap
                scale = total / active_cap
            kept_rows.append(np.sort(rows))
            kept_entities.append(eid)
            rescale.append(scale)
        return kept_rows, kept_entities, rescale
