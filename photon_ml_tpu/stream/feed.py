"""Double-buffered device feed: fixed-shape batch uploads -> [n, d] arrays.

The ingest fills pow2-sized [batch_rows, d] host blocks and pushes them
here; ``DeviceFeed`` uploads each block non-blocking
(``utils/transfer.stream_device_put``) and DEFERS the donated
``dynamic_update_slice`` write into the preallocated [n, d] device array
until ``max_in_flight`` newer uploads are in flight — so batch N's device
write overlaps batch N+1's host->device transfer, the double-buffering the
tentpole names.  All uploads share ONE [batch_rows, d] shape per group
(plus one ragged-tail shape), so the jitted update compiles twice total
and the solve kernels downstream never see a shape they haven't AOT'd.

Device-memory peak: the [n, d] outputs + ``max_in_flight`` batches (the
update donates the output buffer — see ``utils/transfer._update_at``).
"""

from __future__ import annotations

import collections
from typing import Dict

import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.utils.transfer import stream_device_put, stream_update


class DeviceFeed:
    """Assembles per-group [n, d] device arrays from a batch stream."""

    def __init__(self, n: int, group_dims: Dict[object, int], dtype,
                 max_in_flight: int = 2):
        self._out = {gid: jnp.zeros((n, d), dtype)
                     for gid, d in group_dims.items()}
        self._dtype = dtype
        self.max_in_flight = max(1, int(max_in_flight))
        self._inflight: collections.deque = collections.deque()
        self.batches_pushed = 0

    def push(self, blocks: Dict[object, np.ndarray], lo: int,
             rows: int) -> Dict[object, object]:
        """Upload one batch (async) and apply the oldest deferred write.

        ``blocks`` maps group id -> [B, d] host block whose first ``rows``
        rows are valid; the caller must hand over OWNERSHIP (on CPU
        backends ``jnp.asarray`` may alias the host buffer zero-copy, so
        reusing a pushed block would corrupt an in-flight upload — the
        ingest allocates a fresh block per batch).  Returns the uploaded
        device blocks so stream consumers (opt/streamfold) can fold over
        them without a second upload.
        """
        parts = {gid: stream_device_put(b, self._dtype)
                 for gid, b in blocks.items()}
        self._inflight.append((parts, lo, rows))
        self.batches_pushed += 1
        while len(self._inflight) > self.max_in_flight:
            self._apply(self._inflight.popleft())
        return parts

    def _apply(self, item) -> None:
        parts, lo, rows = item
        for gid, part in parts.items():
            self._out[gid] = stream_update(self._out[gid], part, lo, rows)

    def finish(self) -> Dict[object, jnp.ndarray]:
        """Drain deferred writes and fence; returns the [n, d] arrays."""
        while self._inflight:
            self._apply(self._inflight.popleft())
        for out in self._out.values():
            out.block_until_ready()
        return self._out
