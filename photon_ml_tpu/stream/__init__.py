"""photonstream: out-of-core streaming data plane.

Shards Avro / libsvm inputs into block-aligned chunks
(``data/avro.scan_container_blocks``), decodes them on a bounded background
thread pool (``ChunkPipeline``), and double-buffers fixed-shape host->device
batch uploads (``DeviceFeed`` over ``utils/transfer.stream_device_put`` /
``stream_update``) so batch N+1's transfer overlaps batch N's device write.
``stream_game_data`` assembles the SAME ``GameData`` the eager reader
produces — design matrices live on device, assembled in place from the
batch stream; scalar columns (labels, offsets, weights, id tags) stay host
— so the existing estimator runs unchanged and coefficients match the
in-memory path bitwise on RAM-sized data, while peak host memory on bigger
data is bounded by ~2 in-flight chunks + pipeline buffers.
"""

from photon_ml_tpu.stream.chunks import (AvroStreamSource, Chunk,
                                         LibsvmStreamSource)
from photon_ml_tpu.stream.feed import DeviceFeed
from photon_ml_tpu.stream.ingest import stream_game_data, stream_libsvm
from photon_ml_tpu.stream.pipeline import ChunkPipeline
from photon_ml_tpu.stream.stats import EntityStats

__all__ = [
    "AvroStreamSource",
    "Chunk",
    "ChunkPipeline",
    "DeviceFeed",
    "EntityStats",
    "LibsvmStreamSource",
    "stream_game_data",
    "stream_libsvm",
]
