"""Streaming GameData assembly: chunk stream -> device design matrices.

``stream_game_data`` is the out-of-core twin of
``data/reader.read_game_data_avro``: same inputs, same ``GameData`` out,
but the dense design matrices are assembled ON DEVICE from fixed-shape
batch uploads instead of materializing [n, d] host arrays.  Bitwise parity
with the eager path is by construction, not by luck:

- chunks decode in parallel but are CONSUMED in file/block order, and each
  record flows through the SAME ``reader.fill_record_row`` the eager loop
  uses — identical float accumulation order, identical grow-on-first-sight
  entity-id assignment;
- uploads move bytes, not math: ``float32(x)`` uploaded then gathered is
  the same bits as ``float32(x)`` indexed on host.

What stays host-resident: the O(8 bytes/row) scalar columns (labels,
offsets, weights, uids, id-tag columns) — the same columns every solve
needs densely and repeatedly.  What never materializes on host: any
[n, d] design block; peak host memory is ~(workers + depth) decoded
chunks + ``max_in_flight`` batch buffers.

Malformed input follows the pipeline's policy knob: ``raise`` surfaces the
first corrupt chunk; ``skip`` keeps the epoch AND the row count honest —
a payload-torn block's rows (count known from its header) stay allocated
with ``weight=0`` (inert in every weighted loss and every sufficient
statistic) and are counted in ``stream_skipped_rows_total``; a header-torn
block (count unknowable) is excluded from ``n`` by the scan itself and
counted as a chunk error.  No silent short epochs.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

from photon_ml_tpu.data.reader import (DEFAULT_INPUT_COLUMNS, EntityIndex,
                                       _shard_groups, fill_record_row)
from photon_ml_tpu.game.data import GameData
from photon_ml_tpu.obs.registry import get_registry
from photon_ml_tpu.stream.chunks import AvroStreamSource, LibsvmStreamSource
from photon_ml_tpu.stream.feed import DeviceFeed
from photon_ml_tpu.stream.pipeline import ChunkPipeline
from photon_ml_tpu.stream.stats import EntityStats


def stream_game_data(
    paths: Iterable[str],
    index_maps: Dict[str, object],
    id_tag_names: Iterable[str] = (),
    entity_indexes: Optional[Dict[str, EntityIndex]] = None,
    dtype=np.float32,
    input_columns: Optional[Dict[str, str]] = None,
    batch_rows: int = 4096,
    workers: int = 2,
    depth: int = 2,
    on_error: str = "raise",
    active_caps: Optional[Dict[str, int]] = None,
    seed: int = 0,
    validate: bool = False,
    sparse_shards: Optional[Iterable[str]] = None,
    folds: Optional[Dict[str, object]] = None,
) -> Tuple[GameData, Dict[str, EntityIndex]]:
    """TrainingExampleAvro files -> GameData with DEVICE design matrices.

    ``batch_rows`` should be a power of two (the fixed device-feed batch
    shape; default 4096).  ``active_caps`` maps id-tag -> that coordinate's
    ``active_cap`` so ``EntityStats`` can accumulate the capped reservoir
    in O(entities * cap); tags without an entry accumulate full row lists.
    ``validate=True`` finite-checks every batch (labels, offsets, weights,
    design blocks) before upload and raises ValueError — data invalidity is
    not subject to the ``on_error`` chunk policy, which covers malformed
    FILES.  ``sparse_shards`` must be empty: streamed sparse assembly is a
    ROADMAP follow-on.  ``folds`` maps shard name ->
    ``opt.streamfold.StreamingFixedEffectFold``: each uploaded batch is
    folded into that shard's fixed-effect sufficient statistics in the same
    pass, reusing the feed's device blocks.
    """
    if sparse_shards and set(sparse_shards):
        raise ValueError("streaming ingest does not support sparse shards "
                         "yet (ROADMAP item 5 follow-on); use the eager "
                         "reader for sparse-shard configs")
    cols = {**DEFAULT_INPUT_COLUMNS, **(input_columns or {})}
    if isinstance(paths, str):
        paths = [paths]
    source = AvroStreamSource(paths)
    n = source.num_rows
    batch_rows = max(1, int(batch_rows))

    groups, group_maps, group_sparse = _shard_groups(index_maps, set())
    group_dims = {gid: m.size for gid, m in group_maps.items()}

    y = np.zeros(n, dtype)
    offset = np.zeros(n, dtype)
    weight = np.ones(n, dtype)
    uids = np.empty(n, object)
    id_tag_names = list(id_tag_names)
    entity_indexes = entity_indexes or {}
    for tag in id_tag_names:
        entity_indexes.setdefault(tag, EntityIndex())
    tags = {tag: np.full(n, -1, np.int64) for tag in id_tag_names}
    stats = {tag: EntityStats((active_caps or {}).get(tag), seed)
             for tag in id_tag_names}

    feed = DeviceFeed(n, group_dims, dtype, max_in_flight=2)
    registry = get_registry()

    def fresh_bufs():
        # fresh buffers every batch: the previous batch may still be
        # uploading, and jnp.asarray can alias host memory zero-copy
        return {gid: np.zeros((batch_rows, d), dtype)
                for gid, d in group_dims.items()}

    bufs = fresh_bufs()
    lo = 0      # global row where the current batch starts
    fill = 0    # valid rows in the current batch
    row = 0     # next global row

    folds = folds or {}
    gid_of_shard = {shard: gid for gid, shards_of in groups.items()
                    for shard in shards_of}
    for shard in folds:
        if shard not in gid_of_shard:
            raise ValueError(f"fold for unknown shard {shard!r}")

    def flush():
        nonlocal bufs, lo, fill
        if fill == 0:
            return
        if validate:
            for gid, b in bufs.items():
                if not np.isfinite(b[:fill]).all():
                    shard = groups[gid][0]
                    raise ValueError(
                        f"non-finite feature value in shard {shard!r}, "
                        f"rows [{lo}, {lo + fill})")
        parts = feed.push(bufs, lo, fill)
        for shard, fold in folds.items():
            fold.accumulate(parts[gid_of_shard[shard]], y[lo:lo + fill],
                            offset[lo:lo + fill], weight[lo:lo + fill], fill)
        bufs = fresh_bufs()
        lo += fill
        fill = 0

    pipeline = ChunkPipeline(source, workers=workers, depth=depth,
                             on_error=on_error)
    for chunk, records, err in pipeline:
        if chunk.n_rows < 0:
            continue  # header-torn: no rows allocated, error already counted
        if err is not None:
            # lost chunk with KNOWN count: keep its rows, inert (weight 0),
            # so n and every downstream row range stay exact
            weight[row:row + chunk.n_rows] = 0.0
            registry.inc("stream_skipped_rows_total", chunk.n_rows)
            remaining = chunk.n_rows
            while remaining > 0:
                take = min(remaining, batch_rows - fill)
                fill += take
                row += take
                remaining -= take
                if fill == batch_rows:
                    flush()
            continue
        base = row
        for rec in records:
            fill_record_row(rec, cols, row, fill, y, offset, weight, uids,
                            tags, entity_indexes, id_tag_names, group_maps,
                            group_sparse, bufs)
            row += 1
            fill += 1
            if fill == batch_rows:
                flush()
        if validate:
            for name, col in (("response", y), ("offset", offset),
                              ("weight", weight)):
                if not np.isfinite(col[base:row]).all():
                    raise ValueError(f"non-finite {name} in {chunk.path}, "
                                     f"rows [{base}, {row})")
        for tag in id_tag_names:
            stats[tag].update(tags[tag][base:row], base)
    flush()
    outs = feed.finish()

    mats: Dict[str, object] = {}
    for gid, shards_of in groups.items():
        for shard in shards_of:
            mats[shard] = outs[gid]

    data = GameData(y=y, features=mats, offset=offset, weight=weight,
                    id_tags=tags, uids=uids,
                    entity_stats=stats if id_tag_names else None)
    return data, entity_indexes


def stream_libsvm(path: str, num_features: int, add_intercept: bool = True,
                  binary_labels_01: bool = True, dtype=np.float32,
                  batch_rows: int = 4096, workers: int = 2, depth: int = 2,
                  on_error: str = "raise"):
    """Streaming twin of ``reader.read_libsvm``: (X on device, y, intercept).

    ``num_features`` is REQUIRED (the eager default scans for the max
    index, which would cost a full extra parse pass out-of-core).  Parity:
    duplicate indices overwrite (last wins) exactly like the eager
    assignment fill, and the -1/+1 -> 0/1 label mapping applies over the
    FULL label vector at the end, matching the eager reader's whole-file
    check.
    """
    if num_features is None:
        raise ValueError("stream_libsvm requires explicit num_features")
    source = LibsvmStreamSource(path, rows_per_chunk=batch_rows)
    n = source.num_rows
    extra = 1 if add_intercept else 0
    d = int(num_features) + extra

    y = np.zeros(n, dtype)
    feed = DeviceFeed(n, {"x": d}, dtype, max_in_flight=2)
    buf = np.zeros((batch_rows, d), dtype)
    if add_intercept:
        buf[:, 0] = 1.0
    lo = fill = row = 0

    def fresh():
        b = np.zeros((batch_rows, d), dtype)
        if add_intercept:
            b[:, 0] = 1.0
        return b

    def flush():
        nonlocal buf, lo, fill
        if fill == 0:
            return
        feed.push({"x": buf}, lo, fill)
        buf = fresh()
        lo += fill
        fill = 0

    pipeline = ChunkPipeline(source, workers=workers, depth=depth,
                             on_error=on_error)
    for chunk, rows_parsed, err in pipeline:
        if err is not None:
            # lost chunk: rows stay allocated but fully zero (label 0,
            # no intercept) so they contribute nothing to any Gram/moment
            row += chunk.n_rows
            remaining = chunk.n_rows
            while remaining > 0:
                take = min(remaining, batch_rows - fill)
                if add_intercept:
                    buf[fill:fill + take, 0] = 0.0
                fill += take
                remaining -= take
                if fill == batch_rows:
                    flush()
            continue
        for label, pairs in rows_parsed:
            y[row] = label
            for j, v in pairs:
                if j > num_features:
                    raise ValueError(f"{path}: feature index {j} exceeds "
                                     f"num_features={num_features}")
                buf[fill, j - 1 + extra] = v
            row += 1
            fill += 1
            if fill == batch_rows:
                flush()
    flush()
    x = feed.finish()["x"]
    if binary_labels_01 and set(np.unique(y)) <= {-1.0, 1.0}:
        y = (y > 0).astype(dtype)
    return x, y, (0 if add_intercept else None)
