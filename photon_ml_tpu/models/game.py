"""GAME model containers: fixed-effect, random-effect, and the composite model.

Reference: photon-api .../model/FixedEffectModel.scala:146 (Broadcast[GLM] +
feature shard), RandomEffectModel.scala:304 (RDD[(REId, GLM)] + REType +
shard, score via join by REId), photon-lib .../model/GameModel.scala:32-110
(Map[CoordinateId -> DatumScoringModel], score = sum of coordinate scores).

TPU-native shape: the random-effect "RDD of models" is a dense stacked matrix
W[num_entities, d] plus a host-side entity-id -> row map; scoring any sample
set is a gather + row-wise dot (parallel/bucketing.score_samples).  Missing
entities score 0, matching the reference.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from typing import TYPE_CHECKING

if TYPE_CHECKING:  # annotation-only: avoids models<->game import cycle
    from photon_ml_tpu.game.data import GameData

from photon_ml_tpu.models.glm import Coefficients, GLMModel
from photon_ml_tpu.parallel.bucketing import score_samples
from photon_ml_tpu.types import TaskType

Array = jax.Array


class DatumScoringModel:
    """Contract: score a GameData (reference DatumScoringModel.scala)."""

    def score(self, data: GameData) -> Array:
        raise NotImplementedError


@dataclasses.dataclass(frozen=True)
class FixedEffectModel(DatumScoringModel):
    """Global GLM over one feature shard (reference FixedEffectModel.scala:146).

    No Broadcast wrapper: under SPMD the coefficient vector is a replicated
    array; nothing is shipped per evaluation.
    """

    coefficients: Coefficients
    feature_shard: str
    task: TaskType = TaskType.LOGISTIC_REGRESSION

    def score(self, data: GameData) -> Array:
        shard = data.features[self.feature_shard]
        if hasattr(shard, "indices"):  # SparseShard: gather-based margins
            w = jnp.asarray(self.coefficients.means)
            vals = jnp.asarray(shard.values)
            return jnp.einsum("nk,nk->n", vals, w[jnp.asarray(shard.indices)])
        return self.coefficients.score(shard)

    def glm(self) -> GLMModel:
        return GLMModel(coefficients=self.coefficients, task=self.task)


@dataclasses.dataclass(frozen=True)
class RandomEffectModel(DatumScoringModel):
    """Per-entity GLMs as a stacked coefficient matrix
    (reference RandomEffectModel.scala:304).

    ``w_stack[slot_of[entity_id]]`` is that entity's coefficient vector;
    samples whose entity has no model score 0 (reference convention).
    ``variances`` optional, aligned with w_stack rows.

    Scale note: the stack is DENSE [num_entities, d] — the right layout for
    device gather-scoring and the modest per-entity bags the reference's
    GLMix deployments use.  For wide vocabularies, ``to_compact()`` yields
    the sparse twin (CompactRandomEffectModel below: memory ∝ observed
    columns, like the reference's per-REId sparse vectors), matching the
    training path, which never densifies (bucket_by_entity_sparse).
    On-disk NTV storage is already sparse (nonzero means only,
    storage/model_io.py); the compact container also saves natively sparse
    in the columnar format."""

    w_stack: np.ndarray  # [num_entities, d]
    slot_of: Dict[int, int]
    random_effect_type: str  # the id-tag column name
    feature_shard: str
    task: TaskType = TaskType.LOGISTIC_REGRESSION
    variances: Optional[np.ndarray] = None

    @property
    def num_entities(self) -> int:
        return self.w_stack.shape[0]

    def slots_for(self, data: GameData) -> np.ndarray:
        return _entity_slots(self, data)

    def score(self, data: GameData) -> Array:
        shard = data.features[self.feature_shard]
        slots = jnp.asarray(self.slots_for(data))
        # the stack uploads ONCE per instance (repeat scoring of one model
        # used to re-transfer the full [E, d] stack every call)
        (w_dev,) = _cached_device_copies(self, self.w_stack)
        if hasattr(shard, "indices"):
            # row-sparse shard: O(n*k) two-level gather, never [n, d_full]
            from photon_ml_tpu.parallel.bucketing import score_samples_sparse

            return score_samples_sparse(
                w_dev, slots,
                jnp.asarray(np.asarray(shard.indices)),
                jnp.asarray(np.asarray(shard.values, self.w_stack.dtype)))
        x = jnp.asarray(shard)
        return score_samples(w_dev, slots, x)

    def coefficients_for(self, entity_id: int) -> Optional[Coefficients]:
        slot = self.slot_of.get(int(entity_id))
        if slot is None:
            return None
        var = self.variances[slot] if self.variances is not None else None
        return Coefficients(means=self.w_stack[slot], variances=var)

    def to_compact(self, k: Optional[int] = None) -> "CompactRandomEffectModel":
        """Sparse per-entity container: O(entities x observed columns)
        instead of O(entities x vocabulary) — the published-model twin of
        the training path's bucket_by_entity_sparse (see the scale note
        above).  ``k``: per-entity coefficient capacity (default: the max
        nonzero count across entities; an explicit k BELOW that is an error
        — truncation would silently change scores — while a roomier k just
        pads).  Models carrying coefficient VARIANCES refuse: the variance
        rows are dense on a different support (prior-only fill lives at
        zero-coefficient columns), so compacting on the coefficient pattern
        would silently drop them."""
        if self.variances is not None:
            raise ValueError(
                "to_compact would silently drop coefficient variances "
                "(their support differs from the coefficients' — prior-only "
                "variances live at zero-coefficient columns); keep the "
                "dense model, or compact a variance-free copy deliberately")
        w = np.asarray(self.w_stack)
        e, d = w.shape
        # O(nnz) build: np.nonzero walks row-major, so cols arrive grouped
        # by row in ascending column order (searchsorted-ready) — no
        # full-width [e, d] argsort/int64 transient (which would dwarf the
        # stack itself at the wide-vocabulary scale this container targets)
        rows, cols = np.nonzero(w)
        counts = np.bincount(rows, minlength=e)
        k_need = int(counts.max()) if e else 0
        if k is None:
            k = max(1, k_need)
        elif k < k_need:
            raise ValueError(
                f"capacity k={k} < densest entity's {k_need} nonzero "
                "coefficients — truncation would silently change scores")
        offsets = np.zeros(e + 1, np.int64)
        np.cumsum(counts, out=offsets[1:])
        pos = np.arange(len(rows)) - offsets[rows]  # position within row
        idx = np.full((e, k), d, np.int32)
        val = np.zeros((e, k), w.dtype)
        idx[rows, pos] = cols
        val[rows, pos] = w[rows, cols]
        return CompactRandomEffectModel(
            indices=idx, values=val, dim=d, slot_of=dict(self.slot_of),
            random_effect_type=self.random_effect_type,
            feature_shard=self.feature_shard, task=self.task)


def _entity_slots(model, data: "GameData") -> np.ndarray:
    from photon_ml_tpu.game.coordinate import _slots_from

    return _slots_from(model.slot_of, data.id_tags[model.random_effect_type])


def score_compact_dense(w_idx: Array, w_val: Array, slots: Array,
                        x: Array) -> Array:
    """Σ_t values[e,t] * x[i, indices[e,t]] — gather the DENSE design at
    each entity's observed columns (never materializing [E, d]).  Plain
    traceable math: the model wrapper below jits it, and the serving
    engine's AOT kernels (serving/engine.py) inline it so batch and online
    compact scoring share ONE definition."""
    e = jnp.where(slots >= 0, slots, 0)
    idx = w_idx[e]  # [n, k]
    xv = jnp.take_along_axis(x, jnp.clip(idx, 0, x.shape[1] - 1), axis=1)
    s = jnp.sum(w_val[e] * jnp.where(idx < x.shape[1], xv, 0.0), axis=1)
    return jnp.where(slots >= 0, s, 0.0)


def score_compact_sparse(w_idx: Array, w_val: Array, slots: Array,
                         f_idx: Array, f_val: Array) -> Array:
    """Sparse-features x sparse-model margins: binary-search each sample
    feature id into its entity's sorted coefficient columns (miss -> 0).
    Plain traceable math (see score_compact_dense).  On TPU the
    searchsorted/take_along_axis chain is replaced by the pallas match-dot
    kernel (ops/compact_score.py — same math, one VMEM pass, parity-tested
    in interpret mode; PHOTON_COMPACT_DISABLE_PALLAS=1 escape hatch)."""
    from photon_ml_tpu.ops import compact_score

    if compact_score.eligible(w_idx.shape[1], f_idx.shape[1]):
        return compact_score.score_sparse_compact(w_idx, w_val, slots,
                                                  f_idx, f_val)
    e = jnp.where(slots >= 0, slots, 0)
    rows_idx = w_idx[e]  # [n, k_model] sorted, padded with dim
    rows_val = w_val[e]
    pos = jax.vmap(jnp.searchsorted)(rows_idx, f_idx)  # [n, k_feat]
    pos_c = jnp.clip(pos, 0, rows_idx.shape[1] - 1)
    hit = jnp.take_along_axis(rows_idx, pos_c, axis=1) == f_idx
    wv = jnp.where(hit, jnp.take_along_axis(rows_val, pos_c, axis=1), 0.0)
    s = jnp.sum(f_val * wv, axis=1)
    return jnp.where(slots >= 0, s, 0.0)


_score_dense_compact = jax.jit(score_compact_dense)
_score_sparse_compact = jax.jit(score_compact_sparse)


def _cached_device_copies(model, *arrays) -> tuple:
    """Per-instance device copies of host coefficient arrays, uploaded ONCE.

    Scoring previously re-ran ``jnp.asarray`` on the full stacks every
    call — a full host->device upload per batch on accelerator backends.
    The cache is keyed by the host arrays' identities, so the functional
    mutation idiom (``dataclasses.replace`` with new arrays — the only
    mutation these frozen containers support) naturally invalidates it:
    a replaced instance starts with no cache, and rebinding an array in
    place (object.__setattr__) changes the identity key."""
    cache = getattr(model, "_dev_cache", None)
    if cache is not None and len(cache[0]) == len(arrays) and all(
            c is a for c, a in zip(cache[0], arrays)):
        return cache[1]
    dev = tuple(jnp.asarray(a) for a in arrays)
    object.__setattr__(model, "_dev_cache", (arrays, dev))
    return dev


@dataclasses.dataclass(frozen=True)
class CompactRandomEffectModel(DatumScoringModel):
    """Per-entity GLMs as SPARSE coefficient rows — the wide-vocabulary
    published container (reference RandomEffectModel.scala:304 holds
    per-REId GLMs whose coefficient vectors are sparse Breeze vectors; the
    dense ``RandomEffectModel`` is the right layout for modest bags, this
    one decouples entity count from vocabulary width).

    ``indices[slot]`` are that entity's observed column ids, ascending,
    padded with ``dim`` (out of range — inert everywhere); ``values`` align,
    padded with 0.  Scoring never builds an [E, d] stack: dense shards
    gather x at the entity's observed columns, sparse shards binary-search
    each sample feature into the entity's sorted columns.  Missing entities
    score 0 (reference convention)."""

    indices: np.ndarray  # [num_entities, k] int32, sorted, dim-padded
    values: np.ndarray   # [num_entities, k]
    dim: int
    slot_of: Dict[int, int]
    random_effect_type: str
    feature_shard: str
    task: TaskType = TaskType.LOGISTIC_REGRESSION

    @property
    def num_entities(self) -> int:
        return self.indices.shape[0]

    def slots_for(self, data: GameData) -> np.ndarray:
        return _entity_slots(self, data)

    def score(self, data: GameData) -> Array:
        shard = data.features[self.feature_shard]
        if shard.shape[1] != self.dim:
            # loud, like the dense twin's einsum shape error — the padding
            # mask in the scoring kernels would otherwise silently zero
            # real coefficients on a mis-bound shard
            raise ValueError(
                f"shard {self.feature_shard!r} has {shard.shape[1]} "
                f"features but this model was trained on {self.dim}")
        slots = jnp.asarray(self.slots_for(data))
        # one upload per instance, not per call (the satellite fix: every
        # score() used to re-run jnp.asarray on the full indices/values)
        w_idx, w_val = _cached_device_copies(self, self.indices, self.values)
        if hasattr(shard, "indices"):
            return _score_sparse_compact(
                w_idx, w_val, slots,
                jnp.asarray(np.asarray(shard.indices, np.int32)),
                jnp.asarray(np.asarray(shard.values, self.values.dtype)))
        return _score_dense_compact(w_idx, w_val, slots,
                                    jnp.asarray(shard, self.values.dtype))

    def to_dense(self) -> RandomEffectModel:
        e, k = self.indices.shape
        w = np.zeros((e, self.dim), self.values.dtype)
        rows = np.repeat(np.arange(e), k)
        idx = self.indices.reshape(-1)
        keep = idx < self.dim
        w[rows[keep], idx[keep]] = self.values.reshape(-1)[keep]
        return RandomEffectModel(
            w_stack=w, slot_of=dict(self.slot_of),
            random_effect_type=self.random_effect_type,
            feature_shard=self.feature_shard, task=self.task)


@dataclasses.dataclass
class GameModel:
    """Composite model: coordinate id -> scoring model
    (reference GameModel.scala:32-110)."""

    models: Dict[str, DatumScoringModel]

    def score(self, data: GameData) -> Array:
        """Sum of coordinate raw scores (GameModel.score:99-110) via the
        shared composition (game/scoring.additive_total — the same function
        the online serving kernels use, so batch and serving cannot drift)."""
        from photon_ml_tpu.game.scoring import additive_total

        return additive_total(data.num_samples,
                              (m.score(data) for m in self.models.values()))

    def predict(self, data: GameData, task: TaskType) -> Array:
        from photon_ml_tpu.core.losses import loss_for_task

        z = self.score(data) + jnp.asarray(data.offset)
        return loss_for_task(task).mean(z)

    def updated(self, coordinate_id: str, model: DatumScoringModel) -> "GameModel":
        out = dict(self.models)
        out[coordinate_id] = model
        return GameModel(models=out)

    def __getitem__(self, cid: str) -> DatumScoringModel:
        return self.models[cid]

    def __contains__(self, cid: str) -> bool:
        return cid in self.models
