"""GAME model containers: fixed-effect, random-effect, and the composite model.

Reference: photon-api .../model/FixedEffectModel.scala:146 (Broadcast[GLM] +
feature shard), RandomEffectModel.scala:304 (RDD[(REId, GLM)] + REType +
shard, score via join by REId), photon-lib .../model/GameModel.scala:32-110
(Map[CoordinateId -> DatumScoringModel], score = sum of coordinate scores).

TPU-native shape: the random-effect "RDD of models" is a dense stacked matrix
W[num_entities, d] plus a host-side entity-id -> row map; scoring any sample
set is a gather + row-wise dot (parallel/bucketing.score_samples).  Missing
entities score 0, matching the reference.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from typing import TYPE_CHECKING

if TYPE_CHECKING:  # annotation-only: avoids models<->game import cycle
    from photon_ml_tpu.game.data import GameData

from photon_ml_tpu.models.glm import Coefficients, GLMModel
from photon_ml_tpu.parallel.bucketing import score_samples
from photon_ml_tpu.types import TaskType

Array = jax.Array


class DatumScoringModel:
    """Contract: score a GameData (reference DatumScoringModel.scala)."""

    def score(self, data: GameData) -> Array:
        raise NotImplementedError


@dataclasses.dataclass(frozen=True)
class FixedEffectModel(DatumScoringModel):
    """Global GLM over one feature shard (reference FixedEffectModel.scala:146).

    No Broadcast wrapper: under SPMD the coefficient vector is a replicated
    array; nothing is shipped per evaluation.
    """

    coefficients: Coefficients
    feature_shard: str
    task: TaskType = TaskType.LOGISTIC_REGRESSION

    def score(self, data: GameData) -> Array:
        shard = data.features[self.feature_shard]
        if hasattr(shard, "indices"):  # SparseShard: gather-based margins
            w = jnp.asarray(self.coefficients.means)
            vals = jnp.asarray(shard.values)
            return jnp.einsum("nk,nk->n", vals, w[jnp.asarray(shard.indices)])
        return self.coefficients.score(shard)

    def glm(self) -> GLMModel:
        return GLMModel(coefficients=self.coefficients, task=self.task)


@dataclasses.dataclass(frozen=True)
class RandomEffectModel(DatumScoringModel):
    """Per-entity GLMs as a stacked coefficient matrix
    (reference RandomEffectModel.scala:304).

    ``w_stack[slot_of[entity_id]]`` is that entity's coefficient vector;
    samples whose entity has no model score 0 (reference convention).
    ``variances`` optional, aligned with w_stack rows.

    Scale note: the stack is DENSE [num_entities, d] — the right layout for
    device gather-scoring and the modest per-entity bags the reference's
    GLMix deployments use, but it couples the entity axis to the vocabulary
    width (1M entities x 1M-feature bags would need a compact per-entity
    storage like the reference's sparse per-REId vectors; the training path
    already never densifies — bucket_by_entity_sparse — so the gap is this
    published container + its scoring gather, recorded here as future
    work).  On-disk NTV storage is already sparse (nonzero means only,
    storage/model_io.py)."""

    w_stack: np.ndarray  # [num_entities, d]
    slot_of: Dict[int, int]
    random_effect_type: str  # the id-tag column name
    feature_shard: str
    task: TaskType = TaskType.LOGISTIC_REGRESSION
    variances: Optional[np.ndarray] = None

    @property
    def num_entities(self) -> int:
        return self.w_stack.shape[0]

    def slots_for(self, data: GameData) -> np.ndarray:
        from photon_ml_tpu.game.coordinate import _slots_from

        return _slots_from(self.slot_of, data.id_tags[self.random_effect_type])

    def score(self, data: GameData) -> Array:
        shard = data.features[self.feature_shard]
        slots = jnp.asarray(self.slots_for(data))
        if hasattr(shard, "indices"):
            # row-sparse shard: O(n*k) two-level gather, never [n, d_full]
            from photon_ml_tpu.parallel.bucketing import score_samples_sparse

            return score_samples_sparse(
                jnp.asarray(self.w_stack), slots,
                jnp.asarray(np.asarray(shard.indices)),
                jnp.asarray(np.asarray(shard.values, self.w_stack.dtype)))
        x = jnp.asarray(shard)
        return score_samples(jnp.asarray(self.w_stack), slots, x)

    def coefficients_for(self, entity_id: int) -> Optional[Coefficients]:
        slot = self.slot_of.get(int(entity_id))
        if slot is None:
            return None
        var = self.variances[slot] if self.variances is not None else None
        return Coefficients(means=self.w_stack[slot], variances=var)


@dataclasses.dataclass
class GameModel:
    """Composite model: coordinate id -> scoring model
    (reference GameModel.scala:32-110)."""

    models: Dict[str, DatumScoringModel]

    def score(self, data: GameData) -> Array:
        """Sum of coordinate raw scores (GameModel.score:99-110)."""
        total = jnp.zeros((data.num_samples,))
        for model in self.models.values():
            total = total + model.score(data)
        return total

    def predict(self, data: GameData, task: TaskType) -> Array:
        from photon_ml_tpu.core.losses import loss_for_task

        z = self.score(data) + jnp.asarray(data.offset)
        return loss_for_task(task).mean(z)

    def updated(self, coordinate_id: str, model: DatumScoringModel) -> "GameModel":
        out = dict(self.models)
        out[coordinate_id] = model
        return GameModel(models=out)

    def __getitem__(self, cid: str) -> DatumScoringModel:
        return self.models[cid]

    def __contains__(self, cid: str) -> bool:
        return cid in self.models
