"""GLM model containers.

Reference: photon-lib .../model/Coefficients.scala:31-53 (means + optional
variances + computeScore) and photon-api supervised/model/** —
GeneralizedLinearModel.scala:168 with LogisticRegression/LinearRegression/
PoissonRegression/SmoothedHingeLossLinearSVM subclasses whose only real
difference is the inverse link (computeMean).  Here the subclass hierarchy
collapses to GLMModel carrying its TaskType; the mean function comes from the
task's PointwiseLoss.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.core.losses import loss_for_task
from photon_ml_tpu.types import TaskType

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class Coefficients:
    """means[d] + optional variances[d] (reference Coefficients.scala:31)."""

    means: np.ndarray
    variances: Optional[np.ndarray] = None

    @property
    def dim(self) -> int:
        return self.means.shape[-1]

    def score(self, x: Array) -> Array:
        """Raw dot-product score (reference Coefficients.computeScore:53)."""
        return jnp.asarray(x) @ jnp.asarray(self.means)

    @classmethod
    def zeros(cls, dim: int, dtype=np.float32) -> "Coefficients":
        return cls(means=np.zeros(dim, dtype))


@dataclasses.dataclass(frozen=True)
class GLMModel:
    """A trained GLM: coefficients + task (reference GeneralizedLinearModel).

    The reference's per-task subclasses only override ``computeMean``; here
    ``predict`` dispatches through the task's loss inverse-link.
    """

    coefficients: Coefficients
    task: TaskType = TaskType.LOGISTIC_REGRESSION

    def score(self, x: Array) -> Array:
        return self.coefficients.score(x)

    def predict(self, x: Array, offset: Optional[Array] = None) -> Array:
        """Inverse-link mean at margin x·w + offset (computeMeanFunction)."""
        z = self.score(x)
        if offset is not None:
            z = z + offset
        return loss_for_task(self.task).mean(z)
