"""Regularization-path GLM training — the reference's legacy single-model API.

Reference: photon-api ModelTraining.trainGeneralizedLinearModel:106-228 —
sort the regularization weights descending ("potentially speed up the overall
convergence time", :174), warm-start each fit from the previous λ's model
(or from a supplied warm-start model for the first λ, :186-200), return the
per-λ models in ascending-input order plus per-λ solver states (the
ModelTracker analog).

TPU design: ONE jitted solve is compiled with the objective as a traced
argument; every λ on the path reuses it (reg is a pytree leaf, no recompile —
the reference instead mutates the L2 mixin / OWLQN weight in place,
DistributedOptimizationProblem.updateRegularizationWeight:64-75).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.core.batch import DenseBatch
from photon_ml_tpu.core.losses import loss_for_task
from photon_ml_tpu.core.normalization import NormalizationContext, no_normalization
from photon_ml_tpu.core.objective import GLMObjective
from photon_ml_tpu.core.regularization import Regularization, RegularizationType
from photon_ml_tpu.models.glm import Coefficients, GLMModel
from photon_ml_tpu.opt.solve import compute_variances, make_solver
from photon_ml_tpu.opt.types import SolverConfig, SolverResult
from photon_ml_tpu.types import OptimizerType, TaskType, VarianceComputationType

Array = jax.Array


def train_glm_reg_path(
    x: np.ndarray,
    y: np.ndarray,
    task: TaskType,
    reg_weights: Sequence[float],
    reg_type: RegularizationType = RegularizationType.L2,
    elastic_net_alpha: float = 1.0,
    optimizer: OptimizerType = OptimizerType.LBFGS,
    solver: Optional[SolverConfig] = None,
    offset: Optional[np.ndarray] = None,
    weight: Optional[np.ndarray] = None,
    norm: Optional[NormalizationContext] = None,
    intercept_index: Optional[int] = None,
    box: Optional[Tuple[Array, Array]] = None,
    warm_start_models: Optional[Dict[float, GLMModel]] = None,
    use_warm_start: bool = True,
    variance: VarianceComputationType = VarianceComputationType.NONE,
    dtype=np.float32,
) -> Tuple[List[Tuple[float, GLMModel]], Dict[float, SolverResult]]:
    """Train one GLM per regularization weight along a warm-started path.

    Returns ``(weight, model)`` pairs ordered by DESCENDING weight (the
    training order, reference :175) and a per-weight ``SolverResult`` map
    (the ModelTracker analog, reference :224).  Models are published in
    ORIGINAL feature space when ``norm`` is given.
    """
    if not reg_weights:
        raise ValueError("need at least one regularization weight")

    x = np.asarray(x, dtype)
    n, d = x.shape
    batch = DenseBatch(
        x=jnp.asarray(x),
        y=jnp.asarray(np.asarray(y, dtype)),
        offset=jnp.asarray(np.zeros(n, dtype) if offset is None
                           else np.asarray(offset, dtype)),
        weight=jnp.asarray(np.ones(n, dtype) if weight is None
                           else np.asarray(weight, dtype)),
    )
    norm_ctx = norm if norm is not None else no_normalization()

    # L1 presence is a static property of the whole path (reg_type + alpha),
    # so the optimizer dispatch inside make_solver is stable across λs.
    reg0 = Regularization.from_context(reg_type, float(reg_weights[0]),
                                       elastic_net_alpha)
    objective = GLMObjective(loss=loss_for_task(task), reg=reg0, norm=norm_ctx,
                             fused=True)
    solve = make_solver(objective, optimizer, solver, box=box)
    # batch as an ARGUMENT (a closed-over array lowers to a baked XLA
    # constant; compile time then grows with the dataset)
    fit = jax.jit(lambda obj, w0, b: solve(w0, b, objective=obj))

    sorted_weights = sorted((float(w) for w in reg_weights), reverse=True)
    warm_start_models = warm_start_models or {}

    path: List[Tuple[float, GLMModel]] = []
    trackers: Dict[float, SolverResult] = {}
    prev_w: Optional[Array] = None
    for lam in sorted_weights:
        if prev_w is not None and use_warm_start:
            w0 = prev_w  # previous λ's transformed-space solution (:206-210)
        elif warm_start_models:
            max_lam = max(warm_start_models)  # reference :197-200
            means = np.asarray(warm_start_models[max_lam].coefficients.means, dtype)
            w0 = norm_ctx.model_to_transformed_space(jnp.asarray(means),
                                                     intercept_index)
        else:
            w0 = jnp.zeros(d, dtype)

        obj = objective.replace(
            reg=Regularization.from_context(reg_type, lam, elastic_net_alpha))
        res = fit(obj, w0, batch)
        prev_w = res.w

        w_orig = norm_ctx.model_to_original_space(res.w, intercept_index)
        variances = compute_variances(obj, res.w, batch, variance)
        path.append((lam, GLMModel(
            coefficients=Coefficients(
                means=np.asarray(w_orig),
                variances=None if variances is None else np.asarray(variances)),
            task=task)))
        trackers[lam] = res
    return path, trackers


def select_best_glm(
    path: List[Tuple[float, GLMModel]],
    x_val: np.ndarray,
    y_val: np.ndarray,
    metric: Optional[str] = None,
    offset: Optional[np.ndarray] = None,
    weight: Optional[np.ndarray] = None,
) -> Tuple[float, GLMModel]:
    """Best (λ, model) on validation data — the legacy driver's model
    selection (reference ModelSelection.scala:29-92: AUC for classifiers,
    RMSE for linear regression, Poisson loss for Poisson models; the
    task-default metric applies unless ``metric`` overrides it).
    """
    from photon_ml_tpu.evaluation.evaluator import make_evaluator

    if not path:
        raise ValueError("empty regularization path")
    task = path[0][1].task
    if metric is None:
        if task == TaskType.NONE:
            raise ValueError("task NONE has no default metric; pass metric=")
        metric = {
            TaskType.LOGISTIC_REGRESSION: "auc",
            TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM: "auc",
            TaskType.LINEAR_REGRESSION: "rmse",
            TaskType.POISSON_REGRESSION: "poisson_loss",
        }[task]
    evaluator = make_evaluator(metric)
    x_val = np.asarray(x_val)
    y_val = np.asarray(y_val)
    n = len(y_val)
    offset = np.zeros(n) if offset is None else np.asarray(offset)
    weight = np.ones(n) if weight is None else np.asarray(weight)

    best: Optional[Tuple[float, GLMModel, float]] = None
    for lam, model in path:
        scores = np.asarray(model.score(x_val)) + offset
        value = float(np.asarray(
            evaluator.evaluate(scores, y_val, weight)))
        if best is None or evaluator.better_than(value, best[2]):
            best = (lam, model, value)
    return best[0], best[1]
