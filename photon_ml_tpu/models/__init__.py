from photon_ml_tpu.models.glm import Coefficients, GLMModel  # noqa: F401
from photon_ml_tpu.models.game import (  # noqa: F401
    CompactRandomEffectModel,
    DatumScoringModel,
    FixedEffectModel,
    RandomEffectModel,
    GameModel,
)
