"""GAME hyperparameter tuning glue: vectorize per-coordinate regularization
weights and retrain through the estimator.

Reference: photon-client .../estimators/GameEstimatorEvaluationFunction.scala:40-244
(GameOptimizationConfiguration <-> log-scale DenseVector; apply() retrains via
estimator.fit) and GameTrainingDriver.runHyperparameterTuning:643-674
(HyperparameterTuningMode RANDOM | BAYESIAN).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from photon_ml_tpu.core.regularization import Regularization
from photon_ml_tpu.evaluation.evaluator import EvaluationSuite
from photon_ml_tpu.game.config import FixedEffectConfig, GameConfig, RandomEffectConfig
from photon_ml_tpu.game.data import GameData
from photon_ml_tpu.game.descent import DescentHistory
from photon_ml_tpu.game.estimator import (GameEstimator, GameFitResult,
                                          GameTransformer)
from photon_ml_tpu.tune.search import DomainDim, GaussianProcessSearch, RandomSearch, SearchDomain


def _with_l2(cfg, l2: float):
    reg = Regularization(l1=cfg.reg.l1, l2=l2)
    return dataclasses.replace(cfg, reg=reg)


class GameEstimatorEvaluationFunction:
    """params vector (one L2 weight per coordinate, log-tuned) -> validation
    metric via a full GAME retrain (the reference retrains per tuning
    iteration too, GameEstimatorEvaluationFunction.apply)."""

    def __init__(self, estimator: GameEstimator, base_config: GameConfig,
                 data: GameData, validation_data: GameData, seed: int = 0,
                 initial_model=None, locked_coordinates=None):
        if estimator.validation_suite is None:
            raise ValueError("tuning needs an estimator with a validation suite")
        self.estimator = estimator
        self.base_config = base_config
        self.data = data
        self.validation_data = validation_data
        self.seed = seed
        self.initial_model = initial_model
        self.locked = set(locked_coordinates or ())
        # locked coordinates are never retrained, so their L2 is not a
        # tunable dimension (partial retraining, GameEstimator :106-112)
        self.coordinate_ids = [c for c in base_config.coordinates
                               if c not in self.locked]
        if not self.coordinate_ids:
            raise ValueError("all coordinates are locked; nothing to tune")
        self.results: List[GameFitResult] = []
        self._sweep = None  # None = not built; False = un-fusable
        # phase accounting (bench reports the breakdown; reset_phases())
        self.fit_seconds = 0.0
        self.eval_seconds = 0.0

    def config_for(self, params: np.ndarray) -> GameConfig:
        # keep every coordinate (locked ones must stay in the config so the
        # descent can re-score them); override only the tuned L2s
        coords = dict(self.base_config.coordinates)
        for i, cid in enumerate(self.coordinate_ids):
            coords[cid] = _with_l2(coords[cid], float(params[i]))
        return dataclasses.replace(self.base_config, coordinates=coords)

    def _fused_sweep(self):
        """ONE FusedSweep shared by every tuning fit — reg weights are
        traced sweep inputs, so the whole tuning loop compiles exactly one
        descent program (the estimator's own sweep cache is local to each
        fit() call and would re-trace per tuning iteration)."""
        if self._sweep is False:
            return None
        if self._sweep is None:
            from photon_ml_tpu.game.fused import FusedSweep
            from photon_ml_tpu.types import VarianceComputationType

            needs_var = any(c.variance != VarianceComputationType.NONE
                            for c in self.base_config.coordinates.values())
            if self.base_config.num_outer_iterations > 1 and needs_var:
                # multi-iteration fused tuning runs via per-iteration
                # snapshots, which don't carry variances (FusedSweep
                # .run_snapshots) — host path keeps exact semantics
                self._sweep = False
                return None
            try:
                coords = {
                    cid: self.estimator.build_one_coordinate(
                        cid, self.data, ccfg, self.base_config.task, self.seed,
                        initial_model=self.initial_model)
                    for cid, ccfg in self.base_config.coordinates.items()}
                sweep = FusedSweep(
                    coords, order=list(self.base_config.coordinates),
                    num_iterations=self.base_config.num_outer_iterations)
                # the warm-start carry is constant for the life of this
                # evaluation function — score the initial model ONCE, not
                # once per tuning iteration
                carry0 = (sweep.init_carry(self.initial_model)
                          if self.initial_model is not None else None)
                # variance-free tuning runs FULLY fused: held-out scoring +
                # best-iteration selection ride the validated program
                # (run_validated); variance-computing single-iteration
                # configs keep the run() + host-evaluate path (plan=None)
                plan = (None if needs_var else sweep.validation_plan(
                    self.validation_data, self.estimator.validation_suite))
                self._sweep = (sweep, carry0, plan)
            except NotImplementedError:
                self._sweep = False  # un-fusable coordinate: host path
                return None
        return self._sweep

    def _select_and_record(self, config: GameConfig, snapshots) -> float:
        """Evaluate each snapshot on validation, keep the best (host-loop
        best-model retention semantics), record the fit."""
        import time

        suite = self.estimator.validation_suite
        t0 = time.perf_counter()
        best_model, best_ev = None, None
        for m in snapshots:
            ev = GameTransformer(m, config.task).evaluate(
                self.validation_data, suite)
            if best_ev is None or suite.better_than(ev, best_ev):
                best_model, best_ev = m, ev
        self.eval_seconds += time.perf_counter() - t0
        self.results.append(GameFitResult(model=best_model, config=config,
                                          evaluation=best_ev,
                                          history=DescentHistory()))
        return best_ev.primary

    def __call__(self, params: np.ndarray) -> float:
        import time

        config = self.config_for(params)
        # Fused fast path: train WITHOUT per-update validation (the whole
        # retrain is one jitted sweep, reused across every tuning fit).
        # Best-model retention (reference CoordinateDescent.scala:163-314)
        # compares FULL models at sweep boundaries only, so per-iteration
        # snapshots from the fused program (FusedSweep.run_snapshots) carry
        # exactly the candidates the host loop would compare — each is
        # evaluated on validation here and the best kept.  One outer
        # iteration degenerates to evaluating the final model via run().
        fused_ok = (not self.locked and self.estimator.fused is not False)
        sweep = self._fused_sweep() if fused_ok else None
        if sweep is not None:
            sweep_obj, carry0, plan = sweep
            regs = [config.coordinates[cid].reg for cid in config.coordinates]
            t0 = time.perf_counter()
            if plan is not None:
                # fully fused validated fit: training, held-out scoring and
                # per-update losses in ONE compiled program; the suite runs
                # per sweep boundary on the stacked in-program scores
                model, _evals, best_ev, _losses = sweep_obj.run_validated(
                    plan, initial=self.initial_model, carry0=carry0,
                    regs=regs, seed=self.seed)
                self.fit_seconds += time.perf_counter() - t0
                self.results.append(GameFitResult(
                    model=model, config=config, evaluation=best_ev,
                    history=DescentHistory()))
                return best_ev.primary
            if config.num_outer_iterations == 1:
                model, _scores = sweep_obj.run(initial=self.initial_model,
                                               carry0=carry0, regs=regs,
                                               seed=self.seed)
                snapshots = [model]
            else:
                snapshots = sweep_obj.run_snapshots(
                    initial=self.initial_model, carry0=carry0, regs=regs,
                    seed=self.seed)
            self.fit_seconds += time.perf_counter() - t0
            return self._select_and_record(config, snapshots)
        t0 = time.perf_counter()
        res = self.estimator.fit(self.data, [config],
                                 validation_data=self.validation_data, seed=self.seed,
                                 initial_model=self.initial_model,
                                 locked_coordinates=self.locked or None)[0]
        self.fit_seconds += time.perf_counter() - t0
        self.results.append(res)
        return res.evaluation.primary

    def evaluate_batch(self, params_batch) -> List[float]:
        """Evaluate several parameter vectors in ONE vmapped grid fit
        (FusedSweep.run_grid/_snapshots): all grid lanes share the same
        design-matrix streams, so q tuning fits cost far less than q
        sequential retrains — the batched half of batch Bayesian
        optimization (the search picks the q candidates).  Order of
        ``results`` matches sequential evaluation.  Falls back to
        sequential calls when the fused path is unavailable."""
        import time

        params_batch = [np.asarray(p, float) for p in params_batch]
        if not params_batch:
            return []
        fused_ok = (not self.locked and self.estimator.fused is not False)
        sweep = self._fused_sweep() if fused_ok else None
        if sweep is None or len(params_batch) == 1:
            return [self(p) for p in params_batch]
        sweep_obj, carry0, _plan = sweep  # grid fits host-evaluate snapshots
        configs = [self.config_for(p) for p in params_batch]
        regs_grid = [[c.coordinates[cid].reg for cid in c.coordinates]
                     for c in configs]
        t0 = time.perf_counter()
        # key off the per-candidate configs like __call__ does (advisor r4);
        # a batched fused grid shares ONE program, so candidates that
        # disagree on iteration count cannot ride it — fall back to
        # sequential evaluation, which honors each candidate's own count
        iters = {c.num_outer_iterations for c in configs}
        if len(iters) > 1:
            return [self(p) for p in params_batch]
        if configs[0].num_outer_iterations == 1:
            snap_lists = [[m] for m, _scores in sweep_obj.run_grid(
                regs_grid, initial=self.initial_model, carry0=carry0,
                seed=self.seed)]
        else:
            snap_lists = sweep_obj.run_grid_snapshots(
                regs_grid, initial=self.initial_model, carry0=carry0,
                seed=self.seed)
        self.fit_seconds += time.perf_counter() - t0
        return [self._select_and_record(config, snaps)
                for config, snaps in zip(configs, snap_lists)]

    def reset_phases(self) -> None:
        self.fit_seconds = 0.0
        self.eval_seconds = 0.0

    def vectorize(self, config: GameConfig) -> np.ndarray:
        """Config -> params vector (reference configurationToVector)."""
        return np.asarray([config.coordinates[cid].reg.l2 for cid in self.coordinate_ids])

    def warmup(self, grid_sizes: Sequence[int] = ()) -> None:
        """Compile the shared fused tuning program (one throwaway fit at the
        base config's weights, not recorded).  Benchmarks call this so the
        timed window measures tuning-fit throughput, not XLA compilation —
        the same convention as the sweep benches' warm-up run.

        ``grid_sizes``: additionally pre-compile the batched grid program
        for each q (run_grid traces one program per distinct grid size) —
        callers that tune with ``batch_size=q`` warm q here so no compile
        lands inside their measured window."""
        n = len(self.results)
        fit_s, eval_s = self.fit_seconds, self.eval_seconds
        base = self.vectorize(self.base_config)
        self(base)
        fused_ok = (not self.locked and self.estimator.fused is not False
                    and self._fused_sweep() is not None)
        if fused_ok:  # without a fused sweep there is no grid program to
            for q in grid_sizes:  # compile — evaluate_batch would just run
                if q > 1:  # q discarded sequential retrains
                    self.evaluate_batch([base] * q)
        del self.results[n:]
        # warmup contributes nothing to phase accounting, but a reused
        # evaluation function keeps the history it accumulated before
        self.fit_seconds, self.eval_seconds = fit_s, eval_s


DEFAULT_L2_RANGE = (1e-4, 1e4)


def default_l2_domain(coordinate_ids, l2_range=DEFAULT_L2_RANGE) -> SearchDomain:
    """The standard per-coordinate log-scale L2 search domain (shared by
    tune_game_model and the driver's shrink branch)."""
    return SearchDomain([
        DomainDim(name=f"l2:{cid}", low=l2_range[0], high=l2_range[1],
                  log_scale=True)
        for cid in coordinate_ids
    ])


def tune_game_model(
    estimator: GameEstimator,
    base_config: GameConfig,
    data: GameData,
    validation_data: GameData,
    n_iterations: int = 10,
    mode: str = "bayesian",  # reference HyperparameterTuningMode {RANDOM, BAYESIAN}
    l2_range: Tuple[float, float] = DEFAULT_L2_RANGE,
    seed: int = 0,
    initial_model=None,
    locked_coordinates=None,
    search_domain: Optional[SearchDomain] = None,
    prior_observations: Optional[List[Tuple[np.ndarray, float]]] = None,
    evaluation_function: Optional[GameEstimatorEvaluationFunction] = None,
    batch_size: int = 1,
) -> Tuple[GameFitResult, "RandomSearch", List[GameFitResult]]:
    """Search per-coordinate L2 weights; returns (best fit, search object,
    all tuned fits in evaluation order — the driver's TUNED/ALL output modes
    save these, reference GameTrainingDriver.selectModels:683-701).

    ``initial_model``/``locked_coordinates``: forwarded to every tuning fit
    (warm start + partial retraining); locked coordinates are excluded from
    the search space.

    ``search_domain``: override the per-coordinate L2 domain (e.g. parsed
    from a reference-format JSON config, tune/serialization.py); dim order
    must match the unlocked-coordinate order.  ``prior_observations``:
    (params, value) pairs seeded into the search
    (HyperparameterSerialization.priorFromJson)."""
    if evaluation_function is not None:
        # caller pre-built (and possibly warmup()-compiled) the evaluation
        # function — it must wrap the SAME estimator/config, and the
        # per-fit knobs must not be double-specified (they live on fn)
        fn = evaluation_function
        if fn.estimator is not estimator or fn.base_config is not base_config:
            raise ValueError(
                "evaluation_function was built for a different estimator or "
                "base_config than the ones passed to tune_game_model")
        if fn.data is not data or fn.validation_data is not validation_data:
            raise ValueError(
                "evaluation_function was built for different data or "
                "validation_data than the ones passed to tune_game_model")
        if initial_model is not None or locked_coordinates is not None:
            raise ValueError(
                "pass initial_model/locked_coordinates to the "
                "GameEstimatorEvaluationFunction constructor, not to "
                "tune_game_model, when supplying evaluation_function")
        if seed != fn.seed:
            raise ValueError(
                f"seed {seed} != evaluation_function's seed {fn.seed}")
    else:
        fn = GameEstimatorEvaluationFunction(estimator, base_config, data, validation_data,
                                             seed, initial_model=initial_model,
                                             locked_coordinates=locked_coordinates)
    if search_domain is not None:
        if search_domain.d != len(fn.coordinate_ids):
            raise ValueError(
                f"search domain has {search_domain.d} dims but there are "
                f"{len(fn.coordinate_ids)} tunable coordinates")
        domain = search_domain
    else:
        domain = default_l2_domain(fn.coordinate_ids, l2_range)
    minimize = not estimator.validation_suite.primary.larger_is_better
    cls = GaussianProcessSearch if mode == "bayesian" else RandomSearch
    # batch_size > 1: each search round evaluates its candidates as ONE
    # vmapped grid fit (fn.evaluate_batch -> FusedSweep.run_grid) — batch
    # Bayesian optimization, total fit count unchanged
    search = cls(domain, minimize=minimize, seed=seed, batch_size=batch_size)
    # a reused evaluation_function may carry fits from a previous search —
    # this run's results are everything appended from here on
    start = len(fn.results)
    # prior: supplied observations (values already in the primary metric's
    # raw orientation), then the base config's own weights, evaluated first
    # (warm prior, reference ShrinkSearchRange / prior JSON defaults)
    priors = list(prior_observations or [])
    prior_params = fn.vectorize(base_config)
    if np.all(prior_params > 0):
        priors.append((prior_params, fn(prior_params)))
    search.find(fn, n=n_iterations, priors=priors or None,
                evaluate_batch=fn.evaluate_batch)

    results = list(fn.results[start:])
    best = estimator.best(results)
    return best, search, results
