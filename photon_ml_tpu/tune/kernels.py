"""GP covariance kernels.

Reference: photon-lib .../hyperparameter/estimators/kernels/StationaryKernel.scala:35-177,
Matern52.scala, RBF.scala — stationary kernels with amplitude, noise, and ARD
lengthscales, plus the log-likelihood used for kernel-parameter sampling.

Small-matrix (n_obs <= a few hundred) host-side numpy: the GP tuner drives
full GAME retrains (each costing seconds of TPU time), so the kernel algebra
is never the bottleneck; float64 numpy keeps Cholesky stable.
"""

from __future__ import annotations

import dataclasses

import numpy as np
from scipy.linalg import solve_triangular


def _scaled_sqdist(x1: np.ndarray, x2: np.ndarray, lengthscale: np.ndarray) -> np.ndarray:
    a = x1 / lengthscale
    b = x2 / lengthscale
    return np.maximum(
        np.sum(a * a, 1)[:, None] + np.sum(b * b, 1)[None, :] - 2.0 * a @ b.T, 0.0
    )


@dataclasses.dataclass(frozen=True)
class Kernel:
    """amplitude * k(r) + noise on the diagonal (reference StationaryKernel)."""

    amplitude: float = 1.0
    noise: float = 1e-4
    lengthscale: np.ndarray = dataclasses.field(default_factory=lambda: np.ones(1))

    def _k(self, sq: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def __call__(self, x1: np.ndarray, x2: np.ndarray) -> np.ndarray:
        ls = np.broadcast_to(np.asarray(self.lengthscale, float), (x1.shape[1],))
        return self.amplitude * self._k(_scaled_sqdist(x1, x2, ls))

    def with_params(self, amplitude: float, noise: float, lengthscale: np.ndarray) -> "Kernel":
        return dataclasses.replace(self, amplitude=amplitude, noise=noise,
                                   lengthscale=np.asarray(lengthscale, float))

    def log_likelihood(self, x: np.ndarray, y: np.ndarray) -> float:
        """GP marginal log-likelihood (reference StationaryKernel.logLikelihood).

        np.linalg.cholesky + triangular solves, NOT scipy cho_factor: the
        slice sampler calls this hundreds of times per GP fit on tiny
        (n_obs x n_obs) matrices, where scipy's check_finite/asarray
        wrapping is most of the wall time (gp_tune profile)."""
        n = len(x)
        k = self(x, x) + self.noise * np.eye(n)
        try:
            c = np.linalg.cholesky(k)
        except np.linalg.LinAlgError:
            return -np.inf
        z = solve_triangular(c, y, lower=True, check_finite=False)
        logdet = 2.0 * np.sum(np.log(np.diagonal(c)))
        return float(-0.5 * z @ z - 0.5 * logdet - 0.5 * n * np.log(2 * np.pi))


@dataclasses.dataclass(frozen=True)
class RBF(Kernel):
    """exp(-r^2 / 2) (reference RBF.scala)."""

    def _k(self, sq: np.ndarray) -> np.ndarray:
        return np.exp(-0.5 * sq)


@dataclasses.dataclass(frozen=True)
class Matern52(Kernel):
    """(1 + sqrt(5) r + 5 r^2 / 3) exp(-sqrt(5) r) (reference Matern52.scala)."""

    def _k(self, sq: np.ndarray) -> np.ndarray:
        r = np.sqrt(sq)
        s5r = np.sqrt(5.0) * r
        return (1.0 + s5r + 5.0 * sq / 3.0) * np.exp(-s5r)
