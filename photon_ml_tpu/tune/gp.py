"""Gaussian-process regression with slice-sampled kernel hyperparameters.

Reference: photon-lib .../hyperparameter/estimators/GaussianProcessEstimator.scala:54-142 —
fit: slice-sample (amplitude, noise, lengthscale) in log space from the GP
posterior given the observations, keep a handful of kernel samples, and
predict by averaging the per-sample posteriors (MCMC marginalization).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import numpy as np
from scipy.linalg import cho_factor, cho_solve

from photon_ml_tpu.tune.kernels import Kernel, Matern52
from photon_ml_tpu.tune.slice_sampler import slice_sample


def _kernel_from_log_params(base: Kernel, theta: np.ndarray, d: int) -> Kernel:
    amplitude = float(np.exp(theta[0]))
    noise = float(np.exp(theta[1]))
    lengthscale = np.exp(theta[2: 2 + d])
    return base.with_params(amplitude, noise, lengthscale)


@dataclasses.dataclass
class GaussianProcess:
    """GP regressor whose kernel parameters are marginalized by slice sampling."""

    base_kernel: Kernel = dataclasses.field(default_factory=Matern52)
    n_kernel_samples: int = 3
    burn_in: int = 10
    normalize_y: bool = True

    _x: Optional[np.ndarray] = None
    _y_mean: float = 0.0
    _y_std: float = 1.0
    _posteriors: List[Tuple[Kernel, np.ndarray, object]] = dataclasses.field(default_factory=list)

    def fit(self, x: np.ndarray, y: np.ndarray, seed: int = 0) -> "GaussianProcess":
        x = np.asarray(x, float)
        y = np.asarray(y, float)
        n, d = x.shape
        self._x = x
        if self.normalize_y and len(y) > 1 and y.std() > 0:
            self._y_mean, self._y_std = float(y.mean()), float(y.std())
        else:
            self._y_mean, self._y_std = float(np.mean(y)), 1.0
        yn = (y - self._y_mean) / self._y_std

        rng = np.random.default_rng(seed)

        def log_density(theta: np.ndarray) -> float:
            # log posterior = log likelihood + weak log-normal prior on params
            if np.any(np.abs(theta) > 10.0):
                return -np.inf
            kern = _kernel_from_log_params(self.base_kernel, theta, d)
            return kern.log_likelihood(x, yn) - 0.5 * float(theta @ theta) / 9.0

        theta0 = np.zeros(2 + d)
        theta0[1] = np.log(1e-2)  # start with small noise
        samples = slice_sample(log_density, theta0, self.n_kernel_samples, rng,
                               burn_in=self.burn_in)

        self._posteriors = []
        for theta in samples:
            kern = _kernel_from_log_params(self.base_kernel, theta, d)
            k = kern(x, x) + kern.noise * np.eye(n)
            try:
                c = cho_factor(k)
            except np.linalg.LinAlgError:
                continue
            alpha = cho_solve(c, yn)
            self._posteriors.append((kern, alpha, c))
        if not self._posteriors:
            # fall back to the prior kernel with jitter
            kern = self.base_kernel
            k = kern(x, x) + (kern.noise + 1e-6) * np.eye(n)
            c = cho_factor(k)
            self._posteriors.append((kern, cho_solve(c, yn), c))
        return self

    def predict(self, x_new: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """Posterior (mean, std), averaged over kernel samples
        (reference GaussianProcessEstimator.predict)."""
        assert self._x is not None, "fit first"
        x_new = np.asarray(x_new, float)
        means, variances = [], []
        for kern, alpha, c in self._posteriors:
            ks = kern(x_new, self._x)
            mu = ks @ alpha
            v = cho_solve(c, ks.T)
            var = np.maximum(kern.amplitude - np.sum(ks * v.T, axis=1), 1e-12)
            means.append(mu)
            variances.append(var)
        means = np.asarray(means)
        variances = np.asarray(variances)
        # moment-match the mixture
        mu = means.mean(0)
        var = variances.mean(0) + means.var(0)
        return mu * self._y_std + self._y_mean, np.sqrt(var) * self._y_std
