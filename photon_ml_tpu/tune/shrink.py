"""Warm-start search-range shrinking.

Reference: photon-client hyperparameter/ShrinkSearchRange.getBounds:40-100 —
fit a GP (Matern52) on rescaled prior observations, draw a Sobol candidate
pool, predict, take the best-predicted point, and return a [best - radius,
best + radius] box in the unit cube mapped back to real ranges (clamped to
the original domain).
"""

from __future__ import annotations

import dataclasses
from typing import List, Sequence, Tuple

import numpy as np
from scipy.stats import qmc

from photon_ml_tpu.tune.gp import GaussianProcess
from photon_ml_tpu.tune.kernels import Matern52
from photon_ml_tpu.tune.search import DomainDim, SearchDomain


def shrink_search_range(
    domain: SearchDomain,
    prior_observations: Sequence[Tuple[np.ndarray, float]],
    radius: float = 0.25,
    minimize: bool = True,
    candidate_pool_size: int = 1024,
    seed: int = 0,
) -> SearchDomain:
    """New, narrower SearchDomain centered on the GP-predicted best point.

    ``prior_observations``: (real-space params, value) pairs (e.g. from
    tune/serialization.prior_from_json).  ``radius`` is in the rescaled
    [0, 1] space, like the reference's.
    """
    if not prior_observations:
        raise ValueError("shrink_search_range needs at least one prior observation")
    params = np.stack([domain.to_unit(np.asarray(p, float))
                       for p, _ in prior_observations])
    values = np.asarray([v if minimize else -v for _, v in prior_observations])

    gp = GaussianProcess(base_kernel=Matern52()).fit(params, values, seed=seed)
    sobol = qmc.Sobol(domain.d, scramble=True, seed=seed)
    candidates = sobol.random(candidate_pool_size)
    mu, _ = gp.predict(candidates)
    best = candidates[int(np.argmin(mu))]

    lo_unit = np.clip(best - radius, 0.0, 1.0)
    hi_unit = np.clip(best + radius, 0.0, 1.0)
    lo = domain.to_real(lo_unit)
    hi = domain.to_real(hi_unit)

    dims: List[DomainDim] = []
    for j, dim in enumerate(domain.dims):
        dims.append(dataclasses.replace(dim, low=float(lo[j]), high=float(hi[j])))
    return SearchDomain(dims)
