"""Hyperparameter search: Sobol quasi-random + GP Bayesian optimization.

Reference: photon-lib .../hyperparameter/search/RandomSearch.scala:46-124
(Sobol sequence candidates; find/findWithPriors loop) and
GaussianProcessSearch.scala:52-123 (fit GP on observations, draw 250 Sobol
candidates, pick the best Expected Improvement, evaluate, repeat).

``SearchDomain`` handles the reference's VectorRescaling (hyperparameters live
in [0,1]^d for the search; linear or log transform to the real range).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np
from scipy.stats import qmc

from photon_ml_tpu.tune.acquisition import expected_improvement
from photon_ml_tpu.tune.gp import GaussianProcess

EvalFn = Callable[[np.ndarray], float]  # real-space params -> metric


@dataclasses.dataclass(frozen=True)
class DomainDim:
    name: str
    low: float
    high: float
    log_scale: bool = False  # reg weights etc. tune in log space


@dataclasses.dataclass
class SearchDomain:
    """[0,1]^d <-> real-space transform (reference VectorRescaling.scala:150)."""

    dims: List[DomainDim]

    @property
    def d(self) -> int:
        return len(self.dims)

    def to_real(self, unit: np.ndarray) -> np.ndarray:
        out = np.empty_like(unit, float)
        for j, dim in enumerate(self.dims):
            u = unit[..., j]
            if dim.log_scale:
                lo, hi = np.log(dim.low), np.log(dim.high)
                out[..., j] = np.exp(lo + u * (hi - lo))
            else:
                out[..., j] = dim.low + u * (dim.high - dim.low)
        return out

    def to_unit(self, real: np.ndarray) -> np.ndarray:
        out = np.empty_like(real, float)
        for j, dim in enumerate(self.dims):
            r = real[..., j]
            if dim.log_scale:
                lo, hi = np.log(dim.low), np.log(dim.high)
                out[..., j] = (np.log(r) - lo) / (hi - lo)
            else:
                out[..., j] = (r - dim.low) / (dim.high - dim.low)
        return np.clip(out, 0.0, 1.0)


@dataclasses.dataclass
class Observation:
    params: np.ndarray  # real space
    value: float  # metric, minimization orientation


class RandomSearch:
    """Sobol quasi-random search (reference RandomSearch.scala:46-124).

    ``batch_size``: candidates proposed (and evaluated) per round.  1 is
    the reference's sequential loop; >1 enables BATCH evaluation — find()
    hands each round's candidates to ``evaluate_batch`` so backends that
    can amortize a multi-candidate fit (FusedSweep.run_grid: one vmapped
    program sharing the design-matrix streams) pay far less than
    batch_size sequential retrains."""

    def __init__(self, domain: SearchDomain, minimize: bool = True, seed: int = 0,
                 batch_size: int = 1):
        self.domain = domain
        self.minimize = minimize
        self.seed = seed
        self.batch_size = max(1, int(batch_size))
        self._sobol = qmc.Sobol(domain.d, scramble=True, seed=seed)
        self.observations: List[Observation] = []
        self.gp_seconds = 0.0  # candidate-proposal time (GP fit + EI)

    def _record(self, params: np.ndarray, raw_value: float) -> None:
        v = raw_value if self.minimize else -raw_value
        self.observations.append(Observation(params=params, value=v))

    def next_candidates(self, q: int) -> List[np.ndarray]:
        u = self._sobol.random(q)
        return [self.domain.to_real(u[i]) for i in range(q)]

    def next_candidate(self) -> np.ndarray:
        return self.next_candidates(1)[0]

    def find(self, evaluate: EvalFn, n: int,
             priors: Optional[Sequence[Tuple[np.ndarray, float]]] = None,
             evaluate_batch=None) -> Tuple[np.ndarray, float]:
        """Evaluate n candidates; returns (best params, best raw value).
        ``priors``: previous observations to seed the search
        (reference findWithPriors:61-93).  ``evaluate_batch``: optional
        callable(list of params) -> list of values used for rounds of more
        than one candidate (see batch_size)."""
        import time

        for p, v in priors or []:
            self._record(np.asarray(p, float), v)
        done = 0
        while done < n:
            t0 = time.perf_counter()
            cands = self.next_candidates(min(self.batch_size, n - done))
            self.gp_seconds += time.perf_counter() - t0
            if evaluate_batch is not None and len(cands) > 1:
                values = evaluate_batch(cands)
            else:
                values = [evaluate(c) for c in cands]
            for c, v in zip(cands, values):
                self._record(c, float(v))
            done += len(cands)
        best = min(self.observations, key=lambda o: o.value)
        return best.params, (best.value if self.minimize else -best.value)


class GaussianProcessSearch(RandomSearch):
    """Bayesian search: GP posterior + Expected Improvement over Sobol
    candidates (reference GaussianProcessSearch.scala:52-123).

    With batch_size q > 1 each round proposes the TOP-q EI candidates from
    the Sobol draw (batch Bayesian optimization's simplest portfolio: the
    250-candidate pool is quasi-random, so the top-q are well-separated in
    practice) — the GP refits once per round instead of once per fit."""

    def __init__(self, domain: SearchDomain, minimize: bool = True, seed: int = 0,
                 n_candidates: int = 250, n_initial: int = 3,
                 batch_size: int = 1):
        super().__init__(domain, minimize, seed, batch_size)
        self.n_candidates = n_candidates  # reference draws 250
        self.n_initial = n_initial

    def next_candidates(self, q: int) -> List[np.ndarray]:
        n_obs = len(self.observations)
        if n_obs < self.n_initial:
            # fill the initial design first (possibly the whole round)
            return super().next_candidates(min(q, self.n_initial - n_obs))
        x = self.domain.to_unit(np.stack([o.params for o in self.observations]))
        y = np.asarray([o.value for o in self.observations])
        gp = GaussianProcess().fit(x, y, seed=self.seed + n_obs)
        cand = self._sobol.random(self.n_candidates)
        mu, sigma = gp.predict(cand)
        ei = expected_improvement(mu, sigma, best=float(y.min()))
        top = np.argsort(-ei)[:q]
        return [self.domain.to_real(cand[int(i)]) for i in top]
