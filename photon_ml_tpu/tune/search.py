"""Hyperparameter search: Sobol quasi-random + GP Bayesian optimization.

Reference: photon-lib .../hyperparameter/search/RandomSearch.scala:46-124
(Sobol sequence candidates; find/findWithPriors loop) and
GaussianProcessSearch.scala:52-123 (fit GP on observations, draw 250 Sobol
candidates, pick the best Expected Improvement, evaluate, repeat).

``SearchDomain`` handles the reference's VectorRescaling (hyperparameters live
in [0,1]^d for the search; linear or log transform to the real range).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np
from scipy.stats import qmc

from photon_ml_tpu.tune.acquisition import expected_improvement
from photon_ml_tpu.tune.gp import GaussianProcess

EvalFn = Callable[[np.ndarray], float]  # real-space params -> metric


@dataclasses.dataclass(frozen=True)
class DomainDim:
    name: str
    low: float
    high: float
    log_scale: bool = False  # reg weights etc. tune in log space


@dataclasses.dataclass
class SearchDomain:
    """[0,1]^d <-> real-space transform (reference VectorRescaling.scala:150)."""

    dims: List[DomainDim]

    @property
    def d(self) -> int:
        return len(self.dims)

    def to_real(self, unit: np.ndarray) -> np.ndarray:
        out = np.empty_like(unit, float)
        for j, dim in enumerate(self.dims):
            u = unit[..., j]
            if dim.log_scale:
                lo, hi = np.log(dim.low), np.log(dim.high)
                out[..., j] = np.exp(lo + u * (hi - lo))
            else:
                out[..., j] = dim.low + u * (dim.high - dim.low)
        return out

    def to_unit(self, real: np.ndarray) -> np.ndarray:
        out = np.empty_like(real, float)
        for j, dim in enumerate(self.dims):
            r = real[..., j]
            if dim.log_scale:
                lo, hi = np.log(dim.low), np.log(dim.high)
                out[..., j] = (np.log(r) - lo) / (hi - lo)
            else:
                out[..., j] = (r - dim.low) / (dim.high - dim.low)
        return np.clip(out, 0.0, 1.0)


@dataclasses.dataclass
class Observation:
    params: np.ndarray  # real space
    value: float  # metric, minimization orientation


class RandomSearch:
    """Sobol quasi-random search (reference RandomSearch.scala:46-124)."""

    def __init__(self, domain: SearchDomain, minimize: bool = True, seed: int = 0):
        self.domain = domain
        self.minimize = minimize
        self.seed = seed
        self._sobol = qmc.Sobol(domain.d, scramble=True, seed=seed)
        self.observations: List[Observation] = []

    def _record(self, params: np.ndarray, raw_value: float) -> None:
        v = raw_value if self.minimize else -raw_value
        self.observations.append(Observation(params=params, value=v))

    def next_candidate(self) -> np.ndarray:
        return self.domain.to_real(self._sobol.random(1)[0])

    def find(self, evaluate: EvalFn, n: int,
             priors: Optional[Sequence[Tuple[np.ndarray, float]]] = None
             ) -> Tuple[np.ndarray, float]:
        """Evaluate n candidates; returns (best params, best raw value).
        ``priors``: previous observations to seed the search
        (reference findWithPriors:61-93)."""
        for p, v in priors or []:
            self._record(np.asarray(p, float), v)
        for _ in range(n):
            params = self.next_candidate()
            self._record(params, evaluate(params))
        best = min(self.observations, key=lambda o: o.value)
        return best.params, (best.value if self.minimize else -best.value)


class GaussianProcessSearch(RandomSearch):
    """Bayesian search: GP posterior + Expected Improvement over Sobol
    candidates (reference GaussianProcessSearch.scala:52-123)."""

    def __init__(self, domain: SearchDomain, minimize: bool = True, seed: int = 0,
                 n_candidates: int = 250, n_initial: int = 3):
        super().__init__(domain, minimize, seed)
        self.n_candidates = n_candidates  # reference draws 250
        self.n_initial = n_initial

    def next_candidate(self) -> np.ndarray:
        if len(self.observations) < self.n_initial:
            return super().next_candidate()
        x = self.domain.to_unit(np.stack([o.params for o in self.observations]))
        y = np.asarray([o.value for o in self.observations])
        gp = GaussianProcess().fit(x, y, seed=self.seed + len(self.observations))
        cand = self._sobol.random(self.n_candidates)
        mu, sigma = gp.predict(cand)
        ei = expected_improvement(mu, sigma, best=float(y.min()))
        return self.domain.to_real(cand[int(np.argmax(ei))])
