"""Acquisition criteria for Bayesian search.

Reference: photon-lib .../hyperparameter/criteria/ExpectedImprovement.scala:58
and ConfidenceBound.scala:48.  Convention here: MINIMIZATION — the search
negates metrics where larger is better (as the reference's evaluation
function does with isOptMax).
"""

from __future__ import annotations

import numpy as np
from scipy.stats import norm


def expected_improvement(mu: np.ndarray, sigma: np.ndarray, best: float) -> np.ndarray:
    """EI for minimization: E[max(best - f, 0)]."""
    sigma = np.maximum(sigma, 1e-12)
    z = (best - mu) / sigma
    return (best - mu) * norm.cdf(z) + sigma * norm.pdf(z)


def confidence_bound(mu: np.ndarray, sigma: np.ndarray, beta: float = 2.0) -> np.ndarray:
    """Lower confidence bound, returned NEGATED so argmax = most promising."""
    return -(mu - beta * sigma)
