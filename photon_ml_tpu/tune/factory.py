"""Pluggable hyperparameter tuners.

Reference: photon-api hyperparameter/tuner/HyperparameterTunerFactory.scala:20-48
— the tuner implementation is resolved by NAME and loaded reflectively
(DUMMY = no-op; ATLAS = LinkedIn-internal class not present in the repo).
Here: DUMMY (no-op), BUILTIN (tune/game_tuning.tune_game_model), or any
``module.path:ClassName`` whose instances implement ``tune(...)`` with the
same signature as ``BuiltinTuner.tune``.
"""

from __future__ import annotations

import importlib
from typing import List, Optional, Tuple

DUMMY = "DUMMY"
BUILTIN = "BUILTIN"


class DummyTuner:
    """No-op (reference DummyTuner.scala): returns no tuned results."""

    #: capability flag the driver checks before doing search-domain prep work
    uses_search_domain = False

    def tune(self, estimator, base_config, data, validation_data, **kwargs
             ) -> Tuple[Optional[object], Optional[object], List[object]]:
        return None, None, []


class BuiltinTuner:
    """The in-tree Sobol/GP search (tune/game_tuning.tune_game_model)."""

    uses_search_domain = True

    def tune(self, estimator, base_config, data, validation_data, **kwargs
             ) -> Tuple[object, object, List[object]]:
        from photon_ml_tpu.tune.game_tuning import tune_game_model

        return tune_game_model(estimator, base_config, data, validation_data,
                               **kwargs)


def tuner_factory(name: str):
    """Tuner NAME -> tuner instance (HyperparameterTunerFactory.scala:31-44).

    ``DUMMY`` | ``BUILTIN`` | ``module.path:ClassName`` (reflection-loaded,
    like the reference's ATLAS hook).
    """
    key = (name or BUILTIN).strip()
    if key.upper() == DUMMY:
        return DummyTuner()
    if key.upper() == BUILTIN:
        return BuiltinTuner()
    if ":" not in key:
        raise ValueError(
            f"unknown tuner {name!r}: use DUMMY, BUILTIN, or module:Class")
    mod_name, _, cls_name = key.partition(":")
    try:
        cls = getattr(importlib.import_module(mod_name), cls_name)
    except (ImportError, AttributeError) as e:
        raise ValueError(f"couldn't load tuner {name!r}: {e}") from e
    try:
        tuner = cls()
    except Exception as e:
        raise ValueError(f"couldn't instantiate tuner {name!r}: {e}") from e
    if not callable(getattr(tuner, "tune", None)):
        raise ValueError(f"tuner {name!r} has no tune() method")
    return tuner
