"""Hyperparameter config / prior-observation JSON de-serialization.

Reference: photon-lib hyperparameter/HyperparameterSerialization.scala —
``configFromJson`` parses ``{"tuning_mode": "BAYESIAN"|"RANDOM", "variables":
{name: {"type", "transform": "LOG"|"SQRT"|absent, "min", "max"}}}`` (for LOG
variables min/max are base-10 exponents, VectorRescaling.scala:150);
``priorFromJson`` parses ``{"records": [{<param>: "<value>", ...,
"evaluationValue": "<value>"}]}`` filling missing params from defaults
(GameHyperparameterDefaults.priorDefault).
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from photon_ml_tpu.tune.search import DomainDim, SearchDomain

LOG_TRANSFORM = "LOG"
SQRT_TRANSFORM = "SQRT"


def config_from_json(json_config: str) -> Tuple[str, SearchDomain]:
    """JSON -> (tuning mode, search domain).

    LOG-transform variables are declared by exponent (min=-3, max=3 means
    10^-3..10^3) and search in log space; SQRT/linear variables are declared
    by value.  SQRT searching is approximated as linear (the reference uses
    SQRT only to soften rounding of integer dims).
    """
    cfg = json.loads(json_config)
    mode = str(cfg["tuning_mode"]).upper()
    if mode not in ("BAYESIAN", "RANDOM"):
        raise ValueError(f"unknown tuning mode {mode!r}")
    dims: List[DomainDim] = []
    for name, spec in cfg["variables"].items():
        transform = spec.get("transform")
        lo, hi = float(spec["min"]), float(spec["max"])
        if transform == LOG_TRANSFORM:
            dims.append(DomainDim(name=name, low=10.0 ** lo, high=10.0 ** hi,
                                  log_scale=True))
        else:
            dims.append(DomainDim(name=name, low=lo, high=hi))
    return mode, SearchDomain(dims)


def prior_from_json(
    prior_json: str,
    prior_default: Dict[str, str],
    hyperparameter_names: Sequence[str],
) -> List[Tuple[np.ndarray, float]]:
    """JSON records -> [(params vector ordered by ``hyperparameter_names``,
    evaluation value)] (HyperparameterSerialization.priorFromJson)."""
    data = json.loads(prior_json)
    records = data["records"]
    out: List[Tuple[np.ndarray, float]] = []
    for rec in records:
        value = float(rec["evaluationValue"])
        params = np.asarray([
            float(rec.get(name, prior_default[name]))
            for name in hyperparameter_names
        ])
        out.append((params, value))
    return out


def config_to_json(mode: str, domain: SearchDomain) -> str:
    """Inverse of config_from_json (round-trips LOG dims to exponents)."""
    variables = {}
    for dim in domain.dims:
        if dim.log_scale:
            variables[dim.name] = {"type": "FLOAT", "transform": LOG_TRANSFORM,
                                   "min": float(np.log10(dim.low)),
                                   "max": float(np.log10(dim.high))}
        else:
            variables[dim.name] = {"type": "FLOAT", "min": dim.low, "max": dim.high}
    return json.dumps({"tuning_mode": mode, "variables": variables}, indent=2)


def game_prior_default(coordinate_ids: Sequence[str]) -> Dict[str, str]:
    """Per-coordinate L2 prior defaults (GameHyperparameterDefaults.priorDefault
    uses 0.0 per regularizer)."""
    return {f"l2:{cid}": "0.0" for cid in coordinate_ids}
