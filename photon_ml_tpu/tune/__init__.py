from photon_ml_tpu.tune.search import (  # noqa: F401
    RandomSearch,
    GaussianProcessSearch,
    SearchDomain,
)
from photon_ml_tpu.tune.gp import GaussianProcess  # noqa: F401
from photon_ml_tpu.tune.kernels import Matern52, RBF  # noqa: F401
from photon_ml_tpu.tune.acquisition import expected_improvement, confidence_bound  # noqa: F401
from photon_ml_tpu.tune.slice_sampler import slice_sample  # noqa: F401
from photon_ml_tpu.tune.game_tuning import (  # noqa: F401
    GameEstimatorEvaluationFunction,
    tune_game_model,
)
from photon_ml_tpu.tune.serialization import (  # noqa: F401
    config_from_json,
    config_to_json,
    game_prior_default,
    prior_from_json,
)
from photon_ml_tpu.tune.shrink import shrink_search_range  # noqa: F401
from photon_ml_tpu.tune.factory import BuiltinTuner, DummyTuner, tuner_factory  # noqa: F401
