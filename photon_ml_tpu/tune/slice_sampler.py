"""Univariate stepping-out slice sampler, applied coordinate-wise.

Reference: photon-lib .../hyperparameter/SliceSampler.scala:52-207 (Neal 2003
slice sampling with stepping-out and shrinkage, used to sample GP kernel
hyperparameters from their posterior).
"""

from __future__ import annotations

from typing import Callable

import numpy as np

LogDensity = Callable[[np.ndarray], float]


def _slice_1d(log_density: LogDensity, x: np.ndarray, dim: int, rng: np.random.Generator,
              step: float = 1.0, max_steps: int = 32,
              f0: "float | None" = None):
    """One coordinate-wise slice update; returns (x', f(x')).

    ``f0``: the current point's (already-known) log density — the sweep
    caller threads it through so the density is evaluated once per MOVE,
    not once per call (each evaluation is a GP Cholesky; this is the
    tuner's hot loop)."""
    x = x.copy()
    if f0 is None:
        f0 = log_density(x)
    log_u = f0 + np.log(rng.random() + 1e-300)

    # stepping out
    left = x[dim] - step * rng.random()
    right = left + step
    j = int(rng.integers(0, max_steps))
    k = max_steps - 1 - j
    xt = x.copy()
    while j > 0:
        xt[dim] = left
        if log_density(xt) <= log_u:
            break
        left -= step
        j -= 1
    while k > 0:
        xt[dim] = right
        if log_density(xt) <= log_u:
            break
        right += step
        k -= 1

    # shrinkage
    for _ in range(100):
        xt[dim] = left + rng.random() * (right - left)
        ft = log_density(xt)
        if ft > log_u:
            return xt, ft
        if xt[dim] < x[dim]:
            left = xt[dim]
        else:
            right = xt[dim]
    return x, f0  # shrunk to nothing: keep the current point


def slice_sample(log_density: LogDensity, x0: np.ndarray, n_samples: int,
                 rng: np.random.Generator, step: float = 1.0,
                 burn_in: int = 10) -> np.ndarray:
    """Draw n_samples points (coordinate-wise sweeps) from exp(log_density)."""
    x = np.asarray(x0, float).copy()
    out = np.empty((n_samples, len(x)))
    total = burn_in + n_samples
    fx = None  # threaded through so each move costs one density evaluation
    for i in range(total):
        for dim in range(len(x)):
            x, fx = _slice_1d(log_density, x, dim, rng, step=step, f0=fx)
        if i >= burn_in:
            out[i - burn_in] = x
    return out
