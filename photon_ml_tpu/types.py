"""Shared enums and type aliases.

Reference: photon-lib .../TaskType.scala:25, .../Types.scala:21-44,
optimization/VarianceComputationType.scala:25, util/ConvergenceReason.scala:38.
"""

from __future__ import annotations

import enum
from typing import Mapping, Tuple

# Reference Types.scala: UniqueSampleId = Long, CoordinateId/REType/REId/FeatureShardId = String.
UniqueSampleId = int
CoordinateId = str
REType = str
REId = str
FeatureShardId = str

# Box constraints: feature index -> (lower, upper).  Reference OptimizationUtils.scala.
ConstraintMap = Mapping[int, Tuple[float, float]]


class TaskType(enum.Enum):
    """Training-task types (reference TaskType.scala:25)."""

    LOGISTIC_REGRESSION = "logistic_regression"
    LINEAR_REGRESSION = "linear_regression"
    POISSON_REGRESSION = "poisson_regression"
    SMOOTHED_HINGE_LOSS_LINEAR_SVM = "smoothed_hinge_loss_linear_svm"
    NONE = "none"


class VarianceComputationType(enum.Enum):
    """Coefficient-variance computation (reference VarianceComputationType.scala:25).

    SIMPLE = 1 / diag(H); FULL = diag(H^-1) via Cholesky
    (reference DistributedOptimizationProblem.scala:84-108).
    """

    NONE = "none"
    SIMPLE = "simple"
    FULL = "full"


class ConvergenceReason(enum.IntEnum):
    """Why an optimizer stopped (reference util/ConvergenceReason.scala:38).

    IntEnum with a stable device-side encoding: solvers carry the reason as an
    int32 inside jitted while_loops; NOT_CONVERGED means still running.
    """

    NOT_CONVERGED = 0
    FUNCTION_VALUES_CONVERGED = 1
    GRADIENT_CONVERGED = 2
    MAX_ITERATIONS = 3
    OBJECTIVE_NOT_IMPROVING = 4


class NormalizationType(enum.Enum):
    """Feature-normalization flavors (reference NormalizationType.scala:42)."""

    NONE = "none"
    SCALE_WITH_MAX_MAGNITUDE = "scale_with_max_magnitude"
    SCALE_WITH_STANDARD_DEVIATION = "scale_with_standard_deviation"
    STANDARDIZATION = "standardization"


class OptimizerType(enum.Enum):
    """Reference OptimizerType.scala:23 {LBFGS, TRON} + OWLQN (selected implicitly
    by L1 regularization in the reference; explicit here)."""

    LBFGS = "lbfgs"
    TRON = "tron"
    OWLQN = "owlqn"


class ProjectorType(enum.Enum):
    """Random-effect feature projection (reference ProjectorType.scala:30)."""

    IDENTITY = "identity"
    INDEX_MAP = "index_map"
    RANDOM = "random"
