from photon_ml_tpu.game.data import GameData  # noqa: F401
from photon_ml_tpu.game.config import (  # noqa: F401
    CoordinateConfig,
    FixedEffectConfig,
    RandomEffectConfig,
)
from photon_ml_tpu.game.coordinate import (  # noqa: F401
    Coordinate,
    FixedEffectCoordinate,
    RandomEffectCoordinate,
    build_coordinate,
)
from photon_ml_tpu.game.descent import CoordinateDescent, DescentHistory  # noqa: F401
from photon_ml_tpu.game.fused import FusedSweep  # noqa: F401
from photon_ml_tpu.game.estimator import GameEstimator, GameTransformer  # noqa: F401
