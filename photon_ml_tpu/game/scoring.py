"""Shared score composition — the ONE definition of GAME additive scoring.

Reference: photon-lib .../model/GameModel.scala:99-110 (score = sum of
coordinate raw scores) and photon-api transformers/GameTransformer.scala:
263 (scoreGameDataset: raw totals, offset applied by the caller) plus the
scoring driver's mean transform (GameScoringDriver.scala: predicted mean is
the inverse link of margin + offset).

Every consumer of "add up the coordinate scores, then apply offset and the
task's inverse link" goes through here: the batch paths (models/game
.GameModel.score, game/estimator.GameTransformer, cli/score.py) and the
online serving engine (serving/engine.py), whose compiled per-bucket kernels
call ``additive_total`` on per-coordinate margins exactly like the batch
path does — one code path, so batch and online scores cannot drift.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Iterable, Optional

import jax
import jax.numpy as jnp
import numpy as np

if TYPE_CHECKING:  # annotation-only: avoid import cycles
    from photon_ml_tpu.game.data import GameData
    from photon_ml_tpu.models.game import GameModel
    from photon_ml_tpu.types import TaskType

Array = jax.Array


def additive_total(num_samples: int, margins: Iterable[Array]) -> Array:
    """Sum per-coordinate raw margins into the total score vector.

    The accumulation order and the zero-init are part of the scoring
    contract (GameModel.score:99-110): serving reuses this function inside
    its jitted kernels so padded-bucket totals are bitwise the batch totals.
    """
    total = jnp.zeros((num_samples,))
    # photonlint: disable=tracer-safety -- margins is a Python iterable with
    # one [n] array per coordinate (static structure); inside serving's
    # jitted kernels this unrolls over coordinates by design, keeping the
    # accumulation order identical to the batch path
    for m in margins:
        total = total + m
    return total


def raw_scores(model: "GameModel", data: "GameData") -> np.ndarray:
    """Raw margin + offset per sample (reference scoreGameDataset:263 plus
    the driver-side offset add) — the input both evaluators and the mean
    transform expect."""
    return np.asarray(model.score(data)) + np.asarray(data.offset)


def output_scores(raw: np.ndarray, task: "TaskType",
                  predict_mean: bool = False) -> np.ndarray:
    """Final output transform: raw margins, or the task's inverse-link mean
    (a pointwise function of the raw margin — never re-scores)."""
    if not predict_mean:
        return raw
    from photon_ml_tpu.core.losses import loss_for_task

    return np.asarray(loss_for_task(task).mean(raw))
