"""Per-coordinate configuration.

Reference: photon-api .../data/CoordinateDataConfiguration.scala:94 (fixed/
random data configs: randomEffectType, featureShard, active-data bounds),
optimization/game/CoordinateOptimizationConfiguration.scala:99 (optimizer +
regularization + downSamplingRate per coordinate).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple, Union

# Per-feature-index box constraints (reference constraintMap:
# Map[Int, (lowerBound, upperBound)], OptimizerConfig.scala:47, applied via
# OptimizationUtils.projectCoefficientsToSubspace): ((index, lo, hi), ...).
# Index-keyed like the reference's; name/term resolution against the feature
# index map happens in the CLI layer (cli/config_grammar.resolve_constraints).
ConstraintMap = Tuple[Tuple[int, float, float], ...]

from photon_ml_tpu.core.regularization import Regularization
from photon_ml_tpu.opt.types import SolverConfig
from photon_ml_tpu.types import (OptimizerType, ProjectorType, TaskType,
                                 VarianceComputationType)


@dataclasses.dataclass(frozen=True)
class FixedEffectConfig:
    """One global GLM coordinate (reference FixedEffectDataConfiguration +
    FixedEffectOptimizationConfiguration)."""

    feature_shard: str
    optimizer: OptimizerType = OptimizerType.LBFGS
    solver: Optional[SolverConfig] = None
    reg: Regularization = Regularization()
    down_sampling_rate: float = 1.0  # negative down-sampling (binary tasks)
    intercept_index: Optional[int] = None  # needed by shift normalization
    # Coefficient variances on the final model (reference
    # DistributedOptimizationProblem.scala:84-108; stored in
    # BayesianLinearModelAvro.variances)
    variance: VarianceComputationType = VarianceComputationType.NONE
    # Mixed precision (TPU-native; no reference analog — the JVM is f64):
    # store the design matrix at this width ("bfloat16"/"float16") while the
    # solver state, reductions, labels and weights stay at the compute dtype.
    # Matmuls run with storage-width MXU operands and compute-width
    # accumulation — halves objective-pass HBM traffic on large n.
    storage_dtype: Optional[str] = None
    # Shard w (and dense design columns) over the mesh's ``feature`` axis —
    # the huge-vocabulary scale path (reference: sparse vectors over PalDB
    # 1e8-feature index maps, PalDBIndexMap.scala:16-60).  No-op unless the
    # estimator mesh has a feature axis > 1.  See parallel/fixed.py.
    feature_sharded: bool = False
    # Box constraints on coefficients (see ConstraintMap above); LBFGS only
    # (projected-gradient path, opt/lbfgs.py) — reference parity: TRON/OWLQN
    # reject constraints too.
    constraints: Optional[ConstraintMap] = None
    # Which coefficient space the bounds constrain (see
    # _canonicalize_constraints for the semantics of each value).
    constraint_space: str = "original"

    def __post_init__(self):
        _canonicalize_constraints(self)


@dataclasses.dataclass(frozen=True)
class RandomEffectConfig:
    """One per-entity coordinate (reference RandomEffectDataConfiguration +
    RandomEffectOptimizationConfiguration)."""

    random_effect_type: str  # id-tag column with entity ids
    feature_shard: str
    optimizer: OptimizerType = OptimizerType.LBFGS
    solver: Optional[SolverConfig] = None
    reg: Regularization = Regularization()
    active_cap: Optional[int] = None  # per-entity sample cap (reservoir)
    min_active_samples: int = 1  # lower-bound entity filter
    # Feature projection (reference ProjectorType.scala:30 + featuresToSamplesRatio,
    # RandomEffectDataConfiguration): solve each entity in a reduced feature space.
    projector: ProjectorType = ProjectorType.IDENTITY
    projected_dim: Optional[int] = None  # required for ProjectorType.RANDOM
    features_to_samples_ratio: Optional[float] = None  # per-entity Pearson top-k cap
    intercept_index: Optional[int] = None  # column the Pearson filter must keep
    variance: VarianceComputationType = VarianceComputationType.NONE
    # Mixed-precision design-matrix storage (see FixedEffectConfig).
    storage_dtype: Optional[str] = None
    # Per-entity regularization: multiplicative factors on this coordinate's
    # L2 weight, keyed by entity id (the reference ENVISIONED per-entity λ —
    # RandomEffectOptimizationProblem.scala:42 keeps one problem per entity
    # for exactly this — but never implemented it).  Multiplicative so a
    # tuned/grid L2 scales every entity while relative strengths persist.
    # Accepts a dict; stored canonically as a sorted tuple of pairs.
    per_entity_l2_multipliers: "Optional[tuple]" = None
    # Box constraints on coefficients, applied to EVERY entity's solve
    # (see ConstraintMap above); IDENTITY projector + LBFGS only — bounds
    # have no meaning in a projected solve space.
    constraints: Optional[ConstraintMap] = None
    # See FixedEffectConfig.constraint_space.
    constraint_space: str = "original"

    def __post_init__(self):
        m = self.per_entity_l2_multipliers
        if isinstance(m, dict):
            object.__setattr__(self, "per_entity_l2_multipliers",
                               tuple(sorted((int(k), float(v))
                                            for k, v in m.items())))
        elif m is not None:
            object.__setattr__(self, "per_entity_l2_multipliers",
                               tuple(sorted((int(k), float(v)) for k, v in m)))
        if (self.projected_dim is not None
                and self.projector != ProjectorType.RANDOM):
            # validated at CONFIG time so every path agrees: the dense
            # IDENTITY path used to silently ignore projected_dim and the
            # sparse path raised mid-build — one loud, early answer instead
            raise ValueError(
                "projected_dim applies only to ProjectorType.RANDOM "
                f"(got projector={self.projector.name})")
        _canonicalize_constraints(self)


def _canonicalize_constraints(cfg) -> None:
    """Accept a dict {index: (lo, hi)} or iterable of (index, lo, hi);
    store a sorted tuple (hashable — configs are frozen/compared) and
    validate bounds (reference GLMSuite.createConstraintFeatureMap:193-232:
    lo < hi, not both infinite).

    ``constraint_space`` semantics:

    - "original" (default): bounds constrain the PUBLISHED original-space
      coefficients.  Mathematically consistent; under scaling normalization
      the solver-space box becomes [lo/f, hi/f], and shift normalization is
      refused (per-feature original-space bounds are non-separable under
      the intercept shift fold).
    - "transformed": reference-compat — bounds applied RAW to the
      TRANSFORMED (solver-space) coefficients every iteration, exactly what
      the reference does (TRON.scala:228 projects constraintMap bounds onto
      the scaled+shifted iterate, OptimizationUtils.scala:56-58), i.e. the
      published original-space coefficients may VIOLATE the written bounds
      whenever normalization rescales.  Faithful but questionable; exists
      so reference jobs migrate bit-for-bit.  See MIGRATION.md.
    """
    if cfg.constraint_space not in ("original", "transformed"):
        raise ValueError(
            f"constraint_space must be 'original' or 'transformed' "
            f"(got {cfg.constraint_space!r})")
    c = cfg.constraints
    if c is None:
        return
    if isinstance(c, dict):
        c = tuple((int(j), *map(float, bounds)) for j, bounds in c.items())
    else:
        c = tuple((int(j), float(lo), float(hi)) for j, lo, hi in c)
    seen = set()
    for j, lo, hi in c:
        if j in seen:
            raise ValueError(
                f"duplicate constraint for feature index {j} (later entries "
                "would silently overwrite earlier bounds)")
        seen.add(j)
        if not lo < hi:
            raise ValueError(
                f"constraint on feature {j}: lower bound {lo} must be < "
                f"upper bound {hi}")
        if lo == float("-inf") and hi == float("inf"):
            raise ValueError(
                f"constraint on feature {j}: both bounds infinite "
                "(not a constraint)")
    object.__setattr__(cfg, "constraints", tuple(sorted(c)))


CoordinateConfig = Union[FixedEffectConfig, RandomEffectConfig]


@dataclasses.dataclass(frozen=True)
class GameConfig:
    """Full GAME training configuration: ordered coordinates + task.

    The coordinate ORDER is the descent order (reference
    GameTrainingDriver coordinate update sequence)."""

    task: TaskType
    coordinates: "dict[str, CoordinateConfig]"
    num_outer_iterations: int = 1
