"""Block coordinate descent over GAME coordinates.

Reference: photon-lib .../algorithm/CoordinateDescent.scala:38-346 —
residual-based descent: each coordinate trains against
partialScore = fullTrainingScore - ownScore folded into the offsets
(:197-204), re-scores, and the full score is updated; validation metrics are
computed on the FULL model after every coordinate update (:257-289) and the
best full model by the primary evaluator is retained (:293-325).  Locked
(pre-trained) coordinates are re-scored but never re-trained
(ModelCoordinate.scala; GameEstimator partial retraining :237-269).

Host-level orchestration (like the reference's driver loop): the per-update
device work is the jitted solvers inside each Coordinate; the bookkeeping
here is O(n) numpy vector adds.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from photon_ml_tpu.evaluation.evaluator import EvaluationResults, EvaluationSuite
from photon_ml_tpu.game.coordinate import Coordinate
from photon_ml_tpu.game.data import GameData
from photon_ml_tpu.models.game import DatumScoringModel, GameModel
from photon_ml_tpu.obs import get_registry
from photon_ml_tpu.obs.registry import MetricsRegistry
from photon_ml_tpu.obs.trace import span as obs_span

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class DescentHistory:
    """Per-update telemetry (reference per-iteration logging + trackers).

    Bookkeeping lives in the unified metrics registry — every ``add``
    increments ``descent_updates_total{coordinate=...}`` and observes
    ``descent_update_seconds{coordinate=...}``, so training timings land in
    the same export surface (JSON / Prometheus) serving uses.  ``steps``
    remains the in-order record API consumers iterate (estimator results,
    tuning); ``registry=None`` binds to the process default."""

    steps: List[dict] = dataclasses.field(default_factory=list)
    registry: Optional[MetricsRegistry] = None

    def add(self, iteration: int, coordinate_id: str, seconds: float,
            validation: Optional[EvaluationResults]) -> None:
        self.steps.append(dict(iteration=iteration, coordinate=coordinate_id,
                               seconds=seconds, validation=validation))
        reg = self.registry or get_registry()
        reg.inc("descent_updates_total", coordinate=coordinate_id)
        reg.observe("descent_update_seconds", seconds,
                    coordinate=coordinate_id)
        reg.set_gauge("descent_iteration", iteration)


class CoordinateDescent:
    """run(): descend over coordinates in order (CoordinateDescent.scala:93-107).

    ``validation``: (data, suite, group_ids) — evaluated on the full model
    after every coordinate update; best model kept by the primary evaluator.
    ``locked``: coordinate ids whose model comes from ``initial`` and is only
    re-scored, never re-trained.
    """

    def __init__(self, coordinates: Dict[str, Coordinate], order: Optional[Sequence[str]] = None,
                 num_iterations: int = 1,
                 validation: Optional[Tuple[GameData, EvaluationSuite]] = None,
                 locked: Optional[Set[str]] = None,
                 registry: Optional[MetricsRegistry] = None):
        self.coordinates = coordinates
        self.order = list(order) if order is not None else list(coordinates)
        if set(self.order) != set(coordinates):
            raise ValueError(f"descent order {self.order} != coordinate ids {set(coordinates)}")
        self.num_iterations = num_iterations
        self.validation = validation
        self.locked = locked or set()
        self.registry = registry  # None -> process-default obs registry
        missing = self.locked - set(coordinates)
        if missing:
            raise ValueError(f"locked coordinates not present: {missing}")

    def run(self, initial: Optional[GameModel] = None, seed: int = 0,
            checkpoint_hook=None, resume_cursor: Optional[Dict[str, int]] = None,
            resume_best: Optional[Tuple[GameModel, EvaluationResults]] = None,
            ) -> Tuple[GameModel, DescentHistory, Optional[EvaluationResults]]:
        """``checkpoint_hook(model, cursor, updated=cid, best=(m, ev) | None,
        best_changed=bool)``: called after every coordinate update with the
        current full model and the cursor of the NEXT update
        ({"iteration": i, "coordinate": k} indices).  ``resume_cursor``: skip
        updates before it — ``initial`` must then be the checkpointed model
        (storage/checkpoint.py; mid-job resume the reference lacks,
        SURVEY.md §5).  ``resume_best``: seeds best-model tracking so the
        best-by-primary-metric retention survives preemption."""
        coords = self.coordinates
        n = next(iter(coords.values()))._n if coords else 0
        history = DescentHistory(registry=self.registry)

        # Initial scores: warm-start models (and locked coordinates) contribute
        # their score from the start (CoordinateDescent warm-start path).
        models: Dict[str, DatumScoringModel] = {}
        scores: Dict[str, np.ndarray] = {}
        for cid, coord in coords.items():
            if initial is not None and cid in initial:
                models[cid] = initial[cid]
                scores[cid] = np.asarray(coord.score(initial[cid]))
            else:
                if cid in self.locked:
                    raise ValueError(f"locked coordinate {cid!r} needs an initial model")
                scores[cid] = np.zeros(n)

        total = np.sum(list(scores.values()), axis=0) if scores else np.zeros(n)
        best_model: Optional[GameModel] = None
        best_eval: Optional[EvaluationResults] = None
        if resume_best is not None:
            best_model, best_eval = resume_best
        last_eval: Optional[EvaluationResults] = None
        # the update that completes each sweep (locked coordinates never
        # update, so "full model" means after the last UNLOCKED one)
        active = [k for k, c in enumerate(self.order) if c not in self.locked]
        last_active = active[-1] if active else -1

        for it in range(self.num_iterations):
            for k, cid in enumerate(self.order):
                coord = coords[cid]
                if cid in self.locked:
                    continue  # locked: score already folded into total
                if resume_cursor is not None and (
                        (it, k) < (resume_cursor.get("iteration", 0),
                                   resume_cursor.get("coordinate", 0))):
                    continue  # already done before the checkpoint
                t0 = time.perf_counter()
                # one span per (iteration, coordinate) update — the unit the
                # reference logs and the unit a Perfetto timeline nests the
                # solve/score children under
                with obs_span("descent.update", iteration=it, coordinate=cid):
                    # Residual trick (CoordinateDescent.scala:197-204):
                    # everything the OTHER coordinates explain becomes an
                    # offset.
                    partial = total - scores[cid]
                    offsets = coord._base_offset_host() + partial
                    with obs_span("descent.solve", coordinate=cid):
                        model, _tracker = coord.update(offsets, seed=seed + it,
                                                       init=models.get(cid))
                    if logger.isEnabledFor(logging.DEBUG):
                        # reference logs tracker summaries at debug
                        # (CoordinateDescent.scala:238-250)
                        try:
                            logger.debug("coord %s solvers: %s", cid,
                                         coord.tracker_summary(_tracker))
                        except Exception:  # telemetry must never kill training
                            logger.debug("coord %s: tracker summary unavailable", cid)
                    with obs_span("descent.score", coordinate=cid):
                        new_score = np.asarray(coord.score(model))
                    models[cid] = model
                    scores[cid] = new_score
                    total = partial + new_score
                dt = time.perf_counter() - t0

                val_res = None
                best_changed = False
                if self.validation is not None:
                    val_data, suite = self.validation
                    current = GameModel(models=dict(models))
                    with obs_span("descent.validate", iteration=it,
                                  coordinate=cid):
                        val_scores = np.asarray(current.score(val_data)) \
                            + np.asarray(val_data.offset)
                        val_res = suite.evaluate(
                            val_scores, val_data.y, val_data.weight,
                            group_ids=val_data.id_tags)
                    last_eval = val_res
                    # best-model retention compares FULL models only — after
                    # a complete update sequence, never inside the coordinate
                    # loop, where a "best" snapshot could have untrained
                    # coordinates (reference CoordinateDescent.scala:163-167,
                    # explicit NOTE).  Mid-sweep evaluations are still
                    # computed and logged, like the reference's.
                    if k == last_active and suite.better_than(
                            val_res, best_eval):
                        best_eval = val_res
                        best_model = current
                        best_changed = True
                    logger.info("iter %d coord %s: %s (%.2fs)", it, cid, val_res.values, dt)
                history.add(it, cid, dt, val_res)
                if checkpoint_hook is not None:
                    nxt = ((it, k + 1) if k + 1 < len(self.order) else (it + 1, 0))
                    best = ((best_model, best_eval)
                            if best_model is not None and best_eval is not None else None)
                    checkpoint_hook(GameModel(models=dict(models)),
                                    {"iteration": nxt[0], "coordinate": nxt[1]},
                                    updated=cid, best=best, best_changed=best_changed)

        final = GameModel(models=models)
        if best_model is not None:
            return best_model, history, best_eval
        return final, history, last_eval
