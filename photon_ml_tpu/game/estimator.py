"""GameEstimator / GameTransformer — the top-level fit/transform API.

Reference: photon-api .../estimators/GameEstimator.scala:299-781 (fit:
prepare per-coordinate datasets, validation suite, build coordinates, run
coordinate descent per optimization configuration with warm start between
configurations) and transformers/GameTransformer.scala:150-318 (score a
prepared GAME dataset with a GameModel + optional evaluation).

TPU-native: "preparing datasets" is building device-resident coordinates
(one-time layout, no shuffles); each (coordinate-config -> fit) pair reuses
the same jitted solvers.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np
from jax.sharding import Mesh

from photon_ml_tpu.evaluation.evaluator import EvaluationResults, EvaluationSuite
from photon_ml_tpu.game.config import CoordinateConfig, GameConfig
from photon_ml_tpu.game.coordinate import build_coordinate
from photon_ml_tpu.game.data import GameData
from photon_ml_tpu.game.descent import CoordinateDescent, DescentHistory
from photon_ml_tpu.models.game import GameModel
from photon_ml_tpu.types import TaskType


@dataclasses.dataclass(eq=False)  # identity equality: fields hold arrays
class GameFitResult:
    """One (configuration, model, validation) outcome
    (reference fit() returns Seq[(GameModel, config, Option[EvaluationResults])])."""

    model: GameModel
    config: GameConfig
    evaluation: Optional[EvaluationResults]
    history: DescentHistory


class GameEstimator:
    """fit() over one or more GAME configurations with warm start between them.

    ``locked_coordinates``: partial retraining — these coordinates keep their
    model from ``initial_model`` and are only re-scored
    (reference GameEstimator.scala:110-112, 237-269).
    """

    def __init__(self, mesh: Optional[Mesh] = None,
                 validation_suite: Optional[EvaluationSuite] = None,
                 normalization: Optional[Dict[str, "NormalizationContext"]] = None,
                 fused: "bool | str" = "auto", dtype=np.float32):
        """``normalization``: per-feature-shard NormalizationContext applied
        to EVERY coordinate on that shard — fixed effects and random effects
        alike (reference GameEstimator normalization wrappers fit:430-436 +
        NormalizationContextRDD; models come out in original space).  Living
        on the estimator (not fit()) so tuning retrains inherit it.

        ``fused``: "auto" (default) runs each configuration as ONE jitted
        program (game/fused.FusedSweep — no host round-trips between
        coordinate updates) whenever the fit has no per-update host work
        (no validation suite, checkpointing, locked coordinates, or resume);
        both built-in coordinate flavors support every configuration in the
        fused program.  True requires the fused path (raising on per-update
        host work, or on a custom Coordinate subclass without the
        traceable-step interface); False always uses the host-paced loop."""
        self.mesh = mesh
        self.validation_suite = validation_suite
        self.normalization = normalization or {}
        self.fused = fused
        # Compute precision for coordinate device arrays: the reference runs
        # on JVM doubles; np.float64 gives reference-precision solves (CPU),
        # the float32 default is the TPU-throughput choice.
        self.dtype = dtype

    def build_one_coordinate(self, cid, data, ccfg, task, seed: int = 0,
                             initial_model=None):
        """The ONE construction call for a coordinate under this estimator's
        settings (mesh / normalization / dtype) — shared by fit() and the
        tuning fast path so they can never drift apart.  ``initial_model``:
        its entity keys feed the random-effect lower bound's existing-model
        semantics (RandomEffectDataset.scala:322-333)."""
        keys = None
        if initial_model is not None and cid in initial_model:
            m = initial_model[cid]
            if hasattr(m, "slot_of"):
                keys = frozenset(m.slot_of)
        return build_coordinate(cid, data, ccfg, task, self.mesh,
                                norm=self.normalization.get(ccfg.feature_shard),
                                seed=seed, dtype=self.dtype,
                                existing_model_keys=keys)

    def fit(
        self,
        data: GameData,
        configs: Sequence[GameConfig],
        validation_data: Optional[GameData] = None,
        initial_model: Optional[GameModel] = None,
        locked_coordinates: Optional[Set[str]] = None,
        seed: int = 0,
        checkpoint_hook=None,
        resume_cursor: Optional[Dict[str, int]] = None,
        resume_best=None,
    ) -> List[GameFitResult]:
        """``checkpoint_hook(model, cursor, **kw)`` fires after every coordinate
        update with cursor {"config": ci, "iteration": i, "coordinate": k}.
        ``resume_cursor``: skip work before it (``initial_model`` must be the
        checkpointed model).  NOTE on resume: configs before the cursor are
        skipped entirely, so model selection only considers the resumed-and-
        later grid points."""
        results: List[GameFitResult] = []
        warm = initial_model
        # existing-model lower-bound semantics apply to a user-supplied WARM
        # START only: on checkpoint resume, initial_model is the mid-job
        # checkpoint — treating its entities as "existing" would freeze
        # under-bound entities the uninterrupted run kept retraining,
        # breaking resume equivalence
        prior_for_bounds = initial_model if resume_cursor is None else None
        prev: Dict[str, object] = {}
        prev_sweep = None  # (key, FusedSweep) — reuse the compiled program
        # when every coordinate object survived config-to-config (same `prev`
        # reuse that keeps solver jits alive)
        prev_plan = None  # (sweep, val_data, ValidationPlan) — held-out
        # designs upload once per sweep, not once per grid point
        for ci, config in enumerate(configs):
            if resume_cursor is not None and ci < resume_cursor.get("config", 0):
                continue
            coordinates = {}
            for cid, ccfg in config.coordinates.items():
                old = prev.get(cid)
                if old is not None and old.config == ccfg:
                    coordinates[cid] = old  # identical config: reuse jits too
                elif old is not None:
                    try:
                        coordinates[cid] = old.rebind(ccfg)  # same data, new opt settings
                    except ValueError:
                        coordinates[cid] = self.build_one_coordinate(
                            cid, data, ccfg, config.task, seed,
                            initial_model=prior_for_bounds)
                else:
                    coordinates[cid] = self.build_one_coordinate(
                        cid, data, ccfg, config.task, seed,
                        initial_model=prior_for_bounds)
            prev = coordinates
            validation = None
            if validation_data is not None and self.validation_suite is not None:
                validation = (validation_data, self.validation_suite)

            # Per-update HOST work (checkpoint hooks, locked coordinates,
            # resume) forces the host-paced loop; a validation suite no
            # longer does — the validated program (FusedSweep.run_validated)
            # scores the held-out set and tracks per-update losses inside
            # the scanned program, and the host evaluates the metric suite
            # per sweep boundary with the host loop's exact best-model
            # retention.  The two validated carve-outs that stay host-paced:
            # coefficient variances (per-snapshot variances would multiply
            # the curvature work T-fold) and a custom Coordinate without the
            # external-scoring interface.
            fused_ok = (self.fused is not False and checkpoint_hook is None
                        and not locked_coordinates and resume_cursor is None)
            if fused_ok:
                from photon_ml_tpu.game.fused import FusedSweep

                # reg weights are traced sweep inputs, so a λ grid over
                # data/solver-identical coordinates reuses ONE compiled sweep
                key = (tuple((cid, coordinates[cid].sweep_key())
                             for cid in config.coordinates),
                       config.num_outer_iterations)
                fitted = None
                try:
                    if prev_sweep is not None and prev_sweep[0] == key:
                        sweep = prev_sweep[1]
                    else:
                        sweep = FusedSweep(coordinates,
                                           order=list(config.coordinates),
                                           num_iterations=config.num_outer_iterations)
                        prev_sweep = (key, sweep)
                    regs = [coordinates[cid].config.reg
                            for cid in config.coordinates]
                    if validation is None:
                        model, _scores = sweep.run(initial=warm, regs=regs,
                                                   seed=seed)
                        fitted = (model, None)
                    else:
                        if prev_plan is not None and prev_plan[0] is sweep \
                                and prev_plan[1] is validation_data:
                            plan = prev_plan[2]
                        else:
                            plan = sweep.validation_plan(
                                validation_data, self.validation_suite)
                            prev_plan = (sweep, validation_data, plan)
                        model, _evals, best_ev, _losses = sweep.run_validated(
                            plan, initial=warm, regs=regs, seed=seed)
                        fitted = (model, best_ev)
                except NotImplementedError:
                    # a custom Coordinate subclass without the traceable-step
                    # (or, for validated fits, external-scoring) interface,
                    # or a variance-computing validated fit — host loop
                    if self.fused is True and validation is None:
                        raise
                if fitted is not None:
                    model, ev = fitted
                    results.append(GameFitResult(model=model, config=config,
                                                 evaluation=ev,
                                                 history=DescentHistory()))
                    warm = model
                    continue
            elif self.fused is True:
                raise ValueError(
                    "fused=True needs a fit with no per-update host work "
                    "(no checkpoint hook, locked coordinates, or resume)")
            descent = CoordinateDescent(
                coordinates,
                order=list(config.coordinates),
                num_iterations=config.num_outer_iterations,
                validation=validation,
                locked=locked_coordinates,
            )
            if checkpoint_hook is None:
                hook = None
            else:
                # First save of each config forces a FULL snapshot: the
                # in-memory baseline (warm start = previous config's BEST
                # model when validation is on) can differ from the previous
                # checkpoint version's final iterate, so hard-linking
                # "unchanged" coordinates from it would capture stale data.
                first_save = {"pending": True}

                def hook(m, cur, ci=ci, first_save=first_save, **kw):
                    if first_save["pending"]:
                        kw["updated"] = None
                        first_save["pending"] = False
                    checkpoint_hook(m, {**cur, "config": ci}, **kw)
            resuming_here = (resume_cursor is not None
                             and ci == resume_cursor.get("config", 0))
            model, history, ev = descent.run(
                initial=warm, seed=seed, checkpoint_hook=hook,
                resume_cursor=resume_cursor if resuming_here else None,
                resume_best=resume_best if resuming_here else None)
            results.append(GameFitResult(model=model, config=config, evaluation=ev,
                                         history=history))
            warm = model  # warm start the next configuration (fit:344-360)
        return results

    def best(self, results: List[GameFitResult]) -> GameFitResult:
        """Model selection by primary validation metric
        (reference GameTrainingDriver.selectBestModel:683-748)."""
        if self.validation_suite is None or all(r.evaluation is None for r in results):
            return results[-1]
        best = None
        for r in results:
            if r.evaluation is None:
                continue
            if best is None or self.validation_suite.primary.better_than(
                    r.evaluation.primary, best.evaluation.primary):
                best = r
        return best if best is not None else results[-1]


class GameTransformer:
    """Score/evaluate a GameData with a trained GameModel
    (reference GameTransformer.scala:150-318)."""

    def __init__(self, model: GameModel, task: TaskType):
        self.model = model
        self.task = task

    def score(self, data: GameData) -> np.ndarray:
        """Raw total scores (no offset; reference scoreGameDataset:263)."""
        return np.asarray(self.model.score(data))

    def predict(self, data: GameData) -> np.ndarray:
        return np.asarray(self.model.predict(data, self.task))

    def evaluate(self, data: GameData, suite: EvaluationSuite) -> EvaluationResults:
        from photon_ml_tpu.game.scoring import raw_scores

        return suite.evaluate(raw_scores(self.model, data), data.y,
                              data.weight, group_ids=data.id_tags)
