"""GAME dataset container.

Reference data model: GameDatum(response, offsetOpt, weightOpt,
featureShardContainer: Map[shard -> Vector], idTagToValueMap)
(photon-lib .../data/GameDatum.scala:39-74) held as
RDD[(UniqueSampleId, GameDatum)] after GameConverters (photon-api
.../data/GameConverters.scala:173).

TPU-native shape: one host-side columnar container for the WHOLE dataset —
labels/offsets/weights as flat arrays, one design matrix per feature shard,
and integer id columns per id-tag (entity ids already passed through a feature
index map / entity index).  Sample order IS the unique-sample-id space: row i
everywhere refers to the same example, which replaces the reference's
uniqueId-keyed joins with positional alignment.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Union

import numpy as np


@dataclasses.dataclass
class SparseShard:
    """Row-padded COO design "matrix" for wide sparse vocabularies.

    The reference streams Breeze SparseVectors per datum; here the whole
    shard is two [n, k] arrays (k = max active features per row + intercept)
    matching core/batch.SparseBatch's layout, so a 1e6-feature CTR shard
    costs O(n*k), not O(n*d).  Padded slots carry (index 0, value 0) —
    inert in margins and gradients.  Duplicate indices within a row are
    tolerated (they accumulate in margins/gradients, like repeated (name,
    term) entries accumulate in the dense path) but make SIMPLE-variance
    Hessian diagonals approximate.
    """

    indices: np.ndarray  # [n, k] int32 column ids
    values: np.ndarray   # [n, k] float
    dim: int             # vocabulary size (d)

    @property
    def shape(self):
        # mimics a dense [n, d] matrix so shard_dim / row checks just work
        return (self.indices.shape[0], self.dim)


ShardData = Union[np.ndarray, SparseShard]


@dataclasses.dataclass
class GameData:
    """Columnar GAME dataset (training or validation)."""

    y: np.ndarray  # [n]
    features: Dict[str, "ShardData"]  # shard id -> [n, d] dense matrix or SparseShard
    offset: Optional[np.ndarray] = None  # [n]
    weight: Optional[np.ndarray] = None  # [n]
    id_tags: Dict[str, np.ndarray] = dataclasses.field(default_factory=dict)  # tag -> [n] int64
    uids: Optional[np.ndarray] = None  # [n] original unique sample ids (object)
    #: tag -> stream.EntityStats accumulated during streaming ingest; lets
    #: random-effect coordinates reuse the per-entity grouping computed
    #: chunk-by-chunk instead of re-scanning the id column.  None on the
    #: eager path (coordinates fall back to bucketing._group_rows).
    entity_stats: Optional[Dict[str, object]] = None

    def __post_init__(self):
        n = len(self.y)
        self.y = np.asarray(self.y)
        if self.offset is None:
            self.offset = np.zeros(n, self.y.dtype if self.y.dtype.kind == "f" else np.float32)
        if self.weight is None:
            self.weight = np.ones(n, self.offset.dtype)
        self.offset = np.asarray(self.offset)
        self.weight = np.asarray(self.weight)
        for shard, x in self.features.items():
            if x.shape[0] != n:
                raise ValueError(f"feature shard {shard!r} has {x.shape[0]} rows, expected {n}")
        if self.uids is not None and len(self.uids) != n:
            raise ValueError(f"uids has {len(self.uids)} rows, expected {n}")
        for tag, ids in self.id_tags.items():
            if len(ids) != n:
                raise ValueError(f"id tag {tag!r} has {len(ids)} rows, expected {n}")
            self.id_tags[tag] = np.asarray(ids, np.int64)

    @property
    def num_samples(self) -> int:
        return len(self.y)

    def shard_dim(self, shard: str) -> int:
        return self.features[shard].shape[1]
