"""Fully-jitted GLMix coordinate-descent sweeps.

The host-orchestrated ``CoordinateDescent`` (descent.py) mirrors the
reference's driver loop (CoordinateDescent.scala:119-346): one device
dispatch per solve/score plus host-side residual bookkeeping between
coordinates.  That loop is the right place for validation, checkpointing and
locked coordinates — but for raw training throughput the whole sweep can be
ONE XLA program: ``lax.scan`` over outer iterations whose body chains every
coordinate's traceable step (``Coordinate.trace_update``), residual fold, and
re-scoring.  No host round-trips, no per-phase dispatch latency, and XLA
overlaps/fuses across phases (e.g. the residual subtraction folds into the
next solver's first objective pass).

This is the TPU-native answer to the reference's persist/broadcast
choreography between coordinate updates (CoordinateDescent.scala:208-232):
instead of caching RDD scores between Spark jobs, the scores never leave HBM.

Every coordinate flavor is fused-eligible.  Per-update down-sampling runs
inside the program (a per-(iteration, coordinate) fold of the sweep's PRNG
key); coefficient variances are computed in the scan body on the final
iteration only, at the exact offsets/weights/reg of that coordinate's last
update (what the host loop publishes); projected random effects solve in
their compact per-bucket spaces and back-project inside ``trace_publish``.
Only per-fit HOST work (validation suites, checkpoint hooks, locked
coordinates, resume) forces the host-paced CoordinateDescent.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from photon_ml_tpu.game.coordinate import Coordinate
from photon_ml_tpu.models.game import GameModel
from photon_ml_tpu.obs.trace import span as obs_span
from photon_ml_tpu.types import VarianceComputationType

Array = jax.Array


class FusedSweep:
    """jit(scan)-compiled block coordinate descent over GAME coordinates.

    Semantics match ``CoordinateDescent.run`` with no validation suite: cold
    start (or ``initial`` warm start), residual offsets, warm start across
    outer iterations, final full model returned.  Compiles ONE sweep body
    regardless of ``num_iterations``.
    """

    def __init__(self, coordinates: Dict[str, Coordinate],
                 order: Optional[Sequence[str]] = None,
                 num_iterations: int = 1):
        if not coordinates:
            raise ValueError("FusedSweep needs at least one coordinate")
        self.coordinates = coordinates
        self.order = list(order) if order is not None else list(coordinates)
        # positional carries double-count a repeated coordinate's score, so a
        # duplicate id must be rejected (the host descent tolerates repeats)
        if len(self.order) != len(coordinates) or set(self.order) != set(coordinates):
            raise ValueError(f"order {self.order} != ids {set(coordinates)}")
        self.num_iterations = num_iterations

        first = coordinates[self.order[0]]
        self._n = first.num_samples
        self._dtype = first.dtype
        order, coords = self.order, self.coordinates

        needs_var = [coords[cid].config.variance != VarianceComputationType.NONE
                     for cid in self.order]
        needs_rand = [getattr(coords[cid].config, "down_sampling_rate", 1.0) < 1.0
                      for cid in self.order]
        self._needs_var = needs_var
        self._needs_rand = needs_rand
        self._snap_program = None  # built lazily by run_snapshots
        self._grid_program = None  # built lazily by run_grid
        self._grid_snap_program = None  # built lazily by run_grid_snapshots
        self._val_program = None   # built lazily by run_validated

        def program(states0, scores0, vars0, regs, base_key, base, datas):
            # regs: per-coordinate Regularization pytree, TRACED — a
            # reg-weight grid re-enters this one compiled program.
            # base_key: sweep PRNG key, folded per (iteration, coordinate)
            # for stochastic per-update work (down-sampling) — a new draw
            # each outer iteration, like the reference's seed-per-update
            # (DistributedOptimizationProblem.runWithSampling).  Folds are
            # emitted only for coordinates that down-sample, so the common
            # no-sampling program carries no threefry code at all.
            # base/datas: base offsets + per-coordinate design-matrix pytrees
            # as ARGUMENTS — closed-over device arrays would lower to baked
            # XLA constants, with compile time linear in constant bytes.
            def body(carry, it):
                states, scores, vars_ = carry
                vars_ = list(vars_)
                it_key = (jax.random.fold_in(base_key, it)
                          if any(needs_rand) else None)
                states, scores, partials, keys = self._sweep_iteration(
                    states, scores, regs, it_key, base, datas)
                for i, cid in enumerate(order):
                    if needs_var[i]:
                        # Only the LAST update's variances survive into the
                        # published model (host-path semantics), so skip the
                        # curvature work on every earlier iteration — FULL
                        # variance is a d×d Hessian + Cholesky per lane.
                        vars_[i] = lax.cond(
                            it == self.num_iterations - 1,
                            lambda s, o, r, k: coords[cid].trace_variances(
                                s, o, reg=r, key=k, data=datas[i]),
                            lambda s, o, r, k: vars_[i],
                            states[i], base + partials[i], regs[i], keys[i])
                return (tuple(states), tuple(scores), tuple(vars_)), None

            carry, _ = lax.scan(body, (states0, scores0, vars0),
                                jnp.arange(self.num_iterations))
            states, scores, vars_ = carry
            published = tuple(coords[cid].trace_publish(states[i],
                                                        data=datas[i])
                              for i, cid in enumerate(order))
            return published, scores, vars_

        self._program_fn = program  # unjitted: the grid path vmaps it
        self._program = jax.jit(program)
        self._base = jnp.asarray(np.asarray(first._base_offset_host(),
                                            self._dtype))
        self._datas = tuple(coords[cid].sweep_data() for cid in self.order)
        # Cold-start carry built eagerly: surfaces a coordinate without the
        # traceable-step interface at construction time (base-class
        # init_sweep_state raises) and is reused by run().
        self._cold = self._init_carry(None)
        self._vars0 = tuple(coordinates[cid].init_sweep_variances()
                            for cid in self.order)

    def _sweep_iteration(self, states, scores, regs, it_key, base, datas,
                         on_update=None):
        """Traceable: ONE outer iteration's coordinate loop — the single
        source of the descent math (residual fold + per-coordinate update,
        CoordinateDescent.scala:197-204) shared by the main program, the
        snapshot program and the validated program.  Returns (states',
        scores', partials, keys): partials[i] is the residual offset
        coordinate i was solved against and keys[i] the PRNG key its update
        used — variance computation must see the SAME offsets and
        down-sampling mask as the published coefficients, so it re-uses both
        rather than re-deriving them.  ``on_update(i, cid, state_i)``:
        traced hook after each coordinate's update (the validated program's
        per-update held-out bookkeeping)."""
        order, coords = self.order, self.coordinates
        needs_rand = self._needs_rand
        states, scores = list(states), list(scores)
        partials, keys = [], []
        total = scores[0]
        # photonlint: disable=tracer-safety -- scores is a Python list with
        # one entry per coordinate (static length at trace time); the loop
        # unrolls over coordinates, not over a traced array's elements
        for s in scores[1:]:
            total = total + s
        for i, cid in enumerate(order):
            # residual trick (CoordinateDescent.scala:197-204)
            partial = total - scores[i]
            key = (jax.random.fold_in(it_key, i) if needs_rand[i] else None)
            states[i], scores[i] = coords[cid].trace_update(
                states[i], base + partial, reg=regs[i], key=key,
                data=datas[i])
            partials.append(partial)
            keys.append(key)
            total = partial + scores[i]
            if on_update is not None:
                on_update(i, cid, states[i])
        return states, scores, partials, keys

    def _init_carry(self, initial: Optional[GameModel]):
        states, scores = [], []
        for cid in self.order:
            coord = self.coordinates[cid]
            init = initial[cid] if initial is not None and cid in initial else None
            states.append(coord.init_sweep_state(init))
            if init is None:
                scores.append(jnp.zeros(self._n, self._dtype))
                continue
            s = np.asarray(coord.score(init), self._dtype)
            c = coord.carry_through_scores(init)
            if c is not None:
                # the carried (never-retrained) contribution rides the BASE
                # offsets for the whole program (_base_with_carry_through);
                # keeping it out of the per-coordinate carry score prevents
                # double-counting it in the first update's residual
                s = s - np.asarray(c, self._dtype)
            scores.append(jnp.asarray(s))
        return tuple(states), tuple(scores)

    def init_carry(self, initial: Optional[GameModel]):
        """Public warm-start carry builder: callers re-running one sweep many
        times from the SAME initial model (tuning) compute this once and pass
        it via ``run(carry0=...)`` instead of re-scoring the initial model
        per call."""
        return self._cold if initial is None else self._init_carry(initial)

    def run_device(self, initial: Optional[GameModel] = None,
                   regs: Optional[Sequence] = None, seed: int = 0,
                   carry0=None):
        """One fused descent, DEVICE outputs only: returns
        ``(published, scores, vars_, carried)`` where the first three are
        the program's output pytrees of device arrays — nothing is pulled
        to host.  For benchmarking (time the sweep, not the [n]-vector
        downloads — over slow transports those dominate) and for callers
        that pipeline further device work; ``run()`` wraps this with the
        host export."""
        carry = carry0 if carry0 is not None else self.init_carry(initial)
        if regs is None:
            regs = tuple(self.coordinates[cid].config.reg for cid in self.order)
        base, carried = self._base_with_carry_through(initial)
        published, scores, vars_ = self._program(
            *carry, self._vars0, tuple(regs), jax.random.PRNGKey(seed),
            base, self._datas)
        return published, scores, vars_, carried

    def run(self, initial: Optional[GameModel] = None,
            regs: Optional[Sequence] = None, seed: int = 0,
            carry0=None) -> Tuple[GameModel, Dict[str, np.ndarray]]:
        """One fused descent; returns (model, per-coordinate final scores).

        ``regs``: per-coordinate (order-aligned) Regularization overrides —
        lets one compiled sweep serve a whole reg-weight grid (the caller
        typically reads them off rebind-updated configs).  ``seed``: PRNG
        seed for in-program stochastic work (down-sampling); a traced input,
        so varying it reuses the compiled program.  ``carry0``: precomputed
        ``init_carry`` result (overrides ``initial``)."""
        # the whole sweep is ONE device program — per-coordinate host spans
        # can't exist here; device_sync brackets actual execution, so the
        # fused span is comparable with the host loop's descent.update sum
        with obs_span("descent.fused_sweep", device_sync=True,
                      coordinates=len(self.order),
                      iterations=self.num_iterations):
            published, scores, vars_, carried = self.run_device(
                initial, regs, seed, carry0)
        models = {cid: self.coordinates[cid].export_model(np.asarray(published[i]))
                  for i, cid in enumerate(self.order)}
        final_scores = {cid: np.asarray(scores[i])
                        for i, cid in enumerate(self.order)}
        for cid, c in carried.items():
            # published scores include the carried contribution, exactly as
            # the host loop's re-scoring of the merged model does
            final_scores[cid] = final_scores[cid] + c
        models = self._attach_variances(models, vars_)
        models = self._merge_carry_through(models, initial)
        return GameModel(models=models), final_scores

    def _base_with_carry_through(self, initial: Optional[GameModel]):
        """(base offsets + carried-entity scores, per-coordinate carried
        scores).  Carried entities never retrain, so their contribution is a
        CONSTANT the program must see in its offsets — otherwise every
        residual after a coordinate's first in-program update would drop it,
        diverging from the host loop (which re-scores the merged model each
        update)."""
        carried = {}
        base = self._base
        if initial is not None:
            for cid in self.order:
                c = self.coordinates[cid].carry_through_scores(
                    initial[cid] if cid in initial else None)
                if c is not None:
                    carried[cid] = c
                    base = base + jnp.asarray(np.asarray(c, self._dtype))
        return base, carried

    def _merge_carry_through(self, models, initial: Optional[GameModel]):
        """Warm-start state the program could not retrain (prior-model
        entities with no active data) passes through on host — the same
        leftOuterJoin semantics the host path applies
        (Coordinate.merge_carry_through)."""
        if initial is None:
            return models
        return {cid: self.coordinates[cid].merge_carry_through(
                    m, initial[cid] if cid in initial else None)
                for cid, m in models.items()}

    def run_snapshots(self, initial: Optional[GameModel] = None,
                      regs: Optional[Sequence] = None, seed: int = 0,
                      carry0=None) -> Sequence[GameModel]:
        """One fused descent, returning the FULL model after EVERY outer
        iteration (still one compiled program — the scan emits each
        iteration's published coefficients as its per-step output).

        This is what host-paced best-model retention needs from a fused
        sweep: the host loop compares full models at sweep boundaries only
        (descent.py, reference CoordinateDescent.scala:163-167), so a caller
        holding these snapshots can evaluate each on validation data and keep
        the best — without per-update host round-trips.  Used by the tuning
        fast path (tune/game_tuning.py) for multi-iteration configs.

        Variance computation is not supported here (the host loop publishes
        each update's own variances; per-snapshot variances would multiply
        the curvature work T-fold) — callers fall back to the host descent.
        """
        if any(self._needs_var):
            raise NotImplementedError(
                "run_snapshots does not compute coefficient variances; use "
                "run() (final model only) or the host CoordinateDescent")
        if self._snap_program is None:
            self._snap_program = jax.jit(self._snap_fn())
        carry = carry0 if carry0 is not None else self.init_carry(initial)
        if regs is None:
            regs = tuple(self.coordinates[cid].config.reg for cid in self.order)
        base, _carried = self._base_with_carry_through(initial)
        pubs, _scores = self._snap_program(
            *carry, tuple(regs), jax.random.PRNGKey(seed),
            base, self._datas)
        pubs = [np.asarray(p) for p in pubs]
        return [
            GameModel(models=self._merge_carry_through(
                {cid: self.coordinates[cid].export_model(pubs[i][t])
                 for i, cid in enumerate(self.order)}, initial))
            for t in range(self.num_iterations)
        ]

    def _snap_fn(self):
        """The snapshot program (shared by run_snapshots and the vmapped
        grid twin): same _sweep_iteration core as the main program (no
        variances), but each iteration ALSO publishes — scan stacks the
        published coefficients along a leading T axis."""
        order, coords = self.order, self.coordinates
        needs_rand = self._needs_rand

        def program(states0, scores0, regs, base_key, base, datas):
            def body(carry, it):
                states, scores = carry
                it_key = (jax.random.fold_in(base_key, it)
                          if any(needs_rand) else None)
                states, scores, _, _ = self._sweep_iteration(
                    states, scores, regs, it_key, base, datas)
                published = tuple(
                    coords[cid].trace_publish(states[i], data=datas[i])
                    for i, cid in enumerate(order))
                return (tuple(states), tuple(scores)), published

            (_, scores), pubs = lax.scan(
                body, (states0, scores0), jnp.arange(self.num_iterations))
            return pubs, scores

        return program

    # --- fused validated sweeps -----------------------------------------

    def validation_plan(self, data, suite) -> "ValidationPlan":
        """Build (once per held-out set) the device-resident inputs
        ``run_validated`` scores against — per-coordinate designs/slots via
        each coordinate's ``external_data``.  Raises NotImplementedError
        for a coordinate without the external-scoring interface (the
        estimator then falls back to the host-paced CoordinateDescent)."""
        return ValidationPlan(self, data, suite)

    def _validated_fn(self):
        """The validated program: the same ``_sweep_iteration`` core as the
        main program, with per-update held-out bookkeeping fused in —
        after every coordinate update the scanned body re-scores THAT
        coordinate's held-out margins from its published coefficients,
        folds them into the running held-out total with the same
        residual-style replace the training scores use, and records the
        weighted held-out loss (the in-program twin of the host loop's
        per-update ``descent.validate`` evaluation).  Each iteration also
        emits its published coefficients and held-out totals, so the host
        evaluates the full metric suite per sweep boundary from ONE
        device->host pull — a validated multi-iteration fit is ONE XLA
        program."""
        order, coords = self.order, self.coordinates
        needs_rand = self._needs_rand
        loss = self._val_loss

        def program(states0, scores0, vscores0, regs, base_key, base, datas,
                    vdatas, val_base, val_y, val_wt):
            wt_sum = jnp.maximum(val_wt.sum(), jnp.asarray(1e-30, self._dtype))

            def body(carry, it):
                states, scores, vscores = carry
                vscores = list(vscores)
                it_key = (jax.random.fold_in(base_key, it)
                          if any(needs_rand) else None)
                published = [None] * len(order)
                losses = []
                vtotal = vscores[0]
                # photonlint: disable=tracer-safety -- static per-coordinate
                # list, unrolled at trace time like _sweep_iteration's
                for s in vscores[1:]:
                    vtotal = vtotal + s

                def on_update(i, cid, state_i):
                    nonlocal vtotal
                    w_pub = coords[cid].trace_publish(state_i, data=datas[i])
                    vm = coords[cid].trace_score_external(
                        w_pub, vdatas[i]).astype(self._dtype)
                    vtotal = vtotal - vscores[i] + vm
                    vscores[i] = vm
                    published[i] = w_pub
                    z = vtotal + val_base
                    losses.append((val_wt * loss.loss(z, val_y)).sum()
                                  / wt_sum)

                states, scores, _, _ = self._sweep_iteration(
                    states, scores, regs, it_key, base, datas,
                    on_update=on_update)
                return ((tuple(states), tuple(scores), tuple(vscores)),
                        (tuple(published), vtotal, jnp.stack(losses)))

            carry, (pubs, vtotals, losses) = lax.scan(
                body, (states0, scores0, vscores0),
                jnp.arange(self.num_iterations))
            return pubs, vtotals, losses

        return program

    def run_validated(self, plan: "ValidationPlan",
                      initial: Optional[GameModel] = None,
                      regs: Optional[Sequence] = None, seed: int = 0,
                      carry0=None):
        """One fused descent WITH the validation suite: training updates,
        held-out scoring and per-update held-out losses all run inside one
        compiled program; the host evaluates ``plan.suite`` on each
        iteration's held-out totals and keeps the best full model — the
        exact best-model retention the host loop applies (full models at
        sweep boundaries only, CoordinateDescent.scala:163-167 /
        descent.py), without any per-update device round-trips.

        Returns ``(best_model, evals, best_eval, losses)``: the retained
        GameModel, one EvaluationResults per outer iteration (boundary
        evaluations, in order), the best's results, and the in-program
        per-(iteration, coordinate) held-out loss matrix [T, C].

        Eligibility mirrors run_snapshots: no coefficient variances (the
        host loop publishes each update's own variances; per-snapshot
        variances would multiply the curvature work T-fold) — callers with
        variance-computing coordinates fall back to the host descent.
        Checkpoint hooks / locked coordinates / resume are host-loop work by
        definition and never reach here (game/estimator.py gates)."""
        if any(self._needs_var):
            raise NotImplementedError(
                "run_validated does not compute coefficient variances; use "
                "the host CoordinateDescent for variance-computing validated "
                "fits")
        if self._val_program is None:
            # the held-out loss fn is static program structure; it derives
            # from the sweep's task, so every plan over this sweep agrees
            self._val_loss = plan.loss
            self._val_program = jax.jit(self._validated_fn())
        carry = carry0 if carry0 is not None else self.init_carry(initial)
        if regs is None:
            regs = tuple(self.coordinates[cid].config.reg
                         for cid in self.order)
        base, _carried = self._base_with_carry_through(initial)
        vscores0, val_base_np = plan.initial_state(initial)
        with obs_span("descent.fused_validated", device_sync=True,
                      coordinates=len(self.order),
                      iterations=self.num_iterations):
            pubs, vtotals, losses = self._val_program(
                *carry, vscores0, tuple(regs), jax.random.PRNGKey(seed),
                base, self._datas, plan.datas,
                jnp.asarray(val_base_np), plan.y_dev, plan.wt_dev)
            # one bulk pull per output (the only device->host transfers of
            # the whole validated fit)
            vtotals = np.asarray(vtotals)
            losses = np.asarray(losses)
            pubs = [np.asarray(jax.device_get(p)) for p in pubs]
        evals, best_t, best_ev = [], 0, None
        for t in range(self.num_iterations):
            ev = plan.suite.evaluate(vtotals[t] + val_base_np, plan.y,
                                     plan.weight, group_ids=plan.group_ids)
            evals.append(ev)
            # strict-improvement retention in iteration order — identical
            # tie-breaking to the host loop's better_than chain
            if plan.suite.better_than(ev, best_ev):
                best_ev, best_t = ev, t
        models = {cid: self.coordinates[cid].export_model(pubs[i][best_t])
                  for i, cid in enumerate(self.order)}
        model = GameModel(models=self._merge_carry_through(models, initial))
        return model, evals, best_ev, losses

    # --- regularization-grid batching -----------------------------------
    # A λ grid's descents are INDEPENDENT programs over the SAME data, and
    # these solves are bandwidth-bound: vmapping the sweep over the reg
    # axis shares every design-matrix stream, so a B-point grid costs far
    # less than B sequential sweeps.  The reference trains its grid
    # sequentially (GameEstimator.fit over configurations;
    # GameEstimatorEvaluationFunction.apply per tuning iteration) — this is
    # the TPU-native replacement.  All grid lanes must share the L1 regime
    # (same static constraint as run()'s reg overrides, see sweep_key).

    def _stack_regs(self, regs_grid: Sequence[Sequence]) -> tuple:
        return jax.tree.map(
            lambda *leaves: jnp.stack(
                [jnp.asarray(v, self._dtype) for v in leaves]),
            *[tuple(regs) for regs in regs_grid])

    def run_grid(self, regs_grid: Sequence[Sequence],
                 initial: Optional[GameModel] = None, seed: int = 0,
                 carry0=None) -> list:
        """B fused descents over a regularization grid in ONE vmapped
        program; returns a list of B (model, scores-dict) pairs, each
        exactly what run() returns for that grid point."""
        if self._grid_program is None:
            self._grid_program = jax.jit(jax.vmap(
                self._program_fn,
                in_axes=(None, None, None, 0, None, None, None)))
        carry = carry0 if carry0 is not None else self.init_carry(initial)
        base, carried = self._base_with_carry_through(initial)
        published, scores, vars_ = self._grid_program(
            *carry, self._vars0, self._stack_regs(regs_grid),
            jax.random.PRNGKey(seed), base, self._datas)
        # one bulk device->host transfer per output array, host-indexed per
        # grid point (B*C per-slice transfers would multiply round-trip
        # latency on slow transports)
        published = [np.asarray(jax.device_get(p)) for p in published]
        scores = [np.asarray(s) for s in scores]
        vars_ = tuple(np.asarray(v) for v in vars_)
        out = []
        for b in range(len(regs_grid)):
            models = {cid: self.coordinates[cid].export_model(published[i][b])
                      for i, cid in enumerate(self.order)}
            final_scores = {cid: scores[i][b]
                            for i, cid in enumerate(self.order)}
            for cid, c in carried.items():
                final_scores[cid] = final_scores[cid] + c
            models = self._attach_variances(
                models, tuple(v[b] for v in vars_))
            models = self._merge_carry_through(models, initial)
            out.append((GameModel(models=models), final_scores))
        return out

    def run_grid_snapshots(self, regs_grid: Sequence[Sequence],
                           initial: Optional[GameModel] = None, seed: int = 0,
                           carry0=None) -> list:
        """Grid twin of run_snapshots: returns a list of B lists of
        per-iteration GameModels (one list per grid point)."""
        if any(self._needs_var):
            raise NotImplementedError(
                "run_grid_snapshots does not compute coefficient variances; "
                "use run_grid() or the host CoordinateDescent")
        if self._grid_snap_program is None:
            self._grid_snap_program = jax.jit(jax.vmap(
                self._snap_fn(), in_axes=(None, None, 0, None, None, None)))
        carry = carry0 if carry0 is not None else self.init_carry(initial)
        base, _carried = self._base_with_carry_through(initial)
        pubs, _scores = self._grid_snap_program(
            *carry, self._stack_regs(regs_grid), jax.random.PRNGKey(seed),
            base, self._datas)
        pubs = [np.asarray(p) for p in pubs]  # [coord][B, T, ...]
        return [
            [GameModel(models=self._merge_carry_through(
                {cid: self.coordinates[cid].export_model(pubs[i][b][t])
                 for i, cid in enumerate(self.order)}, initial))
             for t in range(self.num_iterations)]
            for b in range(len(regs_grid))
        ]

    def _attach_variances(self, models, vars_):
        """Attach the in-sweep-computed variances (the LAST update's, exactly
        as the host loop publishes) to the exported models."""
        import dataclasses

        from photon_ml_tpu.models.game import FixedEffectModel
        from photon_ml_tpu.models.glm import Coefficients

        for i, cid in enumerate(self.order):
            coord = self.coordinates[cid]
            if coord.config.variance == VarianceComputationType.NONE:
                continue
            v = coord.export_variances(vars_[i])
            m = models[cid]
            if isinstance(m, FixedEffectModel):
                models[cid] = dataclasses.replace(
                    m, coefficients=Coefficients(
                        means=m.coefficients.means, variances=v))
            else:  # random effect: stacked per-entity variances
                models[cid] = dataclasses.replace(m, variances=v)
        return models


class ValidationPlan:
    """Device-resident held-out inputs for ``FusedSweep.run_validated``.

    Built ONCE per (sweep, held-out set, suite): per-coordinate scoring
    pytrees (``Coordinate.external_data`` — designs + trained-slot maps,
    uploaded once), the label/weight device twins the in-program loss
    consumes, and the host-side arrays/suite the per-iteration metric
    evaluation reads.  The per-fit constants (warm-start held-out margins,
    carried-entity contributions) are computed by ``initial_state`` at run
    time — they depend on the initial model, not the plan.
    """

    def __init__(self, sweep: FusedSweep, data, suite):
        from photon_ml_tpu.core.losses import loss_for_task

        self.sweep = sweep
        self.data = data
        self.suite = suite
        self.n = data.num_samples
        self.y = np.asarray(data.y)
        self.weight = np.asarray(data.weight)
        self.offset = np.asarray(data.offset)
        self.group_ids = data.id_tags
        # raises NotImplementedError for a coordinate without the
        # external-scoring interface — callers fall back to the host loop
        self.datas = tuple(
            sweep.coordinates[cid].external_data(data)
            for cid in sweep.order)
        first = sweep.coordinates[sweep.order[0]]
        self.loss = loss_for_task(first.task)
        self.y_dev = jnp.asarray(np.asarray(self.y, sweep._dtype))
        self.wt_dev = jnp.asarray(np.asarray(self.weight, sweep._dtype))

    def initial_state(self, initial):
        """(per-coordinate initial held-out margins as device arrays,
        host ``val_base`` = offsets + carried-entity contributions) — the
        held-out twin of ``FusedSweep._init_carry`` +
        ``_base_with_carry_through``: warm-start models contribute their
        held-out score from the start, carried (never-retrained) entities
        ride the base as a constant so every in-program replace matches the
        host loop's full-model re-scoring."""
        sweep = self.sweep
        dtype = sweep._dtype
        val_base = np.asarray(self.offset, dtype).copy()
        vscores = []
        for i, cid in enumerate(sweep.order):
            coord = sweep.coordinates[cid]
            init = (initial[cid] if initial is not None and cid in initial
                    else None)
            if init is None:
                vscores.append(jnp.zeros(self.n, dtype))
                continue
            s = np.asarray(init.score(self.data), dtype)
            c = coord.carry_through_scores_on(init, self.data)
            if c is not None:
                # carried contribution rides val_base for the whole program
                # (same no-double-count split as _init_carry's)
                s = s - np.asarray(c, dtype)
                val_base += np.asarray(c, dtype)
            vscores.append(jnp.asarray(s))
        return tuple(vscores), val_base
