"""Fully-jitted GLMix coordinate-descent sweeps.

The host-orchestrated ``CoordinateDescent`` (descent.py) mirrors the
reference's driver loop (CoordinateDescent.scala:119-346): one device
dispatch per solve/score plus host-side residual bookkeeping between
coordinates.  That loop is the right place for validation, checkpointing and
locked coordinates — but for raw training throughput the whole sweep can be
ONE XLA program: ``lax.scan`` over outer iterations whose body inlines every
coordinate's solver, residual fold, and re-scoring.  No host round-trips, no
per-phase dispatch latency, and XLA overlaps/fuses across phases (e.g. the
residual subtraction folds into the next solver's first objective pass).

This is the TPU-native answer to the reference's persist/broadcast
choreography between coordinate updates (CoordinateDescent.scala:208-232):
instead of caching RDD scores between Spark jobs, the scores never leave HBM.

Supported (v1): FixedEffectCoordinate over a dense batch with
down_sampling_rate >= 1 (no per-update resampling inside the scan), and
RandomEffectCoordinate with the IDENTITY projector.  Anything else -> use
CoordinateDescent (identical semantics, host-paced).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from photon_ml_tpu.core.batch import DenseBatch
from photon_ml_tpu.game.coordinate import (Coordinate, FixedEffectCoordinate,
                                           RandomEffectCoordinate)
from photon_ml_tpu.models.game import (FixedEffectModel, GameModel,
                                       RandomEffectModel)
from photon_ml_tpu.models.glm import Coefficients
from photon_ml_tpu.parallel.bucketing import score_samples

Array = jax.Array


class FusedSweep:
    """jit(scan)-compiled block coordinate descent over GAME coordinates.

    Semantics match ``CoordinateDescent.run`` with no validation suite: cold
    start (or ``initial`` warm start), residual offsets, warm start across
    outer iterations, final full model returned.  Compiles ONE sweep body
    regardless of ``num_iterations``.
    """

    def __init__(self, coordinates: Dict[str, Coordinate],
                 order: Optional[Sequence[str]] = None,
                 num_iterations: int = 1):
        if not coordinates:
            raise ValueError("FusedSweep needs at least one coordinate")
        self.coordinates = coordinates
        self.order = list(order) if order is not None else list(coordinates)
        # positional carries double-count a repeated coordinate's score, so a
        # duplicate id must be rejected (the host descent tolerates repeats)
        if len(self.order) != len(coordinates) or set(self.order) != set(coordinates):
            raise ValueError(f"order {self.order} != ids {set(coordinates)}")
        self.num_iterations = num_iterations

        first = coordinates[self.order[0]]
        self._n = first._n
        self._dtype = first._dtype

        self._kinds: List[str] = []
        self._slot_idx: Dict[str, List[Array]] = {}
        for cid in self.order:
            coord = coordinates[cid]
            if isinstance(coord, FixedEffectCoordinate):
                if not isinstance(coord._batch, DenseBatch):
                    raise NotImplementedError(
                        f"fused sweep needs a dense fixed-effect batch ({cid!r})")
                if coord.config.down_sampling_rate < 1.0:
                    raise NotImplementedError(
                        f"fused sweep does not resample per update; coordinate "
                        f"{cid!r} has down_sampling_rate < 1 — use CoordinateDescent")
                self._kinds.append("fixed")
            elif isinstance(coord, RandomEffectCoordinate):
                if coord._proj is not None:
                    raise NotImplementedError(
                        f"fused sweep supports IDENTITY projection only ({cid!r})")
                self._kinds.append("random")
                # per-bucket lane -> slot row in the stacked model; invalid
                # lanes scatter out of range and are dropped
                from photon_ml_tpu.game.coordinate import _slots_from

                num_entities = len(coord._sorted_ids)
                self._slot_idx[cid] = [
                    jnp.asarray(np.where(
                        (s := _slots_from(coord._slot_of,
                                          np.asarray(b.entity_lanes, np.int64))) < 0,
                        num_entities, s).astype(np.int32))
                    for b in coord.buckets.buckets
                ]
            else:
                raise TypeError(f"unknown coordinate type {type(coord)!r}")

        base = jnp.asarray(np.asarray(first._base_offset, self._dtype))
        n, order, coords = self._n, self.order, self.coordinates
        kinds, slot_idx = self._kinds, self._slot_idx

        def body(carry, _):
            ws, lanes, scores = carry
            ws, lanes, scores = list(ws), list(lanes), list(scores)
            total = scores[0]
            for s in scores[1:]:
                total = total + s
            for i, cid in enumerate(order):
                coord = coords[cid]
                # residual trick (CoordinateDescent.scala:197-204)
                partial = total - scores[i]
                offs = base + partial
                if kinds[i] == "fixed":
                    pad = coord._padded_n - n
                    offs_p = jnp.pad(offs, (0, pad)) if pad else offs
                    res = coord._solve(ws[i], offs_p, coord._base_weight)
                    ws[i] = res.w
                    w_orig = coord._norm.model_to_original_space(
                        res.w, coord.config.intercept_index)
                    s = coord._batch.margins(w_orig)[:n]
                else:
                    new_lanes = []
                    for bi, dev in enumerate(coord._dev):
                        off_b = jnp.where(dev["valid"], offs[dev["rows"]],
                                          0.0).astype(offs.dtype)
                        res = coord._vsolve(lanes[i][bi], dev["x"], dev["y"],
                                            off_b, dev["w"])
                        new_lanes.append(res.w)
                    lanes[i] = tuple(new_lanes)
                    w_stack = self._stack(cid, new_lanes)
                    s = score_samples(w_stack, coord._sample_slots, coord._x_full)[:n]
                scores[i] = s
                total = partial + s
            return (tuple(ws), tuple(lanes), tuple(scores)), None

        def program(ws0, lanes0, scores0):
            carry, _ = lax.scan(body, (ws0, lanes0, scores0), None,
                                length=self.num_iterations)
            ws, lanes, scores = carry
            outs = []
            for i, cid in enumerate(order):
                coord = coords[cid]
                if kinds[i] == "fixed":
                    outs.append(coord._norm.model_to_original_space(
                        ws[i], coord.config.intercept_index))
                else:
                    outs.append(self._stack(cid, list(lanes[i])))
            return tuple(outs), scores

        self._program = jax.jit(program)

    def _stack(self, cid: str, lane_ws: List[Array]) -> Array:
        coord = self.coordinates[cid]
        num_entities = len(coord._sorted_ids)
        d = lane_ws[0].shape[-1]
        w_stack = jnp.zeros((num_entities, d), lane_ws[0].dtype)
        for bi, lw in enumerate(lane_ws):
            w_stack = w_stack.at[self._slot_idx[cid][bi]].set(lw, mode="drop")
        return w_stack

    def _init_carry(self, initial: Optional[GameModel]):
        ws, lanes, scores = [], [], []
        for i, cid in enumerate(self.order):
            coord = self.coordinates[cid]
            init = initial[cid] if initial is not None and cid in initial else None
            if self._kinds[i] == "fixed":
                if init is not None:
                    w0 = coord._norm.model_to_transformed_space(
                        jnp.asarray(np.asarray(init.coefficients.means,
                                               self._dtype)),
                        coord.config.intercept_index)
                else:
                    w0 = jnp.zeros(coord.dim, self._dtype)
                ws.append(w0)
                lanes.append(())
            else:
                # entity-lane sharding must match the closed-over bucket data
                # (RandomEffectCoordinate.update routes w0 the same way)
                bucket_ws = []
                for bi, b in enumerate(coord.buckets.buckets):
                    if init is not None:
                        bucket_ws.append(coord._put_entity(
                            coord._warm_start(bi, init)))
                    else:
                        bucket_ws.append(coord._put_entity(
                            np.zeros((b.num_lanes, coord.dim), self._dtype)))
                ws.append(())
                lanes.append(tuple(bucket_ws))
            scores.append(jnp.zeros(self._n, self._dtype) if init is None
                          else jnp.asarray(np.asarray(coord.score(init),
                                                      self._dtype)))
        return tuple(ws), tuple(lanes), tuple(scores)

    def run(self, initial: Optional[GameModel] = None
            ) -> Tuple[GameModel, Dict[str, np.ndarray]]:
        """One fused descent; returns (model, per-coordinate final scores)."""
        ws0, lanes0, scores0 = self._init_carry(initial)
        outs, scores = self._program(ws0, lanes0, scores0)
        models: Dict[str, object] = {}
        final_scores: Dict[str, np.ndarray] = {}
        for i, cid in enumerate(self.order):
            coord = self.coordinates[cid]
            if self._kinds[i] == "fixed":
                models[cid] = FixedEffectModel(
                    coefficients=Coefficients(means=np.asarray(outs[i])),
                    feature_shard=coord.config.feature_shard, task=coord.task)
            else:
                models[cid] = RandomEffectModel(
                    w_stack=np.asarray(outs[i]), slot_of=dict(coord._slot_of),
                    random_effect_type=coord.config.random_effect_type,
                    feature_shard=coord.config.feature_shard, task=coord.task)
            final_scores[cid] = np.asarray(scores[i])
        return GameModel(models=models), final_scores
